"""Central registry of every jit entry the consensus planes dispatch.

One name -> EntrySpec table replacing the hand-maintained entry lists
that used to live in three places at once (DeviceDriver's import
block, ServePipeline.warmup's import block, and whatever audit script
was being written that week).  Three consumers:

* **DeviceDriver / ServePipeline** resolve their dispatch entries here
  (`jit_entry(name)`), so the driver, the serve warmup, and any audit
  all agree on WHICH compiled object a name means — and tests can
  `override()` an entry with a stub to exercise host-side machinery
  with zero XLA compiles.
* **The static analyzer** (`agnes_tpu/analysis/jaxpr_audit.py`)
  enumerates `entries()` and abstractly traces each one: donation
  honored, collective census, no host callbacks, dtype policy.  An
  entry that is not registered is an entry the auditor cannot see —
  which is why `analysis/lint.py` flags any import-time `jax.jit`
  whose result is not registered here.
* **The retrace tripwire** (`analysis/retrace.py`) keys its expected
  (entry, shape-signature) sets by registry name.

Registration happens at the DEFINING module's import time (step.py,
parallel/sharded.py, device/tally.py, crypto/...), so the table is
complete exactly when those modules are importable; `entries()`
imports the canonical module list first so enumeration never depends
on what the caller happened to import.

This module is a leaf: it imports nothing from the rest of the
package (the registered objects are passed IN), so any module may
import it without cycles.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

#: modules whose import populates the full registry (ordered; heavy
#: crypto modules last).  entries()/ensure_populated() import these.
CANONICAL_MODULES = (
    "agnes_tpu.device.state_machine",
    "agnes_tpu.device.tally",
    "agnes_tpu.device.step",
    "agnes_tpu.parallel.sharded",
    "agnes_tpu.crypto.ed25519_jax",
    "agnes_tpu.crypto.msm_jax",
    "agnes_tpu.crypto.bls_jax",
    "agnes_tpu.crypto.bls_pairing_jax",
    "agnes_tpu.crypto.pallas_verify",
    "agnes_tpu.crypto.pallas_ed25519",
    "agnes_tpu.crypto.pallas_field",
)

#: the backend names a Pallas entry may claim lowering support for
#: (analysis/pallas_support.py polices the record; "triton" stays
#: unclaimed until the GPU lane actually lowers a kernel there)
PALLAS_BACKENDS = ("tpu", "triton", "interpret")


@dataclasses.dataclass(frozen=True)
class EntrySpec:
    """One registered jit entry (or sharded-entry factory).

    `statics` names the entry's static argnames; `donated` the
    donate_argnums its jit was built with (the auditor asserts the
    LOWERED text actually carries the aliasing/donor attrs — a spec
    that claims donation its jit does not implement is a finding).
    `hot` marks serve/offline hot-path entries: the auditor requires
    abstract-args coverage for them and the lint treats their call
    sites as host-sync-sensitive.  `sharded` entries register the
    FACTORY (mesh, **statics) -> jitted fn instead of a jit object.

    `pallas_backends` is the per-backend LOWERING-SUPPORT record every
    Pallas-bearing entry must carry (ISSUE 18): the subset of
    `PALLAS_BACKENDS` the kernel is known to lower on, audited by the
    `agnes-lint --pass pallas` rule so the GPU lane inherits a
    known-good kernel set instead of discovering lowering failures at
    dispatch.  None for plain XLA entries."""

    name: str
    fn: Callable                       # the traceable python function
    jit: Optional[Callable] = None     # jitted entry (None for sharded)
    statics: Tuple[str, ...] = ()
    donated: Tuple[int, ...] = ()
    sharded: bool = False
    factory: Optional[Callable] = None  # sharded: (mesh, **statics)
    hot: bool = True                    # audited hot-path entry
    pallas_backends: Optional[Tuple[str, ...]] = None

    def __post_init__(self):
        if self.sharded:
            assert self.factory is not None, self.name
        else:
            assert self.jit is not None, self.name
        if self.pallas_backends is not None:
            bad = set(self.pallas_backends) - set(PALLAS_BACKENDS)
            assert self.pallas_backends and not bad, \
                f"{self.name}: bad pallas_backends {bad or '()'}"


_REGISTRY: Dict[str, EntrySpec] = {}


def register(spec: EntrySpec) -> EntrySpec:
    """Idempotent by name: re-importing a defining module re-registers
    the same spec; a DIFFERENT spec under an existing name — any field
    differing, including the jit/factory OBJECT identity — is a
    programming error (two modules claiming one entry, or a reload
    rebuilding a jit the auditor already vouched for)."""
    old = _REGISTRY.get(spec.name)
    if old is not None and old != spec:
        raise ValueError(f"jit entry {spec.name!r} already registered "
                         f"with a different spec")
    _REGISTRY[spec.name] = spec
    return spec


def get(name: str) -> EntrySpec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown jit entry {name!r}; registered: "
            f"{sorted(_REGISTRY)}") from None


def jit_entry(name: str) -> Callable:
    """The dispatchable object for `name` — the driver/pipeline seam
    (tests override() this to stub device dispatch).  Identity-
    preserving: returns exactly the registered jit (the lint's
    `is_registered_jit` and tests' `is` assertions depend on it);
    dispatch-path callers that want the first call TIMED go through
    `timed_entry` instead."""
    return get(name).jit


# -- first-dispatch compile wall (ISSUE 8 satellite) -------------------------
#
# The FIRST call of a jit entry in a process pays trace + compile
# synchronously (execution stays async), so its host wall IS the
# compile cost to within dispatch noise — the number that turns the
# next silent-double-compile class of bug (the PR 3 217s stall) into
# a `compile_ms_<entry>` gauge in drain reports and bench verdicts
# instead of a mystery.  First-write-wins per entry name; recording
# fires the `on_compile` observers (flight recorders) exactly once.

_COMPILE_MS: Dict[str, float] = {}
_COMPILE_CBS: List[Callable[[str, float], None]] = []
_COMPILE_LOCK = threading.Lock()    # guards the first-write-wins


def compile_ms() -> Dict[str, float]:
    """{entry name -> first-dispatch wall ms} observed so far."""
    return dict(_COMPILE_MS)


def compile_gauges() -> Dict[str, float]:
    """The same view under the metrics well-known gauge names
    (`compile_ms_<entry>`) — what drain reports, heartbeat lines and
    the /metrics endpoint carry."""
    return {f"compile_ms_{k}": round(v, 1)
            for k, v in _COMPILE_MS.items()}


def on_compile(cb: Callable[[str, float], None]) -> None:
    """Observe first-dispatch recordings (cb(name, wall_ms)); each
    entry fires at most once per process.  Observers are exception-
    contained — telemetry must never fail a dispatch."""
    _COMPILE_CBS.append(cb)


def record_compile_ms(name: str, wall_ms: float) -> bool:
    """First-write-wins; True iff this call recorded `name`.  The
    check+write is locked so two threads racing an entry's first
    dispatch (warmup vs a dispatch loop) cannot both record — and the
    observers fire at most once per entry, outside the lock."""
    with _COMPILE_LOCK:
        if name in _COMPILE_MS:
            return False
        _COMPILE_MS[name] = float(wall_ms)
        cbs = list(_COMPILE_CBS)
    for cb in cbs:
        try:
            cb(name, float(wall_ms))
        except Exception:  # noqa: BLE001 — observers never fail a
            pass           # dispatch
    return True


def reset_compile_ms() -> None:
    """Test seam: forget recorded walls (process-lifetime data)."""
    _COMPILE_MS.clear()


def timed_call(name: str, fn: Callable, *args, **kwargs):
    """Call `fn`; if `name` has no recorded wall yet, time this call
    and record it.  Steady state (name recorded) is a dict lookup."""
    if name in _COMPILE_MS:
        return fn(*args, **kwargs)
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    record_compile_ms(name, (time.perf_counter() - t0) * 1e3)
    return out


def timed_entry(name: str) -> Callable:
    """`jit_entry(name)`, wrapped so the entry's FIRST dispatch in the
    process records `compile_ms_<name>`.  Once recorded the raw jit is
    returned — zero steady-state overhead.  The driver and the serve
    warmup dispatch through this; `jit_entry` stays identity-
    preserving for the auditor/lint/override seams."""
    spec = get(name)
    if spec.name in _COMPILE_MS:
        return spec.jit

    def first_timed(*args, **kwargs):
        return timed_call(spec.name, spec.jit, *args, **kwargs)

    return first_timed


def names() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def is_registered_jit(obj) -> bool:
    """Identity check used by analysis/lint.py's import-time-jit rule:
    a module-level jit object is sanctioned iff it IS some registered
    entry's jit (or a registered factory's memoized product — those
    are created inside functions, not at import, so only `jit` is
    checked here)."""
    return any(s.jit is obj for s in _REGISTRY.values())


def ensure_populated() -> None:
    """Import the canonical defining modules so enumeration is
    complete regardless of caller import order."""
    import importlib

    for m in CANONICAL_MODULES:
        importlib.import_module(m)


def entries(hot_only: bool = False) -> Tuple[EntrySpec, ...]:
    ensure_populated()
    out = tuple(_REGISTRY[n] for n in sorted(_REGISTRY))
    if hot_only:
        out = tuple(s for s in out if s.hot)
    return out


@contextlib.contextmanager
def override(name: str, **changes):
    """Temporarily replace fields of a registered spec (tests stub
    `jit=` to run pipeline/driver machinery with zero XLA compiles).
    Restores the original spec on exit, always."""
    old = get(name)
    _REGISTRY[name] = dataclasses.replace(old, **changes)
    try:
        yield _REGISTRY[name]
    finally:
        _REGISTRY[name] = old
