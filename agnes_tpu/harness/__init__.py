"""Event-stream simulation harness.

The reference's entire test strategy is "the consumer fabricates the
event stream" (README.md:8-14, :36-42; SURVEY.md §4): no cluster, no
network, no timers — just scripted events.  This package extends that
philosophy to both planes:

  simulator.py      in-memory multi-node network over ConsensusExecutor
                    (host plane), with Byzantine node behaviors.
  device_driver.py  closed-loop driver for the fused device step:
                    fabricates dense vote phases, routes the step's own
                    output votes back in, reads decisions off the
                    message stream.
  configs.py        the five BASELINE.json benchmark configs (+ a partition/heal liveness drill), runnable
                    as `python -m agnes_tpu.harness.configs N`.
  replay.py         cross-plane differential: tap a Network's nodes,
                    replay each node's exact processing stream through
                    the bridge + fused device step, compare decisions.
"""

from agnes_tpu.harness.simulator import Network, NodeSpec  # noqa: F401
from agnes_tpu.harness.replay import (  # noqa: F401
    ReplayResult,
    replay_trace,
    trace_network,
)

# DeviceDriver is re-exported LAZILY (PEP 562): importing it pulls jax,
# and the model checker's spawned workers (analysis/modelcheck.py) need
# `harness.simulator` in a jax-free interpreter — both for spawn cost
# and for the zero-XLA-compile guarantee of the agnes_modelcheck gate.
_LAZY = {"DeviceDriver": ("agnes_tpu.harness.device_driver",
                          "DeviceDriver")}


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    return getattr(importlib.import_module(mod_name), attr)
