"""Cross-plane differential replay: host-plane traffic into the device plane.

The framework has two full consensus planes over one state machine:

  host plane    harness.Network routing N `ConsensusExecutor`s
                (core/executor.py — the completed consensus_executor.rs
                driver, with re-entrant execute and a TimerWheel);
  device plane  bridge.VoteBatcher densifying wire votes into phases
                for the fused device step (device/step.py), with the
                batcher's host fallback covering past-window rounds.

Each plane is pinned to the shared Python oracle by its own suite, but
the planes do NOT share tally/event *ordering* (device re-query cursor,
device/step.py stages 3-4, vs the executor's `_requery`,
core/executor.py) — exactly where an ordering divergence would hide.
This module closes that gap with a replay differential:

  1. `trace_network` taps every node's `execute` — because the executor
     is re-entrant (self-produced proposals/votes and fired timeouts
     all loop back through `execute`, the reference's
     consensus_executor.rs:36,:40 intent), the tap captures the node's
     COMPLETE processing stream in exact order: peer deliveries,
     self-deliveries, timeouts.
  2. `replay_trace` replays one node's stream through the production
     device path — VoteBatcher (layering/dedup/slot interning/window
     hold-back/host fallback) feeding the fused device step — and
     reports what the device plane decided.

Identical decisions per (node, height) across planes is the invariant
the reference's testability argument (README.md:8-14) demands once two
implementations of the executor loop exist.  tests/test_cross_plane.py
fuzzes this over seeded Byzantine schedules (honest/silent/
equivocator/nil-flood mixes, partition/heal).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

import numpy as np

from agnes_tpu.core.executor import (WireProposal, WireTimeout,
                                     epoch_boundary_at)
from agnes_tpu.core.state_machine import EventTag, TimeoutStep
from agnes_tpu.types import Vote

_TIMEOUT_TAG = {
    TimeoutStep.PROPOSE: int(EventTag.TIMEOUT_PROPOSE),
    TimeoutStep.PREVOTE: int(EventTag.TIMEOUT_PREVOTE),
    TimeoutStep.PRECOMMIT: int(EventTag.TIMEOUT_PRECOMMIT),
}


def trace_network(net) -> List[List[object]]:
    """Install a processing-order tap on every node of a
    harness.Network (before `net.start()`).  Returns one list per node;
    each fills with the wire messages that node processes, in exact
    order (including re-entrant self-deliveries and timeout firings)."""
    traces: List[List[object]] = [[] for _ in net.nodes]

    def _wrap(node, rec):
        orig = node.execute

        def tapped(msg):
            rec.append(msg)
            orig(msg)

        node.execute = tapped

    for node, rec in zip(net.nodes, traces):
        _wrap(node, rec)
    return traces


@dataclass
class ReplayResult:
    """Device-plane outcome of replaying one node's stream.  The
    scalar `decided`/`value`/`round` view is HEIGHT 0 (the single
    height every pre-epoch replay covered); `decisions` carries every
    height the device decided — height -> (round, value) — so the
    cross-plane differential holds host == device THROUGH a
    validator-set change."""

    decided: bool = False
    value: Optional[int] = None          # decoded value id
    round: Optional[int] = None
    decisions: Dict[int, tuple] = field(default_factory=dict)
    equivocators: Set[int] = field(default_factory=set)
    steps: int = 0
    host_fallback_decisions: int = 0     # decided via PRECOMMIT_VALUE ext


def replay_trace(trace: List[object], n_validators: int,
                 powers: Optional[np.ndarray] = None,
                 n_rounds: int = 4, n_slots: int = 4,
                 epochs: Optional[Dict[int, object]] = None
                 ) -> ReplayResult:
    """Replay one node's processed-message stream through the
    bridge + fused-device pipeline (the production device plane) and
    return the per-height outcomes.

    The device instance is built as a NON-proposer: the node's own
    proposal arrives in the trace as a re-entrant WireProposal and is
    injected as a PROPOSAL ext event, its own votes ride the dense
    phases like peer votes (device/step.py module docstring), and
    timeouts fire exactly where the host TimerWheel fired them.

    `epochs` is a validator-set epoch schedule {boundary_height:
    [V] powers} in SORTED index order (the executor/simulator
    contract, core/executor.py `epochs`): at every height the table
    with the largest boundary <= height applies, `powers` (or
    all-ones) below the first boundary.  Each boundary is installed
    through the REAL epoch entry points — `DeviceDriver.
    set_validators` between heights (after the deciding step, before
    the next dispatch) and `VoteBatcher.set_validators` right after
    the `sync_device` that advanced heights — so a replay across a
    boundary exercises the exact call pattern a production height
    change performs."""
    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.harness.device_driver import DeviceDriver

    d = DeviceDriver(1, n_validators, n_rounds=n_rounds, n_slots=n_slots,
                     proposer_is_self=False, advance_height=True)
    genesis = np.asarray(powers) if powers is not None \
        else np.ones(n_validators, np.int64)
    if powers is not None:
        d.set_validators(powers)
    bat = VoteBatcher(1, n_validators, n_slots=n_slots, n_rounds=n_rounds,
                      powers=powers)
    res = ReplayResult()

    def height() -> int:
        return int(np.asarray(d.state.height)[0])

    def epoch_powers_at(h: int) -> np.ndarray:
        best = epoch_boundary_at(epochs, h)
        return genesis if best is None \
            else np.asarray(epochs[best], np.int64)

    installed = {"driver": None, "batcher": None}

    def install_epoch(which: str, setter) -> None:
        """Idempotently adopt the epoch live at the device's CURRENT
        height through the real `set_validators` boundary call."""
        if not epochs:
            return
        h = height()
        pw = epoch_powers_at(h)
        if installed[which] is None or \
                not np.array_equal(installed[which], pw):
            setter(pw)
            installed[which] = pw

    def after_step() -> None:
        res.steps += 1
        if bool(d.stats.decided[0]):
            # the step entered at the height it decided; with
            # advance_height the post-step height is already +1, so
            # the decision belongs to height() - 1.  Decode NOW: the
            # next sync_device resets the slot maps for the advanced
            # height.  Slot-space decisions decode through the
            # batcher; host-fallback decisions carry the raw 31-bit
            # value id in the lane (drain_host_events docstring) —
            # value ids are content-derived/harness ints >= n_slots,
            # so the ranges are disjoint.
            dec_h = height() - 1
            dv = int(d.stats.decision_value[0])
            rnd = int(d.stats.decision_round[0])
            val = bat.decode_slot(0, dv) if 0 <= dv < n_slots else dv
            res.decisions.setdefault(dec_h, (rnd, val))
            if dec_h == 0:
                res.decided, res.round, res.value = True, rnd, val
            # unlatch so the NEXT height's decision records too
            # (DriverStats latches the first decision per instance)
            d.stats.decided[0] = False
            d.stats.decision_round[0] = -1
            # the decision advanced the height: adopt the new epoch
            # before the next dispatch (the driver's between-heights
            # contract; heights only move on decisions, so no other
            # step can change the live epoch)
            install_epoch("driver", d.set_validators)

    def step(ext=None, phase=None) -> None:
        d.step(ext=ext, phase=phase)
        after_step()

    def sync() -> None:
        bat.sync_device(np.asarray(d.tally.base_round),
                        np.asarray(d.state.height))
        # right after the sync that (may have) advanced heights: the
        # batcher's host-fallback tallies must quorum against the
        # live epoch (bridge/ingest.py set_validators contract)
        install_epoch("batcher", bat.set_validators)

    def drain() -> None:
        for inst, hgt, rnd, vid in bat.drain_host_events():
            if hgt == height():   # commit-from-any-round, still live
                # the decode in after_step tells slots from value ids by
                # range — enforce the disjointness it relies on
                assert vid >= n_slots, (
                    f"value id {vid} collides with the slot range "
                    f"[0, {n_slots}); use larger value ids")
                before = len(res.decisions)
                step(ext=d.ext(int(EventTag.PRECOMMIT_VALUE), rnd, vid))
                if len(res.decisions) > before:
                    res.host_fallback_decisions += 1

    def pump() -> None:
        """Sync the batcher to the device window and feed until quiet.
        Looping matters: feeding a phase can advance the device round,
        and the NEXT sync may release votes the batcher held back as
        future-window — without the loop (or after window-moving ext
        steps / at end of trace) held votes the host tallied would
        silently never reach the device."""
        while True:
            sync()
            phases = bat.build_phases()
            if not phases:
                drain()
                return
            for phase, _ in phases:
                step(phase=phase)
            drain()

    def flush(chunk: List[Vote]) -> None:
        if not chunk:
            return
        bat.add_arrays(
            np.zeros(len(chunk), np.int64),
            np.asarray([v.validator for v in chunk], np.int64),
            np.asarray([v.height for v in chunk], np.int64),
            np.asarray([v.round for v in chunk], np.int64),
            np.asarray([int(v.typ) for v in chunk], np.int64),
            np.asarray([-1 if v.value is None else v.value for v in chunk],
                       np.int64))
        pump()

    # genesis may itself sit past an epoch boundary (a set rotated in
    # at height 0): adopt it before the entry dispatch
    install_epoch("driver", d.set_validators)
    install_epoch("batcher", bat.set_validators)
    step()                       # round-0 entry, like the host start()
    chunk: List[Vote] = []
    for msg in trace:
        if isinstance(msg, Vote):
            if chunk and (msg.round != chunk[-1].round
                          or msg.typ != chunk[-1].typ
                          or msg.height != chunk[-1].height):
                flush(chunk)
                chunk = []
            chunk.append(msg)
            continue
        flush(chunk)
        chunk = []
        if isinstance(msg, WireProposal):
            if msg.height != height():
                continue          # same screen as executor._on_proposal
            sync()
            slot = bat.slots.slot_for(0, msg.value)
            if slot is None:      # slot overflow: host-fallback territory
                continue
            step(ext=d.ext(int(EventTag.PROPOSAL), msg.round, slot,
                           msg.pol_round))
        elif isinstance(msg, WireTimeout):
            if msg.height != height():
                continue          # same screen as executor._on_timeout
            step(ext=d.ext(_TIMEOUT_TAG[msg.step], msg.round))
            pump()                # timeouts move the window: release holds
    flush(chunk)
    pump()                        # end of trace: release remaining holds

    res.equivocators = {int(v) for v in
                        np.nonzero(np.asarray(d.tally.equiv)[0])[0]}
    return res
