"""The five BASELINE.json benchmark configs (+ a liveness drill),
runnable standalone.

    python -m agnes_tpu.harness.configs <1..6> [--small]

Each config returns a metrics dict (one JSON line on stdout).  The
reference publishes no numbers (SURVEY.md §6); the comparison anchor is
the north star: >= 1M Ed25519 verifies/sec/chip and 10k concurrent
1000-validator instances.  `--small` shrinks shapes for CPU/test runs.

  1. 4-validator single-height happy path — host executor network,
     CPU parity (reference state_machine.rs:331-345 trace).
  2. 100-validator prevote/precommit with Ed25519 batch verify —
     the vote_executor path with real signatures.
  3. 1000-validator multi-round with timeouts + nil prevotes —
     the round_votes tally on device.
  4. 10k parallel heights, vmapped — consensus_executor fuzz/throughput.
  5. Byzantine equivocation sweep — 1M double-sign votes, on-device
     slashing detection.
  6. Partition/heal liveness drill — a quorum-less split stalls
     without deciding, a majority split decides alone, and heal
     converges everyone (simulator partition fault model).
"""

from __future__ import annotations

import json
import sys
import time

from agnes_tpu.types import VoteType


def config1_happy_path(small: bool = False) -> dict:
    """Host-plane parity: a 4-node network decides 20 heights; then raw
    state-machine apply throughput (the reference's only benchmarkable
    surface)."""
    from agnes_tpu.core import state_machine as sm
    from agnes_tpu.harness.simulator import Network

    heights = 3 if small else 20
    net = Network(n=4)
    net.start()
    t0 = time.perf_counter()
    net.run_until(lambda: net.decided(heights - 1))
    dt = time.perf_counter() - t0
    for h in range(heights):
        vals = set(net.decisions(h))
        assert vals == {100 + h}, (h, vals)

    # raw apply throughput (pure python transition fn)
    s = sm.State.new(0)
    ev = sm.Event.new_round()
    n = 20_000 if small else 200_000
    t1 = time.perf_counter()
    for _ in range(n):
        s2, _ = sm.apply(s, 0, ev)
    apply_rate = n / (time.perf_counter() - t1)
    return {"config": 1, "heights": heights,
            "heights_per_sec": round(heights / dt, 2),
            "host_applies_per_sec": round(apply_rate)}


def config2_verify_100(small: bool = False) -> dict:
    """100 validators, one height: every prevote+precommit is a real
    Ed25519 signature, batch-verified on device (JAX) with the C++
    verifier as cross-check, then tallied to decision."""
    import jax
    import numpy as np

    from agnes_tpu.core import native
    from agnes_tpu.core.state_machine import Step, State, Event
    from agnes_tpu.core.vote_executor import VoteExecutor
    from agnes_tpu.crypto import ed25519_jax as ejax
    from agnes_tpu.crypto.encoding import vote_signing_bytes
    from agnes_tpu.types import Vote

    V = 8 if small else 100
    value = 42
    seeds = [bytes([i % 251 + 1, i // 251]) + bytes(30) for i in range(V)]
    pks = [native.pubkey(s) for s in seeds]

    msgs, sigs, votes = [], [], []
    for typ in (VoteType.PREVOTE, VoteType.PRECOMMIT):
        for i in range(V):
            m = vote_signing_bytes(1, 0, int(typ), value)
            msgs.append(m)
            sigs.append(native.sign(seeds[i], m))
            votes.append(Vote(typ=typ, round=0, value=value, validator=i,
                              height=1))

    pub, sig, blocks = ejax.pack_verify_inputs_host(pks + pks, msgs, sigs)
    t0 = time.perf_counter()
    ok = ejax.verify_batch_jit(pub, sig, blocks)
    ok.block_until_ready()
    compile_and_run = time.perf_counter() - t0
    t1 = time.perf_counter()
    ok = ejax.verify_batch_jit(pub, sig, blocks)
    ok.block_until_ready()
    dt = time.perf_counter() - t1
    assert bool(np.asarray(ok).all())
    # C++ cross-check
    assert native.verify_batch(pks + pks, msgs, sigs) == [True] * (2 * V)

    # verified votes -> tally -> decision
    state = State.new(1)
    vx = VoteExecutor(height=1, total_weight=V)
    state, _ = state.apply(0, Event.new_round_proposer(value))
    state, _ = state.apply(0, Event.proposal(-1, value))
    for v, valid in zip(votes, np.asarray(ok).tolist()):
        if valid:
            ev = vx.apply(v, 1)
            if ev is not None:
                state, msg = state.apply(0, ev)
    assert state.step == Step.COMMIT
    return {"config": 2, "validators": V,
            "verifies_per_sec": round(2 * V / dt),
            "first_call_s": round(compile_and_run, 2),
            "decided": True}


def config3_multiround(small: bool = False) -> dict:
    """1000-validator tally, multi-round: round 0 times out on nil
    votes, round 1 receives a proposal and decides."""
    import numpy as np

    from agnes_tpu.harness.device_driver import DeviceDriver

    I, V = (8, 64) if small else (256, 1000)
    d = DeviceDriver(I, V, proposer_is_self=False)
    t0 = time.perf_counter()
    d.run_nil_round(0)
    d.run_proposed_round(1, slot=1)
    d.block_until_ready()
    dt = time.perf_counter() - t0
    assert d.all_decided()
    assert (np.asarray(d.stats.decision_round) == 1).all()
    return {"config": 3, "instances": I, "validators": V,
            "rounds": 2, "votes_tallied": d.stats.votes_ingested,
            "votes_per_sec": round(d.stats.votes_ingested / dt)}


def config4_parallel_heights(small: bool = False) -> dict:
    """10k concurrent instances x 1000 validators, vmapped — the north
    star shape, honest path."""
    from agnes_tpu.harness.device_driver import DeviceDriver

    I, V = (16, 32) if small else (10_000, 1000)
    d = DeviceDriver(I, V, advance_height=True)
    # warmup/compile on the real shapes (fused: the whole honest height
    # is ONE device dispatch — device/step.py honest_heights)
    d.run_heights_fused(1)
    d.block_until_ready()
    d2 = DeviceDriver(I, V, advance_height=True)
    t0 = time.perf_counter()
    d2.run_heights_fused(1)
    d2.block_until_ready()
    dt = time.perf_counter() - t0
    assert d2.all_decided()
    votes = d2.stats.votes_ingested
    return {"config": 4, "instances": I, "validators": V,
            "votes_per_sec": round(votes / dt),
            "decisions_per_sec": round(I / dt)}


def config5_byzantine_sweep(small: bool = False) -> dict:
    """Equivocation sweep: every validator double-signs in every
    instance — 1M conflicting votes at full shape — and every one is
    detected on device (the per-validator seen-record, SURVEY §2.3
    fix 2), while the honest quorum still decides."""
    import numpy as np

    from agnes_tpu.harness.device_driver import DeviceDriver

    I, V = (8, 32) if small else (1000, 1000)
    d = DeviceDriver(I, V)
    t0 = time.perf_counter()
    d.step()  # entry + self-proposal
    # first prevote: everyone votes slot 1; then everyone re-votes
    # conflicting slot 2 (double-sign)
    expected = d.run_equivocation_phase(0, VoteType.PREVOTE, 1, 2, 1.0)
    d.block_until_ready()
    dt = time.perf_counter() - t0
    det = d.equivocators_detected()
    assert (det == expected).all(), (det[:4], expected)
    # first votes kept counting: the polka for slot 1 still stands
    d.step(phase=d.phase(0, VoteType.PRECOMMIT, 1))
    assert d.all_decided()
    double_signs = I * V
    return {"config": 5, "instances": I, "validators": V,
            "double_sign_votes": double_signs,
            "detected_per_instance": int(det[0]),
            "detect_votes_per_sec": round(2 * double_signs / dt),
            "decided_despite_byzantine": True}


def config6_partition_liveness(small: bool = False) -> dict:
    """Network-fault liveness drill on the host plane: (a) a 2-2 split
    of 4 nodes has no +2/3 side — nobody decides; (b) heal delivers
    the gossip-held traffic and the timeout chain drives a unanimous
    round>=1 decision; (c) a 5-2 split decides on the majority side
    alone and the minority catches up on heal (commit-from-any-round
    over held precommits)."""
    from agnes_tpu.harness.simulator import Network

    t0 = time.perf_counter()
    net = Network(n=4)
    net.start()
    heal_round = net.partition_heal_drill([0, 1], [2, 3])

    # majority side must keep +2/3: 4-1 at small, 5-2 at full
    n2, n_min = (5, 1) if small else (7, 2)
    maj = list(range(n2 - n_min))
    minority = list(range(n2 - n_min, n2))
    net2 = Network(n=n2)
    net2.start()
    net2.partition(maj, minority)
    net2.run_until(lambda: all(0 in net2.nodes[i].decided for i in maj))
    assert not any(0 in net2.nodes[i].decided for i in minority)
    net2.heal()
    net2.run_until(lambda: net2.decided(0))
    assert len(set(net2.decisions(0))) == 1
    dt = time.perf_counter() - t0
    return {"config": 6, "quorumless_split_stalled": True,
            "healed_decision_round": int(heal_round),
            "majority_decided_alone": True,
            "minority_caught_up_on_heal": True,
            "wall_s": round(dt, 2)}


CONFIGS = {1: config1_happy_path, 2: config2_verify_100,
           3: config3_multiround, 4: config4_parallel_heights,
           5: config5_byzantine_sweep, 6: config6_partition_liveness}


def main(argv=None) -> None:
    # best-effort cache-off (compile_cache.py policy): under `-m` the
    # package import already initialized the backend, but the cache
    # config still applies to the compiles below; the de-race XLA_FLAGS
    # must come from the caller's env (scripts/run_hw_suite.sh)
    from agnes_tpu.utils.compile_cache import disable_persistent_cache
    disable_persistent_cache()
    argv = argv if argv is not None else sys.argv[1:]
    if not argv or argv[0] not in {str(k) for k in CONFIGS}:
        print(__doc__)
        raise SystemExit(2)
    small = "--small" in argv
    print(json.dumps(CONFIGS[int(argv[0])](small=small)))


if __name__ == "__main__":
    main()
