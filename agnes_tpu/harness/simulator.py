"""In-memory multi-node network simulation (host plane).

Drives N `ConsensusExecutor` nodes with a toy router: no sockets, no
threads, a virtual clock — multi-node consensus exercised exactly the
way the reference argues it should be (README.md:8-14: shrink the
object graph; timeouts are injected events).  Byzantine behaviors are
router policies + misbehaving signers layered on honest nodes:

  silent        drops every outbound message (crash fault)
  equivocator   additionally signs and sends a conflicting vote for a
                different value to every peer (double-sign; feeds the
                slashing surface, BASELINE config 5)
  nil_flood     replaces own votes with nil votes (liveness attack)

Network faults are router policies too: `partition(groups)` HOLDS
BACK every message crossing a group boundary (the consumer's gossip
layer retransmits once connectivity returns, so a partition delays
rather than destroys — README.md:46-49 leaves transport to the
consumer) and `heal()` delivers the held traffic.  A side without
+2/3 power cannot decide while split (nodes stall exactly where
Tendermint stalls: Prevote with no PolkaAny means no timeout), and
after heal the mixed nil/value prevotes drive PolkaAny ->
TimeoutPrevote -> PrecommitAny -> TimeoutPrecommit -> a fresh round
where the reunited quorum decides — the classic liveness-recovery
scenario, no cluster required.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace as dc_replace
from typing import Callable, Dict, List, Optional, Sequence

from agnes_tpu.core.executor import ConsensusExecutor, TimeoutConfig
from agnes_tpu.core.round_votes import Equivocation
from agnes_tpu.core.validators import Validator, ValidatorSet
from agnes_tpu.crypto import ed25519_ref as ed
from agnes_tpu.crypto import host_sign as _sign
from agnes_tpu.crypto.encoding import vote_signing_bytes
from agnes_tpu.types import Vote

BEHAVIORS = ("honest", "silent", "equivocator", "nil_flood")


@dataclass
class NodeSpec:
    behavior: str = "honest"
    power: int = 1


@dataclass
class Network:
    """N executors + router.  `specs[i].behavior` picks the fault model
    for node i (indices are into the address-sorted validator set)."""

    n: int = 4
    specs: Optional[Sequence[NodeSpec]] = None
    timeout_config: TimeoutConfig = field(default_factory=TimeoutConfig)
    get_value: Callable[[int], int] = lambda h: 100 + h
    verify_signatures: bool = True

    def __post_init__(self):
        specs = list(self.specs or [NodeSpec() for _ in range(self.n)])
        assert len(specs) == self.n
        seeds = [bytes([i + 1]) * 32 for i in range(self.n)]
        keyed = sorted(zip([ed.keypair(s)[1] for s in seeds], seeds,
                           range(self.n)))
        # specs are re-indexed to sorted order so specs[i] matches node i
        self.specs = [specs[orig] for _, _, orig in keyed]
        self.seeds = [seed for _, seed, _ in keyed]
        self.vset = ValidatorSet(
            [Validator(pk, self.specs[i].power)
             for i, (pk, _, _) in enumerate(keyed)])
        self.nodes: List[ConsensusExecutor] = [
            ConsensusExecutor(
                self.vset, index=i, seed=self.seeds[i],
                get_value=self.get_value,
                timeout_config=self.timeout_config,
                verify_signatures=self.verify_signatures)
            for i in range(self.n)]
        self._delivered = [0] * self.n
        self.dropped = 0
        self._group: Optional[List[int]] = None   # node -> partition id
        self._held_cross: List = []               # (target, msg) queue
        self.held_partition = 0

    # -- fault models -------------------------------------------------------

    def _outbound(self, i: int, msg) -> List[object]:
        """Apply node i's behavior to an outbound message."""
        b = self.specs[i].behavior
        if b == "silent":
            self.dropped += 1
            return []
        if b == "equivocator" and isinstance(msg, Vote) \
                and msg.value is not None:
            other = msg.value + 1_000_000
            sig = _sign(self.seeds[i], vote_signing_bytes(
                msg.height, msg.round, int(msg.typ), other))
            evil = dc_replace(msg, value=other, signature=sig)
            return [msg, evil]
        if b == "nil_flood" and isinstance(msg, Vote):
            sig = _sign(self.seeds[i], vote_signing_bytes(
                msg.height, msg.round, int(msg.typ), None))
            return [dc_replace(msg, value=None, signature=sig)]
        return [msg]

    # -- network faults -----------------------------------------------------

    def partition(self, *groups: Sequence[int]) -> None:
        """Split the network: messages between different groups are
        held back until `heal()`.  Every node must appear in exactly
        one group (sorted-set indices, like `specs`)."""
        gmap = [-1] * self.n
        for g, members in enumerate(groups):
            for i in members:
                assert gmap[i] == -1, f"node {i} in two groups"
                gmap[i] = g
        assert -1 not in gmap, "every node must be in a group"
        self._group = gmap

    def heal(self) -> None:
        """Restore connectivity and deliver the held cross-partition
        traffic (gossip retransmission)."""
        self._group = None
        held, self._held_cross = self._held_cross, []
        for j, msg in held:
            self.nodes[j].execute(msg)

    def partition_heal_drill(self, *groups: Sequence[int],
                             stall_iters: int = 30) -> int:
        """The canonical quorum-less-split liveness drill (shared by
        config 6 and the harness tests): partition into `groups` (none
        with +2/3 power), prove nobody decides the current height
        (only run_until's exhaustion counts as the stall — any other
        assert surfaces), heal, converge, and return the earliest
        decision round — asserted >= 1, since a real stall means the
        round-0 quorum never assembled."""
        h = min(n.height for n in self.nodes)
        self.partition(*groups)
        stalled = False
        try:
            self.run_until(lambda: self.decided(h), max_iters=stall_iters)
        except AssertionError as e:
            assert "predicate" in str(e), e
            stalled = True
        assert stalled and not any(h in n.decided for n in self.nodes)
        self.heal()
        self.run_until(lambda: self.decided(h))
        assert len(set(self.decisions(h))) == 1
        heal_round = min(n.decided[h].round for n in self.nodes)
        assert heal_round >= 1, heal_round
        return int(heal_round)

    # -- driving ------------------------------------------------------------

    def start(self) -> None:
        for node in self.nodes:
            node.start()

    def step_router(self) -> bool:
        """Deliver every pending outbox message; True if any moved."""
        progress = False
        for i, node in enumerate(self.nodes):
            while self._delivered[i] < len(node.outbox):
                msg = node.outbox[self._delivered[i]]
                self._delivered[i] += 1
                progress = True
                for out in self._outbound(i, msg):
                    for j, other in enumerate(self.nodes):
                        if j == i:
                            continue
                        if (self._group is not None
                                and self._group[i] != self._group[j]):
                            self._held_cross.append((j, out))
                            self.held_partition += 1
                            continue
                        other.execute(out)
        return progress

    def advance_time(self, to: float) -> None:
        for i, node in enumerate(self.nodes):
            if self.specs[i].behavior != "silent":
                node.advance_time(to)

    def run_until(self, pred: Callable[[], bool], max_iters: int = 500,
                  time_step: float = 5.0) -> None:
        """Route until `pred()`; when the network quiesces without
        progress, advance the virtual clock (fires timeouts).  The
        clock resumes from the furthest node wheel, not 0 — a second
        run_until must not burn its budget re-advancing through time
        the first one already covered."""
        t = max((n.wheel.now for n in self.nodes), default=0.0)
        for _ in range(max_iters):
            if pred():
                return
            if not self.step_router():
                t += time_step
                self.advance_time(t)
                if not self.step_router() and pred():
                    return
        raise AssertionError("network did not reach the predicate")

    def honest_nodes(self) -> List[ConsensusExecutor]:
        return [n for i, n in enumerate(self.nodes)
                if self.specs[i].behavior != "silent"]

    def decided(self, height: int) -> bool:
        return all(height in n.decided for n in self.honest_nodes())

    def decisions(self, height: int) -> List[int]:
        return [n.decided[height].value for n in self.honest_nodes()]

    def equivocations(self) -> Dict[int, List[Equivocation]]:
        """Evidence collected per honest node index (all heights)."""
        out = {}
        for i, n in enumerate(self.nodes):
            if self.specs[i].behavior == "silent":
                continue
            ev = n.all_equivocations()
            if ev:
                out[i] = ev
        return out
