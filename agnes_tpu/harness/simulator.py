"""In-memory multi-node network simulation (host plane).

Drives N `ConsensusExecutor` nodes with a toy router: no sockets, no
threads, a virtual clock — multi-node consensus exercised exactly the
way the reference argues it should be (README.md:8-14: shrink the
object graph; timeouts are injected events).  Byzantine behaviors are
router policies + misbehaving signers layered on honest nodes:

  silent        drops every outbound message (crash fault)
  equivocator   additionally signs and sends a conflicting vote for a
                different value to every peer (double-sign; feeds the
                slashing surface, BASELINE config 5)
  nil_flood     replaces own votes with nil votes (liveness attack)

Network faults are router policies too: `partition(groups)` HOLDS
BACK every message crossing a group boundary (the consumer's gossip
layer retransmits once connectivity returns, so a partition delays
rather than destroys — README.md:46-49 leaves transport to the
consumer) and `heal()` delivers the held traffic.  A side without
+2/3 power cannot decide while split (nodes stall exactly where
Tendermint stalls: Prevote with no PolkaAny means no timeout), and
after heal the mixed nil/value prevotes drive PolkaAny ->
TimeoutPrevote -> PrecommitAny -> TimeoutPrecommit -> a fresh round
where the reunited quorum decides — the classic liveness-recovery
scenario, no cluster required.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field, replace as dc_replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from agnes_tpu.core.executor import (
    ConsensusExecutor,
    TimeoutConfig,
    WireProposal,
    WireTimeout,
    epoch_boundary_at,
)
from agnes_tpu.core.round_votes import Equivocation
from agnes_tpu.core.state_machine import TimeoutStep
from agnes_tpu.core.validators import Validator, ValidatorSet
from agnes_tpu.crypto import ed25519_ref as ed
from agnes_tpu.crypto import host_sign as _sign
from agnes_tpu.crypto.encoding import vote_signing_bytes
from agnes_tpu.types import Vote

BEHAVIORS = ("honest", "silent", "equivocator", "nil_flood")


@functools.lru_cache(maxsize=64)
def _cached_pubkey(seed: bytes) -> bytes:
    """Seeds here are tiny deterministic test vectors; the pure-Python
    keypair derivation costs ~ms each, and the model checker builds
    THOUSANDS of Networks (one per delta-debug probe) — memoize."""
    return ed.keypair(seed)[1]


@dataclass
class NodeSpec:
    behavior: str = "honest"
    power: int = 1


@dataclass
class Network:
    """N executors + router.  `specs[i].behavior` picks the fault model
    for node i (indices are into the address-sorted validator set)."""

    n: int = 4
    specs: Optional[Sequence[NodeSpec]] = None
    timeout_config: TimeoutConfig = field(default_factory=TimeoutConfig)
    get_value: Callable[[int], int] = lambda h: 100 + h
    verify_signatures: bool = True
    # model-checking knobs: skip Ed25519 entirely (the checker explores
    # consensus logic, not crypto — every vote still carries identity,
    # delivery stays index-trusted with verify_signatures=False), and
    # swap in a doctored executor class (the mutation-test surface)
    sign_messages: bool = True
    executor_cls: type = ConsensusExecutor
    # validator-set epoch schedule: {boundary_height: (power, ...)} in
    # ORIGINAL (pre-sort) index order, like `specs`; re-indexed to the
    # sorted set here and handed to every executor.  Powers below the
    # first boundary come from the specs (genesis) set.  Identities
    # and the proposer rotation are epoch-invariant (power 0 models
    # removal — the device plane's static-[V]-table contract,
    # device_driver.set_validators).
    epochs: Optional[Dict[int, Sequence[int]]] = None

    def __post_init__(self):
        assert self.sign_messages or not self.verify_signatures, \
            "unsigned networks cannot verify signatures"
        specs = list(self.specs or [NodeSpec() for _ in range(self.n)])
        assert len(specs) == self.n
        seeds = [bytes([i + 1]) * 32 for i in range(self.n)]
        keyed = sorted(zip([_cached_pubkey(s) for s in seeds], seeds,
                           range(self.n)))
        # specs are re-indexed to sorted order so specs[i] matches node i
        self.specs = [specs[orig] for _, _, orig in keyed]
        self.seeds = [seed for _, seed, _ in keyed]
        self.vset = ValidatorSet(
            [Validator(pk, self.specs[i].power)
             for i, (pk, _, _) in enumerate(keyed)])
        if self.epochs is not None:
            for h, pw in self.epochs.items():
                assert len(pw) == self.n, (h, pw)
            self.epochs = {
                int(h): tuple(pw[orig] for _, _, orig in keyed)
                for h, pw in sorted(self.epochs.items())}
        self.nodes: List[ConsensusExecutor] = [
            self.executor_cls(
                self.vset, index=i,
                seed=self.seeds[i] if self.sign_messages else None,
                get_value=self.get_value,
                timeout_config=self.timeout_config,
                verify_signatures=self.verify_signatures,
                epochs=self.epochs)
            for i in range(self.n)]
        self._delivered = [0] * self.n
        self.dropped = 0
        self._group: Optional[List[int]] = None   # node -> partition id
        self._held_cross: List = []               # (target, msg) queue
        self.held_partition = 0
        self._step_mode = False

    # -- validator-set epochs ------------------------------------------------

    def epoch_powers_at(self, height: int) -> Tuple[int, ...]:
        """The TRUE per-validator (sorted-index) power vector live at
        `height` under the epoch schedule — computed from the config,
        never through an executor, so the model checker's monitors can
        hold a doctored (stale-epoch) executor against the real set."""
        best = epoch_boundary_at(self.epochs, height)
        if best is None:
            return tuple(v.voting_power for v in self.vset)
        return self.epochs[best]

    def epoch_total_at(self, height: int) -> int:
        return sum(self.epoch_powers_at(height))

    # -- fault models -------------------------------------------------------

    def _outbound(self, i: int, msg) -> List[object]:
        """Apply node i's behavior to an outbound message."""
        b = self.specs[i].behavior
        if b == "silent":
            self.dropped += 1
            return []
        if b == "equivocator" and isinstance(msg, Vote) \
                and msg.value is not None:
            other = msg.value + 1_000_000
            sig = _sign(self.seeds[i], vote_signing_bytes(
                msg.height, msg.round, int(msg.typ), other)) \
                if self.sign_messages else None
            evil = dc_replace(msg, value=other, signature=sig)
            return [msg, evil]
        if b == "nil_flood" and isinstance(msg, Vote):
            sig = _sign(self.seeds[i], vote_signing_bytes(
                msg.height, msg.round, int(msg.typ), None)) \
                if self.sign_messages else None
            return [dc_replace(msg, value=None, signature=sig)]
        return [msg]

    # -- network faults -----------------------------------------------------

    def partition(self, *groups: Sequence[int]) -> None:
        """Split the network: messages between different groups are
        held back until `heal()`.  Every node must appear in exactly
        one group (sorted-set indices, like `specs`)."""
        gmap = [-1] * self.n
        for g, members in enumerate(groups):
            for i in members:
                assert gmap[i] == -1, f"node {i} in two groups"
                gmap[i] = g
        assert -1 not in gmap, "every node must be in a group"
        self._group = gmap

    def heal(self) -> None:
        """Restore connectivity and deliver the held cross-partition
        traffic (gossip retransmission)."""
        self._group = None
        held, self._held_cross = self._held_cross, []
        for j, msg in held:
            self.nodes[j].execute(msg)

    def partition_heal_drill(self, *groups: Sequence[int],
                             stall_iters: int = 30) -> int:
        """The canonical quorum-less-split liveness drill (shared by
        config 6 and the harness tests): partition into `groups` (none
        with +2/3 power), prove nobody decides the current height
        (only run_until's exhaustion counts as the stall — any other
        assert surfaces), heal, converge, and return the earliest
        decision round — asserted >= 1, since a real stall means the
        round-0 quorum never assembled."""
        h = min(n.height for n in self.nodes)
        self.partition(*groups)
        stalled = False
        try:
            self.run_until(lambda: self.decided(h), max_iters=stall_iters)
        except AssertionError as e:
            assert "predicate" in str(e), e
            stalled = True
        assert stalled and not any(h in n.decided for n in self.nodes)
        self.heal()
        self.run_until(lambda: self.decided(h))
        assert len(set(self.decisions(h))) == 1
        heal_round = min(n.decided[h].round for n in self.nodes)
        assert heal_round >= 1, heal_round
        return int(heal_round)

    # -- driving ------------------------------------------------------------

    def start(self) -> None:
        for node in self.nodes:
            node.start()

    def step_router(self) -> bool:
        """Deliver every pending outbox message; True if any moved."""
        assert not self._step_mode, \
            "step-mode networks are driven via mc_apply/run_schedule"
        progress = False
        for i, node in enumerate(self.nodes):
            while self._delivered[i] < len(node.outbox):
                msg = node.outbox[self._delivered[i]]
                self._delivered[i] += 1
                progress = True
                for out in self._outbound(i, msg):
                    for j, other in enumerate(self.nodes):
                        if j == i:
                            continue
                        if (self._group is not None
                                and self._group[i] != self._group[j]):
                            self._held_cross.append((j, out))
                            self.held_partition += 1
                            continue
                        other.execute(out)
        return progress

    def advance_time(self, to: float) -> None:
        for i, node in enumerate(self.nodes):
            if self.specs[i].behavior != "silent":
                node.advance_time(to)

    def run_until(self, pred: Callable[[], bool], max_iters: int = 500,
                  time_step: float = 5.0) -> None:
        """Route until `pred()`; when the network quiesces without
        progress, advance the virtual clock (fires timeouts).  The
        clock resumes from the furthest node wheel, not 0 — a second
        run_until must not burn its budget re-advancing through time
        the first one already covered."""
        t = max((n.wheel.now for n in self.nodes), default=0.0)
        for _ in range(max_iters):
            if pred():
                return
            if not self.step_router():
                t += time_step
                self.advance_time(t)
                if not self.step_router() and pred():
                    return
        raise AssertionError("network did not reach the predicate")

    def honest_nodes(self) -> List[ConsensusExecutor]:
        return [n for i, n in enumerate(self.nodes)
                if self.specs[i].behavior != "silent"]

    def decided(self, height: int) -> bool:
        return all(height in n.decided for n in self.honest_nodes())

    def decisions(self, height: int) -> List[int]:
        return [n.decided[height].value for n in self.honest_nodes()]

    def equivocations(self) -> Dict[int, List[Equivocation]]:
        """Evidence collected per honest node index (all heights)."""
        out = {}
        for i, n in enumerate(self.nodes):
            if self.specs[i].behavior == "silent":
                continue
            ev = n.all_equivocations()
            if ev:
                out[i] = ev
        return out

    # ======================================================================
    # Single-step scheduler mode (the model checker's surface,
    # analysis/modelcheck.py).
    #
    # In step mode the router stops auto-delivering: outbound traffic is
    # drained into per-(src, dst) FIFO channels (per-link FIFO order, the
    # standard asynchronous-network assumption) and an external scheduler
    # picks ONE atomic action at a time:
    #
    #   ("d", i, j)             deliver the head of channel i->j
    #   ("t", j, h, r, step)    fire node j's pending (h, r, step) timeout
    #                           (the asynchronous abstraction: a scheduled
    #                           timer may expire at ANY point, so deadline
    #                           values stop mattering)
    #   ("p",)                  split into the configured partition groups
    #   ("h",)                  heal the partition
    #   ("s", j)                node j falls asleep (TOB-SVD sleepy churn:
    #                           deliveries to it hold, its timers freeze;
    #                           bounded by the churn budget)
    #   ("w", j)                node j wakes (held traffic becomes
    #                           deliverable again, timers thaw, and the
    #                           node's on_wake hook fires)
    #
    # Every action is followed by a deterministic re-route of all outboxes,
    # so the post-action state is a pure function of (initial config,
    # action sequence) — the determinism `run_schedule` and the regression
    # corpus rely on.  A partition HOLDS cross-group channels (delivery
    # disabled, nothing dropped) exactly like the classic router's
    # held-until-heal policy, just at delivery rather than routing time.
    # ======================================================================

    def enable_step_mode(self, partition_groups=None, max_height: int = 1,
                         max_partition_cycles: int = 1,
                         churn_budget: int = 0,
                         churnable=None) -> None:
        """Switch the router into externally-scheduled single-step mode
        (before `start()`).  `partition_groups` is the one partition
        shape the ("p",) action applies, or None to disable it.
        `churn_budget` bounds the sleepy-churn alphabet the way the
        partition cycle cap bounds ("p",): at most that many ("s", j)
        sleep actions are ever enabled (wakes are free — each sleep
        admits at most one), so the explored schedule space stays
        finite.  `churnable` restricts which (sorted-index) nodes may
        sleep; None = every honest node (byzantine behaviors already
        own their fault models)."""
        assert not self._step_mode and not any(
            nd._started for nd in self.nodes)
        self._step_mode = True
        self._channels: Dict[Tuple[int, int], List[object]] = {}
        self._mc_partition_groups = None if partition_groups is None else \
            tuple(tuple(sorted(g)) for g in partition_groups)
        self._max_partition_cycles = max_partition_cycles
        self._partition_cycles = 0
        self._churn_budget = int(churn_budget)
        self._churn_used = 0
        self._asleep = [False] * self.n
        if churnable is None:
            self._churnable = frozenset(
                i for i in range(self.n)
                if self.specs[i].behavior == "honest")
        else:
            self._churnable = frozenset(int(i) for i in churnable)
            bad = [i for i in self._churnable if not 0 <= i < self.n]
            assert not bad, (
                f"churnable indices {bad} out of range for n={self.n}")
        # height -> set of value ids any node ever put in a WireProposal
        # (recorded pre-behavior, so a silent proposer's value counts):
        # the validity monitor's ground truth
        self._proposed: Dict[int, set] = {}
        # per target node: (validator, height, round, typ) -> values
        # delivered AND counted (vote height matched the node's live
        # height); two+ distinct values mean round_votes must have
        # surfaced equivocation evidence — the completeness monitor
        self._dv: List[Dict[Tuple[int, int, int, int], set]] = [
            {} for _ in range(self.n)]
        self._expected_ev: List[set] = [set() for _ in range(self.n)]
        for nd in self.nodes:
            nd.prefill_proposers(max_height + 2)

    def mc_start(self) -> None:
        """Start every node and route the initial burst (proposals,
        propose timeouts) into the channels."""
        assert self._step_mode
        self.start()
        self._mc_route()

    def _mc_route(self) -> None:
        """Drain every outbox through the behavior policies into the
        channels, then TRUNCATE the outboxes — in step mode history
        lives in the schedule, and clone cost must not grow with it."""
        for i, node in enumerate(self.nodes):
            for msg in node.outbox[self._delivered[i]:]:
                if isinstance(msg, WireProposal):
                    self._proposed.setdefault(msg.height,
                                              set()).add(msg.value)
                for out in self._outbound(i, msg):
                    for j in range(self.n):
                        if j != i:
                            self._channels.setdefault((i, j),
                                                      []).append(out)
            node.outbox.clear()
            self._delivered[i] = 0

    def _cross(self, i: int, j: int) -> bool:
        return (self._group is not None
                and self._group[i] != self._group[j])

    def mc_enabled(self, max_round: Optional[int] = None) -> List[tuple]:
        """Every action enabled in the current state, in canonical
        order (the determinism + partial-order-reduction key order).
        `max_round` prunes TIMEOUT_PRECOMMIT fires that would push a
        node past the round bound — rounds only ever advance off those
        fires, so this caps the explored round space."""
        assert self._step_mode
        acts: List[tuple] = []
        for (i, j), q in sorted(self._channels.items()):
            if q and not self._cross(i, j) and not self._asleep[j]:
                acts.append(("d", i, j))
        if self._group is not None:
            acts.append(("h",))
        if (self._mc_partition_groups is not None
                and self._group is None
                and self._partition_cycles < self._max_partition_cycles):
            acts.append(("p",))
        if self._churn_used < self._churn_budget:
            for j in sorted(self._churnable):
                if not self._asleep[j]:
                    acts.append(("s", j))
        for j in range(self.n):
            if self._asleep[j]:
                acts.append(("w", j))
        for j, node in enumerate(self.nodes):
            if self.specs[j].behavior == "silent" or self._asleep[j]:
                continue    # crash fault / asleep: the clock never fires
            seen = set()
            for t in node.wheel.pending():
                if not node.timer_live(t):
                    continue
                if (max_round is not None
                        and t.step == TimeoutStep.PRECOMMIT
                        and node.state.round >= max_round):
                    continue
                key = ("t", j, t.height, t.round, int(t.step))
                if key not in seen:
                    seen.add(key)
                    acts.append(key)
        return acts

    def mc_apply(self, act: tuple) -> bool:
        """Apply one action; False (state untouched) when it is not
        currently enabled — the tolerance delta-debug minimization
        leans on (a shrunk schedule stays runnable)."""
        assert self._step_mode
        kind = act[0]
        if kind == "d":
            _, i, j = act
            q = self._channels.get((i, j))
            if not q or self._cross(i, j) or self._asleep[j]:
                return False
            msg = q.pop(0)
            self._mc_track_delivery(j, msg)
            self.nodes[j].execute(msg)
        elif kind == "t":
            _, j, h, r, s = act
            t = WireTimeout(h, r, TimeoutStep(s))
            if self.specs[j].behavior == "silent" or self._asleep[j] \
                    or not self.nodes[j].wheel.remove(t):
                return False
            self.nodes[j].execute(t)
        elif kind == "s":
            _, j = act
            if (self._asleep[j] or j not in self._churnable
                    or self._churn_used >= self._churn_budget):
                return False
            self._asleep[j] = True
            self._churn_used += 1
        elif kind == "w":
            _, j = act
            if not self._asleep[j]:
                return False
            self._asleep[j] = False
            self.nodes[j].on_wake()
        elif kind == "p":
            if (self._group is not None
                    or self._mc_partition_groups is None
                    or self._partition_cycles
                    >= self._max_partition_cycles):
                return False
            self.partition(*self._mc_partition_groups)
            self._partition_cycles += 1
        elif kind == "h":
            if self._group is None:
                return False
            self._group = None      # channels become deliverable again
        else:
            raise ValueError(f"unknown action {act!r}")
        self._mc_route()
        return True

    def _mc_track_delivery(self, j: int, msg) -> None:
        if (isinstance(msg, Vote) and msg.validator is not None
                and msg.height == self.nodes[j].height):
            key = (msg.validator, msg.height, msg.round, int(msg.typ))
            vals = self._dv[j].setdefault(key, set())
            vals.add(-2 if msg.value is None else msg.value)
            if len(vals) > 1:
                self._expected_ev[j].add(key)

    # -- schedule serialization --------------------------------------------

    _ACT_NAMES = {"d": "deliver", "t": "timeout", "p": "partition",
                  "h": "heal", "s": "sleep", "w": "wake"}
    _ACT_CODES = {v: k for k, v in _ACT_NAMES.items()}

    @classmethod
    def action_to_json(cls, act: tuple) -> list:
        return [cls._ACT_NAMES[act[0]], *act[1:]]

    @classmethod
    def action_from_json(cls, a: list) -> tuple:
        return (cls._ACT_CODES[a[0]], *(int(x) for x in a[1:]))

    def run_schedule(self, actions: Sequence,
                     on_action: Optional[Callable] = None) -> List[bool]:
        """Deterministically replay a serialized schedule: start (if
        needed), then apply each action — JSON form or tuple form —
        skipping the not-currently-enabled ones.  `on_action(k, act,
        applied)` is the monitor hook.  Returns the applied flags."""
        assert self._step_mode
        if not any(nd._started for nd in self.nodes):
            self.mc_start()
        applied = []
        for k, a in enumerate(actions):
            act = self.action_from_json(a) if a[0] in self._ACT_CODES \
                else tuple(a)
            ok = self.mc_apply(act)
            applied.append(ok)
            if on_action is not None:
                on_action(k, act, ok)
        return applied

    # -- state-space branching ---------------------------------------------

    def mc_clone(self) -> "Network":
        """O(live state) copy of the whole stepped network — the
        exploration branch operation.  Shares: specs/seeds/vset/config
        (immutable after init), the partition scenario, and each
        node's frozen proposer memo (executor.clone)."""
        assert self._step_mode
        cls = type(self)
        net = cls.__new__(cls)
        net.n = self.n
        net.specs = self.specs
        net.timeout_config = self.timeout_config
        net.get_value = self.get_value
        net.verify_signatures = self.verify_signatures
        net.sign_messages = self.sign_messages
        net.executor_cls = self.executor_cls
        net.epochs = self.epochs     # post-init form: sorted-index, frozen
        net.seeds = self.seeds
        net.vset = self.vset
        net.nodes = [nd.clone() for nd in self.nodes]
        net._delivered = [0] * self.n
        net.dropped = self.dropped
        net._group = None if self._group is None else list(self._group)
        net._held_cross = []
        net.held_partition = 0
        net._step_mode = True
        net._channels = {k: list(q)
                         for k, q in self._channels.items() if q}
        net._mc_partition_groups = self._mc_partition_groups
        net._max_partition_cycles = self._max_partition_cycles
        net._partition_cycles = self._partition_cycles
        net._churn_budget = self._churn_budget
        net._churn_used = self._churn_used
        net._asleep = list(self._asleep)
        net._churnable = self._churnable
        net._proposed = {h: set(v) for h, v in self._proposed.items()}
        net._dv = [{k: set(v) for k, v in d.items()} for d in self._dv]
        net._expected_ev = [set(s) for s in self._expected_ev]
        return net

    @staticmethod
    def _canon_msg(m, perm: Optional[Sequence[int]] = None) -> tuple:
        if isinstance(m, Vote):
            v = m.validator
            if v is not None and perm is not None:
                v = perm[v]
            return (0, int(m.typ), m.round,
                    -2 if m.value is None else m.value,
                    -2 if v is None else v,
                    -2 if m.height is None else m.height)
        if isinstance(m, WireProposal):
            p = m.proposer if perm is None else perm[m.proposer]
            return (1, m.height, m.round, m.value, m.pol_round, p)
        raise TypeError(f"uncanonicalizable channel message {m!r}")

    def mc_canonical(self, perm: Optional[Sequence[int]] = None) -> tuple:
        """Canonical, int-only form of the global state: node states
        (executor.canonical_state), channel contents in per-link FIFO
        order, partition status, and the monitor trackers (included so
        two paths that agree on executor state but disagree on what
        the monitors should expect never merge).

        `perm` (old index -> new index) relabels the nodes — the
        symmetry-reduction surface (analysis/modelcheck.Symmetry):
        node i's state lands at position perm[i] with every embedded
        validator index rewritten, channel (i, j) becomes
        (perm[i], perm[j]).  Only sound for permutations that are true
        automorphisms of the network (equal behavior/power, proposer
        slots fixed, partition groups preserved) — the caller's
        contract, enforced by the group construction there."""
        assert self._step_mode
        if perm is None:
            nodes = tuple(nd.canonical_state() for nd in self.nodes)
            chans = tuple((i, j, tuple(self._canon_msg(m) for m in q))
                          for (i, j), q in sorted(self._channels.items())
                          if q)
            group = None if self._group is None else tuple(self._group)
            ev = tuple(tuple(sorted(s)) for s in self._expected_ev)
            asleep = tuple(self._asleep)
        else:
            by_pos = [None] * self.n
            for i, nd in enumerate(self.nodes):
                by_pos[perm[i]] = nd.canonical_state(perm)
            nodes = tuple(by_pos)
            chans = tuple(sorted(
                (perm[i], perm[j],
                 tuple(self._canon_msg(m, perm) for m in q))
                for (i, j), q in self._channels.items() if q))
            if self._group is None:
                group = None
            else:
                g = [0] * self.n
                for i in range(self.n):
                    g[perm[i]] = self._group[i]
                group = tuple(g)
            ev_pos: List[tuple] = [()] * self.n
            for i, s in enumerate(self._expected_ev):
                ev_pos[perm[i]] = tuple(sorted(
                    (perm[val], h, r, t) for (val, h, r, t) in s))
            ev = tuple(ev_pos)
            sl = [False] * self.n
            for i in range(self.n):
                sl[perm[i]] = self._asleep[i]
            asleep = tuple(sl)
        return (
            nodes,
            chans,
            group,
            self._partition_cycles,
            tuple(sorted((h, tuple(sorted(v)))
                         for h, v in self._proposed.items())),
            ev,
            asleep,
            self._churn_used,
        )

    def mc_digest(self, perm: Optional[Sequence[int]] = None) -> bytes:
        """16-byte stable digest of mc_canonical — the dedup key.
        The canonical form is pure ints/None/tuples with every
        container SORTED, serialized through `marshal` (a canonical
        byte encoding of exactly those types): no repr-format
        dependence, no dict-insertion-order sensitivity, no
        PYTHONHASHSEED sensitivity; negligible collision odds at
        corpus scale."""
        import hashlib
        import marshal

        return hashlib.blake2b(marshal.dumps(self.mc_canonical(perm), 2),
                               digest_size=16).digest()
