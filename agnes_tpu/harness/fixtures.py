"""Shared signed-traffic fixtures for benches, tests and the driver
compile check.

One canonical builder for "every validator signs its vote for one
(height, class, value)" traffic — the entry compile check
(__graft_entry__), the fused pipeline bench (bench.py) and the
differential suite (tests/test_step_signed.py) all consume THIS, so a
change to the canonical signing-message layout (vote_messages_np) or
the seed convention cannot silently diverge between the path that is
compile-checked, the path that is benched, and the path that is
tested."""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from agnes_tpu.bridge.ingest import vote_messages_np
from agnes_tpu.core import native


def deterministic_seeds(n_validators: int) -> List[bytes]:
    """The fixture keyspace: 32-byte seeds derived from the validator
    index (little-endian in the first 4 bytes)."""
    return [v.to_bytes(4, "little") + bytes(28)
            for v in range(n_validators)]


def validator_pubkeys(seeds: List[bytes]) -> np.ndarray:
    """[V, 32] uint8 table for the given seeds."""
    return np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                     for s in seeds])


def sign_class(seeds: List[bytes], height: int, typ: int, value: int,
               round_: int = 0,
               forge_validator: Optional[int] = None) -> np.ndarray:
    """[V, 64] uint8 signatures, one per validator, over the canonical
    vote message for (height, round, typ, value).  `forge_validator`
    signs with its neighbor's key instead (a forged lane that fails
    verification against the validator's own pubkey)."""
    V = len(seeds)
    msgs = vote_messages_np(np.full(V, height, np.int64),
                            np.full(V, round_, np.int64),
                            np.full(V, typ, np.int64),
                            np.full(V, value, np.int64))
    sigs = np.stack([np.frombuffer(
        native.sign(seeds[v], msgs[v].tobytes()), np.uint8)
        for v in range(V)])
    if forge_validator is not None:
        wrong = (forge_validator + 1) % V
        sigs[forge_validator] = np.frombuffer(
            native.sign(seeds[wrong],
                        msgs[forge_validator].tobytes()), np.uint8)
    return sigs


def full_mesh_cols(n_instances: int, n_validators: int, seeds: List[bytes],
                   height: int, typ: int, value: int, round_: int = 0,
                   forge_validator: Optional[int] = None) -> Tuple:
    """add_arrays/push column set for "every validator votes `value`
    in every instance", with real signatures: (instance, validator,
    height, round, typ, value, signatures[N, 64])."""
    I, V = n_instances, n_validators
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V
    sigs = sign_class(seeds, height, typ, value, round_=round_,
                      forge_validator=forge_validator)
    return (inst, val, np.full(n, height, np.int64),
            np.full(n, round_, np.int64), np.full(n, typ, np.int64),
            np.full(n, value, np.int64), sigs[val])
