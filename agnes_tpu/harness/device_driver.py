"""Closed-loop driver for the fused device consensus step.

Runs I independent consensus instances on device (SURVEY.md §2.7
"instance parallelism"), with the harness playing the network: it
fabricates the dense vote phases for the non-self validators according
to a schedule, routes each instance's OWN output votes back into the
next phase (self-votes take the same path as peer votes — the
re-entrant intent of consensus_executor.rs:36-41), and collects
decisions/timeouts off the message stream.

Schedules express the §4(c) scenarios without a cluster:

  honest                every validator votes the proposed value
  nil_round             round r gets only nil votes + timeouts (the
                        BASELINE config-3 multi-round path)
  equivocation(frac)    a fraction of validators double-sign: two
                        conflicting phases for the same (round, class)
                        (BASELINE config 5; detection = tally.equiv)
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from agnes_tpu.core.state_machine import MsgTag
from agnes_tpu.device import registry as _registry
from agnes_tpu.device.encoding import I32, DeviceState
from agnes_tpu.device.step import (  # noqa: F401 — registers entries
    DenseSignedPhases,
    ExtEvent,
    NULL_EVENT,
    VotePhase,
)
from agnes_tpu.device.tally import TallyConfig, TallyState
from agnes_tpu.types import NIL_ID, VoteType
from agnes_tpu.core.state_machine import EventTag

# Dispatch entries resolve through the registry at call time (ONE
# name -> jit table shared with ServePipeline.warmup, the jaxpr
# auditor and the retrace tripwire; tests registry.override() a name
# to stub device dispatch with zero compiles).  timed_entry: the
# FIRST dispatch of each entry records its wall as compile_ms_<entry>
# (trace+compile dominates that call — registry.compile_ms, ISSUE 8);
# once recorded it returns the raw jit, zero steady-state overhead.
_jit = _registry.timed_entry


@dataclass
class DriverStats:
    votes_ingested: int = 0
    steps: int = 0
    decisions_total: int = 0                  # across heights
    decided: Optional[np.ndarray] = None      # [I] bool
    decision_value: Optional[np.ndarray] = None
    decision_round: Optional[np.ndarray] = None


class DeviceDriver:
    """I instances x V validators on one device (or a mesh via the
    sharded step; see parallel/)."""

    def __init__(self, n_instances: int, n_validators: int,
                 n_rounds: int = 4, n_slots: int = 4,
                 proposer_is_self: bool = True,
                 advance_height: bool = False,
                 mesh=None, defer_collect: bool = False,
                 verify_chunk=None, hbm_budget_bytes: int = None,
                 audit: bool = False):
        """With `mesh` (flat data x val or hierarchical
        slice x data x val, parallel/mesh.py) the closed loop runs the
        shard_map-sharded step with every argument placed per the
        parallel/sharded.py layout — the multi-chip driver, same API.

        `defer_collect` exploits JAX async dispatch deliberately: the
        per-step message collection (`_collect`) forces a host sync on
        the step OUTPUTS, serializing host feed k+1 behind device step
        k.  Deferred, step() returns the moment dispatch is queued and
        the host overlaps densify/verify of the next phase with the
        running device step; `collect()` (or `block_until_ready`)
        drains the queued message batches when the stats are needed.

        `verify_chunk` bounds the fused signed verify's HBM peak
        (utils/budget.py; VERDICT r5 weak #3): None runs the
        historical single-batch verify; an int streams that many
        instance rows per microbatch through the dense path (lanes
        scale by V per row on the packed-lane path); "auto" sizes the
        tile from the device HBM budget (`hbm_budget_bytes` override,
        else memory_stats/16 GiB default) — on a mesh the plan is made
        on the per-device LOCAL shape.  Chunked and unchunked paths
        are bit-identical (tests/test_step_signed.py).

        `audit=True` (or a ready RetraceSentinel) installs the
        recompile tripwire (analysis/retrace.py) on every dispatch
        path: each call's (entry, shape-signature) is observed, the
        PR3 same-shapes-different-sharding double compile fails
        loudly immediately, and ServePipeline.warmup() arms the
        closed expected-trace set on top."""
        self.I, self.V = n_instances, n_validators
        if audit:
            from agnes_tpu.analysis.retrace import RetraceSentinel

            self.sentinel = (audit if isinstance(audit, RetraceSentinel)
                             else RetraceSentinel())
        else:
            self.sentinel = None
        self.advance_height = advance_height
        self.defer_collect = defer_collect
        self.verify_chunk = verify_chunk
        self.hbm_budget_bytes = hbm_budget_bytes
        self._verify_plans: dict = {}          # (Ps|None, I, V) -> plan
        self._deferred_msgs: list = []
        self._pending_rejects: list = []       # device-verify rejects
        self.rejected_signature_device = 0
        # the LAST step_async dispatch's deferred rejected-lane count
        # (a lazy device array; None for unsigned dispatches): the
        # serve pipeline snapshots it per in-flight batch so settle()
        # can gate dedup-cache insertion on "this dispatch's verify
        # rejected nothing" (serve/cache.py poisoning safety)
        self.last_step_rejects = None
        # optional utils/flightrec.FlightRecorder (ISSUE 8): when set
        # (VoteService wires its own through; bench arms a global one)
        # every step_async dispatch and retrace trip leaves a
        # structured event in the crash-surviving ring
        self.flightrec = None
        self.mesh = mesh
        if mesh is not None:
            from agnes_tpu.parallel import (
                make_sharded_step,
                make_sharded_step_seq,
                make_sharded_step_seq_signed,
            )
            self._sharded_step = make_sharded_step(
                mesh, advance_height=advance_height)
            self._sharded_step_seq = make_sharded_step_seq(
                mesh, advance_height=advance_height)
            # keyed by (verify_chunk, donate): the chunk is a static
            # trace parameter of the sharded signed step, donation a
            # property of the compiled executable
            self._sharded_signed_cache: dict = {}
            self._make_sharded_signed = make_sharded_step_seq_signed
            self._make_sharded_seq = make_sharded_step_seq
            self._sharded_honest: dict = {}   # heights -> jitted fn
        self.cfg = TallyConfig(n_validators=n_validators, n_rounds=n_rounds,
                               n_slots=n_slots)
        self.state = DeviceState.new((self.I,))
        self.tally = TallyState.new(self.I, self.cfg)
        if mesh is not None:
            # commit per the layout table NOW: otherwise the first
            # dispatch (uncommitted host arrays) and every later one
            # (committed sharded outputs) key two jit cache entries
            # for one graph — a double compile the serve warmup could
            # never cover (parallel/sharded.place_step_state)
            from agnes_tpu.parallel import place_step_state
            self.state, self.tally = place_step_state(
                mesh, self.state, self.tally)
        self.powers = jnp.ones((self.V,), I32)
        self.total = jnp.asarray(self.V, I32)
        # every instance's node proposes every round by default: the
        # self-proposal stage then exercises the full propose path
        self.proposer_flag = jnp.full((self.I, n_rounds),
                                      proposer_is_self, bool)
        self.propose_value = jnp.full((self.I,), 1, I32)
        self.stats = DriverStats(
            decided=np.zeros(self.I, bool),
            decision_value=np.full(self.I, NIL_ID, np.int32),
            decision_round=np.full(self.I, -1, np.int32))

    def set_validators(self, powers) -> None:
        """Validator-set epoch at a height boundary (reference
        validators.rs:38-46 intent, SURVEY §2.6 "re-uploaded on set
        changes"): re-upload the voting-power table the quorum math
        uses.  The device shape [V] is static — a power of 0 models a
        removed validator, an updated row a power change; additions
        beyond V need a re-built driver.  Call between heights (after
        the decision, before the next entry step): mid-height changes
        would mix quorum denominators within one tally window."""
        pw = np.asarray(powers)
        if pw.shape != (self.V,):
            raise ValueError(f"powers must be [{self.V}], got {pw.shape}")
        self.powers = jnp.asarray(pw, I32)
        self.total = jnp.asarray(int(pw.sum()), I32)

    def set_proposer_table(self, flags, rotation_period: int) -> None:
        """Install a round-varying proposer table.  The device indexes
        it round % R (device/step.py stage 5), which is exact only when
        R is a multiple of the rotation period (weighted round-robin
        repeats every total_power rounds) — enforced here because the
        device can't check a static shape against a traced total."""
        flags = jnp.asarray(flags, bool)
        const = bool(np.asarray(
            (flags == flags[:, :1]).all()))  # row-constant: any R valid
        if not const:
            assert flags.shape[1] % rotation_period == 0, (
                f"proposer table covers {flags.shape[1]} rounds; must be"
                f" a multiple of the rotation period {rotation_period}")
        self.proposer_flag = flags

    # -- verify chunk planning -----------------------------------------------

    def _local_shape(self):
        """(I, V) as ONE device sees them — the shapes the chunk plan
        must bound (under shard_map the verify runs on local cells)."""
        from agnes_tpu.utils.budget import mesh_local_shape

        return mesh_local_shape(self.mesh, self.I, self.V)

    def _resolve_dense_chunk(self, n_phases: int):
        """Instance rows per verify microbatch for the dense signed
        path, or None for the single-batch call.  "auto" consults the
        budget planner once per (Ps, local shape) and falls through to
        None when the whole batch already fits (identical trace cache
        key to the legacy path — no recompile)."""
        if self.verify_chunk is None:
            return None
        local_i, local_v = self._local_shape()
        if self.verify_chunk != "auto":
            c = int(self.verify_chunk)
            # a tile >= the (local) instance count is the unchunked
            # call: normalize to None so it reuses the SAME jit cache
            # entry (a distinct static arg would recompile an
            # identical graph — minutes per trace with the persistent
            # cache deliberately off, utils/compile_cache.py).
            # <= 0 means "no chunking" too (matches the kernel's falsy
            # handling on the lane path; 0 rows is not a tiling)
            return None if c <= 0 or c >= local_i else c
        from agnes_tpu.utils.budget import plan_dense_verify

        key = (n_phases, local_i, local_v)
        if key not in self._verify_plans:
            self._verify_plans[key] = plan_dense_verify(
                n_phases, local_i, local_v,
                hbm_bytes=self.hbm_budget_bytes)
        plan = self._verify_plans[key]
        return plan.tile if plan.chunked else None

    def _resolve_lane_chunk(self, n_lanes: int):
        """Lanes per verify microbatch for the packed-lane signed path
        (single-device), or None."""
        if self.verify_chunk is None or n_lanes == 0:
            return None
        if self.verify_chunk != "auto":
            # driver-level knob is in instance rows; a packed lane is
            # one (instance, validator) cell of one phase.  A chunk
            # covering the whole batch IS the unchunked call — and
            # <= 0 rows means "no chunking" — normalize both to None
            # to share the unchunked jit cache entry.
            rows = int(self.verify_chunk)
            if rows <= 0:
                return None
            c = rows * self.V
            return None if c >= n_lanes else c
        from agnes_tpu.utils.budget import plan_lane_verify

        key = (None, n_lanes, self.V)
        if key not in self._verify_plans:
            self._verify_plans[key] = plan_lane_verify(
                n_lanes, hbm_bytes=self.hbm_budget_bytes)
        plan = self._verify_plans[key]
        return plan.tile if plan.chunked else None

    # -- retrace tripwire ----------------------------------------------------

    def _observe(self, entry: str, args, statics=()) -> None:
        """Feed one dispatch's (entry, shape-signature) to the
        sentinel when auditing (analysis/retrace.py) — unarmed it
        learns the expected set (and still catches sharding-variant
        double compiles); armed, any signature outside the set fails
        loudly and bumps `retrace_unexpected`."""
        if self.sentinel is not None:
            from agnes_tpu.analysis.retrace import signature

            try:
                self.sentinel.observe(entry, signature(args, statics))
            except Exception:
                # an armed-set trip is ALSO a flight-recorder event:
                # the heartbeat trail must date the unexpected trace
                # even if the raising dispatch takes the process down
                if self.flightrec is not None:
                    self.flightrec.event("retrace_unexpected",
                                         entry=entry)
                raise

    # -- phase builders ------------------------------------------------------

    def empty_phase(self) -> VotePhase:
        return VotePhase(
            round=jnp.zeros(self.I, I32),
            typ=jnp.zeros(self.I, I32),
            slots=jnp.full((self.I, self.V), NIL_ID, I32),
            mask=jnp.zeros((self.I, self.V), bool),
            height=self.state.height)

    def phase(self, round: int, typ: VoteType, slot: int,
              frac: float = 1.0, offset: int = 0) -> VotePhase:
        """Validators [offset, offset + frac*V) vote `slot` (NIL_ID for
        nil) in `round` for class `typ` — same for every instance."""
        k = int(round_half_up(frac * self.V))
        idx = jnp.arange(self.V)
        voters = (idx >= offset) & (idx < offset + k)
        return VotePhase(
            round=jnp.full(self.I, round, I32),
            typ=jnp.full(self.I, int(typ), I32),
            slots=jnp.where(voters[None, :], slot, NIL_ID).astype(I32)
            * jnp.ones((self.I, 1), I32),
            mask=jnp.broadcast_to(voters[None, :], (self.I, self.V)),
            height=self.state.height)

    def ext(self, tag: int = NULL_EVENT, round: int = 0, value: int = NIL_ID,
            pol_round: int = -1) -> ExtEvent:
        return ExtEvent(
            tag=jnp.full(self.I, tag, I32),
            round=jnp.full(self.I, round, I32),
            value=jnp.full(self.I, value, I32),
            pol_round=jnp.full(self.I, pol_round, I32))

    # -- stepping ------------------------------------------------------------

    def step(self, ext: Optional[ExtEvent] = None,
             phase: Optional[VotePhase] = None) -> "jnp.ndarray":
        """One fused step; returns the stacked DeviceMessage batch."""
        ext = ext if ext is not None else self.ext()
        phase = phase if phase is not None else self.empty_phase()
        if self.mesh is not None:
            from agnes_tpu.parallel import shard_step_args
            args = shard_step_args(
                self.mesh, self.state, self.tally, ext, phase,
                self.powers, self.total, self.proposer_flag,
                self.propose_value)
            self._observe("sharded_step", args,
                          (self.advance_height,))
            out = self._sharded_step(*args)
        else:
            args = (self.state, self.tally, ext, phase, self.powers,
                    self.total, self.proposer_flag, self.propose_value)
            self._observe("consensus_step", args,
                          (self.advance_height,))
            out = _jit("consensus_step")(
                *args, advance_height=self.advance_height)
        self.state, self.tally = out.state, out.tally
        self.stats.steps += 1
        self.stats.votes_ingested += int(np.asarray(phase.mask).sum())
        if self.defer_collect:
            self._deferred_msgs.append(out.msgs)
        else:
            self._collect(out.msgs)
        return out.msgs

    def step_seq(self, phases, exts=None) -> "jnp.ndarray":
        """P fused steps in ONE device dispatch (consensus_step_seq):
        `phases` is a list of VotePhase (e.g. every dedup layer of a
        built vote class), `exts` an optional matching list.  Identical
        semantics to P step() calls — tests/test_step_seq.py holds the
        two paths equal leaf-for-leaf — at 1/P the dispatch overhead."""
        P = len(phases)
        exts = exts if exts is not None else [self.ext()] * P
        phases_st = jax.tree.map(lambda *xs: jnp.stack(xs), *phases)
        exts_st = jax.tree.map(lambda *xs: jnp.stack(xs), *exts)
        args = (self.state, self.tally, exts_st, phases_st, self.powers,
                self.total, self.proposer_flag, self.propose_value)
        if self.mesh is not None:
            self._observe("sharded_step_seq", args,
                          (self.advance_height, False))
            out = self._sharded_step_seq(*args)
        else:
            self._observe("consensus_step_seq", args,
                          (self.advance_height,))
            out = _jit("consensus_step_seq")(
                *args, advance_height=self.advance_height)
        self.state, self.tally = out.state, out.tally
        self.stats.steps += P
        self.stats.votes_ingested += int(
            sum(int(np.asarray(p.mask).sum()) for p in phases))
        if self.defer_collect:
            self._deferred_msgs.append(out.msgs)
        else:
            self._collect(out.msgs)
        return out.msgs

    def step_seq_signed(self, phases, lanes, exts=None) -> "jnp.ndarray":
        """step_seq with signature verification FUSED into the same
        dispatch (device/step.py consensus_step_seq_signed): `lanes`
        (SignedLanes, from VoteBatcher.build_phases_device) carries the
        packed Ed25519 inputs whose verdicts mask the phases ON
        DEVICE.  Nothing here fetches from the device, so consecutive
        signed sequences queue back-to-back under defer_collect — the
        pipelined flagship path.  Rejected-lane counts accumulate
        lazily; `rejected_signature_device` after collect()/
        block_until_ready() has the total.  The packed-lane layout is
        single-device; ON A MESH use step_seq_signed_dense (the dense
        layout shards with the phases)."""
        if self.mesh is not None:
            raise NotImplementedError(
                "the packed-lane signed step is single-device; on a "
                "mesh use step_seq_signed_dense (+ VoteBatcher."
                "build_phases_device_dense), which shards the lanes "
                "with the phases")
        phases_st, exts_st, P = self._stack_seq(phases, exts)
        chunk = self._resolve_lane_chunk(int(lanes.pub.shape[0]))
        args = (self.state, self.tally, exts_st, phases_st, lanes,
                self.powers, self.total, self.proposer_flag,
                self.propose_value)
        self._observe("consensus_step_seq_signed", args,
                      (self.advance_height, chunk))
        out = _jit("consensus_step_seq_signed")(
            *args, advance_height=self.advance_height,
            verify_chunk=chunk)
        # real lanes only (padding excluded); device rejects are
        # subtracted at settle time so the counter converges to
        # ACCEPTED votes — the same meaning the host-verified paths
        # give it (their phases are post-filter)
        return self._finish_signed(out, P,
                                   int(np.asarray(lanes.real).sum()))

    def step_async(self, phases, lanes=None, exts=None,
                   donate: bool = True,
                   tick: Optional[int] = None) -> "jnp.ndarray":
        """The serve plane's dispatch entry: queue a fused step
        sequence and return the moment dispatch is queued — message
        collection is ALWAYS deferred (regardless of `defer_collect`;
        call collect()/block_until_ready() when the stats are needed),
        so the host immediately overlaps densify of batch k+1 with the
        device's execution of batch k (serve/pipeline.py's double
        buffer).

        `lanes` selects the layout: SignedLanes (packed-lane,
        build_phases_device — single-device only) runs the fused
        signed step; DenseSignedPhases (build_phases_device_dense)
        runs the dense fused signed step, which is also the layout
        that dispatches ON A MESH (make_sharded_step_seq_signed: each
        device verifies its local cells, zero added collectives);
        None runs the plain sequence (host-verified or unsigned
        phases), sharded when the driver has a mesh.  `donate` hands
        the state/tally buffers to XLA for in-place update — the
        steady-state serve configuration; pass False to share the jit
        cache (and buffer semantics) with the non-donating step_seq*
        entries, e.g. for lockstep differentials against the offline
        path.

        NOTE: inputs must not alias the driver's live state when
        donating — build entry phases from HOST heights (the serve
        pipeline does), not from `empty_phase()` whose height leaf IS
        `state.height`; an aliased donation degrades to a copy (jax
        warns) instead of corrupting, but the point of this entry is
        to avoid that copy.

        `tick` is the serve plane's monotonic tick id (ISSUE 8): it
        identifies this dispatch in the flight-recorder trail (and,
        via the pipeline's tracer flow events, in chrome-trace), so a
        postmortem can name the exact tick a wedged run died in."""
        phases_st, exts_st, P = self._stack_seq(phases, exts)
        state, tally = self.state, self.tally
        if donate:
            # DeviceState.new/TallyState.new deliberately reuse one
            # zeros/fill array across fields — harmless normally, but
            # XLA refuses to donate one buffer twice (`f(donate(a),
            # donate(a))`), so the FIRST donated dispatch of a fresh
            # driver must break those aliases (step outputs are
            # distinct buffers, so later dispatches copy nothing)
            state, tally = _dealias_buffers(state, tally)
        n_rejected = None
        if isinstance(lanes, DenseSignedPhases):
            entry_name = ("sharded_step_seq_signed" if self.mesh
                          is not None else
                          "consensus_step_seq_signed_dense_donated"
                          if donate else
                          "consensus_step_seq_signed_dense")
            fn = self._dense_dispatch_fn(int(lanes.sig.shape[0]),
                                         donate=donate)
            out = fn(state, tally, exts_st, phases_st, lanes)
            n_votes = int(sum(int(np.asarray(p.mask).sum())  # lint: allow (host-built phases)
                              for p in phases))
            n_rejected = out.n_rejected
        elif lanes is not None:
            if self.mesh is not None:
                raise NotImplementedError(
                    "the packed-lane signed layout is single-device; "
                    "on a mesh feed step_async DenseSignedPhases "
                    "(VoteBatcher.build_phases_device_dense)")
            name = entry_name = (
                "consensus_step_seq_signed_donated" if donate
                else "consensus_step_seq_signed")
            chunk = self._resolve_lane_chunk(int(lanes.pub.shape[0]))
            args = (state, tally, exts_st, phases_st, lanes,
                    self.powers, self.total, self.proposer_flag,
                    self.propose_value)
            self._observe(name, args, (self.advance_height, chunk))
            out = _jit(name)(*args, advance_height=self.advance_height,
                             verify_chunk=chunk)
            n_votes = int(np.asarray(lanes.real).sum())  # lint: allow (host-built lanes)
            n_rejected = out.n_rejected
        else:
            args = (state, tally, exts_st, phases_st, self.powers,
                    self.total, self.proposer_flag, self.propose_value)
            if self.mesh is not None:
                entry_name = "sharded_step_seq"
                self._observe("sharded_step_seq", args,
                              (self.advance_height, donate))
                fn = self._make_sharded_seq(
                    self.mesh, advance_height=self.advance_height,
                    donate=donate)
                fn = partial(_registry.timed_call,
                             "sharded_step_seq", fn)
            else:
                name = entry_name = (
                    "consensus_step_seq_donated" if donate
                    else "consensus_step_seq")
                self._observe(name, args, (self.advance_height,))
                fn = partial(_jit(name),
                             advance_height=self.advance_height)
            out = fn(*args)
            n_votes = int(sum(int(np.asarray(p.mask).sum())  # lint: allow (host-built phases)
                              for p in phases))
        self.last_step_rejects = n_rejected
        if self.flightrec is not None:
            self.flightrec.event("dispatch", tick=tick, votes=n_votes,
                                 entry=entry_name)
        return self._finish_step(out, P, n_votes, n_rejected,
                                 force_defer=True)

    def _stack_seq(self, phases, exts):
        P = len(phases)
        exts = exts if exts is not None else [self.ext()] * P
        phases_st = jax.tree.map(lambda *xs: jnp.stack(xs), *phases)
        exts_st = jax.tree.map(lambda *xs: jnp.stack(xs), *exts)
        return phases_st, exts_st, P

    def _finish_signed(self, out, P: int, n_votes: int):
        """Shared tail of the signed step variants: stats, deferred
        reject settlement, message collection."""
        return self._finish_step(out, P, n_votes, out.n_rejected)

    def _finish_step(self, out, P: int, n_votes: int, n_rejected=None,
                     force_defer: bool = False):
        """THE bookkeeping tail of every step-sequence dispatch:
        state/tally swap, stats, deferred reject settlement, message
        collection (`force_defer` = step_async's always-deferred
        contract, independent of `defer_collect`)."""
        self.state, self.tally = out.state, out.tally
        self.stats.steps += P
        self.stats.votes_ingested += n_votes
        if n_rejected is not None:
            self._pending_rejects.append(n_rejected)
        if self.defer_collect or force_defer:
            self._deferred_msgs.append(out.msgs)
        else:
            self._collect(out.msgs)
            self._settle_rejects()
        return out.msgs

    def _settle_rejects(self) -> None:
        """Fold deferred device-verify reject counts into the stats
        (forces a device fetch per pending count — call from collect/
        block_until_ready, never mid-pipeline).  Counts are scalars
        from the lane path or [I] from the dense/sharded path."""
        rejects, self._pending_rejects = self._pending_rejects, []
        for r in rejects:
            n = int(np.asarray(r).sum())
            self.rejected_signature_device += n
            self.stats.votes_ingested -= n

    def _dense_dispatch_fn(self, n_dense_phases: int, donate: bool):
        """Resolve the dense fused-signed entry for a Ps-class dense
        batch — sharded on a mesh, jitted single-device otherwise;
        donated or not — as f(state, tally, exts_st, phases_st, dense).
        The serve pipeline's dense dispatch and warmup go through this
        too, so they hit the exact executable the offline path uses."""
        chunk = self._resolve_dense_chunk(n_dense_phases)
        if self.mesh is not None:
            key = (chunk, bool(donate))
            if key not in self._sharded_signed_cache:
                self._sharded_signed_cache[key] = \
                    self._make_sharded_signed(
                        self.mesh, advance_height=self.advance_height,
                        verify_chunk=chunk, donate=donate)
            fn = self._sharded_signed_cache[key]

            def dispatch(st, ta, ex, ph, dn):
                args = (st, ta, ex, ph, dn, self.powers, self.total,
                        self.proposer_flag, self.propose_value)
                self._observe("sharded_step_seq_signed", args,
                              (self.advance_height, chunk, donate))
                # jit reshards the host-built arrays per the in_specs;
                # timed_call records the first dispatch's compile wall
                return _registry.timed_call("sharded_step_seq_signed",
                                            fn, *args)

            return dispatch
        name = ("consensus_step_seq_signed_dense_donated" if donate
                else "consensus_step_seq_signed_dense")

        def dispatch(st, ta, ex, ph, dn):
            args = (st, ta, ex, ph, dn, self.powers, self.total,
                    self.proposer_flag, self.propose_value)
            self._observe(name, args, (self.advance_height, chunk))
            return _jit(name)(
                *args, advance_height=self.advance_height,
                verify_chunk=chunk)

        return dispatch

    def step_seq_signed_dense(self, phases, dense, exts=None
                              ) -> "jnp.ndarray":
        """Fused verify+step with DENSE per-cell lanes
        (consensus_step_seq_signed_dense) — the variant that also runs
        on a MESH (make_sharded_step_seq_signed: each device verifies
        its local (instance, validator) cells; no added collectives).
        `dense` must align with the TAIL len(dense.sig) phases of
        `phases` (leading phases, e.g. the entry phase, carry no
        lanes).  Build both with VoteBatcher.build_phases_device_dense
        and prepend driver-side phases as needed."""
        phases_st, exts_st, P = self._stack_seq(phases, exts)
        fn = self._dense_dispatch_fn(int(dense.sig.shape[0]),
                                     donate=False)
        out = fn(self.state, self.tally, exts_st, phases_st, dense)
        return self._finish_signed(
            out, P, int(sum(int(np.asarray(p.mask).sum())
                            for p in phases)))

    def _collect(self, msgs) -> None:
        """Fold one message batch into the stats.  Leaves are
        [stages, I] from step(), or [P, ..., stages, I] from step_seq/
        run_heights_fused — the leading sequence axes flatten into the
        stage axis (step order is preserved, so first-decision latching
        is unchanged); decisions_total counts every DECISION message,
        which with height advance is one per (instance, height)."""
        tags_nd = np.asarray(msgs.tag)
        tags = tags_nd.reshape(-1, self.I)
        dec = tags == int(MsgTag.DECISION)
        # one-decision-per-step-per-instance is an invariant (an
        # instance commits at most once per step; with height advance
        # the reset happens between steps) — assert it so dec.sum()
        # counting can never silently inflate (ADVICE r4)
        assert (dec.reshape(-1, tags_nd.shape[-2], self.I)
                .sum(axis=1) <= 1).all(), \
            "multiple DECISION stages for one instance in one step"
        self.stats.decisions_total += int(dec.sum())
        if dec.any():
            decided_now = dec.any(axis=0)
            stage = dec.argmax(0)
            rows = np.arange(self.I)
            val = np.asarray(msgs.value).reshape(-1, self.I)[stage, rows]
            rnd = np.asarray(msgs.round).reshape(-1, self.I)[stage, rows]
            new = decided_now & ~self.stats.decided
            self.stats.decision_value[new] = val[new]
            self.stats.decision_round[new] = rnd[new]
            self.stats.decided |= decided_now

    # -- canned scenarios ----------------------------------------------------

    def run_honest_round(self, round: int = 0, slot: int = 1) -> None:
        """Drive one honest round to decision.  With proposer_is_self the
        step's stages 5-6 produce the proposal + own prevote; the full
        phases then deliver every validator's matching votes (the self
        vote rides the dense phase like any peer vote)."""
        self.step()  # round entry + self proposal -> instances prevote
        self.step(phase=self.phase(round, VoteType.PREVOTE, slot))
        self.step(phase=self.phase(round, VoteType.PRECOMMIT, slot))

    def run_nil_round(self, round: int = 0) -> None:
        """Round that times out (build with proposer_is_self=False: the
        instance waits for a proposal that never comes): propose timeout
        -> nil prevotes -> nil precommits -> precommit timeout -> the
        instance moves to round + 1 (the config-3 multi-round path)."""
        self.step()  # round entry: NEW_ROUND -> schedules timeout propose
        self.step(ext=self.ext(int(EventTag.TIMEOUT_PROPOSE), round))
        self.step(phase=self.phase(round, VoteType.PREVOTE, NIL_ID))
        self.step(phase=self.phase(round, VoteType.PRECOMMIT, NIL_ID))
        self.step(ext=self.ext(int(EventTag.TIMEOUT_PRECOMMIT), round))

    def run_proposed_round(self, round: int = 0, slot: int = 1,
                           pol_round: int = -1) -> None:
        """Non-proposer instances receive a complete proposal and the
        full honest vote phases for it."""
        self.step()  # round entry (NEW_ROUND when not proposer)
        self.step(ext=self.ext(int(EventTag.PROPOSAL), round, slot,
                               pol_round))
        self.step(phase=self.phase(round, VoteType.PREVOTE, slot))
        self.step(phase=self.phase(round, VoteType.PRECOMMIT, slot))

    def run_heights(self, n_heights: int, slot: int = 1) -> None:
        """Drive every instance through `n_heights` consecutive honest
        heights (requires advance_height=True: the device's stage-8
        reset installs State::new(h+1) after each decision, the
        reference's consumer contract README.md:43-44)."""
        assert self.advance_height, "construct with advance_height=True"
        for _ in range(n_heights):
            self.run_honest_round(0, slot)

    def run_heights_fused(self, n_heights: int, slot: int = 1,
                          frac: float = 1.0) -> None:
        """run_heights in ONE device dispatch (honest_heights_jit: a
        lax.scan over heights whose phases take round/height from the
        carried state).  Equivalent to run_heights — held equal by
        tests/test_step_seq.py — with 1/(3H) the dispatch overhead;
        this is what lets config-4-shape multi-height throughput run
        at device speed on the tunneled TPU."""
        assert self.advance_height, "construct with advance_height=True"
        voters = jnp.arange(self.V) < round_half_up(frac * self.V)
        slots = jnp.where(voters[None, :], slot, -1).astype(I32) \
            * jnp.ones((self.I, 1), I32)
        mask = jnp.broadcast_to(voters[None, :], (self.I, self.V))
        args = (self.state, self.tally, slots, mask, self.powers,
                self.total, self.proposer_flag, self.propose_value)
        if self.mesh is not None:
            if n_heights not in self._sharded_honest:
                from agnes_tpu.parallel import make_sharded_honest_heights
                self._sharded_honest[n_heights] = \
                    make_sharded_honest_heights(self.mesh, n_heights)
            self._observe("sharded_honest_heights", args, (n_heights,))
            out = self._sharded_honest[n_heights](*args)
        else:
            self._observe("honest_heights", args, (n_heights,))
            out = _jit("honest_heights")(*args, heights=n_heights)
        self.state, self.tally = out.state, out.tally
        self.stats.steps += 3 * n_heights
        self.stats.votes_ingested += 2 * n_heights * int(
            np.asarray(mask).sum())
        if self.defer_collect:
            self._deferred_msgs.append(out.msgs)
        else:
            self._collect(out.msgs)

    def run_equivocation_phase(self, round: int, typ: VoteType,
                               slot_a: int, slot_b: int,
                               frac: float = 1.0) -> int:
        """A fraction of validators vote slot_a then conflictingly
        slot_b for the same (round, class).  Returns expected number of
        newly flagged equivocators per instance."""
        self.step(phase=self.phase(round, typ, slot_a, frac))
        self.step(phase=self.phase(round, typ, slot_b, frac))
        return int(round_half_up(frac * self.V))

    def equivocators_detected(self) -> np.ndarray:
        """[I] count of flagged validators per instance."""
        return np.asarray(self.tally.equiv).sum(axis=1)

    def all_decided(self, value: Optional[int] = None) -> bool:
        self.collect()               # stats must see deferred batches
        if not bool(self.stats.decided.all()):
            return False
        if value is not None:
            return bool((self.stats.decision_value == value).all())
        return True

    def state_copies(self):
        """Throwaway (state, tally) copies for warmup dispatches —
        outputs of a donated warmup must not eat the live buffers.
        A hook (not an inline tree.map) because the pod driver
        (distributed/driver.py) must copy through a jitted pod
        computation: eager per-leaf copies of multi-host arrays are
        unsupported eager ops."""
        import jax

        return (jax.tree.map(lambda x: x.copy(), self.state),
                jax.tree.map(lambda x: x.copy(), self.tally))

    def collect(self) -> None:
        """Drain deferred message batches into the stats (in step
        order — decision latching is order-sensitive), and settle any
        device-verify rejected-lane counts."""
        msgs, self._deferred_msgs = self._deferred_msgs, []
        for m in msgs:
            self._collect(m)
        self._settle_rejects()

    def block_until_ready(self):
        self.collect()
        jax.block_until_ready(self.state)
        return self


def _dealias_buffers(*trees):
    """Copy any pytree leaf whose device buffer is already used by an
    earlier leaf (across ALL given trees), so the whole set can be
    donated in one dispatch.  Leaves that alias are the tiny [I]
    state fields, so the occasional copy is nanoseconds."""
    seen = set()
    out = []
    for t in trees:
        leaves, treedef = jax.tree.flatten(t)
        fixed = []
        for x in leaves:
            try:
                key = x.unsafe_buffer_pointer()
            except Exception:  # noqa: BLE001 — fall back to identity
                key = id(x)
            if key in seen:
                x = x.copy()
            else:
                seen.add(key)
            fixed.append(x)
        out.append(jax.tree.unflatten(treedef, fixed))
    return out


def round_half_up(x: float) -> int:
    return int(np.floor(x + 0.5))
