"""agnes_lint CLI: the static invariant analyzer's entry point.

Runs the four analysis passes over the repo — all CPU, ZERO XLA
compiles (abstract tracing only) — and exits non-zero on any finding:

  jaxpr    abstract-trace every registered jit entry: donation
           honored, collective census + verify_chunk invariance, no
           host callbacks, dtype policy
  retrace  static warmup-coverage proof: every signed shape the serve
           plane can dispatch is covered by the warmup plan (the
           no-live-compile invariant; the runtime half is
           DeviceDriver(audit=True))
  locks    serve/threaded.py two-lock discipline + no bare
           .acquire()/.release() anywhere in serve//utils.metrics
  lint     serve hot-path host syncs, unregistered import-time jits,
           unhashable static-argnum candidates
  pallas   per-backend lowering-support audit (ISSUE 18): every
           registered Pallas-bearing entry (a `pallas_call` in its
           defining module, or the `pallas_field` kernel-lane static)
           must record which backends it lowers on
           (EntrySpec.pallas_backends), and claims must stay inside
           registry.PALLAS_BACKENDS — the GPU lane inherits a
           known-good kernel set instead of discovering lowering
           failures at dispatch
  census   hot-entry traced-op-count regression gate (ISSUE 13):
           totals at the audit shape vs tests/baselines/
           jaxpr_census.json, ±10%; `--update-baseline` rewrites the
           file after a deliberate graph change.  Runs LAST so a
           `--pass all` reuses the jaxpr pass's traces (zero extra
           tracing); standalone it traces only the baselined entries

Invoked as `scripts/agnes_lint.py` (the repo shim) or the installed
`agnes-lint` console script (pyproject [project.scripts]).  The CLI
logic lives HERE, inside the package, so the entry point resolves
without shipping a top-level `scripts` package; the backend env setup
(CPU platform, virtual devices, single-threaded codegen) runs at the
top of `main()` — before any jax import in this process, and inherited
by the spawned audit workers.

The full `--pass all` budget is < 120s on the 2-CPU CI box (the heavy
traces are the Ed25519-bearing entries at ~15-20s of tracing each);
ci.sh bounds it with an enclosing timeout regardless.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

PASSES = ("jaxpr", "retrace", "locks", "lint", "pallas", "census")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def setup_backend_env() -> None:
    """Backend config BEFORE jax import (same dance as
    tests/conftest.py): this environment's sitecustomize registers an
    axon TPU backend; the analyzer must trace on CPU, with enough
    virtual devices for a (data x val) audit mesh, and without the
    racy parallel codegen.  Must run before anything imports jax —
    call it first in main(); the repo shim also runs it at import."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    if "xla_cpu_parallel_codegen_split_count" not in flags:
        flags = (flags + " --xla_cpu_parallel_codegen_split_count=1").strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"


def _jaxpr_worker(task):
    """One audit shard in its own interpreter (spawned): tracing is
    pure-python and the heavy Ed25519 graphs are independent, so the
    shards parallelize across cores; fresh processes also sidestep
    this box's XLA:CPU after-many-operations fragility."""
    names, coverage, union = task
    import jax

    jax.config.update("jax_platforms", "cpu")
    from agnes_tpu.utils.compile_cache import disable_persistent_cache

    disable_persistent_cache()
    import dataclasses

    from agnes_tpu.analysis import jaxpr_audit
    from agnes_tpu.utils.metrics import ANALYSIS_ENTRIES_AUDITED, Metrics

    m = Metrics()
    rep = jaxpr_audit.audit(names=names, metrics=m, coverage=coverage)
    if coverage and union is not None:
        # the shard split itself must cover the full audit plan — a
        # registered entry in no shard would silently never be traced
        rep.findings.extend(
            jaxpr_audit.shard_coverage_findings(union))
    return ([dataclasses.asdict(f) for f in rep.findings],
            [dataclasses.asdict(e) for e in rep.entries],
            rep.skipped,
            m.counters.get(ANALYSIS_ENTRIES_AUDITED, 0))


#: audit shards balanced by trace weight: the chunk-invariance pair
#: (sharded signed, traced twice) in one, the two single-device
#: Ed25519-bearing twins in another, the BLS aggregation MSM (one
#: ~45s trace) and the BLS pairing tower (ISSUE 13, ~25s of rolled
#: Miller/final-exp bodies) in their own, everything cheap in the
#: last
_JAXPR_SHARDS = (
    ["sharded_step_seq_signed"],
    ["consensus_step_seq_signed_donated",
     "consensus_step_seq_signed_dense_donated"],
    ["bls_aggregate"],
    ["bls_pairing_product"],
    # the kernel-lane aliases (pallas_field pinned on) trace far
    # fewer eqns than their rolled rows but still carry the full
    # Miller/MSM structure — one shard for the light MSM alias plus
    # the pairing alias keeps the pool balanced
    ["bls_aggregate_pallas", "bls_pairing_product_pallas"],
    ["consensus_step", "consensus_step_seq",
     "consensus_step_seq_donated", "honest_heights", "sharded_step",
     "sharded_step_seq", "sharded_honest_heights"],
)

#: entry -> traced op total, filled by run_jaxpr so a `--pass all`
#: census never re-traces what the audit already traced
_CENSUS_MEASURED: dict = {}


def run_jaxpr(quick: bool, metrics):
    from agnes_tpu.utils.metrics import ANALYSIS_ENTRIES_AUDITED

    union = sorted(set().union(*_JAXPR_SHARDS))
    if quick:
        tasks = [(_JAXPR_SHARDS[-1], True, None)]
    else:
        tasks = [(names, i == 0, union if i == 0 else None)
                 for i, names in enumerate(_JAXPR_SHARDS)]
    if len(tasks) == 1:
        results = [_jaxpr_worker(tasks[0])]
    else:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")     # no forked-jax state
        with ctx.Pool(processes=min(len(tasks),
                                    max(2, os.cpu_count() or 2))) as p:
            results = p.map(_jaxpr_worker, tasks)
    from agnes_tpu.analysis.jaxpr_audit import Finding

    findings, entries, skipped = [], [], []
    for f_dicts, e_dicts, skip, audited in results:
        findings.extend(Finding(**d) for d in f_dicts)
        entries.extend(e_dicts)
        skipped.extend(skip)
        metrics.count(ANALYSIS_ENTRIES_AUDITED, audited)
    for e in entries:
        if e.get("ops"):
            _CENSUS_MEASURED[e["entry"]] = e["ops"]
    detail = {
        "entries": [{"entry": e["entry"],
                     "collectives": e["collectives"],
                     "aliased": e["aliased"],
                     "ops": e.get("ops")} for e in entries],
        "skipped": skipped,
    }
    return findings, detail


def run_retrace(quick: bool, metrics):
    # static proof only — no arrays, no jax: the serve build policy's
    # dispatchable (P, rung) set vs the warmup plan, checked at a
    # representative deployment shape AND the warmup default
    from agnes_tpu.analysis import retrace
    from agnes_tpu.serve.batcher import ShapeLadder

    ladder = ShapeLadder.plan(64, 32, min_rung=256)
    findings = []
    # the dedup=True shape set strictly contains the dedup=False one
    # (ISSUE 5 split-rung dispatch: the pre-verified stream's unsigned
    # sequence entries join the signed rungs), so one call covers both
    findings += retrace.coverage_findings(ladder, n_phases=(2, 3),
                                          dedup=True)
    findings += retrace.coverage_findings(ladder, n_phases=(2, 3),
                                          dense=True)
    detail = {"ladder_rungs": list(ladder.rungs),
              "covered": not findings}
    return findings, detail


def run_locks(quick: bool, metrics):
    from agnes_tpu.analysis import lockcheck

    findings = lockcheck.check_paths(lockcheck.default_paths(_REPO))
    return findings, {"paths": lockcheck.default_paths(_REPO)}


def run_lint(quick: bool, metrics):
    from agnes_tpu.analysis import lint

    return lint.check_repo(_REPO), {}


def run_pallas(quick: bool, metrics):
    from agnes_tpu.analysis import pallas_support

    findings = pallas_support.check()
    return findings, {"records": pallas_support.support_table()}


#: set by main() from --update-baseline
_UPDATE_BASELINE = False


def _census_worker(names):
    """Trace the named entries and return {name: total ops} — one
    spawned interpreter per shard, same rationale as _jaxpr_worker.
    A name that is no longer registered (or lost its audit coverage)
    is SKIPPED, not raised: its absence from `measured` is what turns
    into the AUD008 finding — a renamed entry must fail the gate with
    the update-the-baseline message, not a traceback."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from agnes_tpu.utils.compile_cache import disable_persistent_cache

    disable_persistent_cache()
    from agnes_tpu.analysis import jaxpr_audit
    from agnes_tpu.device import registry

    registry.ensure_populated()
    out = {}
    for name in names:
        try:
            spec = registry.get(name)
            statics = dict(jaxpr_audit.ENTRY_STATICS[name])
        except KeyError:
            continue
        if spec.sharded:
            continue
        traced = jaxpr_audit.trace_entry(spec, statics)
        out[name] = sum(jaxpr_audit.primitive_census(
            traced.jaxpr.jaxpr).values())
    return out


def run_census(quick: bool, metrics):
    from agnes_tpu.analysis import jaxpr_audit

    path = jaxpr_audit.census_baseline_path(_REPO)
    if _UPDATE_BASELINE:
        # the keyset is DERIVED (every audit-planned unsharded
        # entry), so a new hot entry enters the gate on the next
        # baseline update — never a hand-edited JSON
        want = sorted(jaxpr_audit.census_planned_names())
    else:
        if not os.path.exists(path):
            return [jaxpr_audit.Finding(
                "census", "AUD009", path,
                "census baseline missing — run `agnes-lint --pass "
                "census --update-baseline` and check the file in")], \
                {"baseline": path}
        baseline = jaxpr_audit.load_census_baseline(path)
        want = sorted(baseline)
    if quick:
        # a census that skips the heavy (BLS) entries gates nothing
        return [], {"skipped": want, "note": "quick mode"}
    missing = [n for n in want if n not in _CENSUS_MEASURED]
    if missing:
        import multiprocessing as mp

        # one shard per heavy entry, the cheap rest together —
        # standalone `--pass census` parallelizes like the audit
        from agnes_tpu.analysis.jaxpr_audit import HEAVY

        shards = [[n] for n in missing if n in HEAVY]
        cheap = [n for n in missing if n not in HEAVY]
        if cheap:
            shards.append(cheap)
        if len(shards) == 1:
            results = [_census_worker(shards[0])]
        else:
            ctx = mp.get_context("spawn")
            with ctx.Pool(processes=min(
                    len(shards), max(2, os.cpu_count() or 2))) as p:
                results = p.map(_census_worker, shards)
        for r in results:
            _CENSUS_MEASURED.update(r)
    measured = {n: _CENSUS_MEASURED[n] for n in want
                if n in _CENSUS_MEASURED}
    if _UPDATE_BASELINE:
        jaxpr_audit.write_census_baseline(path, measured)
        return [], {"updated": path, "entries": measured}
    findings = jaxpr_audit.census_findings(measured, baseline)
    findings += jaxpr_audit.census_coverage_findings(baseline)
    return findings, {"entries": measured,
                      "baseline_entries": baseline,
                      "drift_entries": len(findings)}


RUNNERS = {"jaxpr": run_jaxpr, "retrace": run_retrace,
           "locks": run_locks, "lint": run_lint,
           "pallas": run_pallas, "census": run_census}


def main(argv=None) -> int:
    global _UPDATE_BASELINE
    setup_backend_env()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pass", dest="passes", default="all",
                    choices=PASSES + ("all",),
                    help="which analysis pass to run (default: all)")
    ap.add_argument("--quick", action="store_true",
                    help="skip the Ed25519-heavy jaxpr traces")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--update-baseline", action="store_true",
                    help="census: rewrite tests/baselines/"
                         "jaxpr_census.json from this run's measured "
                         "op counts (after a DELIBERATE graph change)")
    args = ap.parse_args(argv)
    _UPDATE_BASELINE = bool(args.update_baseline)
    selected = PASSES if args.passes == "all" else (args.passes,)

    from agnes_tpu.utils.metrics import (
        ANALYSIS_ENTRIES_AUDITED,
        RETRACE_UNEXPECTED,
        Metrics,
    )

    metrics = Metrics()
    report = {"passes": {}, "findings": []}
    t_all = time.perf_counter()
    for name in selected:
        t0 = time.perf_counter()
        findings, detail = RUNNERS[name](args.quick, metrics)
        dt = time.perf_counter() - t0
        report["passes"][name] = {
            "findings": len(findings), "seconds": round(dt, 1),
            **detail,
        }
        report["findings"].extend(
            {"pass": f.pass_name, "code": f.code, "where": f.where,
             "message": f.message} for f in findings)
        if not args.json:
            status = "CLEAN" if not findings else \
                f"{len(findings)} finding(s)"
            print(f"[agnes_lint] {name}: {status} ({dt:.1f}s)",
                  file=sys.stderr, flush=True)
            for f in findings:
                print(f"  {f}", file=sys.stderr, flush=True)
    report["seconds"] = round(time.perf_counter() - t_all, 1)
    report["metrics"] = {
        ANALYSIS_ENTRIES_AUDITED:
            metrics.counters.get(ANALYSIS_ENTRIES_AUDITED, 0),
        RETRACE_UNEXPECTED:
            metrics.counters.get(RETRACE_UNEXPECTED, 0),
    }
    report["ok"] = not report["findings"]
    print(json.dumps(report, sort_keys=True), flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
