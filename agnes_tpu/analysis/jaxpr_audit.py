"""Jaxpr auditor: abstract-trace every registered jit entry and assert
the fused path's structural invariants — no compile, cheap on CPU.

Four properties, each of which has already bitten (or would have):

* **Donation honored.**  Every `*_donated` twin's LOWERED text must
  carry one aliasing/donor attr per donated leaf (`tf.aliasing_output`
  on plain jits, `jax.buffer_donor` through jit-of-shard_map).  A twin
  registered as donated whose jit silently lost its donate_argnums
  would double the serve plane's resident state/tally (320 MB of tally
  alone at the north-star shape) without any test failing.
* **Collective census.**  Count collective primitives (psum &c.) in
  the sharded entries, and assert the count is INVARIANT in
  `verify_chunk`: the chunk loop is a shard-local `lax.map`, so
  chunking must add zero collectives per chunk (the
  zero-added-collectives property parallel/sharded.py promises).
* **No host callbacks.**  `pure_callback`/`debug_callback`/
  `io_callback` in a hot-path jaxpr is a host round-trip per dispatch
  — a silent serve-plane stall (a stray `jax.debug.print` is enough).
* **Dtype policy.**  No float64/complex128 avals and no weakly-typed
  float leaking through an entry: x64 is off by design, and a weak
  float in the int-encoded consensus state means an accidental
  promotion upstream.

Heavy entries (anything containing the Ed25519 verify graph) cost
~15-20s of pure tracing each on the 2-CPU CI box; `quick=True` skips
them for the tier-1 test suite, the CLI default audits everything
(budgeted < 120s, asserted by the ci.sh gate's timeout).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

#: audit shape dims — tiny on purpose: trace cost is graph-size bound,
#: not shape bound, and the invariants are shape-independent.
#: C = pairing class-batch width (bls_pairing_product)
AUDIT_DIMS = dict(I=2, V=4, P=2, Ps=1, R=4, S=4, N=8, H=2, NB=1, C=1)

COLLECTIVES = frozenset({
    "psum", "psum2", "all_reduce", "all_gather", "all_gather_invariant",
    "reduce_scatter", "ppermute", "pshuffle", "all_to_all", "pmin",
    "pmax", "pgather",
})
HOST_CALLBACKS = frozenset({
    "pure_callback", "debug_callback", "io_callback", "callback",
    "outside_call", "host_callback_call",
})
BANNED_DTYPES = ("float64", "complex128")

#: non-donated twins share fn+statics with a donated twin the plan DOES
#: trace — identical jaxpr by construction, so tracing both would just
#: double the heavy-trace bill
TWINS = {
    "consensus_step_seq_signed": "consensus_step_seq_signed_donated",
    "consensus_step_seq_signed_dense":
        "consensus_step_seq_signed_dense_donated",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    pass_name: str
    code: str
    where: str
    message: str

    def __str__(self) -> str:
        return f"[{self.pass_name}:{self.code}] {self.where}: " \
               f"{self.message}"


@dataclasses.dataclass
class EntryReport:
    entry: str
    collectives: Dict[str, int]
    aliased: Optional[int] = None      # donor/alias attrs in lowering
    heavy: bool = False
    ops: Optional[int] = None          # total traced primitives (the
    #                                    census pass's raw number —
    #                                    measured here so `--pass all`
    #                                    never traces an entry twice)


@dataclasses.dataclass
class AuditReport:
    findings: List[Finding]
    entries: List[EntryReport]
    skipped: List[str]

    @property
    def ok(self) -> bool:
        return not self.findings


# -- example inputs -----------------------------------------------------------
# Builders keyed by registry name.  They live HERE (not in the
# registry) because example shapes are an audit concern; a HOT entry
# registered without a builder is itself a finding (AUD000), so the
# table cannot silently fall behind the registry.

def _state_tally(d):
    from agnes_tpu.device.encoding import DeviceState
    from agnes_tpu.device.tally import TallyConfig, TallyState

    cfg = TallyConfig(n_validators=d["V"], n_rounds=d["R"],
                      n_slots=d["S"])
    return DeviceState.new((d["I"],)), TallyState.new(d["I"], cfg)


def _common(d):
    import jax.numpy as jnp

    from agnes_tpu.device.encoding import I32

    powers = jnp.ones((d["V"],), I32)
    total = jnp.asarray(d["V"], I32)
    pf = jnp.ones((d["I"], d["R"]), bool)
    pv = jnp.ones((d["I"],), I32)
    return powers, total, pf, pv


def _ext_phase(d, seq: bool):
    import jax.numpy as jnp

    from agnes_tpu.device.encoding import I32
    from agnes_tpu.device.step import ExtEvent, VotePhase

    lead = (d["P"],) if seq else ()
    z = jnp.zeros(lead + (d["I"],), I32)
    ext = ExtEvent(tag=z, round=z, value=z, pol_round=z)
    phase = VotePhase(
        round=z, typ=z,
        slots=jnp.zeros(lead + (d["I"], d["V"]), I32),
        mask=jnp.zeros(lead + (d["I"], d["V"]), bool),
        height=z)
    return ext, phase


def _lanes(d):
    import jax.numpy as jnp

    from agnes_tpu.device.step import SignedLanes

    n = d["N"]
    z32 = jnp.int32
    return SignedLanes(
        pub=jnp.zeros((n, 32), z32), sig=jnp.zeros((n, 64), z32),
        blocks=jnp.zeros((n, d["NB"], 32), jnp.uint32),
        phase_idx=jnp.zeros(n, z32), inst=jnp.zeros(n, z32),
        val=jnp.zeros(n, z32), real=jnp.zeros(n, bool))


def _dense(d):
    import jax.numpy as jnp

    from agnes_tpu.device.step import DenseSignedPhases

    return DenseSignedPhases(
        pub=jnp.zeros((d["V"], 32), jnp.int32),
        sig=jnp.zeros((d["Ps"], d["I"], d["V"], 64), jnp.int32),
        blocks=jnp.zeros((d["Ps"], d["I"], d["V"], d["NB"], 32),
                         jnp.uint32))


def _step_args(d):
    st, ta = _state_tally(d)
    ext, ph = _ext_phase(d, seq=False)
    return (st, ta, ext, ph) + _common(d)


def _seq_args(d):
    st, ta = _state_tally(d)
    ext, ph = _ext_phase(d, seq=True)
    return (st, ta, ext, ph) + _common(d)


def _signed_args(d):
    st, ta = _state_tally(d)
    ext, ph = _ext_phase(d, seq=True)
    return (st, ta, ext, ph, _lanes(d)) + _common(d)


def _dense_args(d):
    st, ta = _state_tally(d)
    ext, ph = _ext_phase(d, seq=True)
    return (st, ta, ext, ph, _dense(d)) + _common(d)


def _bls_args(d):
    import jax.numpy as jnp

    from agnes_tpu.crypto import bls_jax as BJ

    n = d["N"]
    return (jnp.zeros((n, 2, BJ.NLIMBS), jnp.int32),
            jnp.zeros((n, 4, BJ.NLIMBS), jnp.int32),
            jnp.zeros((n, BJ.W_LIMBS), jnp.int32))


def _bls_pair_args(d):
    import jax.numpy as jnp

    from agnes_tpu.crypto import bls_jax as BJ

    c = d["C"]
    return (jnp.zeros((c, 2, 3, BJ.NLIMBS), jnp.int32),
            jnp.zeros((c, 2, 3, 2, BJ.NLIMBS), jnp.int32))


def _honest_args(d):
    import jax.numpy as jnp

    from agnes_tpu.device.encoding import I32

    st, ta = _state_tally(d)
    slots = jnp.zeros((d["I"], d["V"]), I32)
    mask = jnp.zeros((d["I"], d["V"]), bool)
    return (st, ta, slots, mask) + _common(d)


ARG_BUILDERS: Dict[str, Callable] = {
    "consensus_step": _step_args,
    "consensus_step_seq": _seq_args,
    "consensus_step_seq_donated": _seq_args,
    "consensus_step_seq_signed": _signed_args,
    "consensus_step_seq_signed_donated": _signed_args,
    "consensus_step_seq_signed_dense": _dense_args,
    "consensus_step_seq_signed_dense_donated": _dense_args,
    "honest_heights": _honest_args,
    "bls_aggregate": _bls_args,
    "bls_pairing_product": _bls_pair_args,
    "bls_aggregate_pallas": _bls_args,
    "bls_pairing_product_pallas": _bls_pair_args,
    "sharded_step": _step_args,
    "sharded_step_seq": _seq_args,
    "sharded_step_seq_signed": _dense_args,
    "sharded_honest_heights": _honest_args,
}

#: call-time statics per entry (unsharded) / factory statics (sharded)
ENTRY_STATICS: Dict[str, dict] = {
    "consensus_step": {"advance_height": False},
    "consensus_step_seq": {"advance_height": False},
    "consensus_step_seq_donated": {"advance_height": False},
    "consensus_step_seq_signed": {"advance_height": False,
                                  "verify_chunk": None},
    "consensus_step_seq_signed_donated": {"advance_height": False,
                                          "verify_chunk": None},
    "consensus_step_seq_signed_dense": {"advance_height": False,
                                        "verify_chunk": None},
    "consensus_step_seq_signed_dense_donated": {
        "advance_height": False, "verify_chunk": None},
    "honest_heights": {"heights": 2},
    "bls_aggregate": {"n_windows": 6},
    "bls_pairing_product": {},
    # the kernel-lane aliases trace the SAME jits with the
    # `pallas_field` static pinned on (the production TPU lane), so
    # the census carries a rolled row AND a fused-kernel row per BLS
    # entry — the kernel rows must stay materially below (ISSUE 18)
    "bls_aggregate_pallas": {"n_windows": 6, "pallas_field": True},
    "bls_pairing_product_pallas": {"pallas_field": True},
    "sharded_step": {"advance_height": False},
    "sharded_step_seq": {"advance_height": False, "donate": True},
    "sharded_step_seq_signed": {"advance_height": False,
                                "verify_chunk": None, "donate": True},
    "sharded_honest_heights": {"heights": 2},
}

#: entries whose trace contains the Ed25519 verify graph (~15-20s of
#: tracing each on the CI box), the BLS aggregation MSM (~45s: the
#: Barrett field instantiates ~100k eqns across its six rolled
#: point-add bodies), or the BLS pairing tower (~35k eqns of rolled
#: Miller/final-exp bodies); quick mode skips them
HEAVY = frozenset({
    "consensus_step_seq_signed_donated",
    "consensus_step_seq_signed_dense_donated",
    "sharded_step_seq_signed",
    "bls_aggregate",
    "bls_pairing_product",
    "bls_aggregate_pallas",
    "bls_pairing_product_pallas",
})


# -- jaxpr traversal ----------------------------------------------------------

def _sub_jaxprs(x):
    """Yield every jaxpr reachable from a params value."""
    vals = x if isinstance(x, (list, tuple)) else [x]
    for v in vals:
        if hasattr(v, "eqns"):                 # Jaxpr
            yield v
        elif hasattr(v, "jaxpr"):              # ClosedJaxpr
            inner = v.jaxpr
            if hasattr(inner, "eqns"):
                yield inner


def walk_eqns(jaxpr):
    """Every eqn in `jaxpr` and all nested sub-jaxprs (scan bodies,
    pjit/shard_map calls, cond branches, ...).  A `pallas_call` is a
    LEAF (ISSUE 18): its kernel-body jaxpr compiles as one Mosaic
    custom call and never reaches XLA's op scheduler, so the census —
    a compile-budget proxy — counts the call, not the body (which is
    exactly the op-count win the kernel lane exists for)."""
    for eqn in jaxpr.eqns:
        yield eqn
        if eqn.primitive.name == "pallas_call":
            continue
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from walk_eqns(sub)


def primitive_census(jaxpr) -> Dict[str, int]:
    acc: Dict[str, int] = {}
    for eqn in walk_eqns(jaxpr):
        acc[eqn.primitive.name] = acc.get(eqn.primitive.name, 0) + 1
    return acc


def collective_census(jaxpr) -> Dict[str, int]:
    return {k: v for k, v in primitive_census(jaxpr).items()
            if k in COLLECTIVES}


def _dtype_findings(jaxpr, entry: str) -> List[Finding]:
    import numpy as np

    bad: Dict[str, int] = {}
    weak: Dict[str, int] = {}
    for eqn in walk_eqns(jaxpr):
        for var in tuple(eqn.outvars):
            aval = getattr(var, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is None:
                continue
            if str(dt) in BANNED_DTYPES:
                bad[str(dt)] = bad.get(str(dt), 0) + 1
            if (getattr(aval, "weak_type", False)
                    and np.issubdtype(dt, np.floating)):
                weak[str(dt)] = weak.get(str(dt), 0) + 1
    out = []
    if bad:
        out.append(Finding("jaxpr", "AUD004", entry,
                           f"banned dtypes in traced graph: {bad}"))
    if weak:
        out.append(Finding(
            "jaxpr", "AUD005", entry,
            f"weakly-typed float avals (promotion leak): {weak}"))
    return out


# -- tracing ------------------------------------------------------------------

def _resolve(spec, statics, mesh):
    """(callable, call_statics) for a spec: sharded entries build via
    their factory (statics consumed there), unsharded jits take the
    statics at call time."""
    if spec.sharded:
        return spec.factory(mesh, **statics), {}
    return spec.jit, statics


def trace_entry(spec, statics: dict, mesh=None, dims: dict = None):
    """Abstractly trace one registered entry at the audit shape;
    returns a jax Traced (``.jaxpr``/``.lower()``)."""
    dims = dict(AUDIT_DIMS, **(dims or {}))
    args = ARG_BUILDERS[spec.name](dims)
    fn, call_statics = _resolve(spec, statics, mesh)
    return fn.trace(*args, **call_statics)


def donation_findings(traced, spec, statics: dict,
                      donated_argnums: Tuple[int, ...],
                      dims: dict = None) -> Tuple[List[Finding],
                                                  Optional[int]]:
    """Lower `traced` and assert one aliasing/donor attr per donated
    leaf.  Returns (findings, attrs found)."""
    import jax

    dims = dict(AUDIT_DIMS, **(dims or {}))
    args = ARG_BUILDERS[spec.name](dims)
    expected = len(jax.tree_util.tree_leaves(
        [args[i] for i in donated_argnums]))
    txt = traced.lower().as_text()
    found = txt.count("tf.aliasing_output") + txt.count("jax.buffer_donor")
    if found != expected:
        return [Finding(
            "jaxpr", "AUD001", spec.name,
            f"donation not honored: {found} aliasing/donor attrs in "
            f"the lowered text, expected {expected} (one per donated "
            f"state/tally leaf)")], found
    return [], found


def _audit_one(spec, statics, mesh, metrics, findings, reports,
               dims=None) -> Optional[Dict[str, int]]:
    """Trace + all per-entry checks; returns the collective census."""
    traced = trace_entry(spec, statics, mesh, dims)
    jaxpr = traced.jaxpr.jaxpr
    prims = primitive_census(jaxpr)       # one walk serves both checks
    census = {k: v for k, v in prims.items() if k in COLLECTIVES}
    cbs = {k: v for k, v in prims.items() if k in HOST_CALLBACKS}
    if cbs:
        findings.append(Finding(
            "jaxpr", "AUD003", spec.name,
            f"host callbacks in hot-path jaxpr: {cbs} (a host "
            f"round-trip per dispatch)"))
    findings.extend(_dtype_findings(jaxpr, spec.name))
    donated = spec.donated
    if spec.sharded and statics.get("donate"):
        donated = (0, 1)
    aliased = None
    if donated:
        dn, aliased = donation_findings(traced, spec, statics, donated,
                                        dims)
        findings.extend(dn)
    reports.append(EntryReport(entry=spec.name, collectives=census,
                               aliased=aliased,
                               heavy=spec.name in HEAVY,
                               ops=sum(prims.values())))
    if metrics is not None:
        from agnes_tpu.utils.metrics import ANALYSIS_ENTRIES_AUDITED

        metrics.count(ANALYSIS_ENTRIES_AUDITED)
    return census


def planned_names() -> List[str]:
    """The entry names a full audit traces (registered, arg-covered,
    not a twin) — the set any sharded/parallel execution of the audit
    must jointly cover (see shard_coverage_findings)."""
    from agnes_tpu.device import registry

    specs = {s.name for s in registry.entries()}
    return [n for n in ARG_BUILDERS if n in specs and n not in TWINS]


# -- jaxpr op-count census (ISSUE 13) ----------------------------------------
#
# The graph diet is only a diet while something fails when the graph
# grows back: every hot entry's TOTAL traced-primitive count at the
# audit shape is pinned in a checked-in baseline, and the census pass
# (`agnes-lint --pass census`) fails on >10% drift either way —
# growth is a compile-budget regression, collapse means the audit is
# tracing the wrong thing.  `--update-baseline` rewrites the file
# after a DELIBERATE change (tests/baselines/jaxpr_census.json's
# history then documents the graph-size trajectory).

CENSUS_TOLERANCE = 0.10
CENSUS_BASELINE_REL = "tests/baselines/jaxpr_census.json"


def census_baseline_path(repo_root: str) -> str:
    import os

    return os.path.join(repo_root, *CENSUS_BASELINE_REL.split("/"))


def load_census_baseline(path: str) -> Dict[str, int]:
    import json

    with open(path) as f:
        data = json.load(f)
    return {k: int(v) for k, v in data["entries"].items()}


def write_census_baseline(path: str, measured: Dict[str, int]) -> None:
    import json
    import os

    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"version": 1, "dims": AUDIT_DIMS,
                   "tolerance": CENSUS_TOLERANCE,
                   "entries": {k: int(v) for k, v in
                               sorted(measured.items())}},
                  f, indent=1, sort_keys=True)
        f.write("\n")


def census_planned_names() -> List[str]:
    """The entry set an `--update-baseline` pins: every audit-planned
    UNSHARDED entry (sharded entries need a mesh the standalone
    census workers don't build; in `--pass all` their ops still ride
    the audit report).  Derived, never hand-maintained — a new hot
    entry enters the census gate on the next baseline update without
    anyone editing a list (the shard_coverage_findings lesson)."""
    from agnes_tpu.device import registry

    return [n for n in planned_names() if not registry.get(n).sharded]


def census_findings(measured: Dict[str, int],
                    baseline: Dict[str, int],
                    tolerance: float = CENSUS_TOLERANCE
                    ) -> List[Finding]:
    """Drift findings: measured vs baseline op counts (AUD007), a
    baselined entry the run never traced (AUD008)."""
    out: List[Finding] = []
    for name, want in sorted(baseline.items()):
        got = measured.get(name)
        if got is None:
            out.append(Finding(
                "census", "AUD008", name,
                "baselined entry was not traced (unregistered, "
                "renamed, or dropped from the census shards) — "
                "update the baseline or the shard table"))
            continue
        drift = (got - want) / want
        if abs(drift) > tolerance:
            out.append(Finding(
                "census", "AUD007", name,
                f"traced op count {got} drifted {drift:+.1%} from "
                f"the baseline {want} (tolerance ±{tolerance:.0%}) — "
                f"a graph-size regression, or run `agnes-lint --pass "
                f"census --update-baseline` after a deliberate "
                f"change"))
    return out


def census_coverage_findings(baseline: Dict[str, int]
                             ) -> List[Finding]:
    """A census-PLANNED entry missing from the baseline is itself a
    finding (AUD010): without this, a newly registered hot entry's
    op count stays silently ungated — the exact regression class the
    gate exists for (the shard_coverage_findings lesson, applied to
    the compare path and not just `--update-baseline`)."""
    missing = sorted(set(census_planned_names()) - set(baseline))
    if not missing:
        return []
    return [Finding(
        "census", "AUD010", ",".join(missing),
        "census-planned entries missing from the baseline — run "
        "`agnes-lint --pass census --update-baseline` and check the "
        "file in so the new entries' graph sizes are gated")]


def shard_coverage_findings(union_names) -> List[Finding]:
    """Guard against a THIRD hand-maintained list drifting: a CLI (or
    any parallel runner) that splits the audit plan into shards must
    prove the shard union still covers the full plan — a registered
    entry missing from every shard would silently never be traced."""
    missing = sorted(set(planned_names()) - set(union_names))
    if not missing:
        return []
    return [Finding(
        "jaxpr", "AUD006", ",".join(missing),
        "audit-planned entries missing from every worker shard — "
        "update the shard table (analysis/lint_cli.py) or derive it "
        "from planned_names()")]


def _pod_audit_mesh():
    """A pod-shaped hierarchical mesh (slice=2 hosts x data=1 x
    val=2) over 4 devices, or None below 4 — the ISSUE 15 census
    dimension: the multi-host driver dispatches the SAME sharded
    entries over a mesh whose outer slice axis crosses hosts (DCN),
    and the layout's promise is that NOTHING ever reduces over it."""
    import jax

    from agnes_tpu.parallel.mesh import make_hierarchical_mesh

    devs = jax.devices()
    if len(devs) < 4:
        return None
    return make_hierarchical_mesh(2, 1, 2, devs[:4])


def _audit_mesh():
    """A small (data x val) mesh over the available devices, or None
    when the backend has a single device (sharded entries skipped)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from agnes_tpu.parallel.mesh import DATA_AXIS, VAL_AXIS

    devs = jax.devices()
    if len(devs) >= 4:
        grid = np.array(devs[:4]).reshape(2, 2)
    elif len(devs) >= 2:
        grid = np.array(devs[:2]).reshape(1, 2)
    else:
        return None
    return Mesh(grid, (DATA_AXIS, VAL_AXIS))


def audit(quick: bool = False, names: Optional[List[str]] = None,
          mesh=None, metrics=None, dims: dict = None,
          coverage: bool = True) -> AuditReport:
    """Run the full jaxpr audit over the registered entries.

    `quick` skips the HEAVY (Ed25519-bearing) entries — the tier-1
    test-suite mode; the CLI runs everything (parallelized over
    worker processes, agnes_lint.py).  `names` restricts to a subset
    (tests, CLI workers); `coverage=False` skips the registry
    coverage check (CLI workers run it in exactly one shard).
    Sharded entries need >= 2 devices; on a single-device backend
    they are reported in `skipped`."""
    from agnes_tpu.device import registry

    findings: List[Finding] = []
    reports: List[EntryReport] = []
    skipped: List[str] = []
    specs = {s.name: s for s in registry.entries()}

    # coverage: every HOT entry must be audit-planned (builder +
    # statics), directly or via its identical twin
    for s in specs.values() if coverage else ():
        if not s.hot or s.name in TWINS:
            continue
        if s.name not in ARG_BUILDERS or s.name not in ENTRY_STATICS:
            findings.append(Finding(
                "jaxpr", "AUD000", s.name,
                "hot jit entry registered without audit coverage "
                "(add ARG_BUILDERS/ENTRY_STATICS in jaxpr_audit.py)"))

    plan = [n for n in ARG_BUILDERS
            if n in specs and n not in TWINS]
    if names is not None:
        plan = [n for n in plan if n in names]
    if quick:
        plan = [n for n in plan if n not in HEAVY]

    if mesh is None:
        mesh = _audit_mesh()
    for name in plan:
        spec = specs[name]
        if spec.sharded and mesh is None:
            skipped.append(name)
            continue
        _audit_one(spec, dict(ENTRY_STATICS[name]), mesh, metrics,
                   findings, reports, dims)

    def _census_compare(name, statics, cmp_mesh, code, what):
        """Re-trace `name` under a VARIED configuration and assert
        its collective census is IDENTICAL to the already-audited
        base — the shared scaffold of the two invariance gates below
        (one shape: skip if the entry wasn't planned / already has a
        finding, trace, compare, count the extra audit)."""
        if (name not in plan or cmp_mesh is None
                or any(f.where == name for f in findings)):
            return
        base = next(r.collectives for r in reports if r.entry == name)
        traced = trace_entry(specs[name], statics, cmp_mesh, dims)
        varied = collective_census(traced.jaxpr.jaxpr)
        if varied != base:
            findings.append(Finding(
                "jaxpr", code, name,
                f"{what} changes the collective census: "
                f"base {base} vs varied {varied}"))
        if metrics is not None:
            from agnes_tpu.utils.metrics import ANALYSIS_ENTRIES_AUDITED

            metrics.count(ANALYSIS_ENTRIES_AUDITED)

    name = "sharded_step_seq_signed"
    # chunk invariance (AUD002): chunking the sharded fused verify
    # must add ZERO collectives (the chunk loop is shard-local)
    _census_compare(
        name, dict(ENTRY_STATICS[name], verify_chunk=1), mesh,
        "AUD002", "verify_chunk=1 (chunking must add zero "
        "collectives per chunk)")
    # pod-mesh census (AUD011, ISSUE 15): the global-SPMD serve entry
    # traced over a (slice=hosts, data, val) POD mesh must carry the
    # exact census of the flat mesh — the slice axis is the
    # cross-host (DCN) dimension and parallel/sharded.py's layout
    # promises it carries ZERO collectives, so the cross-host psum
    # count is pinned AT zero the same way the single-host counts are
    # pinned by the baseline.  A psum riding the slice axis is a
    # per-step DCN round-trip — the class of silent regression that
    # only surfaces as a wedged pod round.
    _census_compare(
        name, dict(ENTRY_STATICS[name]), _pod_audit_mesh(),
        "AUD011", "the pod (slice=hosts) mesh (a collective is "
        "riding the cross-host slice axis; instance DP never "
        "communicates across hosts)")
    return AuditReport(findings=findings, entries=reports,
                       skipped=skipped)
