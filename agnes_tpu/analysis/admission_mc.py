"""Serve-plane admission model checker (ISSUE 7 tentpole axis 3).

The admission/batching layer — AdmissionQueue fairness caps + overload
policies, the held-vote window, the dedup split-rung dispatch — is the
largest body of decision-affecting host code that until this module
had NO exhaustive coverage: it is differential-tested on sampled
traffic (tests/test_serve_pipeline.py seeds) and unit-tested on
hand-picked scenarios, exactly the coverage profile the bounded model
checker was built to close for the consensus core (ISSUE 6).

This module drives the SAME schedule enumerator (`modelcheck.Domain` /
`_explore_domain`: depth-bounded DFS, canonical-state dedup, ddmin
minimization) over an `AdmissionSystem`:

  * the REAL `serve.queue.AdmissionQueue` and REAL
    `serve.cache.VerifiedCache` (their `mc_clone`/`mc_canonical`
    hooks are the only serve/ additions) — the admission code under
    check is production code, not a re-model;
  * a deterministic MODEL of the batcher/pipeline stages downstream
    (pending queue, held-vote window, builds capped at `max_rung`,
    the verified/fresh split, preverified chunking to <= 2 vote
    phases) — the real VoteBatcher/ServePipeline carry jax, and the
    checker must stay jax-free for the pre-test ci.sh gate slot.
    Model counterexamples replay through the real, registry-stubbed
    ServePipeline in tests/test_admission_mc.py (the PR 4/5 stub
    pattern: zero XLA compiles).

Actions (the admission schedule alphabet):

  ("s", k)   submit one copy of record template k (bounded per
             template by `max_copies` — gossip duplication included:
             copies are byte-identical, so the dedup cache sees them)
  ("b",)     one pump tick: drain <= `target` records FIFO, build
             capped split builds, dispatch them, age what waited
  ("v",)     settle the oldest unsettled signed dispatch: its wire
             digests become dedup-cache entries (clean-verify model)
  ("w",)     advance the window round once: held future-round rows
             become buildable (the held re-entry path)

Property monitors (the admission-soundness contract):

  conservation   no admitted record is ever lost outside a counted
                 reject: per template, admitted == still-queued +
                 pending + dispatched
  starvation     fairness caps never starve an admitted in-window
                 record forever: its pump-tick age is bounded by
                 `starve_bound` (FIFO drains guarantee it; a
                 reordering/skipping queue violates it)
  pbound         every dispatch is entry + <= 2 vote phases — P in
                 {2, 3}, the warmed-shape contract (an unchunked
                 preverified burst is a live compile stall in
                 production)
  purity         rows in an UNSIGNED (preverified) dispatch carry
                 only dedup-cache-hit digests — a fresh row on a
                 verify-free entry would skip signature verification
                 entirely, the ISSUE 5 security invariant

The mutation registry (`ADMISSION_MUTANTS`) doctors one stage each —
a record-dropping drain, a LIFO (newest-first) drain, an unchunked
preverified build, a taint-splitting build — and `self_test_admission`
proves every monitor has teeth: caught, ddmin-minimized, minimized
schedule clean on the honest model.

Pure numpy + stdlib; ZERO jax imports (asserted by test).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

from agnes_tpu.analysis.modelcheck import (
    Domain,
    Report,
    Violation,
    _ddmin,
    _explore_domain,
)
from agnes_tpu.bridge.native_ingest import pack_wire_votes
from agnes_tpu.serve.cache import VerifiedCache
from agnes_tpu.serve.queue import AdmissionQueue

ADMISSION_PROPERTIES = ("conservation", "starvation", "pbound", "purity")

#: ISSUE 10: the class-bucket extension reuses "conservation" (every
#: FOLDED share is in exactly one of open-class / aggregate-dispatched
#: / fallback-dispatched / forged-dropped) and "purity" (an aggregate
#: dispatch may carry only a pairing-CLEARED class); the pairing
#: verdict itself is an oracle boundary (crypto, not admission), so
#: the model declares it per validator via `bls_forged` — the honest
#: close routes forged classes down the per-share fallback exactly
#: like serve/bls_lane.BlsLane.clear_classes.

#: template = (instance, validator, round, typ); the wire value id is
#: 100 + template index, which is how drained rows are re-identified
_DEFAULT_TEMPLATES = (
    (0, 0, 0, 0),      # instance 0, round 0, prevote
    (0, 1, 0, 1),      # instance 0, round 0, precommit
    (1, 2, 0, 0),      # instance 1, round 0, prevote
    (1, 3, 1, 0),      # instance 1, round 1, prevote (held until "w")
)


@dataclasses.dataclass(frozen=True)
class AdmissionMCConfig:
    """One bounded admission-exploration task.  JSON-able (spawn
    workers, corpus files)."""

    name: str
    n_instances: int = 2
    capacity: int = 6
    instance_cap: Optional[int] = None
    policy: str = "reject_newest"
    target: int = 3            # micro-batch drain size per pump tick
    max_rung: int = 4          # build cap (the ladder's top rung)
    dedup: bool = True
    depth: int = 12
    max_copies: int = 2        # per-template submission bound
    starve_bound: int = 4      # eligible-age bound (pump ticks)
    window_rounds: int = 1     # how many ("w",) advances exist
    templates: Tuple[Tuple[int, int, int, int], ...] = _DEFAULT_TEMPLATES
    # -- BLS class-bucket mode (ISSUE 10) --------------------------------
    bls: bool = False
    #: BLS share templates: (instance, validator, typ) at height 0,
    #: round 0 — each (instance, typ) pair is one aggregate class
    bls_templates: Tuple[Tuple[int, int, int], ...] = ()
    bls_target: int = 2        # class size-close threshold (poll)
    bls_max_classes: int = 2   # BlsClassTable bound
    #: validators whose shares fail the (modeled) pairing — the honest
    #: close falls their class back to per-share dispatch
    bls_forged: Tuple[int, ...] = ()
    #: validators without a verified proof of possession — their folds
    #: are rejected at admission (bls_pop_missing)
    bls_no_pop: Tuple[int, ...] = ()

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["templates"] = [list(t) for t in self.templates]
        d["bls_templates"] = [list(t) for t in self.bls_templates]
        d["bls_forged"] = list(self.bls_forged)
        d["bls_no_pop"] = list(self.bls_no_pop)
        d["kind"] = "admission"
        return d

    @classmethod
    def from_json(cls, d: dict) -> "AdmissionMCConfig":
        d = dict(d)
        d.pop("kind", None)
        d["templates"] = tuple(tuple(t) for t in d["templates"])
        d["bls_templates"] = tuple(
            tuple(t) for t in d.get("bls_templates", ()))
        d["bls_forged"] = tuple(d.get("bls_forged", ()))
        d["bls_no_pop"] = tuple(d.get("bls_no_pop", ()))
        return cls(**d)


_ACT_NAMES = {"s": "submit", "b": "pump", "v": "settle", "w": "window",
              "f": "fold", "c": "classes"}
_ACT_CODES = {v: k for k, v in _ACT_NAMES.items()}


class _McBlsRegistry:
    """Stub BlsKeyRegistry surface for the class table (V / powers /
    pop_ok / epoch) — the REAL registry decompresses device pubkey
    limbs through the jax kernels, and this model must stay jax-free.
    The table under check is the production BlsClassTable."""

    def __init__(self, n_validators: int, no_pop=()):
        self.V = int(n_validators)
        self.powers = np.ones(self.V, np.int64)
        self.pop_ok = np.ones(self.V, bool)
        self.pop_ok[list(no_pop)] = False
        self.forged_strikes = np.zeros(self.V, np.int64)
        self.quarantined = np.zeros(self.V, bool)
        self.epoch = 0


@functools.lru_cache(maxsize=256)
def _bls_share_bytes(idx: int) -> bytes:
    """A VALID (on-twist, non-identity) 192-byte G2 share per template
    index, so the fold's decode screen passes on the real path — not a
    valid signature: the pairing verdict is modeled (`bls_forged`),
    never computed.  Pure python (bls_ref is jax-free)."""
    from agnes_tpu.crypto import bls_ref as ref

    return ref.g2_to_bytes(ref.point_mul(2 + idx, ref.G2))


@functools.lru_cache(maxsize=256)
def _pack_template(tmpl: Tuple[int, int, int, int]) -> bytes:
    """One SIGNED 96-byte wire record for a template: value id is
    100 + instance (one value per instance — the honest dense shape
    VoteBatcher._device_verify_eligible demands, so the serve replay's
    fresh builds keep the signed-lane path), signature REAL over the
    fixture seed scheme (deterministic_seeds) so host-fallback subsets
    verify instead of silently dropping.  The pure-Python ref signer
    keeps this module jax-free (the C++ signer's build-tag generator
    imports the jax kernels); memoized — ddmin rebuilds a system per
    probe and must not re-pay ~ms/signature."""
    from agnes_tpu.crypto.ed25519_ref import sign as _ref_sign
    from agnes_tpu.crypto.encoding import vote_signing_bytes

    inst, val, rnd, typ = tmpl
    value = 100 + inst
    seed = val.to_bytes(4, "little") + bytes(28)
    sig = np.frombuffer(_ref_sign(seed, vote_signing_bytes(
        0, rnd, typ, value)), np.uint8)[None]
    return bytes(pack_wire_votes(
        np.asarray([inst], np.int64), np.asarray([val], np.int64),
        np.zeros(1, np.int64), np.asarray([rnd], np.int64),
        np.asarray([typ], np.int64),
        np.asarray([value], np.int64), sig))


@dataclasses.dataclass
class _Row:
    """One admitted record inside the model batcher's pending stage."""

    template: int
    verified: bool
    age: int


class AdmissionSystem:
    """The checkable system: real queue + real cache + modeled
    batcher/pipeline (module docstring).  Provides the engine's
    mc_clone / mc_apply / mc_enabled / mc_digest surface plus the
    schedule codec (`action_to_json`/`action_from_json`)."""

    #: stage classes — the mutation seams (ADMISSION_MUTANTS)
    queue_cls = AdmissionQueue
    bls_table_cls = None       # default: serve.bls_lane.BlsClassTable
    #: chunk preverified builds to <= this many vote phases (the
    #: honest pipeline's _stage_preverified bound)
    preverified_chunk = 2

    def __init__(self, cfg: AdmissionMCConfig):
        self.cfg = cfg
        assert len(set(cfg.templates)) == len(cfg.templates), \
            "templates must be distinct (identity is the full tuple)"
        cache = VerifiedCache() if cfg.dedup else None
        self.bls_table = None
        if cfg.bls:
            from agnes_tpu.serve.bls_lane import BlsClassTable

            assert len(set(cfg.bls_templates)) == len(cfg.bls_templates)
            reg = _McBlsRegistry(
                1 + max(t[1] for t in cfg.bls_templates),
                no_pop=cfg.bls_no_pop)
            table_cls = self.bls_table_cls or BlsClassTable
            self.bls_table = table_cls(
                reg, cfg.n_instances,
                max_classes=cfg.bls_max_classes, clock=lambda: 0.0)
        self.queue = self.queue_cls(
            cfg.n_instances, cfg.capacity,
            instance_cap=cfg.instance_cap, policy=cfg.policy,
            cache=cache, bls_table=self.bls_table, clock=lambda: 0.0)
        self.cache = cache
        T = len(cfg.templates)
        # template identity: the (instance, validator, round, typ)
        # tuple — NOT the value id, which is one-per-instance so the
        # serve replay's fresh builds stay device-verify eligible
        # (VoteBatcher._device_verify_eligible: at most one distinct
        # non-nil value per instance)
        self._tmpl_key = {t: k for k, t in enumerate(cfg.templates)}
        self._wire = [self._pack(k) for k in range(T)]
        self.submits = [0] * T
        self.admitted = [0] * T
        self.dispatched = [0] * T
        # drop_oldest only: admitted records the queue SHED (counted
        # per template via the before/after queue diff at submit time)
        self.evicted = [0] * T
        # per-template FIFO of queued-record ages (the starvation
        # clock; rows are re-identified by the value column)
        self.q_ages: List[List[int]] = [[] for _ in range(T)]
        self.pending: List[_Row] = []
        self.window_round = 0
        # signed dispatches whose digests await a ("v",) settle:
        # FIFO of [(template, digest bytes, instance)]
        self.unsettled: List[List[tuple]] = []
        # (P, signed, per-template counts, rows) per dispatch — the
        # edge monitors' subject; history, excluded from the digest
        self.dispatch_log: List[tuple] = []
        # -- BLS class-bucket stage (ISSUE 10) ---------------------------
        B = len(cfg.bls_templates)
        self._bls_key = {t: k for k, t in enumerate(cfg.bls_templates)}
        self._bls_wire = [self._pack_bls(k) for k in range(B)]
        self.bls_submits = [0] * B
        self.bls_folded = [0] * B          # accepted folds, per template
        self.bls_agg = [0] * B             # aggregate-dispatched
        self.bls_fallback = [0] * B        # fallback-dispatched (good)
        self.bls_dropped = [0] * B         # forged, dropped at fallback
        # ("agg"|"fallback", member templates, forged templates) per
        # class close — the purity edge monitor's subject
        self.bls_dispatch_log: List[tuple] = []

    # -- wire records --------------------------------------------------------

    def _pack(self, k: int) -> bytes:
        return _pack_template(self.cfg.templates[k])

    def _pack_bls(self, k: int) -> bytes:
        from agnes_tpu.serve.bls_lane import pack_bls_wire

        inst, val, typ = self.cfg.bls_templates[k]
        share = np.frombuffer(_bls_share_bytes(k), np.uint8)[None]
        return pack_bls_wire(
            np.asarray([inst], np.int64), np.asarray([val], np.int64),
            np.zeros(1, np.int64), np.zeros(1, np.int64),
            np.asarray([typ], np.int64),
            np.asarray([100 + inst], np.int64), share)

    def _in_window(self, k: int) -> bool:
        return self.cfg.templates[k][2] <= self.window_round

    def _queued_counts(self) -> List[int]:
        """Per-template row count actually inside the REAL queue (from
        its canonical rows, never the model's own mirrors — a lossy
        queue must not be able to fool the conservation check)."""
        counts = [0] * len(self.cfg.templates)
        for (inst, val, _h, rnd, typ, _value, _v) \
                in self.queue.mc_canonical()[0]:
            k = self._tmpl_key.get((inst, val, rnd, typ))
            if k is not None:
                counts[k] += 1
        return counts

    # -- engine surface ------------------------------------------------------

    def mc_enabled(self) -> List[tuple]:
        acts: List[tuple] = []
        for k in range(len(self.cfg.templates)):
            if self.submits[k] < self.cfg.max_copies:
                acts.append(("s", k))
        if self.queue.depth > 0 or self.pending:
            acts.append(("b",))
        if self.unsettled:
            acts.append(("v",))
        if self.window_round < self.cfg.window_rounds:
            acts.append(("w",))
        for k in range(len(self.cfg.bls_templates)):
            if self.bls_submits[k] < self.cfg.max_copies:
                acts.append(("f", k))
        if self.bls_table is not None and self.bls_table.open_classes:
            acts.append(("c",))
        return acts

    def mc_apply(self, act: tuple) -> bool:
        kind = act[0]
        if kind == "s":
            k = act[1]
            if self.submits[k] >= self.cfg.max_copies:
                return False
            self.submits[k] += 1
            # drop_oldest is the ONLY policy that sheds queued rows at
            # submit time; the per-template eviction diff exists for
            # it alone.  Under reject_newest the diff is skipped — so
            # a doctored queue losing rows on submit surfaces as a
            # CONSERVATION violation instead of being misclassified as
            # eviction (and the hot smoke shard skips two O(queue)
            # walks per submit)
            diff = self.queue.policy == "drop_oldest"
            before = self._queued_counts() if diff else None
            res = self.queue.submit(self._wire[k])
            if res.accepted:
                self.admitted[k] += 1
                self.q_ages[k].append(0)
            if diff:
                # shed OLDEST copies carry the largest ages
                after = self._queued_counts()
                for t in range(len(self.cfg.templates)):
                    gone = before[t] - after[t] \
                        + (res.accepted if t == k else 0)
                    for _ in range(gone):
                        self.evicted[t] += 1
                        if self.q_ages[t]:
                            self.q_ages[t].remove(max(self.q_ages[t]))
            return True
        if kind == "b":
            if self.queue.depth == 0 and not self.pending:
                return False
            self._pump()
            return True
        if kind == "v":
            if not self.unsettled:
                return False
            batch = self.unsettled.pop(0)
            if self.cache is not None and batch:
                dig = np.stack([np.frombuffer(d, np.uint8)
                                for _k, d, _i in batch])
                inst = np.asarray([i for _k, _d, i in batch], np.int64)
                self.cache.insert(dig, inst, np.zeros(len(batch),
                                                      np.int64))
            return True
        if kind == "w":
            if self.window_round >= self.cfg.window_rounds:
                return False
            self.window_round += 1
            return True
        if kind == "f":
            k = act[1]
            if self.bls_submits[k] >= self.cfg.max_copies:
                return False
            self.bls_submits[k] += 1
            res = self.queue.submit_bls(self._bls_wire[k])
            if res.accepted:
                self.bls_folded[k] += 1
            return True
        if kind == "c":
            if self.bls_table is None \
                    or not self.bls_table.open_classes:
                return False
            self._close_classes()
            return True
        raise ValueError(f"unknown admission action {act!r}")

    # -- the pump tick (drain -> split -> build -> dispatch -> age) ----------

    def _pump(self) -> None:
        batch = self.queue.drain(self.cfg.target) \
            if self.queue.depth else None
        if batch is not None:
            for j in range(len(batch)):
                k = self._tmpl_key.get(
                    (int(batch.instance[j]), int(batch.validator[j]),
                     int(batch.round_[j]), int(batch.typ[j])))
                if k is None:
                    continue       # foreign record: conservation's job
                # copies of one template are byte-identical, so which
                # copy left is unobservable: assume FIFO-optimally
                # that the OLDEST (largest age) one did.  Honest FIFO
                # drains truly do; a reordering queue is caught via
                # DISTINCT templates (the starve mutant config)
                age = max(self.q_ages[k], default=0)
                if self.q_ages[k]:
                    self.q_ages[k].remove(age)
                self.pending.append(_Row(k, bool(batch.verified[j]),
                                         age))
        pre, fresh = self._split(self.pending)
        held: List[_Row] = []
        buildable: List[_Row] = []
        for r in fresh:
            (buildable if self._in_window(r.template)
             else held).append(r)
        # fresh builds: capped FIFO slices, grouped by (round, typ),
        # <= 2 vote-phase groups per dispatch (the signed entry-phase
        # shape; a wider tick stages several dispatches)
        while buildable:
            take, buildable = buildable[:self.cfg.max_rung], \
                buildable[self.cfg.max_rung:]
            self._dispatch(take, signed=True)
        pre_buildable = [r for r in pre if self._in_window(r.template)]
        pre_held = [r for r in pre if not self._in_window(r.template)]
        while pre_buildable:
            take, pre_buildable = pre_buildable[:self.cfg.max_rung], \
                pre_buildable[self.cfg.max_rung:]
            self._dispatch(take, signed=False)
        self.pending = held + pre_held
        # age every record still waiting while eligible (in-window)
        for k, ages in enumerate(self.q_ages):
            if self._in_window(k):
                self.q_ages[k] = [a + 1 for a in ages]
        for r in self.pending:
            if self._in_window(r.template):
                r.age += 1

    #: mutation seam: a True here dispatches EVERY closed class as a
    #: cleared aggregate, forged shares included (the purity mutant)
    bls_pairing_blind = False

    def _close_classes(self) -> None:
        """One class-close tick: size-closed classes leave the table
        and dispatch — pairing-CLEARED classes as ONE aggregate,
        classes containing a (declared) forged share down the
        per-share fallback with the forged shares dropped and the
        honest remainder dispatched — the BlsLane.clear_classes
        routing, with the pairing verdict read from `bls_forged`."""
        closed = self.bls_table.poll(
            now=0.0, target_signers=self.cfg.bls_target,
            max_delay_s=1e9)
        forged = set(self.cfg.bls_forged)
        for cls in closed:
            inst, _h, _r, typ, _val = cls.key
            members, bad = [], []
            for v in sorted(cls.shares):
                k = self._bls_key.get((inst, v, typ))
                if k is None:
                    continue
                (bad if v in forged else members).append(k)
            if not bad or self.bls_pairing_blind:
                for k in members + bad:
                    self.bls_agg[k] += 1
                self.bls_dispatch_log.append(
                    ("agg", tuple(members + bad), tuple(bad)))
            else:
                for k in members:
                    self.bls_fallback[k] += 1
                for k in bad:
                    self.bls_dropped[k] += 1
                self.bls_dispatch_log.append(
                    ("fallback", tuple(members), tuple(bad)))

    def _split(self, rows: List[_Row]) -> Tuple[List[_Row], List[_Row]]:
        """Partition pending into (pre-verified, fresh), preserving
        FIFO order — the honest VoteBatcher.split_pending_verified
        model.  A fresh row may NEVER land in the pre stream."""
        if self.cache is None:
            return [], list(rows)
        pre = [r for r in rows if r.verified]
        return pre, [r for r in rows if not r.verified]

    @staticmethod
    def _groups(rows: List[_Row], cfg) -> List[List[_Row]]:
        by: Dict[tuple, List[_Row]] = {}
        for r in rows:
            t = cfg.templates[r.template]
            by.setdefault((t[2], t[3]), []).append(r)
        return [by[k] for k in sorted(by)]

    def _dispatch(self, rows: List[_Row], signed: bool) -> None:
        """Chunked dispatch: every staged step sequence is entry +
        <= 2 vote phases — the warmed-shape discipline (fresh signed
        builds via the eligibility gate, preverified unsigned builds
        via _stage_preverified's chunking, serve/pipeline.py)."""
        import hashlib

        groups = self._groups(rows, self.cfg)
        step = 2 if signed else self.preverified_chunk
        for i in range(0, len(groups), step):
            chunk = groups[i:i + step]
            flat = [r for g in chunk for r in g]
            self._log_dispatch(len(chunk) + 1, signed, flat)
            if signed and self.cache is not None:
                entry = []
                for r in flat:
                    dig = hashlib.sha256(
                        self._wire[r.template]).digest()
                    entry.append((r.template, dig,
                                  self.cfg.templates[r.template][0]))
                self.unsettled.append(entry)

    def _log_dispatch(self, P: int, signed: bool,
                      rows: List[_Row]) -> None:
        counts = [0] * len(self.cfg.templates)
        for r in rows:
            counts[r.template] += 1
            self.dispatched[r.template] += 1
        self.dispatch_log.append(
            (P, signed, tuple(counts),
             tuple((r.template, r.verified) for r in rows)))

    # -- branching / dedup ---------------------------------------------------

    def mc_clone(self) -> "AdmissionSystem":
        s = type(self).__new__(type(self))
        s.cfg = self.cfg
        s.cache = None if self.cache is None else self.cache.mc_clone()
        s.queue = self.queue.mc_clone()
        s.queue.cache = s.cache
        s.bls_table = (None if self.bls_table is None
                       else self.bls_table.mc_clone())
        s.queue.bls_table = s.bls_table
        s._bls_key = self._bls_key
        s._bls_wire = self._bls_wire
        s.bls_submits = list(self.bls_submits)
        s.bls_folded = list(self.bls_folded)
        s.bls_agg = list(self.bls_agg)
        s.bls_fallback = list(self.bls_fallback)
        s.bls_dropped = list(self.bls_dropped)
        s.bls_dispatch_log = list(self.bls_dispatch_log)
        s._wire = self._wire
        s._tmpl_key = self._tmpl_key
        s.submits = list(self.submits)
        s.admitted = list(self.admitted)
        s.dispatched = list(self.dispatched)
        s.evicted = list(self.evicted)
        s.q_ages = [list(a) for a in self.q_ages]
        s.pending = [_Row(r.template, r.verified, r.age)
                     for r in self.pending]
        s.window_round = self.window_round
        s.unsettled = [list(b) for b in self.unsettled]
        s.dispatch_log = list(self.dispatch_log)
        return s

    def mc_canonical(self) -> tuple:
        return (
            tuple(self.submits),
            tuple(self.admitted),
            tuple(self.dispatched),
            tuple(self.evicted),
            self.queue.mc_canonical(),
            None if self.cache is None else self.cache.mc_canonical(),
            tuple(tuple(a) for a in self.q_ages),
            tuple((r.template, r.verified, r.age)
                  for r in self.pending),
            self.window_round,
            tuple(tuple((k, i) for k, _d, i in b)
                  for b in self.unsettled),
            None if self.bls_table is None
            else (self.bls_table.mc_canonical(),
                  tuple(self.bls_submits), tuple(self.bls_folded),
                  tuple(self.bls_agg), tuple(self.bls_fallback),
                  tuple(self.bls_dropped)),
        )

    def mc_digest(self, perm=None) -> bytes:
        import hashlib
        import marshal

        assert perm is None, "admission domain has no symmetry group"
        return hashlib.blake2b(marshal.dumps(self.mc_canonical(), 2),
                               digest_size=16).digest()

    # -- schedule codec (the Counterexample/corpus serialization) ------------

    @classmethod
    def action_to_json(cls, act: tuple) -> list:
        return [_ACT_NAMES[act[0]], *act[1:]]

    @classmethod
    def action_from_json(cls, a: list) -> tuple:
        return (_ACT_CODES[a[0]], *(int(x) for x in a[1:]))

    def run_schedule(self, actions, on_action=None) -> List[bool]:
        applied = []
        for i, a in enumerate(actions):
            act = self.action_from_json(a) if a and a[0] in _ACT_CODES \
                else tuple(a)
            ok = self.mc_apply(act)
            applied.append(ok)
            if on_action is not None:
                on_action(i, act, ok)
        return applied


# ---------------------------------------------------------------------------
# Monitors
# ---------------------------------------------------------------------------


def admission_state_violations(sys: AdmissionSystem) -> List[Violation]:
    out: List[Violation] = []
    queued = sys._queued_counts()
    pend = [0] * len(sys.cfg.templates)
    for r in sys.pending:
        pend[r.template] += 1
    for k in range(len(sys.cfg.templates)):
        have = queued[k] + pend[k] + sys.dispatched[k] + sys.evicted[k]
        if have != sys.admitted[k]:
            out.append(Violation(
                "conservation", k,
                f"template {k}: admitted {sys.admitted[k]} != queued "
                f"{queued[k]} + pending {pend[k]} + dispatched "
                f"{sys.dispatched[k]} + evicted {sys.evicted[k]} — an "
                f"admitted vote was lost outside a counted reject"))
    bound = sys.cfg.starve_bound
    for k, ages in enumerate(sys.q_ages):
        for a in ages:
            if a > bound:
                out.append(Violation(
                    "starvation", k,
                    f"template {k}: queued record waited {a} pump "
                    f"ticks in-window (bound {bound})"))
                break
    for r in sys.pending:
        if sys._in_window(r.template) and r.age > bound:
            out.append(Violation(
                "starvation", r.template,
                f"template {r.template}: pending record waited "
                f"{r.age} pump ticks in-window (bound {bound})"))
            break
    if sys.bls_table is not None:
        # class-bucket conservation (ISSUE 10): every FOLDED share is
        # in exactly one of open-class / aggregate-dispatched /
        # fallback-dispatched / forged-dropped — read the open-class
        # counts from the REAL table's canonical rows, so a lossy fold
        # cannot vouch for itself
        open_counts = [0] * len(sys.cfg.bls_templates)
        for key, signers, _w in sys.bls_table.mc_canonical():
            inst, _h, _r, typ, _val = key
            for v in signers:
                k = sys._bls_key.get((inst, v, typ))
                if k is not None:
                    open_counts[k] += 1
        for k in range(len(sys.cfg.bls_templates)):
            have = (open_counts[k] + sys.bls_agg[k]
                    + sys.bls_fallback[k] + sys.bls_dropped[k])
            if have != sys.bls_folded[k]:
                out.append(Violation(
                    "conservation", k,
                    f"bls template {k}: folded {sys.bls_folded[k]} "
                    f"!= open {open_counts[k]} + aggregate "
                    f"{sys.bls_agg[k]} + fallback "
                    f"{sys.bls_fallback[k]} + dropped "
                    f"{sys.bls_dropped[k]} — a folded share was "
                    f"lost outside a counted path"))
    return out


def admission_edge_snapshot(sys: AdmissionSystem) -> tuple:
    return (len(sys.dispatch_log), len(sys.bls_dispatch_log))


def admission_edge_violations(sys: AdmissionSystem,
                              snap: tuple) -> List[Violation]:
    out: List[Violation] = []
    for kind, members, forged in sys.bls_dispatch_log[snap[1]:]:
        if kind == "agg" and forged:
            out.append(Violation(
                "purity", forged[0],
                f"aggregate dispatch carried a non-pairing-cleared "
                f"class (forged bls templates {sorted(forged)} "
                f"folded into the single aggregate lane)"))
    for P, signed, _counts, rows in sys.dispatch_log[snap[0]:]:
        if P not in (2, 3):
            out.append(Violation(
                "pbound", -1,
                f"dispatch with P={P} phases (entry + vote phases "
                f"outside the warmed {{2, 3}} set)"))
        if not signed:
            bad = [k for k, ver in rows if not ver]
            if bad:
                out.append(Violation(
                    "purity", bad[0],
                    f"unsigned (verify-free) dispatch carried "
                    f"non-cache-hit rows of templates {sorted(set(bad))}"))
    return out


def admission_domain() -> Domain:
    return Domain(
        enabled=lambda s: s.mc_enabled(),
        expandable=lambda s: True,
        state_violations=admission_state_violations,
        edge_snapshot=admission_edge_snapshot,
        edge_violations=admission_edge_violations,
        indep=lambda a, b: False,      # one shared queue: no POR
        near_miss=None,
        symmetry=None,
        codec=AdmissionSystem)


def explore_admission(cfg: AdmissionMCConfig,
                      system_cls: Optional[type] = None,
                      deadline_at: Optional[float] = None,
                      max_states: Optional[int] = None,
                      stop_on_violation: bool = True,
                      collect_digests: bool = False) -> Report:
    """Exhaustive DFS over `cfg`'s admission schedules — the same
    engine as the consensus scopes (`modelcheck._explore_domain`)."""
    root = (system_cls or AdmissionSystem)(cfg)
    return _explore_domain(
        root, cfg, admission_domain(), por=False,
        deadline_at=deadline_at, max_states=max_states,
        stop_on_violation=stop_on_violation,
        collect_digests=collect_digests)


# ---------------------------------------------------------------------------
# Replay + minimization + corpus
# ---------------------------------------------------------------------------


def run_admission_with_monitors(cfg: AdmissionMCConfig, actions,
                                system_cls: Optional[type] = None
                                ) -> Tuple[AdmissionSystem,
                                           List[Violation]]:
    """Deterministic replay with every monitor after every applied
    action — the reproduction predicate for ddmin and the corpus."""
    sys_ = (system_cls or AdmissionSystem)(cfg)
    viols: List[Violation] = list(admission_state_violations(sys_))
    snap = [admission_edge_snapshot(sys_)]

    def on_action(_i, _act, ok):
        if ok:
            viols.extend(admission_edge_violations(sys_, snap[0]))
            viols.extend(admission_state_violations(sys_))
        snap[0] = admission_edge_snapshot(sys_)

    sys_.run_schedule(actions, on_action=on_action)
    return sys_, viols


def admission_reproduces(cfg, actions, prop,
                         system_cls: Optional[type] = None) -> bool:
    _, viols = run_admission_with_monitors(cfg, actions, system_cls)
    return any(v.property == prop for v in viols)


def minimize_admission(cfg, actions, prop,
                       system_cls: Optional[type] = None) -> List[tuple]:
    return _ddmin(
        list(actions),
        lambda acts: admission_reproduces(cfg, acts, prop, system_cls))


def admission_corpus_entry(name: str, cfg: AdmissionMCConfig,
                           actions, origin: str) -> dict:
    """Corpus entry with the honest model's outcome stamped: the full
    dispatch log (P, signed, per-template counts) and the admission
    counters — the replay tests assert bit-stable behavior, and the
    serve-plane replay (tests/test_admission_mc.py) drives the REAL
    ServePipeline through the same schedule."""
    sys_, viols = run_admission_with_monitors(cfg, actions)
    return {
        "kind": "admission",
        "name": name,
        "origin": origin,
        "config": cfg.to_json(),
        "actions": [AdmissionSystem.action_to_json(tuple(a))
                    for a in actions],
        "expect": {
            "violations": sorted({v.property for v in viols}),
            "dispatches": [[p, s, list(c)]
                           for p, s, c, _rows in sys_.dispatch_log],
            "admitted": list(sys_.admitted),
            "dispatched": list(sys_.dispatched),
            "evicted": list(sys_.evicted),
            "queue_counters": {k: int(v)
                               for k, v in sys_.queue.counters.items()},
            "cache_hits": (0 if sys_.cache is None
                           else sys_.cache.counters["hits"]),
            "bls_dispatches": [[kind, list(m), list(f)]
                               for kind, m, f
                               in sys_.bls_dispatch_log],
            "bls_folded": list(sys_.bls_folded),
            "bls_table_counters": (
                {} if sys_.bls_table is None
                else {k: int(v) for k, v
                      in sys_.bls_table.counters.items()}),
        },
    }


def replay_admission_entry(entry: dict) -> Tuple[AdmissionSystem,
                                                 List[Violation]]:
    cfg = AdmissionMCConfig.from_json(entry["config"])
    sys_, viols = run_admission_with_monitors(cfg, entry["actions"])
    exp = entry["expect"]
    got = [[p, s, list(c)] for p, s, c, _r in sys_.dispatch_log]
    assert got == exp["dispatches"], (
        f"{entry['name']}: dispatch log diverged")
    assert list(sys_.admitted) == exp["admitted"], entry["name"]
    assert list(sys_.dispatched) == exp["dispatched"], entry["name"]
    assert list(sys_.evicted) == exp["evicted"], entry["name"]
    assert {k: int(v) for k, v in sys_.queue.counters.items()} \
        == exp["queue_counters"], entry["name"]
    got_bls = [[k, list(m), list(f)]
               for k, m, f in sys_.bls_dispatch_log]
    assert got_bls == exp.get("bls_dispatches", []), (
        f"{entry['name']}: bls dispatch log diverged")
    assert list(sys_.bls_folded) == exp.get("bls_folded", []), \
        entry["name"]
    if sys_.bls_table is not None:
        assert {k: int(v)
                for k, v in sys_.bls_table.counters.items()} \
            == exp["bls_table_counters"], entry["name"]
    assert sorted({v.property for v in viols}) == exp["violations"], (
        f"{entry['name']}: property verdicts diverged")
    return sys_, viols


# ---------------------------------------------------------------------------
# Mutation self-test: doctored stages the monitors MUST catch
# ---------------------------------------------------------------------------


class _LossyDrainQueue(AdmissionQueue):
    """Doctored: drain sheds the LAST drained record without counting
    it anywhere — the classic off-by-one at a split boundary."""

    def drain(self, max_records=None):
        batch = super().drain(max_records)
        if batch is None or len(batch) == 0:
            return batch
        return type(batch)(*[c[:-1] for c in batch[:8]],
                           digest=(None if batch.digest is None
                                   else batch.digest[:-1]),
                           t_first=batch.t_first)


class _LifoDrainQueue(AdmissionQueue):
    """Doctored: drains NEWEST records first — under sustained load
    the oldest admitted record waits forever (starvation).  Builds
    fresh reversed chunks rather than mutating the deque's (chunk
    objects are shared across mc_clone branches)."""

    def _reversed(self):
        import collections

        from agnes_tpu.serve.queue import _Chunk

        return collections.deque(
            _Chunk(tuple(col[::-1] for col in c.cols),
                   None if c.dig is None else c.dig[::-1], c.ts)
            for c in reversed(self._chunks))

    def _pop(self, n, count_drained=True):
        self._chunks = self._reversed()
        out = super()._pop(n, count_drained)
        self._chunks = self._reversed()
        return out


class _UnchunkedSystem(AdmissionSystem):
    """Doctored: preverified builds are NOT chunked — a cache-hit
    burst spanning 3+ (round, class) groups dispatches P >= 4, an
    unwarmed shape (live compile stall in production)."""

    preverified_chunk = 99


class _TaintSplitSystem(AdmissionSystem):
    """Doctored: when ANY pending row is a cache hit, the whole batch
    rides the unsigned build — fresh rows skip verification."""

    def _split(self, rows):
        if self.cache is not None and any(r.verified for r in rows):
            return list(rows), []
        return super()._split(rows)


class _LossySystem(AdmissionSystem):
    queue_cls = _LossyDrainQueue


class _LifoSystem(AdmissionSystem):
    queue_cls = _LifoDrainQueue


def _lossy_fold_table_cls():
    """Doctored BlsClassTable built lazily (the serve import stays off
    the module's import path for the jax-free gate slot): once a class
    holds two shares, fold() silently drops the highest-validator one
    — counters untouched, the classic lost-update under the leaf
    mutex.  Caught by the class-bucket conservation monitor."""
    from agnes_tpu.serve.bls_lane import BlsClassTable

    class _LossyFoldTable(BlsClassTable):
        def fold(self, wire_bytes, decode: bool = True) -> dict:
            res = super().fold(wire_bytes, decode)
            if res["folded"]:
                with self._mu:
                    for cls in self.classes.values():
                        if cls.n_signers >= 2:
                            v = max(cls.shares)
                            del cls.shares[v]
                            cls.signers[v] = False
                            cls.weight -= int(self.registry.powers[v])
                            break
            return res

    return _LossyFoldTable


class _LossyBlsFoldSystem(AdmissionSystem):
    @property
    def bls_table_cls(self):
        return _lossy_fold_table_cls()


class _PairingBlindSystem(AdmissionSystem):
    """Doctored: skips the per-class pairing verdict — forged shares
    ride the single aggregate lane with the class's combined weight.
    Caught by the aggregate-purity edge monitor."""

    bls_pairing_blind = True


#: mutant name -> (system class, property caught by, config)
ADMISSION_MUTANTS: Dict[str, tuple] = {
    "lose_drained_record": (
        _LossySystem, "conservation",
        AdmissionMCConfig(name="mut_lossy", depth=4, max_copies=2,
                          target=2)),
    "starve_oldest_record": (
        _LifoSystem, "starvation",
        # DISTINCT templates (max_copies=1) make every record
        # identifiable, so the fungible-copy FIFO-optimal age
        # assumption (AdmissionSystem._pump) cannot mask the
        # reordering.  capacity/target = 3 < starve_bound = 4, so an
        # HONEST FIFO drain can never violate — only the newest-first
        # mutant can, by draining each freshly-submitted flooder while
        # the first-admitted victim's age climbs past the bound
        AdmissionMCConfig(name="mut_lifo", depth=13, target=1,
                          capacity=3, max_copies=1, starve_bound=4,
                          templates=((1, 6, 0, 0), (0, 0, 0, 0),
                                     (0, 1, 0, 0), (0, 2, 0, 0),
                                     (0, 3, 0, 0), (0, 4, 0, 0),
                                     (0, 5, 0, 0)))),
    "unchunked_preverified_build": (
        _UnchunkedSystem, "pbound",
        AdmissionMCConfig(name="mut_unchunked", depth=13, target=4,
                          max_rung=8, max_copies=2,
                          templates=((0, 0, 0, 0), (0, 1, 0, 1),
                                     (0, 2, 1, 0)))),
    "taint_split_fresh_rides_unsigned": (
        _TaintSplitSystem, "purity",
        AdmissionMCConfig(name="mut_taint", depth=8, target=2,
                          max_copies=2,
                          templates=((0, 0, 0, 0), (1, 1, 0, 0)))),
    # ISSUE 10: a fold that loses a share out of an open class bucket
    # without counting it anywhere — caught by the class-bucket
    # conservation monitor (folded == open + agg + fallback + dropped)
    "lossy_bls_fold": (
        _LossyBlsFoldSystem, "conservation",
        AdmissionMCConfig(name="mut_bls_lossy", depth=5, max_copies=1,
                          templates=((0, 0, 0, 0),), bls=True,
                          bls_templates=((0, 0, 0), (0, 1, 0),
                                         (0, 2, 0)),
                          bls_target=3)),
    # ISSUE 10: a close that "clears" a class without the pairing —
    # forged shares folded into the one aggregate lane (purity)
    "pairing_blind_aggregate": (
        _PairingBlindSystem, "purity",
        AdmissionMCConfig(name="mut_bls_blind", depth=4, max_copies=1,
                          templates=((0, 0, 0, 0),), bls=True,
                          bls_templates=((0, 0, 0), (0, 1, 0)),
                          bls_target=2, bls_forged=(1,))),
}


def self_test_admission() -> dict:
    """Each doctored stage must be caught, its counterexample must
    ddmin-minimize, and the minimized schedule must run CLEAN on the
    honest model (the violation is the mutation's, not the model's)."""
    out = {}
    for name, (sys_cls, prop, cfg) in ADMISSION_MUTANTS.items():
        rep = explore_admission(cfg, system_cls=sys_cls)
        caught = [c for c in rep.violations
                  if c.violation.property == prop]
        assert caught, (
            f"admission mutant {name}: no {prop} violation in "
            f"{rep.states} states")
        ce = caught[0]
        ce.minimized = minimize_admission(cfg, ce.schedule, prop,
                                          system_cls=sys_cls)
        assert admission_reproduces(cfg, ce.minimized, prop,
                                    system_cls=sys_cls)
        _, honest = run_admission_with_monitors(cfg, ce.minimized)
        assert not honest, (
            f"admission mutant {name}: minimized schedule also "
            f"violates on the honest model: {honest}")
        out[name] = {
            "property": prop,
            "states_to_detection": rep.states,
            "schedule_len": len(ce.schedule),
            "minimized_len": len(ce.minimized),
            "counterexample": ce.to_json(),
        }
    return out


# ---------------------------------------------------------------------------
# Corpus emission (tests/corpus/admission/*.json)
# ---------------------------------------------------------------------------

#: hand-written milestone schedules (deterministic coverage witnesses
#: the serve-plane replay test drives through the REAL pipeline):
#: name -> (config, schedule, post-condition on the honest model)
ADMISSION_MILESTONES: Dict[str, tuple] = {}


def _register_milestones() -> None:
    cfg = ADMISSION_SMOKE[0]
    ADMISSION_MILESTONES["adm_dedup_roundtrip"] = (
        cfg,
        # fresh dispatch -> settle caches digests -> identical bytes
        # re-admit pre-verified -> unsigned (verify-free) dispatch
        [("s", 0), ("s", 1), ("b",), ("v",),
         ("s", 0), ("s", 1), ("b",)],
        lambda s: any(not signed
                      for _p, signed, _c, _r in s.dispatch_log))
    ADMISSION_MILESTONES["adm_held_window_flush"] = (
        cfg,
        # a future-round record holds through a pump, re-enters on the
        # window advance, and dispatches on the next tick
        [("s", 3), ("b",), ("w",), ("b",)],
        lambda s: s.dispatched[3] == 1)
# (called at module bottom — the milestone configs live in the scope
# tables defined below)


def emit_admission_corpus(directory: str,
                          include_mutants: bool = True) -> List[str]:
    """(Re)generate the admission regression corpus: the milestone
    schedules plus each admission mutant's minimized counterexample
    (stamped with the HONEST model's outcome — clean, like the
    consensus mutant corpus).  Deterministic."""
    import json
    import os

    os.makedirs(directory, exist_ok=True)
    written = []
    for name, (cfg, sched, check) in ADMISSION_MILESTONES.items():
        sys_, viols = run_admission_with_monitors(cfg, sched)
        assert not viols, (name, viols)
        assert check(sys_), f"milestone {name} post-condition failed"
        entry = admission_corpus_entry(
            name, cfg, sched, origin="hand-written milestone")
        path = os.path.join(directory, f"{name}.json")
        with open(path, "w") as f:
            json.dump(entry, f, indent=1, sort_keys=True)
            f.write("\n")
        written.append(path)
    if include_mutants:
        for mname, r in self_test_admission().items():
            ce = r["counterexample"]
            cfg = AdmissionMCConfig.from_json(ce["config"])
            acts = [AdmissionSystem.action_from_json(a)
                    for a in ce["schedule"]]
            entry = admission_corpus_entry(
                f"adm_mut_{mname}", cfg, acts,
                origin=f"minimized {mname} admission-mutant "
                       f"counterexample (honest replay: clean)")
            path = os.path.join(directory, f"adm_mut_{mname}.json")
            with open(path, "w") as f:
                json.dump(entry, f, indent=1, sort_keys=True)
                f.write("\n")
            written.append(path)
    return written


# ---------------------------------------------------------------------------
# Scopes (aggregated into the modelcheck CLI/gate by run_scope)
# ---------------------------------------------------------------------------

ADMISSION_TINY: Tuple[AdmissionMCConfig, ...] = (
    AdmissionMCConfig(name="adm_tiny", depth=8, max_copies=2,
                      templates=((0, 0, 0, 0), (1, 1, 0, 0),
                                 (1, 2, 1, 0))),
)

#: sized for the 2-CPU gate box: ~210k distinct states, ~90s
#: sequential (dedup_window ~143k/60s is the flagship; the other two
#: shards are ~30-37k each)
ADMISSION_SMOKE: Tuple[AdmissionMCConfig, ...] = (
    # the full alphabet: both instances, both vote classes, a held
    # future-round template, dedup on — fairness + split + window
    AdmissionMCConfig(name="adm_dedup_window", depth=9),
    # dedup OFF + drop_oldest under a tight capacity: the overload
    # policies' conservation story without the cache in the state
    AdmissionMCConfig(name="adm_drop_oldest", depth=14, dedup=False,
                      capacity=4, policy="drop_oldest", max_copies=3,
                      templates=((0, 0, 0, 0), (0, 1, 0, 1),
                                 (1, 2, 0, 0))),
    # fairness cap pressure: one instance may hold at most 2 slots,
    # the other instance's records must still flow (starvation)
    AdmissionMCConfig(name="adm_fairness_cap", depth=10, capacity=4,
                      instance_cap=2, max_copies=2,
                      templates=((0, 0, 0, 0), (0, 1, 0, 0),
                                 (1, 2, 0, 0))),
    # ISSUE 10: BLS class buckets beside the record queue — both vote
    # classes fold, validator 2's shares fail the (modeled) pairing so
    # the prevote class exercises the per-share fallback split, and
    # validator 3 has no proof of possession (folds rejected, counted)
    AdmissionMCConfig(name="adm_bls_classes", depth=10, max_copies=2,
                      templates=((0, 0, 0, 0),), bls=True,
                      bls_templates=((0, 0, 0), (0, 1, 0), (0, 2, 0),
                                     (0, 1, 1), (0, 3, 1)),
                      bls_target=3, bls_max_classes=2,
                      bls_forged=(2,), bls_no_pop=(3,)),
)

ADMISSION_SCOPES = {"tiny": ADMISSION_TINY, "smoke": ADMISSION_SMOKE,
                    "full": ADMISSION_SMOKE}

_register_milestones()
