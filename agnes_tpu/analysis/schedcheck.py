"""Deterministic interleaving explorer for the threaded serve host.

Every real-thread race this repo has shipped — the Inbox close/submit
TOCTOU (PR 3), the native drain shrinkage clamp (PR 14 review-fix),
the busy-frac in-flight attribution bug (PR 14 riders) — was found by
hand review.  lockcheck proves lock ORDER statically and the model
checkers exhaust MODELED schedules; nothing exercised the real
`ThreadedVoteService` loops under controlled interleavings.  This
module does: it runs the REAL host code (`ThreadedVoteService`,
`Inbox`, `AdmissionQueue`, `MicroBatcher`, `VerifiedCache`) on real OS
threads under a cooperative turnstile scheduler that keeps EXACTLY ONE
thread runnable, hands control over only at announced yield points,
and explores the resulting schedule tree exhaustively under CHESS-
style iterative preemption bounding with sleep-set pruning.

Yield points (serialized scheduling choices):
  * lock acquire/release — the existing `InstrumentedLock` seam
    (analysis/lockcheck.py) generalized: `SchedLock` subclasses it,
    overriding the `_raw_acquire`/`_raw_release`/`_sched_point` hooks
    while reusing its order bookkeeping, and the lock SET comes from
    `lockcheck.LOCK_REGISTRY`
  * inbox put/get — through the inbox mutex + a cooperative Condition
  * condition waits — timeout wake-ups are scheduling choices,
    budgeted one per global progress version per thread so idle loops
    cannot spin the schedule space unboundedly
  * native ctypes call boundaries — the GIL-release span a native
    admission queue's submit/drain would release the GIL for, modeled
    by `_NativeQueue` around a real AdmissionQueue
  * clock reads — `SchedClock` advances a fixed logical tick per read

Soundness model: between two announced points the running thread
executes alone (everything else is parked on its semaphore), so each
quantum is atomic and every shared-memory interaction is mediated by
an announced (kind, resource) pair.  Two pending operations are
independent iff their resources differ, which makes the sleep-set
pruning sound: a pruned sibling's subtree is covered by the commuted
order already explored.  `--no-sleep-sets` re-runs the full tree; the
test suite asserts terminal-state equality between the two on a small
config.

Monitors (violations, not asserts — every run completes and reports):
  conservation  inbox residue after drain, enqueued != submitted,
                claimed drained votes != the queue's drained counter
  deadlock      no thread enabled while some are live (includes the
                budget-exhausted idle livelock: a host that never
                quiesces)
  lock-order    lockcheck.LockOrderState promoted from test seam to
                checker monitor (strict=False: record, explore on)
  atomicity     `# schedcheck: atomic` spans (ATOMIC_SPANS): an
                announced read/write on a guarded resource while
                another thread holds its guard lock
  gauge sanity  busy-frac gauges above 1.0 (the clamp + in-flight
                attribution contract)

Proof-of-bite: the three historical races are re-introduced as
MUTANTS, caught by exploration, ddmin-minimized to a replayable
thread schedule (modelcheck._ddmin over the choice list; replay skips
forced choices whose thread is not enabled), and the minimized
schedule replays CLEAN on the honest build.

Caveats (the README section states them): the preemption bound is a
bug-finding bound, not a proof over all schedules; only Python-
visible yield points are serialized — the C++ `ag_*` spans release
the GIL and race internally, which is why ci.sh runs the separate
ThreadSanitizer stress lane over admission.cpp/ingest.cpp; and the
cooperative quantum is COARSER than real instruction interleaving
(races inside one lock-protected section are invisible — but such a
section is exactly what the lock already makes atomic).

Jax-free at import, zero XLA compiles — dispatch is registry-stubbed
(`_SchedService` counts votes instead of running a pipeline), the
pattern every checker here uses.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from agnes_tpu.analysis import lockcheck
from agnes_tpu.analysis.modelcheck import _ddmin
from agnes_tpu.bridge.native_ingest import REC_SIZE, pack_wire_votes
from agnes_tpu.serve.batcher import MicroBatcher, ShapeLadder
from agnes_tpu.serve.cache import VerifiedCache
from agnes_tpu.serve.queue import (
    AdmissionQueue,
    AdmitResult,
    DROP_OLDEST,
    Inbox,
    REJECT_NEWEST,
)
from agnes_tpu.serve.threaded import ThreadedVoteService
from agnes_tpu.utils.metrics import (
    Metrics,
    SCHEDCHECK_SCHEDULES_EXPLORED,
    SCHEDCHECK_VIOLATIONS,
    SERVE_DISPATCH_BUSY_FRAC,
    SERVE_SUBMIT_BUSY_FRAC,
)

#: `# schedcheck: atomic` spans — (file, qualified function) -> the
#: guarded resource.  The comment in the source and this registry are
#: cross-checked by check_atomic_annotations() (and its test), so the
#: annotation cannot rot silently in either direction.  At runtime the
#: guard is enforced via RESOURCE_GUARDS: an announced read/write on
#: the resource while ANOTHER thread holds the guard lock is an
#: atomicity violation (honest code only touches these under the
#: lock; the announce IS the instrumentation of a mutant's unlocked
#: access).
ATOMIC_SPANS: Dict[Tuple[str, str], str] = {
    ("agnes_tpu/serve/queue.py", "Inbox.put"): "inbox",
    ("agnes_tpu/serve/queue.py", "Inbox.close"): "inbox",
    ("agnes_tpu/serve/queue.py", "Inbox.get"): "inbox",
    ("agnes_tpu/serve/threaded.py", "ThreadedVoteService.drain"):
        "inbox",
}

ATOMIC_MARKER = "# schedcheck: atomic"


class _ThreadStop(BaseException):
    """Raised inside a controlled thread at its next yield point when
    the scheduler unwinds a run (deadlock / truncation).  BaseException
    so `except Exception` in exercised code cannot swallow it; the
    host's deliberate `except BaseException` containment CAN catch it,
    but its containment path hits another yield point (inbox.close)
    and re-raises — the unwind always completes."""


class _TCB:
    """Per-thread control block of the turnstile scheduler."""

    __slots__ = ("tid", "name", "sem", "started", "done", "block",
                 "pending", "notified", "last_spin_ver", "error")

    def __init__(self, tid: int, name: str):
        self.tid = tid
        self.name = name
        self.sem = threading.Semaphore(0)
        self.started = False
        self.done = False
        self.block = None            # None = runnable at `pending`
        self.pending = ("start", None)
        self.notified = False
        self.last_spin_ver = -1      # idle-wake budget (progress-gated)
        self.error: Optional[BaseException] = None


@dataclass
class Violation:
    kind: str
    detail: str
    step: int


@dataclass
class Decision:
    """One recorded scheduling choice (only points with >1 enabled
    thread are choices — single-enabled steps are deterministic)."""

    enabled: Tuple[int, ...]
    chosen: int
    running: Optional[int]          # thread granted the prior quantum
    preempts_before: int
    pending: Dict[int, Optional[str]]   # tid -> announced resource


@dataclass
class RunResult:
    choices: List[int]
    decisions: List[Decision]
    violations: List[Violation]
    digest: tuple = ()
    trace: List[tuple] = field(default_factory=list)
    steps: int = 0
    truncated: bool = False
    completed: bool = False


class Scheduler:
    """Cooperative turnstile: the scheduler thread and every worker
    share a baton — exactly one is ever runnable.  Workers announce
    (kind, resource) and park on their semaphore; the scheduler picks
    the next thread (forced prefix, then continue-current default),
    counts preemptions, and records every multi-choice decision for
    the explorer."""

    def __init__(self, forced: Sequence[int] = (),
                 preemption_bound: int = 2, max_steps: int = 20000):
        self.forced = list(forced)
        self._forced_i = 0
        self.preemption_bound = preemption_bound
        self.max_steps = max_steps
        self.tcbs: Dict[int, _TCB] = {}
        self._ident: Dict[int, _TCB] = {}
        self._main_sem = threading.Semaphore(0)
        self._poison = False
        self.running: Optional[int] = None
        self.progress_ver = 0
        self.preemptions = 0
        self.steps = 0
        self.trace: List[tuple] = []
        self.decisions: List[Decision] = []
        self.choices: List[int] = []
        self.violations: List[Violation] = []
        self.truncated = False
        self._guards: Dict[str, "SchedLock"] = {}

    # -- worker-side API ------------------------------------------------------

    def _cur(self) -> _TCB:
        try:
            return self._ident[threading.get_ident()]
        except KeyError:
            raise RuntimeError(
                "SchedPoint reached outside a controlled thread")

    def _yield(self, tcb: _TCB, kind: str, resource, block) -> None:
        if self._poison:
            raise _ThreadStop()
        tcb.pending = (kind, resource)
        tcb.block = block
        # turnstile handoff: release one semaphore, park on another —
        # structurally not a with-block pair
        self._main_sem.release()  # lockcheck: allow (turnstile handoff)
        tcb.sem.acquire()  # lockcheck: allow (turnstile park)
        if self._poison:
            raise _ThreadStop()

    def point(self, kind: str, resource: Optional[str] = None) -> None:
        """Announce-and-yield: the next shared-memory operation of the
        calling thread is (kind, resource); control returns when the
        scheduler grants the quantum."""
        self._yield(self._cur(), kind, resource, None)

    def sleep(self, seconds: float) -> None:  # noqa: ARG002 — logical
        """The host's idle nap: blocks until the next global progress
        version (budgeted — without the gate an idle loop would admit
        unboundedly many no-op wake orderings)."""
        self._yield(self._cur(), "sleep", None, ("sleep",))

    def progress(self) -> None:
        """Bump the global progress version: new work exists, so every
        idle thread earns one more timeout wake-up."""
        self.progress_ver += 1

    def record_violation(self, kind: str, detail: str) -> None:
        self.violations.append(Violation(kind, detail, self.steps))

    def register_guard(self, resource: str, lock: "SchedLock") -> None:
        self._guards[resource] = lock

    def thread_factory(self, target=None, name=None, daemon=True,
                       args=(), kwargs=None) -> "SchedThread":
        return SchedThread(self, target=target, name=name,
                           daemon=daemon, args=args, kwargs=kwargs)

    # -- lock / condition protocol -------------------------------------------

    def lock_acquire(self, lock: "SchedLock") -> None:
        tcb = self._cur()
        self._yield(tcb, "lock", lock.resource, ("lock", lock))
        lock.owner = tcb.tid        # granted only when free

    def lock_release(self, lock: "SchedLock") -> None:
        if self._poison:            # unwind path: just free it
            lock.owner = None
            return
        tcb = self._cur()
        self._yield(tcb, "unlock", lock.resource, None)
        lock.owner = None

    def cond_wait(self, cond: "SchedCondition", can_timeout: bool
                  ) -> None:
        tcb = self._cur()
        lock = cond.lock
        # release is atomic with starting to wait (real Condition
        # semantics) — it only ENABLES others, so performing it before
        # the yield keeps the announce-before-perform invariant for
        # every state-READING operation
        lock.state.stack().remove((lock.name, lock.rank))
        lock.owner = None
        cond.waiters.append(tcb.tid)
        self._yield(tcb, "cond_wait", lock.resource,
                    ("cond", cond, can_timeout))
        lock.__enter__()            # cooperative reacquire

    def cond_notify(self, cond: "SchedCondition",
                    n: Optional[int] = None) -> None:
        for tid in (cond.waiters if n is None else cond.waiters[:n]):
            self.tcbs[tid].notified = True
        self.progress()

    def join(self, target: _TCB) -> None:
        if target.done or not target.started:
            return
        # modeled UNTIMED (drain's join timeout never fires): a stuck
        # thread surfaces as the deadlock monitor, not as a spurious
        # TimeoutError no real-time bound justifies under logical time
        self._yield(self._cur(), "join", f"join:{target.name}",
                    ("join", target))

    # -- scheduler side -------------------------------------------------------

    def _enabled(self, tcb: _TCB) -> bool:
        if not tcb.started or tcb.done:
            return False
        b = tcb.block
        if b is None:
            return True
        if b[0] == "lock":
            return b[1].owner is None
        if b[0] == "cond":
            return tcb.notified or (
                b[2] and self.progress_ver > tcb.last_spin_ver)
        if b[0] == "sleep":
            return self.progress_ver > tcb.last_spin_ver
        if b[0] == "join":
            return b[1].done
        raise AssertionError(f"unknown block {b!r}")

    def _choose(self, enabled: List[int]) -> int:
        while self._forced_i < len(self.forced):
            want = self.forced[self._forced_i]
            self._forced_i += 1
            if want in enabled:
                return want
            # ddmin replay: a forced choice whose thread is not
            # enabled here is SKIPPED — keeps every subset of a
            # schedule well-defined (the modelcheck replay contract)
        if self.running in enabled:
            return self.running     # continue-current default
        return min(enabled)

    def _grant(self, tid: int) -> None:
        tcb = self.tcbs[tid]
        b = tcb.block
        if b is not None:
            if b[0] == "cond":
                cond = b[1]
                if tcb.notified:
                    tcb.notified = False
                else:
                    tcb.last_spin_ver = self.progress_ver
                if tid in cond.waiters:
                    cond.waiters.remove(tid)
            elif b[0] == "sleep":
                tcb.last_spin_ver = self.progress_ver
            elif b[0] == "lock":
                b[1].owner = tid    # ownership fixed AT grant
        tcb.block = None
        kind, resource = tcb.pending
        if kind in ("read", "write"):
            guard = self._guards.get(resource)
            if guard is not None and guard.owner not in (None, tid):
                self.record_violation(
                    "atomicity",
                    f"{tcb.name} {kind}s {resource!r} while "
                    f"{self.tcbs[guard.owner].name} holds "
                    f"{guard.name!r} (# schedcheck: atomic span)")
        self.trace.append((tid, kind, resource))
        self.running = tid
        tcb.sem.release()  # lockcheck: allow (grant the quantum)
        self._main_sem.acquire()  # until its next yield  # lockcheck: allow

    def run(self, driver: Callable[[], None]) -> str:
        """Run `driver` in a controlled thread to completion of ALL
        threads; returns 'done' | 'deadlock' | 'truncated'."""
        d = self.thread_factory(target=driver, name="driver")
        d.start()
        outcome = "done"
        while True:
            live = [t for t in self.tcbs.values()
                    if t.started and not t.done]
            if not live:
                break
            enabled = sorted(t.tid for t in live if self._enabled(t))
            if not enabled:
                blocked = ", ".join(
                    f"{t.name}@{t.pending[0]}:{t.pending[1]}"
                    for t in live)
                self.record_violation(
                    "deadlock",
                    f"no thread enabled; live threads blocked at "
                    f"[{blocked}]")
                outcome = "deadlock"
                break
            if self.steps >= self.max_steps:
                self.truncated = True
                outcome = "truncated"
                break
            self.steps += 1
            if len(enabled) > 1:
                chosen = self._choose(enabled)
                if (self.running is not None
                        and self.running in enabled
                        and chosen != self.running):
                    pre = self.preemptions
                    self.preemptions += 1
                else:
                    pre = self.preemptions
                self.decisions.append(Decision(
                    enabled=tuple(enabled), chosen=chosen,
                    running=self.running,
                    preempts_before=pre,
                    pending={t: self.tcbs[t].pending[1]
                             for t in enabled}))
                self.choices.append(chosen)
            else:
                chosen = enabled[0]
            self._grant(chosen)
        if outcome != "done":
            self._unwind()
        for t in self.tcbs.values():
            if t.error is not None and not self._poison:
                self.record_violation(
                    "exception", f"{t.name}: {t.error!r}")
        return outcome

    def _unwind(self) -> None:
        """Poison every yield point and walk each live thread to
        completion — they raise _ThreadStop at their next wake and
        unwind through the real code's finally blocks."""
        self._poison = True
        for tcb in self.tcbs.values():
            while tcb.started and not tcb.done:
                tcb.sem.release()  # lockcheck: allow (poison wake)
                self._main_sem.acquire()  # lockcheck: allow (turnstile)


class SchedThread:
    """threading.Thread look-alike the host builds via its
    `thread_factory` seam; every lifecycle edge goes through the
    scheduler."""

    def __init__(self, sched: Scheduler, target=None, name=None,
                 daemon=True, args=(), kwargs=None):  # noqa: ARG002
        self.sched = sched
        tid = len(sched.tcbs)
        self.name = name or f"sched-{tid}"
        self.tcb = _TCB(tid, self.name)
        sched.tcbs[tid] = self.tcb
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        self._os = threading.Thread(
            target=self._run, name=self.name,
            daemon=True)  # lint: allow-thread (scheduler turnstile: workers park on semaphores, unwound via _ThreadStop)

    def start(self) -> None:
        self.tcb.started = True
        self._os.start()

    def is_alive(self) -> bool:
        return self.tcb.started and not self.tcb.done

    def join(self, timeout=None) -> None:  # noqa: ARG002 — untimed
        self.sched.join(self.tcb)

    def _run(self) -> None:
        tcb = self.tcb
        self.sched._ident[threading.get_ident()] = tcb
        tcb.sem.acquire()  # wait for the first grant  # lockcheck: allow
        try:
            if not self.sched._poison and self._target is not None:
                self._target(*self._args, **self._kwargs)
        except _ThreadStop:
            pass
        except BaseException as e:  # noqa: BLE001 — surfaced as violation
            tcb.error = e
        finally:
            tcb.done = True
            self.sched.progress()   # joiners + idle budgets advance
            self.sched._main_sem.release()  # lockcheck: allow (exit)


class SchedLock(lockcheck.InstrumentedLock):
    """InstrumentedLock with the SchedPoint hooks overridden: acquire
    and release are announced, explorable yield points; the order
    bookkeeping (LockOrderState) is inherited verbatim and becomes the
    checker's runtime lock-order monitor (strict=False)."""

    def __init__(self, sched: Scheduler, name: str, rank: int,
                 state: lockcheck.LockOrderState, strict: bool = False,
                 resource: Optional[str] = None):
        super().__init__(name, rank, state, strict=False)
        self.sched = sched
        self.resource = resource if resource is not None else name
        self.owner: Optional[int] = None

    def _raw_acquire(self) -> None:
        self.sched.lock_acquire(self)

    def _raw_release(self) -> None:
        self.sched.lock_release(self)


class SchedCondition:
    """Cooperative stand-in for threading.Condition(lock): wait_for is
    a blocking yield whose timeout wake-up is a budgeted scheduling
    choice; notify marks waiters wakeable."""

    def __init__(self, sched: Scheduler, lock: SchedLock, name: str):
        self.sched = sched
        self.lock = lock
        self.name = name
        self.waiters: List[int] = []

    def __enter__(self):
        self.lock.__enter__()
        return self

    def __exit__(self, *exc):
        return self.lock.__exit__(*exc)

    def wait_for(self, pred, timeout: Optional[float] = None) -> bool:
        if pred():
            return True
        if timeout is not None and timeout <= 0:
            return False
        while True:
            try:
                self.sched.cond_wait(self,
                                     can_timeout=timeout is not None)
            except _ThreadStop:
                # unwind mid-wait: cond_wait released the lock and
                # never reacquired — rebalance the order stack so the
                # enclosing `with cond:` __exit__ stays well-formed
                self.lock.state.stack().append(
                    (self.lock.name, self.lock.rank))
                raise
            if pred():
                return True
            if timeout is not None:
                # modeled timeout fire (real code may still have
                # budget left — a superset of real timings, which the
                # caller's None-return path must tolerate anyway)
                return False

    def notify(self, n: int = 1) -> None:
        self.sched.cond_notify(self, n)

    def notify_all(self) -> None:
        self.sched.cond_notify(self, None)


class SchedClock:
    """Logical clock: every read is an announced yield point and
    advances a fixed tick.  `resource=None` marks reads independent
    (sound whenever control flow does not branch on clock VALUES —
    the honest scopes pin max_delay_s=0 and a huge gauge interval to
    guarantee that); the busy-frac scenario sets 'clock' so sample
    windows interleave."""

    def __init__(self, sched: Scheduler, tick_s: float = 0.02,
                 resource: Optional[str] = None):
        self.sched = sched
        self.tick_s = tick_s
        self.resource = resource
        self.t = 0.0

    def __call__(self) -> float:
        self.sched.point("clock", self.resource)
        self.t += self.tick_s
        return self.t


class _SchedEvent:
    """threading.Event stand-in whose set() is a progress edge (stop
    must refresh every idle thread's wake budget or the loops could
    never observe it)."""

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self._flag = False

    def set(self) -> None:
        self._flag = True
        self.sched.progress()

    def is_set(self) -> bool:
        return self._flag


# ---------------------------------------------------------------------------
# The system under test: real host + registry-stubbed dispatch
# ---------------------------------------------------------------------------


class _StubPipeline:
    def __init__(self):
        self._staged: List = []


class _SchedService:
    """VoteService stand-in: REAL AdmissionQueue + MicroBatcher (+
    VerifiedCache) inside, dispatch registry-stubbed to a vote counter
    — zero XLA compiles, same duck surface the threaded host touches
    (tracer/flightrec/bls/pipeline/metrics/queue/micro)."""

    def __init__(self, queue, micro, metrics: Metrics,
                 sched: Scheduler):
        self.queue = queue
        self.micro = micro
        self.metrics = metrics
        self.sched = sched
        self.tracer = None
        self.flightrec = None
        self.bls = None
        self.pipeline = _StubPipeline()
        self.blobs_submitted = 0
        self.votes_drained = 0

    def submit(self, wire_bytes):
        res = self.queue.submit(wire_bytes)
        self.blobs_submitted += 1
        self.sched.progress()       # dispatch's idle nap may now close
        return res

    def _close_batch(self):
        return self.micro.poll()

    def _pump_batch(self, batch) -> None:
        if batch is not None:
            self.votes_drained += len(batch)

    def poll_decisions(self) -> List:
        return []

    def drain(self) -> dict:
        while True:
            batch = self.micro.flush()
            if batch is None:
                break
            self.votes_drained += len(batch)
        return {"metrics": self.metrics.snapshot()}


class _NativeQueue:
    """The ISSUE-14 native admission handle, modeled: wraps a REAL
    AdmissionQueue, reports native=True (the host elides its admission
    lock — the production shape), and announces every call boundary as
    a 'native' SchedPoint: the GIL-release span the Python scheduler
    cannot see into.  The inner call itself is one atomic quantum —
    the real handle's mutex gives exactly that."""

    native = True

    def __init__(self, inner: AdmissionQueue, sched: Scheduler):
        self.inner = inner
        self.sched = sched

    @property
    def depth(self):
        return self.inner.depth

    @property
    def oldest_ts(self):
        return self.inner.oldest_ts

    @property
    def counters(self):
        return self.inner.counters

    @property
    def cache(self):
        return self.inner.cache

    def submit(self, wire_bytes):
        self.sched.point("native", "queue")
        return self.inner.submit(wire_bytes)

    def drain(self, max_records=None):
        self.sched.point("native", "queue")
        return self.inner.drain(max_records)


class _PaddedBatch:
    """What the pre-review-fix drain produced under shrinkage: a batch
    CLAIMING n0 records while holding fewer real ones (the tail rows
    were uninitialized memory)."""

    def __init__(self, cols, claimed: int):
        self.cols = cols
        self.claimed = claimed

    def __len__(self) -> int:
        return self.claimed


class _ShrinkDrainQueue(_NativeQueue):
    """[mutant: native_drain_shrink] the PR 14 pre-review-fix drain:
    batch sized from an UNLOCKED depth read BEFORE the native call
    instead of from the native return value.  A concurrent drain (the
    handle's documented contract — the dispatch loop racing a raw
    drainer) shrinks the queue inside the GIL-release gap, so the
    claimed size exceeds the records actually drained: rows past the
    real count are uninitialized np.empty memory (phantom votes)."""

    def drain(self, max_records=None):
        self.sched.point("native", "queue")
        n0 = self.inner.depth if max_records is None else min(
            self.inner.depth, int(max_records))
        if n0 <= 0:
            return None
        self.sched.point("native", "queue")   # the GIL-release gap
        cols = self.inner.drain(n0)
        actual = 0 if cols is None else len(cols)
        if actual == n0:
            return cols
        return _PaddedBatch(cols, n0)


class _ShardedQueue:
    """The ISSUE-20 sharded native handle, modeled: N REAL
    AdmissionQueues (capacity split evenly, home shard =
    instance // L — the C side's HostPlan-style routing) behind the
    single-queue duck surface.  The HONEST submit is ONE announced
    native span: route + per-shard fan-out inside one quantum, which
    is exactly what the real handle's whole-call GIL release gives.
    The model checks CONSERVATION across the fan-in (`records_in`
    below is the accounting boundary every record crosses before
    routing); byte-level merge determinism is the conformance
    differential's job (tests/test_native_admission.py), not this
    checker's."""

    native = True

    def __init__(self, inners: List[AdmissionQueue],
                 sched: Scheduler, instances_per_shard: int):
        self.shards = inners
        self.sched = sched
        self.L = instances_per_shard
        self.records_in = 0          # records handed to the fan-in

    @property
    def depth(self):
        return sum(q.depth for q in self.shards)

    @property
    def oldest_ts(self):
        live = [t for t in (q.oldest_ts for q in self.shards)
                if t is not None]
        return min(live) if live else None

    @property
    def counters(self):
        out: Dict[str, int] = {}
        for q in self.shards:
            for k, v in q.counters.items():
                out[k] = out.get(k, 0) + v
        return out

    @property
    def cache(self):
        return self.shards[0].cache

    def _route(self, raw: bytes) -> List[bytearray]:
        """Per-shard byte groups, ascending-shard order preserved
        within each group (the real fan-in's arrival order)."""
        groups = [bytearray() for _ in self.shards]
        for k in range(len(raw) // REC_SIZE):
            rec = raw[k * REC_SIZE:(k + 1) * REC_SIZE]
            inst = int.from_bytes(rec[0:4], "little")
            s = min(inst // self.L, len(self.shards) - 1)
            groups[s] += rec
        return groups

    def _fan_out(self, groups) -> AdmitResult:
        rs = [self.shards[s].submit(bytes(g))
              for s, g in enumerate(groups) if g]
        if not rs:
            return AdmitResult(0, 0, 0, 0, 0, 0)
        return AdmitResult(*(sum(f) for f in zip(*rs)))

    def submit(self, wire_bytes):
        raw = wire_bytes if isinstance(wire_bytes, bytes) \
            else bytes(wire_bytes)
        self.records_in += len(raw) // REC_SIZE
        self.sched.point("native", "queue")   # ONE atomic native span
        return self._fan_out(self._route(raw))

    def drain(self, max_records=None):
        self.sched.point("native", "queue")
        for q in self.shards:
            b = q.drain(max_records)
            if b is not None:
                return b
        return None


class _LostRouteShards(_ShardedQueue):
    """[mutant: shard_route_lost] the ISSUE 20 pre-review fan-in: the
    routing scratch lived on the HANDLE (one shared buffer, not a
    stack-local) and the fan-out ran as a SECOND native span.  Two
    concurrent submits: B preempts A inside the gap, routes into the
    shared scratch and consumes it; A resumes to a consumed scratch
    (its records never reach any shard) — or A fans out B's groups
    and B finds the scratch consumed (B's records lost instead).
    Either interleaving breaks fan-in conservation: records_in !=
    the summed per-shard `submitted` counters."""

    _scratch: Optional[List[bytearray]] = None

    def submit(self, wire_bytes):
        raw = wire_bytes if isinstance(wire_bytes, bytes) \
            else bytes(wire_bytes)
        self.records_in += len(raw) // REC_SIZE
        self.sched.point("native", "queue")   # span 1: route
        self._scratch = self._route(raw)
        self.sched.point("native", "queue")   # span 2: fan-out (gap!)
        groups, self._scratch = self._scratch, None
        if groups is None:                    # consumed by the racer
            return AdmitResult(0, 0, 0, 0, 0, 0)
        return self._fan_out(groups)


class _ToctouInbox(Inbox):
    """[mutant: inbox_close_toctou] the PR 3 bug: closed/capacity
    checked OUTSIDE the mutex.  The unlocked reads are announced as
    'read' points on the guarded 'inbox' resource — preempt the
    producer between check and append while drain closes + flushes,
    and an accepted blob lands AFTER the final flush (lost work)."""

    def __init__(self, capacity: int, sched: Scheduler):
        super().__init__(capacity)
        self._sched = sched

    def put(self, blob) -> bool:
        self._sched.point("read", "inbox")      # unlocked closed-check
        if self.closed or len(self._q) >= self.capacity:
            with self._mu:
                self.dropped += 1
            return False
        with self._mu:
            self._q.append(blob)
            self.enqueued += 1
            self._not_empty.notify()
        return True


class _NoInflightHost(ThreadedVoteService):
    """[mutant: busy_frac_inflight] the PR 14 riders bug: busy-frac
    windows read the completed totals only (no in-flight attribution)
    and publish the raw ratio (no clamp) — a span completing just
    after a sample lands whole in the next short window and the gauge
    reads busy_frac > 1 (historically: 60)."""

    def sample_busy_gauges(self, now=None) -> None:
        m = self.service.metrics
        with self._busy_mu:
            now = self._clock() if now is None else now
            t0 = self._busy_sample["t"]
            if t0 is None:
                self._busy_sample["t"] = now
                for name in ("submit", "dispatch"):
                    self._busy_sample[name] = self._busy_totals[name]
                return
            dt = now - t0
            if dt <= 0:
                return
            for name, gauge in (("submit", SERVE_SUBMIT_BUSY_FRAC),
                                ("dispatch", SERVE_DISPATCH_BUSY_FRAC)):
                observed = self._busy_totals[name]     # in-flight lost
                m.gauge(gauge,
                        (observed - self._busy_sample[name]) / dt)
                self._busy_sample[name] = observed
            self._busy_sample["t"] = now


# ---------------------------------------------------------------------------
# Scenario configs + system assembly
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SchedConfig:
    name: str
    producers: int = 1
    blobs: int = 1                  # per producer
    records: int = 2                # per blob
    polls: int = 0                  # driver poll_decisions calls
    #: blobs the driver submits BEFORE start() — work already inboxed
    #: when the loops wake, so drain-phase races need no producer
    #: interleaving (keeps the shrink mutant reachable at bound 1)
    preload: int = 0
    #: extra threads calling queue.drain() directly, racing the
    #: dispatch loop — the native handle's documented concurrent-
    #: drain contract ("the queue may shrink between the two under
    #: concurrent drains"), same topology as the TSan stress harness
    raw_drainers: int = 0
    drain_calls: int = 2            # per raw drainer
    drain_records: int = 3          # max_records per raw drain call
    #: extra threads calling queue.submit() directly, racing each
    #: other and the submit loop — the ISSUE-20 sharded handle's
    #: documented contract (N socket threads through one fan-in, no
    #: shared mutex); the Python queue's contract is the admission
    #: lock, so this too requires native=True
    raw_submitters: int = 0
    submit_blobs: int = 1           # per raw submitter
    instances: int = 2
    capacity: int = 64
    inbox_capacity: int = 8
    target_votes: int = 4
    native: bool = False
    #: >1 models the ISSUE-20 sharded native handle (_ShardedQueue):
    #: N real AdmissionQueues behind one fan-in; requires native=True
    #: and instances % native_shards == 0
    native_shards: int = 1
    drop_oldest: bool = False
    cache: bool = False
    gauge_interval_s: float = 1e9   # huge: no clock-value branching
    tick_s: float = 0.02
    clock_dep: bool = False         # 'clock' reads become dependent
    preemption_bound: int = 2
    max_steps: int = 20000


@dataclass
class _System:
    tsvc: ThreadedVoteService
    svc: _SchedService
    inner_queue: AdmissionQueue
    state: lockcheck.LockOrderState
    accepted: int = 0
    raw_drained: List[int] = field(default_factory=list)


def _blob(cfg: SchedConfig, salt: int) -> bytes:
    n = cfg.records
    idx = np.arange(n, dtype=np.int64)
    return pack_wire_votes(
        (idx + salt) % cfg.instances,        # spread across instances
        (idx + 7 * salt) % 1024,             # distinct validators
        np.zeros(n, np.int64),               # height 0
        np.zeros(n, np.int64),               # round 0
        np.ones(n, np.int64),                # precommit
        np.full(n, 5, np.int64))             # value


def _instrument(tsvc: ThreadedVoteService, sched: Scheduler
                ) -> lockcheck.LockOrderState:
    """Swap every LOCK_REGISTRY lock for a SchedLock (the generalized
    InstrumentedLock seam), plus the structures the registry does not
    cover: the inbox mutex + condition, the busy-sample mutex, and the
    stop event (a progress edge)."""
    state = lockcheck.instrument(
        tsvc, strict=False,
        lock_factory=lambda name, rank, st, strict:
            SchedLock(sched, name, rank, st))
    mu = SchedLock(sched, "inbox._mu", 2, state, resource="inbox")
    tsvc.inbox._mu = mu
    tsvc.inbox._not_empty = SchedCondition(sched, mu, "inbox")
    sched.register_guard("inbox", mu)
    tsvc._busy_mu = SchedLock(sched, "_busy_mu", 2, state)
    tsvc._stop = _SchedEvent(sched)
    return state


class _PlainTick:
    """Non-yielding logical clock for the queue INSIDE a native shim:
    the real native call is one GIL-releasing span, so its internal
    clock read must not be a Python-visible yield point — the shim's
    'native' announce IS the call's one scheduling boundary."""

    def __init__(self, tick_s: float):
        self.tick_s = tick_s
        self.t = 0.0

    def __call__(self) -> float:
        self.t += self.tick_s
        return self.t


def _build(cfg: SchedConfig, sched: Scheduler,
           mutant: Optional[str] = None) -> _System:
    clk = SchedClock(sched, cfg.tick_s,
                     "clock" if cfg.clock_dep else None)
    metrics = Metrics()
    cache = VerifiedCache(max_bytes=1 << 16) if cfg.cache else None
    policy = DROP_OLDEST if cfg.drop_oldest else REJECT_NEWEST
    if cfg.native and cfg.native_shards > 1:
        per_cap = cfg.capacity // cfg.native_shards
        inners = [AdmissionQueue(cfg.instances, per_cap,
                                 policy=policy, cache=cache,
                                 clock=_PlainTick(cfg.tick_s))
                  for _ in range(cfg.native_shards)]
        shim = (_LostRouteShards if mutant == "shard_route_lost"
                else _ShardedQueue)
        # the sharded handle IS the terminal-state authority: its
        # summed counters feed the digest + conservation monitors
        queue = inner = shim(inners, sched,
                             cfg.instances // cfg.native_shards)
    else:
        inner = AdmissionQueue(
            cfg.instances, cfg.capacity, policy=policy, cache=cache,
            clock=_PlainTick(cfg.tick_s) if cfg.native else clk)
        queue = inner
        if cfg.native:
            shim = (_ShrinkDrainQueue if mutant == "native_drain_shrink"
                    else _NativeQueue)
            queue = shim(inner, sched)
    micro = MicroBatcher(queue, ShapeLadder(rungs=(cfg.target_votes,)),
                         target_votes=cfg.target_votes,
                         max_delay_s=0.0, clock=clk)
    svc = _SchedService(queue, micro, metrics, sched)
    host = (_NoInflightHost if mutant == "busy_frac_inflight"
            else ThreadedVoteService)
    tsvc = host(svc, inbox_capacity=cfg.inbox_capacity,
                idle_wait_s=0.001,
                gauge_interval_s=cfg.gauge_interval_s, clock=clk,
                thread_factory=sched.thread_factory, sleep=sched.sleep)
    if mutant == "inbox_close_toctou":
        tsvc.inbox = _ToctouInbox(cfg.inbox_capacity, sched)
    state = _instrument(tsvc, sched)
    sys_ = _System(tsvc=tsvc, svc=svc, inner_queue=inner, state=state)

    # gauge-sanity monitor: busy fractions are fractions
    orig_gauge = metrics.gauge

    def gauge(name, value, _orig=orig_gauge):
        if name in (SERVE_SUBMIT_BUSY_FRAC, SERVE_DISPATCH_BUSY_FRAC) \
                and value > 1.0 + 1e-9:
            sched.record_violation(
                "busy_frac", f"{name} = {value:.3f} > 1.0")
        _orig(name, value)

    metrics.gauge = gauge
    return sys_


def run_once(cfg: SchedConfig, mutant: Optional[str] = None,
             forced: Sequence[int] = ()) -> RunResult:
    """ONE complete execution of the scenario under a (possibly
    forced-prefix) schedule, with all monitors."""
    if cfg.raw_drainers and not cfg.native:
        raise ValueError(
            "raw_drainers requires native=True: only the internally-"
            "synchronized native handle documents concurrent drains; "
            "the Python queue's contract is the _admission lock")
    if cfg.raw_submitters and not cfg.native:
        raise ValueError(
            "raw_submitters requires native=True: only the "
            "internally-synchronized native handle documents "
            "concurrent submits (ISSUE 20 shard fan-in); the Python "
            "queue's contract is the _admission lock")
    if cfg.native_shards > 1 and (
            not cfg.native or cfg.instances % cfg.native_shards
            or cfg.capacity % cfg.native_shards):
        raise ValueError(
            "native_shards > 1 requires native=True and instances/"
            "capacity divisible by the shard count (the real handle's "
            "fail-closed construction screens)")
    sched = Scheduler(forced=forced,
                      preemption_bound=cfg.preemption_bound,
                      max_steps=cfg.max_steps)
    holder: List[_System] = []

    def driver():
        sys_ = _build(cfg, sched, mutant)
        holder.append(sys_)
        tsvc = sys_.tsvc
        for i in range(cfg.preload):
            if tsvc.submit(_blob(cfg, 101 * (i + 1))):
                sys_.accepted += 1
        tsvc.start()
        blobs = [_blob(cfg, 13 * p + b)
                 for p in range(cfg.producers)
                 for b in range(cfg.blobs)]

        def make(p: int):
            def produce():
                for b in range(cfg.blobs):
                    if tsvc.submit(blobs[p * cfg.blobs + b]):
                        sys_.accepted += 1
            return produce

        def make_submitter(i: int):
            def subloop():
                for b in range(cfg.submit_blobs):
                    sys_.svc.queue.submit(
                        _blob(cfg, 211 * (i + 1) + b))
            return subloop

        def make_drainer(i: int):
            def drainloop():
                total = 0
                for _ in range(cfg.drain_calls):
                    b = sys_.svc.queue.drain(cfg.drain_records)
                    if b is not None:
                        total += len(b)
                sys_.raw_drained.append(total)
            return drainloop

        prods = [sched.thread_factory(target=make(p),
                                      name=f"producer-{p}")
                 for p in range(cfg.producers)]
        prods += [sched.thread_factory(target=make_submitter(i),
                                       name=f"submitter-{i}")
                  for i in range(cfg.raw_submitters)]
        prods += [sched.thread_factory(target=make_drainer(i),
                                       name=f"drainer-{i}")
                  for i in range(cfg.raw_drainers)]
        for t in prods:
            t.start()
        for _ in range(cfg.polls):
            tsvc.poll_decisions()
        tsvc.drain(timeout_s=None)
        for t in prods:
            t.join()

    outcome = sched.run(driver)
    res = RunResult(choices=sched.choices, decisions=sched.decisions,
                    violations=sched.violations, trace=sched.trace,
                    steps=sched.steps, truncated=sched.truncated,
                    completed=outcome == "done")
    if holder and outcome == "done":
        sys_ = holder[0]
        inbox, svc, q = sys_.tsvc.inbox, sys_.svc, sys_.inner_queue
        if inbox.depth != 0:
            res.violations.append(Violation(
                "conservation",
                f"inbox residue after drain: depth={inbox.depth} "
                f"(an accepted blob was never admitted)", sched.steps))
        if inbox.enqueued != svc.blobs_submitted:
            res.violations.append(Violation(
                "conservation",
                f"enqueued {inbox.enqueued} != blobs admitted "
                f"{svc.blobs_submitted}", sched.steps))
        if sys_.accepted != inbox.enqueued:
            res.violations.append(Violation(
                "conservation",
                f"producer-accepted {sys_.accepted} != enqueued "
                f"{inbox.enqueued}", sched.steps))
        if isinstance(q, _ShardedQueue) \
                and q.records_in != q.counters["submitted"]:
            res.violations.append(Violation(
                "conservation",
                f"fan-in records {q.records_in} != sharded submitted "
                f"{q.counters['submitted']} (records lost or "
                f"duplicated in shard routing)", sched.steps))
        claimed = svc.votes_drained + sum(sys_.raw_drained)
        if claimed != q.counters["drained"]:
            res.violations.append(Violation(
                "conservation",
                f"claimed drained votes {claimed} != queue drained "
                f"counter {q.counters['drained']} (phantom/lost "
                f"records)", sched.steps))
        if sys_.state.violations:
            res.violations.append(Violation(
                "lock_order", "; ".join(sys_.state.violations),
                sched.steps))
    res.digest = _digest(holder[0] if holder else None, res)
    return res


def _digest(sys_: Optional[_System], res: RunResult) -> tuple:
    """Terminal-state digest (integer counters only — logical-clock
    values are schedule-relative by construction and must not split
    otherwise-equal states)."""
    if sys_ is None:
        return ("no-system",)
    q = sys_.inner_queue
    return (sys_.tsvc.inbox.enqueued, sys_.tsvc.inbox.dropped,
            sys_.tsvc.inbox.depth, sys_.svc.blobs_submitted,
            sys_.svc.votes_drained, sys_.accepted,
            tuple(sorted(q.counters.items())),
            tuple(sorted({v.kind for v in res.violations})))


# ---------------------------------------------------------------------------
# Exploration: preemption-bounded DFS with sleep-set pruning
# ---------------------------------------------------------------------------


def _indep(r1: Optional[str], r2: Optional[str]) -> bool:
    """Two pending operations commute iff their announced resources
    differ (each quantum performs exactly the one announced op on
    shared state — module docstring)."""
    return r1 is None or r2 is None or r1 != r2


@dataclass
class ExploreResult:
    schedules: int = 0
    violations: List[dict] = field(default_factory=list)
    digests: set = field(default_factory=set)
    truncated: int = 0
    complete: bool = True
    max_decisions: int = 0
    first_violating: Optional[RunResult] = None


def explore(cfg: SchedConfig, mutant: Optional[str] = None, *,
            sleep_sets: bool = True,
            max_schedules: Optional[int] = None,
            deadline_at: Optional[float] = None,
            stop_on_violation: bool = False) -> ExploreResult:
    """DFS over the schedule tree: each node is a forced choice
    prefix; one execution per node; children branch at every recorded
    decision past the prefix, bounded by the preemption budget and
    pruned by sleep sets (already-explored independent siblings)."""
    out = ExploreResult()
    stack: List[Tuple[List[int], frozenset]] = [([], frozenset())]
    while stack:
        if max_schedules is not None and out.schedules >= max_schedules:
            out.complete = False
            break
        if deadline_at is not None and time.time() > deadline_at:
            out.complete = False
            break
        prefix, sleep = stack.pop()
        res = run_once(cfg, mutant, forced=prefix)
        out.schedules += 1
        out.digests.add(res.digest)
        out.max_decisions = max(out.max_decisions, len(res.decisions))
        if res.truncated:
            out.truncated += 1
            out.complete = False
        for v in res.violations:
            out.violations.append(
                {"kind": v.kind, "detail": v.detail,
                 "schedule": list(res.choices)})
        if res.violations:
            if out.first_violating is None:
                out.first_violating = res
            if stop_on_violation:
                out.complete = False
                return out
        for i in range(len(prefix), len(res.decisions)):
            d = res.decisions[i]
            base_sleep = sleep if i == len(prefix) else frozenset()
            explored = [d.chosen]
            for alt in d.enabled:
                if alt == d.chosen or alt in base_sleep:
                    continue
                extra = 1 if (d.running in d.enabled
                              and alt != d.running) else 0
                if d.preempts_before + extra > cfg.preemption_bound:
                    continue
                child_sleep = frozenset(
                    b for b in explored
                    if sleep_sets and _indep(d.pending.get(b),
                                             d.pending.get(alt)))
                stack.append((res.choices[:i] + [alt], child_sleep))
                explored.append(alt)
    return out


# ---------------------------------------------------------------------------
# Mutants: shipped (or review-caught) races, resurrected
# ---------------------------------------------------------------------------

#: name -> (config, expected violation kinds, description)
MUTANTS: Dict[str, Tuple[SchedConfig, Tuple[str, ...], str]] = {
    "inbox_close_toctou": (
        SchedConfig("mut_toctou", producers=1, blobs=2, records=2,
                    polls=0, preemption_bound=2),
        ("conservation", "atomicity"),
        "PR 3: Inbox.put checked closed/capacity outside _mu — a "
        "blob accepted after close() lands after the final drain "
        "flush (lost work)"),
    "native_drain_shrink": (
        SchedConfig("mut_shrink", producers=0, preload=1, records=3,
                    native=True, drop_oldest=True, raw_drainers=1,
                    drain_calls=1, drain_records=3,
                    polls=0, preemption_bound=2),
        ("conservation",),
        "PR 14 review-fix: drain sized batches from an unlocked "
        "pre-call depth read; a concurrent drain shrinks the queue "
        "inside the GIL-release gap -> phantom uninitialized rows"),
    "shard_route_lost": (
        SchedConfig("mut_shard_route", producers=0, records=2,
                    native=True, native_shards=2, raw_submitters=2,
                    polls=0, preemption_bound=2),
        ("conservation",),
        "ISSUE 20 pre-review fan-in: the routing scratch lived on the "
        "shard-group handle (shared) and the fan-out ran as a second "
        "native span — a concurrent submit clobbers/consumes the "
        "route inside the gap and records never reach any shard"),
    "busy_frac_inflight": (
        SchedConfig("mut_busy", producers=1, blobs=2, records=2,
                    polls=4, gauge_interval_s=0.02, clock_dep=True,
                    preemption_bound=2, max_steps=40000),
        ("busy_frac",),
        "PR 14 riders: busy-frac windows without in-flight "
        "attribution or clamp — a span completing right after a "
        "sample lands whole in one short window (busy_frac > 1)"),
}


def self_test(deadline_at: Optional[float] = None) -> dict:
    """Prove the checker bites: every mutant caught, its schedule
    ddmin-minimized, and the minimized schedule replaying CLEAN on
    the honest build."""
    import dataclasses

    report = {}
    for name, (cfg, kinds, _desc) in MUTANTS.items():
        # CHESS iterative bounding: most races need ONE preemption, so
        # exhausting bound b before b+1 finds them orders of magnitude
        # sooner than diving straight into the bound-2 tree
        total = 0
        found = None
        for b in range(cfg.preemption_bound + 1):
            found = explore(
                dataclasses.replace(cfg, preemption_bound=b),
                mutant=name, stop_on_violation=True,
                max_schedules=50000, deadline_at=deadline_at)
            total += found.schedules
            if found.first_violating is not None:
                break
        rec = {"caught": found.first_violating is not None,
               "schedules_to_find": total,
               "preemption_bound": b}
        if found.first_violating is not None:
            res = found.first_violating
            rec["kinds"] = sorted({v.kind for v in res.violations})

            def pred(acts, _cfg=cfg, _name=name, _kinds=kinds):
                r = run_once(_cfg, _name, forced=acts)
                return any(v.kind in _kinds for v in r.violations)

            minimized = (_ddmin(list(res.choices), pred)
                         if res.choices and pred(list(res.choices))
                         else list(res.choices))
            honest = run_once(cfg, None, forced=minimized)
            rec["schedule_len"] = len(res.choices)
            rec["minimized_len"] = len(minimized)
            rec["minimized"] = minimized
            rec["honest_clean"] = not honest.violations
        report[name] = rec
    report["ok"] = all(
        r.get("caught") and r.get("honest_clean")
        for n, r in report.items() if n != "ok")
    return report


# ---------------------------------------------------------------------------
# Atomic-annotation cross-check
# ---------------------------------------------------------------------------


def check_atomic_annotations(repo_root: str) -> List[str]:
    """Source `# schedcheck: atomic` markers and the ATOMIC_SPANS
    registry must agree exactly (both directions) — returns problem
    strings, empty when consistent."""
    import ast
    import os

    problems: List[str] = []
    by_file: Dict[str, set] = {}
    for (rel, func), _res in ATOMIC_SPANS.items():
        by_file.setdefault(rel, set()).add(func)
    for rel, funcs in sorted(by_file.items()):
        path = os.path.join(repo_root, rel)
        if not os.path.exists(path):
            problems.append(f"{rel}: registered in ATOMIC_SPANS but "
                            f"file is gone")
            continue
        with open(path) as fh:
            src = fh.read()
        marker_lines = [i + 1 for i, line in
                        enumerate(src.splitlines())
                        if ATOMIC_MARKER in line]
        spans = {}      # qualified function -> (lo, hi)
        tree = ast.parse(src)

        def walk(node, prefix=""):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = prefix + child.name
                    spans[q] = (child.lineno, child.end_lineno)
                    walk(child, q + ".")
                elif isinstance(child, ast.ClassDef):
                    walk(child, prefix + child.name + ".")

        walk(tree)
        marked = set()
        for ln in marker_lines:
            hits = [q for q, (lo, hi) in spans.items()
                    if lo <= ln <= hi]
            if not hits:
                problems.append(f"{rel}:{ln}: marker outside any "
                                f"function")
                continue
            marked.add(max(hits, key=lambda q: spans[q][0]))
        if marked != funcs:
            for q in sorted(funcs - marked):
                problems.append(
                    f"{rel}: ATOMIC_SPANS lists {q} but no "
                    f"'{ATOMIC_MARKER}' marker in it")
            for q in sorted(marked - funcs):
                problems.append(
                    f"{rel}: '{ATOMIC_MARKER}' marker in {q} not "
                    f"registered in ATOMIC_SPANS")
    return problems


# ---------------------------------------------------------------------------
# Scopes + CLI
# ---------------------------------------------------------------------------

SCOPES: Dict[str, List[SchedConfig]] = {
    "tiny": [
        SchedConfig("tiny", producers=1, blobs=1, records=2, polls=0),
    ],
    "smoke": [
        # polls=0 keeps the two-producer envelope exhaustible (~27k
        # schedules); poll_decisions interleavings are exercised by
        # the busy_frac mutant drill (polls=4) in the self-test
        SchedConfig("smoke_base", producers=2, blobs=1, records=2,
                    polls=0),
        SchedConfig("smoke_native", producers=2, blobs=1, records=3,
                    capacity=4, native=True, drop_oldest=True),
        SchedConfig("smoke_cache", producers=1, blobs=2, records=2,
                    cache=True),
    ],
}


def run_scope(scope: str, *, sleep_sets: bool = True,
              max_schedules: Optional[int] = None,
              deadline_at: Optional[float] = None) -> dict:
    t0 = time.perf_counter()
    configs = {}
    total = 0
    viol = 0
    complete = True
    for cfg in SCOPES[scope]:
        r = explore(cfg, sleep_sets=sleep_sets,
                    max_schedules=max_schedules,
                    deadline_at=deadline_at)
        configs[cfg.name] = {
            "schedules": r.schedules,
            "distinct_states": len(r.digests),
            "violations": r.violations,
            "truncated_runs": r.truncated,
            "max_decisions": r.max_decisions,
            "complete": r.complete,
        }
        total += r.schedules
        viol += len(r.violations)
        complete = complete and r.complete
    return {
        "scope": scope,
        "schedules_explored": total,
        "violations": viol,
        "complete": complete,
        "configs": configs,
        "seconds": round(time.perf_counter() - t0, 1),
        "ok": viol == 0,
    }


def main(argv: Optional[List[str]] = None) -> int:
    """CLI (scripts/agnes_schedcheck.py + the agnes-schedcheck console
    script).  Pure CPU, zero XLA compiles; honors the enclosing
    timeout budget (utils/budget.Deadline discovery) so the ci.sh gate
    always gets a parseable record — complete=False is the sentinel
    half of the real-value-or-sentinel contract."""
    import argparse

    from agnes_tpu.utils.budget import Deadline

    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--scope", default="smoke",
                    choices=sorted(SCOPES),
                    help="bounded exploration envelope")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--self-test", action="store_true",
                    help="mutant catch + ddmin + honest-replay suite")
    ap.add_argument("--no-sleep-sets", action="store_true",
                    help="disable sleep-set pruning (debug aid)")
    ap.add_argument("--max-schedules", type=int, default=None)
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="wall budget; default: discovered from "
                         "AGNES_SCHEDCHECK_DEADLINE_S or the "
                         "enclosing `timeout N`")
    args = ap.parse_args(argv)

    if args.deadline_s is not None:
        deadline = Deadline.after(args.deadline_s)
    else:
        deadline = Deadline.discover(
            env_var="AGNES_SCHEDCHECK_DEADLINE_S")
    rem = deadline.remaining()
    deadline_at = None if deadline.at is None \
        else time.time() + max(1.0, rem - min(20.0, rem * 0.2))

    t0 = time.perf_counter()
    if args.self_test:
        report = self_test(deadline_at=deadline_at)
        report["seconds"] = round(time.perf_counter() - t0, 1)
        print(json.dumps(report, sort_keys=True), flush=True)
        return 0 if report["ok"] else 1

    report = run_scope(args.scope,
                       sleep_sets=not args.no_sleep_sets,
                       max_schedules=args.max_schedules,
                       deadline_at=deadline_at)
    report["metrics"] = {
        SCHEDCHECK_SCHEDULES_EXPLORED: report["schedules_explored"],
        SCHEDCHECK_VIOLATIONS: report["violations"],
    }
    report["deadline"] = {"source": deadline.source,
                          "budget_s": None if rem == float("inf")
                          else round(rem, 1)}
    if not args.json:
        for name, r in report["configs"].items():
            status = "EXHAUSTED" if r["complete"] else "partial"
            print(f"[agnes_schedcheck] {name}: {r['schedules']} "
                  f"schedules / {r['distinct_states']} states "
                  f"{status}, {len(r['violations'])} violation(s)",
                  flush=True)
    print(json.dumps(report, sort_keys=True), flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
