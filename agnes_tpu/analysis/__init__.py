"""Static invariant analyzer (ISSUE 4 tentpole).

The paper's throughput story rests on structural invariants of the
fused path that nothing used to check mechanically — and that PR 3
proved can break silently (every sharded entry compiled TWICE for two
rounds, ~217s of hidden stall per entry, because an uncommitted first
dispatch keyed a second jit cache entry).  This package is the gate
that proves the invariants BEFORE a TPU round burns on them, all on
CPU, all WITHOUT a single XLA compile:

  jaxpr_audit.py  abstract-trace every registered jit entry
                  (device/registry.py): donation honored in the
                  lowered text, collective census (chunking adds zero
                  collectives under shard_map), no host callbacks in
                  hot-path jaxprs, dtype policy (no float64 / weak
                  float leaks)
  retrace.py      the recompile tripwire: a trace-count sentinel armed
                  with the closed set of expected (entry,
                  shape-signature) traces from the ShapeLadder +
                  warmup plan; any trace outside the set fails loudly
                  and bumps `retrace_unexpected`.  Catches the PR 3
                  double-compile class (same shapes, different
                  sharding) even unarmed.
  lockcheck.py    AST lint of serve/threaded.py's two-lock discipline
                  (+ a runtime instrumented-lock mode for the threaded
                  tests)
  lint.py         repo-wide AST rules: host syncs in serve hot paths,
                  unregistered import-time jax.jit entries, unhashable
                  static-argnum candidates

CLI: scripts/agnes_lint.py (`--pass jaxpr|retrace|locks|lint|all`),
gated in ci.sh before the test gates.
"""

from agnes_tpu.analysis.jaxpr_audit import Finding, audit  # noqa: F401
from agnes_tpu.analysis.retrace import (  # noqa: F401
    RetraceError,
    RetraceSentinel,
    signature,
)
