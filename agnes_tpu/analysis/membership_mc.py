"""Pod-membership model checker (ISSUE 17 tentpole axis c).

The elastic pod's repartition protocol — leave/join intents latching
mid-epoch, the boundary repartition of `HostPlan` instance ranges, the
held-gossip re-lift onto the new partition, readmission replay — is
decision-affecting control-plane code that, like the admission layer
before ISSUE 7, would otherwise ship on unit tests and one spawned
differential.  This module closes that the same way
`analysis/admission_mc.py` did: the SAME schedule enumerator
(`modelcheck.Domain` / `_explore_domain`: depth-bounded DFS,
canonical-state dedup, ddmin minimization) over a `MembershipSystem`
that drives the REAL `distributed/membership.py` protocol object —
`MembershipEpoch`, `partition_ranges`, `validate_partition`,
`relift_ranges` are the production code under check (their
`mc_clone`/`mc_canonical` hooks are the only distributed/ additions),
with a deterministic MODEL of the traffic plane around it (per-
instance batch heights, the survivor-held gossip counts; the real
plane carries jax and this checker must stay jax-free for the ci.sh
gate slot).

Actions (the membership schedule alphabet — the host-level sleep/wake
+ repartition actions the ISSUE's `host_churn` knob budgets):

  ("s", h)   host h announces leave (TOB-SVD sleepy churn at pod
             granularity; bounded by `host_churn`, and only enabled
             where the prospective live set still splits the instance
             space evenly — the honest deployment envelope, exactly
             what ElasticShard serves)
  ("w", h)   departed (or departing) host h announces rejoin
  ("d", i)   one batch of traffic for global instance i: advances its
             height while i's home host serves, is HELD by the
             adopting survivor while it is departed (bounded per
             instance by `max_height` over heights + held)
  ("b",)     one epoch boundary: latched intents apply, the partition
             recomputes (real `MembershipEpoch.boundary`), held
             batches re-lift along the transfers and replay for
             readmitted hosts

Property monitors (the repartition-soundness contract):

  partition      after EVERY state the live partition is disjoint and
                 covering — the real `validate_partition` predicate,
                 so the proof and the live boundary path police the
                 SAME invariant — and is keyed exactly off the live
                 host set
  conservation   no batch is lost across a repartition/re-lift: sent
                 == advanced heights + still-held, always (the
                 no-decision-loss half of the ISSUE contract)
  monotonic      per-instance heights never regress across a
                 boundary (a re-lift that rolls state back would pass
                 conservation arithmetic while still losing decisions)

The mutation registry (`MEMBERSHIP_MUTANTS`) doctors one boundary
stage each — an overlapping-range repartition, a held-batch-dropping
re-lift — and `self_test_membership` proves both monitors have teeth:
caught, ddmin-minimized, minimized schedule clean on the honest
system.  Corpus entries (tests/corpus/membership/) stamp the honest
outcome and replay deterministically; the device-plane leg
(tests/test_membership_mc.py) re-lifts REAL `seq_in_specs` /
`dense_lane_specs`-shaped numpy leaves along each entry's recorded
repartitions with `relift_tree` and asserts global-assembly identity.

Pure numpy + stdlib; ZERO jax imports (asserted by test).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from agnes_tpu.analysis.modelcheck import (
    Domain,
    Report,
    Violation,
    _ddmin,
    _explore_domain,
)
from agnes_tpu.distributed.membership import (
    MembershipEpoch,
    MembershipError,
    partition_ranges,
    validate_partition,
)

MEMBERSHIP_PROPERTIES = ("partition", "conservation", "monotonic")


@dataclasses.dataclass(frozen=True)
class MembershipMCConfig:
    """One bounded membership-exploration task.  JSON-able (spawn
    workers, corpus files).  `host_churn` is THE ISSUE 17 knob: the
    budget of host-level leave announcements a schedule may spend
    (each may pair with a wake — the sleepy-churn alphabet)."""

    name: str
    n_hosts: int = 2
    n_instances: int = 2
    host_churn: int = 1
    max_height: int = 1        # per-instance bound on heights + held
    depth: int = 8

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["kind"] = "membership"
        return d

    @classmethod
    def from_json(cls, d: dict) -> "MembershipMCConfig":
        d = dict(d)
        d.pop("kind", None)
        return cls(**d)


_ACT_NAMES = {"s": "sleep", "w": "wake", "d": "send", "b": "boundary"}
_ACT_CODES = {v: k for k, v in _ACT_NAMES.items()}


class MembershipSystem:
    """The checkable system: the real `MembershipEpoch` protocol
    object plus the modeled traffic plane (module docstring).
    Provides the engine's mc_clone / mc_apply / mc_enabled / mc_digest
    surface plus the schedule codec."""

    def __init__(self, cfg: MembershipMCConfig):
        assert cfg.n_instances % cfg.n_hosts == 0, \
            "genesis must split evenly (MembershipEpoch's own rule)"
        self.cfg = cfg
        self.epoch = MembershipEpoch(cfg.n_hosts, cfg.n_instances)
        per = cfg.n_instances // cfg.n_hosts
        #: static home host of each instance — the host whose device
        #: block serves it; while the home is departed its traffic is
        #: HELD by the adopting survivor (distributed/elastic.py)
        self.home = tuple(i // per for i in range(cfg.n_instances))
        self.heights = [0] * cfg.n_instances
        self.held = [0] * cfg.n_instances
        self.sent = 0
        self.sleeps = 0
        self.boundaries = 0

    # -- membership helpers --------------------------------------------------

    def _prospective_live(self, extra_leave: Optional[int] = None):
        alive = (set(self.epoch.view.alive)
                 - self.epoch._pending_leave
                 | self.epoch._pending_join)
        if extra_leave is not None:
            alive.discard(extra_leave)
        return alive

    def _home_serving(self, i: int) -> bool:
        return self.home[i] in self.epoch.view.alive

    # -- engine surface ------------------------------------------------------

    def mc_enabled(self) -> List[tuple]:
        acts: List[tuple] = []
        ep = self.epoch
        if self.sleeps < self.cfg.host_churn:
            for h in ep.view.alive:
                if h in ep._pending_leave:
                    continue
                live = self._prospective_live(extra_leave=h)
                # honest envelope: only even-splitting departures (an
                # uneven one fails loudly at the boundary — unit-
                # tested in tests/test_elastic.py, out of model scope)
                if live and self.cfg.n_instances % len(live) == 0:
                    acts.append(("s", h))
        for h in range(self.cfg.n_hosts):
            departed = (h not in ep.view.alive
                        or h in ep._pending_leave)
            if departed and h not in ep._pending_join:
                acts.append(("w", h))
        for i in range(self.cfg.n_instances):
            if self.heights[i] + self.held[i] < self.cfg.max_height:
                acts.append(("d", i))
        if ep.pending() != (0, 0):
            acts.append(("b",))
        return acts

    def mc_apply(self, act: tuple) -> bool:
        kind = act[0]
        ep = self.epoch
        if kind == "s":
            h = act[1]
            if self.sleeps >= self.cfg.host_churn \
                    or h not in ep.view.alive \
                    or h in ep._pending_leave:
                return False
            live = self._prospective_live(extra_leave=h)
            if not live or self.cfg.n_instances % len(live):
                return False
            assert ep.note_leave(h)
            self.sleeps += 1
            return True
        if kind == "w":
            h = act[1]
            return ep.note_join(h)
        if kind == "d":
            i = act[1]
            if self.heights[i] + self.held[i] >= self.cfg.max_height:
                return False
            self.sent += 1
            if self._home_serving(i):
                self.heights[i] += 1
            else:
                self.held[i] += 1
            return True
        if kind == "b":
            if ep.pending() == (0, 0):
                return False
            rep = ep.boundary()
            if rep is not None:
                self.boundaries += 1
                self._relift_held(rep)
                self._install_view(rep)
            return True
        raise ValueError(f"unknown membership action {act!r}")

    # -- the boundary stages (the mutation seams) ----------------------------

    def _relift_held(self, rep) -> None:
        """Re-lift held batches across the repartition: batches held
        for a READMITTED host replay into its instances' heights (the
        catch-up replay, elastic.py `_ingest_reroute`); batches whose
        home is still departed STAY WITH THEIR HOLDER — a count
        no-op, and exactly what the implementation does (the holder's
        process keeps ticking even asleep, so it re-routes once the
        home returns; `_take_reroute` targets the static home, never
        the epoch owner, so no holder hand-off exists to lose them).
        The dropping mutant doctors exactly this stage."""
        for h in rep.joined:
            for i in range(self.cfg.n_instances):
                if self.home[i] == h:
                    self.heights[i] += self.held[i]
                    self.held[i] = 0

    def _install_view(self, rep) -> None:
        """Honest: nothing — `MembershipEpoch.boundary` already
        installed the real repartition.  The overlapping-range mutant
        doctors the installed view here."""

    # -- branching / dedup ---------------------------------------------------

    def mc_clone(self) -> "MembershipSystem":
        s = type(self).__new__(type(self))
        s.cfg = self.cfg
        s.epoch = self.epoch.mc_clone()
        s.home = self.home
        s.heights = list(self.heights)
        s.held = list(self.held)
        s.sent = self.sent
        s.sleeps = self.sleeps
        s.boundaries = self.boundaries
        return s

    def mc_canonical(self) -> tuple:
        # `sent` IS in the key: honest states derive it (sum of
        # heights + held, no extra states), but a lossy re-lift makes
        # it diverge — excluding it would let the mutant's post-drop
        # state dedup against an honest state reached with fewer
        # sends, hiding the violation from the new-state monitors.
        # `boundaries` is excluded for the same reason the epoch
        # counter is (membership.mc_canonical): repetition without
        # behavioral difference must merge or the space is unbounded.
        return (self.epoch.mc_canonical(), tuple(self.heights),
                tuple(self.held), self.sent, self.sleeps)

    def mc_digest(self, perm=None) -> bytes:
        import hashlib
        import marshal

        assert perm is None, "membership domain has no symmetry group"
        return hashlib.blake2b(marshal.dumps(self.mc_canonical(), 2),
                               digest_size=16).digest()

    # -- schedule codec (the Counterexample/corpus serialization) ------------

    @classmethod
    def action_to_json(cls, act: tuple) -> list:
        return [_ACT_NAMES[act[0]], *act[1:]]

    @classmethod
    def action_from_json(cls, a: list) -> tuple:
        return (_ACT_CODES[a[0]], *(int(x) for x in a[1:]))

    def run_schedule(self, actions, on_action=None) -> List[bool]:
        applied = []
        for i, a in enumerate(actions):
            act = self.action_from_json(a) if a and a[0] in _ACT_CODES \
                else tuple(a)
            ok = self.mc_apply(act)
            applied.append(ok)
            if on_action is not None:
                on_action(i, act, ok)
        return applied


# ---------------------------------------------------------------------------
# Monitors
# ---------------------------------------------------------------------------


def membership_state_violations(sys: MembershipSystem
                                ) -> List[Violation]:
    out: List[Violation] = []
    view = sys.epoch.view
    try:
        validate_partition(view.ranges, view.n_instances)
    except MembershipError as e:
        out.append(Violation(
            "partition", -1,
            f"epoch {view.epoch} partition invalid: {e}"))
    if set(view.ranges) != set(view.alive):
        out.append(Violation(
            "partition", -1,
            f"epoch {view.epoch} partition keyed off hosts "
            f"{sorted(view.ranges)} but the live set is "
            f"{list(view.alive)}"))
    have = sum(sys.heights) + sum(sys.held)
    if have != sys.sent:
        out.append(Violation(
            "conservation", -1,
            f"sent {sys.sent} != advanced {sum(sys.heights)} + held "
            f"{sum(sys.held)} — a batch was lost across a "
            f"repartition/re-lift"))
    return out


def membership_edge_snapshot(sys: MembershipSystem) -> tuple:
    return tuple(sys.heights)


def membership_edge_violations(sys: MembershipSystem,
                               snap: tuple) -> List[Violation]:
    out: List[Violation] = []
    for i, h in enumerate(sys.heights):
        if h < snap[i]:
            out.append(Violation(
                "monotonic", i,
                f"instance {i} height regressed {snap[i]} -> {h} "
                f"across a boundary — a re-lift rolled state back"))
    return out


def membership_domain() -> Domain:
    return Domain(
        enabled=lambda s: s.mc_enabled(),
        expandable=lambda s: True,
        state_violations=membership_state_violations,
        edge_snapshot=membership_edge_snapshot,
        edge_violations=membership_edge_violations,
        indep=lambda a, b: False,   # one shared partition: no POR
        near_miss=None,
        symmetry=None,
        codec=MembershipSystem)


def explore_membership(cfg: MembershipMCConfig,
                       system_cls: Optional[type] = None,
                       deadline_at: Optional[float] = None,
                       max_states: Optional[int] = None,
                       stop_on_violation: bool = True,
                       collect_digests: bool = False) -> Report:
    """Exhaustive DFS over `cfg`'s membership schedules — the same
    engine as the consensus/admission scopes."""
    root = (system_cls or MembershipSystem)(cfg)
    return _explore_domain(
        root, cfg, membership_domain(), por=False,
        deadline_at=deadline_at, max_states=max_states,
        stop_on_violation=stop_on_violation,
        collect_digests=collect_digests)


# ---------------------------------------------------------------------------
# Replay + minimization + corpus
# ---------------------------------------------------------------------------


def run_membership_with_monitors(cfg: MembershipMCConfig, actions,
                                 system_cls: Optional[type] = None
                                 ) -> Tuple[MembershipSystem,
                                            List[Violation]]:
    """Deterministic replay with every monitor after every applied
    action — the reproduction predicate for ddmin and the corpus."""
    sys_ = (system_cls or MembershipSystem)(cfg)
    viols: List[Violation] = list(membership_state_violations(sys_))
    snap = [membership_edge_snapshot(sys_)]

    def on_action(_i, _act, ok):
        if ok:
            viols.extend(membership_edge_violations(sys_, snap[0]))
            viols.extend(membership_state_violations(sys_))
        snap[0] = membership_edge_snapshot(sys_)

    sys_.run_schedule(actions, on_action=on_action)
    return sys_, viols


def membership_reproduces(cfg, actions, prop,
                          system_cls: Optional[type] = None) -> bool:
    _, viols = run_membership_with_monitors(cfg, actions, system_cls)
    return any(v.property == prop for v in viols)


def minimize_membership(cfg, actions, prop,
                        system_cls: Optional[type] = None
                        ) -> List[tuple]:
    return _ddmin(
        list(actions),
        lambda acts: membership_reproduces(cfg, acts, prop,
                                           system_cls))


def membership_corpus_entry(name: str, cfg: MembershipMCConfig,
                            actions, origin: str) -> dict:
    """Corpus entry with the honest system's outcome stamped — the
    final heights/held/partition plus EVERY applied repartition
    (old ranges -> new ranges), so the device-plane leg can re-lift
    real spec-tree-shaped leaves along the same boundary sequence."""
    sys_, viols = run_membership_with_monitors(cfg, actions)
    reparts: List[dict] = []
    # second replay to record the repartitions in order (cheap; the
    # model is tiny and the recorder must not perturb the monitors)
    rec = MembershipSystem(cfg)
    for a in actions:
        act = rec.action_from_json(a) if a and a[0] in _ACT_CODES \
            else tuple(a)
        before = rec.epoch.view
        ok = rec.mc_apply(act)
        if ok and act[0] == "b" and rec.epoch.view is not before:
            reparts.append({
                "old": sorted([h, lo, hi] for h, (lo, hi)
                              in before.ranges.items()),
                "new": sorted([h, lo, hi] for h, (lo, hi)
                              in rec.epoch.view.ranges.items()),
            })
    return {
        "kind": "membership",
        "name": name,
        "origin": origin,
        "config": cfg.to_json(),
        "actions": [MembershipSystem.action_to_json(tuple(a))
                    for a in actions],
        "expect": {
            "violations": sorted({v.property for v in viols}),
            "heights": list(sys_.heights),
            "held": list(sys_.held),
            "sent": sys_.sent,
            "alive": list(sys_.epoch.view.alive),
            "ranges": sorted([h, lo, hi] for h, (lo, hi)
                             in sys_.epoch.view.ranges.items()),
            "boundaries": sys_.boundaries,
            "readmissions": sys_.epoch.readmissions,
            "departures": sys_.epoch.departures,
            "repartitions": reparts,
        },
    }


def replay_membership_entry(entry: dict) -> Tuple[MembershipSystem,
                                                  List[Violation]]:
    cfg = MembershipMCConfig.from_json(entry["config"])
    sys_, viols = run_membership_with_monitors(cfg, entry["actions"])
    exp = entry["expect"]
    assert list(sys_.heights) == exp["heights"], entry["name"]
    assert list(sys_.held) == exp["held"], entry["name"]
    assert sys_.sent == exp["sent"], entry["name"]
    assert list(sys_.epoch.view.alive) == exp["alive"], entry["name"]
    got_ranges = sorted([h, lo, hi] for h, (lo, hi)
                        in sys_.epoch.view.ranges.items())
    assert got_ranges == [list(r) for r in exp["ranges"]], (
        f"{entry['name']}: final partition diverged")
    assert sys_.boundaries == exp["boundaries"], entry["name"]
    assert sys_.epoch.readmissions == exp["readmissions"], entry["name"]
    assert sys_.epoch.departures == exp["departures"], entry["name"]
    assert sorted({v.property for v in viols}) == exp["violations"], (
        f"{entry['name']}: property verdicts diverged")
    return sys_, viols


# ---------------------------------------------------------------------------
# Mutation self-test: doctored boundary stages the monitors MUST catch
# ---------------------------------------------------------------------------


class _OverlappingRepartitionSystem(MembershipSystem):
    """Doctored: the installed boundary view extends the lowest live
    host's range one instance into its neighbor — the classic
    off-by-one at a repartition split point.  Caught by the partition
    (disjointness) monitor via the real `validate_partition`."""

    def _install_view(self, rep) -> None:
        view = self.epoch.view
        if len(view.ranges) < 2:
            return
        ranges = dict(view.ranges)
        low = min(ranges)
        lo, hi = ranges[low]
        ranges[low] = (lo, hi + 1)
        self.epoch.view = dataclasses.replace(view, ranges=ranges)


class _DroppingReliftSystem(MembershipSystem):
    """Doctored: the readmission re-lift replays one batch short per
    held instance — held state silently truncated while moving onto
    the new partition.  Caught by the conservation monitor."""

    def _relift_held(self, rep) -> None:
        for h in rep.joined:
            for i in range(self.cfg.n_instances):
                if self.home[i] == h and self.held[i]:
                    self.heights[i] += self.held[i] - 1
                    self.held[i] = 0


#: mutant name -> (system class, property caught by, config)
MEMBERSHIP_MUTANTS: Dict[str, tuple] = {
    # sleep one of three hosts, cross the boundary: the doctored
    # two-survivor partition overlaps at the split point
    "overlapping_range_repartition": (
        _OverlappingRepartitionSystem, "partition",
        MembershipMCConfig(name="mut_overlap", n_hosts=3,
                           n_instances=6, host_churn=1, max_height=1,
                           depth=4)),
    # sleep, hold a batch, rejoin: the doctored re-lift replays one
    # batch short (sent > advanced + held)
    "relift_drops_held_batch": (
        _DroppingReliftSystem, "conservation",
        MembershipMCConfig(name="mut_drop_relift", n_hosts=2,
                           n_instances=2, host_churn=1, max_height=2,
                           depth=7)),
}


def self_test_membership() -> dict:
    """Each doctored boundary stage must be caught, its counterexample
    must ddmin-minimize, and the minimized schedule must run CLEAN on
    the honest system (the violation is the mutation's, not the
    checker's)."""
    out = {}
    for name, (sys_cls, prop, cfg) in MEMBERSHIP_MUTANTS.items():
        rep = explore_membership(cfg, system_cls=sys_cls)
        caught = [c for c in rep.violations
                  if c.violation.property == prop]
        assert caught, (
            f"membership mutant {name}: no {prop} violation in "
            f"{rep.states} states")
        ce = caught[0]
        ce.minimized = minimize_membership(cfg, ce.schedule, prop,
                                           system_cls=sys_cls)
        assert membership_reproduces(cfg, ce.minimized, prop,
                                     system_cls=sys_cls)
        _, honest = run_membership_with_monitors(cfg, ce.minimized)
        assert not honest, (
            f"membership mutant {name}: minimized schedule also "
            f"violates on the honest system: {honest}")
        out[name] = {
            "property": prop,
            "states_to_detection": rep.states,
            "schedule_len": len(ce.schedule),
            "minimized_len": len(ce.minimized),
            "counterexample": ce.to_json(),
        }
    return out


# ---------------------------------------------------------------------------
# Corpus emission (tests/corpus/membership/*.json)
# ---------------------------------------------------------------------------

#: hand-written milestone schedules (deterministic coverage witnesses
#: the spec-tree re-lift test replays): name -> (config, schedule,
#: post-condition on the honest system)
MEMBERSHIP_MILESTONES: Dict[str, tuple] = {
    # the full sleepy-churn cycle: traffic, a leave boundary, a batch
    # held for the departed home, readmission replaying it
    "mem_leave_hold_rejoin_replay": (
        MembershipMCConfig(name="mem_cycle", n_hosts=2, n_instances=2,
                           host_churn=1, max_height=2, depth=10),
        [("d", 0), ("d", 1), ("s", 1), ("b",), ("d", 1), ("d", 0),
         ("w", 1), ("b",)],
        lambda s: (s.heights == [2, 2] and not any(s.held)
                   and s.epoch.readmissions == 1
                   and s.epoch.view.alive == (0, 1))),
    # pod shrinks 3 -> 2 -> 1 live hosts and grows back to 3: every
    # intermediate partition even, both departures counted, both
    # readmissions applied at one boundary
    "mem_shrink_to_one_and_regrow": (
        MembershipMCConfig(name="mem_regrow", n_hosts=3,
                           n_instances=6, host_churn=2, max_height=1,
                           depth=12),
        [("s", 2), ("b",), ("s", 1), ("b",), ("w", 1), ("w", 2),
         ("b",)],
        lambda s: (s.epoch.view.alive == (0, 1, 2)
                   and s.epoch.departures == 2
                   and s.epoch.readmissions == 2
                   and s.epoch.view.ranges
                   == partition_ranges(6, (0, 1, 2)))),
    # an intent flap inside one epoch: leave latched then cancelled by
    # the rejoin before any boundary — the no-op boundary burns no
    # epoch and the partition never moves
    "mem_flap_cancels_before_boundary": (
        MembershipMCConfig(name="mem_flap", n_hosts=2, n_instances=2,
                           host_churn=1, max_height=1, depth=6),
        [("d", 0), ("s", 1), ("w", 1), ("b",), ("d", 1)],
        lambda s: (s.heights == [1, 1] and s.boundaries == 0
                   and s.epoch.view.epoch == 0)),
}


def emit_membership_corpus(directory: str,
                           include_mutants: bool = True) -> List[str]:
    """(Re)generate the membership regression corpus: the milestone
    schedules plus each mutant's minimized counterexample (stamped
    with the HONEST system's outcome — clean, the admission-corpus
    pattern).  Deterministic."""
    import json
    import os

    os.makedirs(directory, exist_ok=True)
    written = []
    for name, (cfg, sched, check) in MEMBERSHIP_MILESTONES.items():
        sys_, viols = run_membership_with_monitors(cfg, sched)
        assert not viols, (name, viols)
        assert check(sys_), f"milestone {name} post-condition failed"
        entry = membership_corpus_entry(
            name, cfg, sched, origin="hand-written milestone")
        path = os.path.join(directory, f"{name}.json")
        with open(path, "w") as f:
            json.dump(entry, f, indent=1, sort_keys=True)
            f.write("\n")
        written.append(path)
    if include_mutants:
        for mname, r in self_test_membership().items():
            ce = r["counterexample"]
            cfg = MembershipMCConfig.from_json(ce["config"])
            acts = [MembershipSystem.action_from_json(a)
                    for a in ce["schedule"]]
            entry = membership_corpus_entry(
                f"mem_mut_{mname}", cfg, acts,
                origin=f"minimized {mname} membership-mutant "
                       f"counterexample (honest replay: clean)")
            path = os.path.join(directory, f"mem_mut_{mname}.json")
            with open(path, "w") as f:
                json.dump(entry, f, indent=1, sort_keys=True)
                f.write("\n")
            written.append(path)
    return written


# ---------------------------------------------------------------------------
# Scopes (aggregated into the modelcheck CLI/gate by run_scope)
# ---------------------------------------------------------------------------

MEMBERSHIP_TINY: Tuple[MembershipMCConfig, ...] = (
    MembershipMCConfig(name="mem_tiny", n_hosts=2, n_instances=2,
                       host_churn=1, max_height=1, depth=6),
)

#: sized for the 2-CPU gate box beside the consensus/admission shards:
#: the flagship shard interleaves two full churn cycles with held
#: traffic on a 3-host pod (every live-set size 3/2/1 reachable) and
#: must EXHAUST >= 50k states — the ISSUE 17 acceptance floor the
#: ci.sh gate asserts
MEMBERSHIP_SMOKE: Tuple[MembershipMCConfig, ...] = (
    MembershipMCConfig(name="mem_churn2", n_hosts=3, n_instances=6,
                       host_churn=2, max_height=2, depth=12),
    MembershipMCConfig(name="mem_pair_deep", n_hosts=2,
                       n_instances=4, host_churn=2, max_height=3,
                       depth=14),
)

MEMBERSHIP_SCOPES = {"tiny": MEMBERSHIP_TINY,
                     "smoke": MEMBERSHIP_SMOKE,
                     "full": MEMBERSHIP_SMOKE}
