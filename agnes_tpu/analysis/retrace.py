"""Recompile tripwire: a trace-count sentinel for drivers/pipelines.

The PR 3 incident: every sharded entry compiled TWICE for two rounds
— the first dispatch passed fresh UNCOMMITTED host arrays, every later
one the committed sharded outputs, and the jit cache (which keys on
input shardings) built the same graph twice at ~217s per extra trace.
Nothing failed; the stall just rode along.  This module turns that
class of bug — plus the serve ladder's no-recompile invariant
(`offladder_builds` asserted 0) — into one mechanically-checked
property:

* Every dispatch computes a cheap **shape signature** of its concrete
  arguments: entry name + resolved statics + per-leaf (shape, dtype,
  sharding key).  The sharding key normalizes through the HLO sharding
  (NamedSharding and GSPMDSharding of the same placement agree), so
  committed-vs-uncommitted is VISIBLE in the signature — exactly what
  the jit cache sees.
* **Unarmed (learning)**: signatures are recorded as the expected set.
  Even unarmed, the sentinel fails loudly when one (entry, statics,
  shapes) key shows up under TWO different sharding keys — the PR 3
  double-compile, caught on the second dispatch instead of two rounds
  later.
* **Armed**: `ServePipeline.warmup()` registers the closed set of
  expected traces from the ShapeLadder + warmup plan, then arms the
  sentinel; ANY signature outside the set fails loudly and bumps the
  `retrace_unexpected` counter (utils/metrics.py) — an off-ladder
  shape, an unwarmed phase count, a sharding drift.

Opt-in: `DeviceDriver(..., audit=True)` installs the sentinel on every
dispatch path; `ServePipeline.warmup()` arms it when present.

The pure-host half — `warmup_covers()` — is the static proof the CLI
pass runs: every shape the serve plane can dispatch (builds capped at
the top rung, lanes padded onto rungs, entry-prepend policy => P in
{2, 3}) must be covered by the warmup plan, checked without building a
single array.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple


class RetraceError(RuntimeError):
    """An unexpected trace signature reached a dispatch entry."""


def _sharding_key(x) -> object:
    """Normalized sharding of one leaf — what the jit cache would key
    on.  Host arrays (numpy/python) key as "host"; jax Arrays key by
    (HLO sharding repr, device ids), which is stable across the
    NamedSharding the driver places and the GSPMD sharding jit outputs
    come back with."""
    s = getattr(x, "sharding", None)
    if s is None:
        return "host"
    try:
        ndim = getattr(x, "ndim", 0)
        hlo = s._to_xla_hlo_sharding(ndim)
        devs = tuple(sorted(d.id for d in s.device_set))
        return (repr(hlo), devs)
    except Exception:  # noqa: BLE001 — exotic shardings: repr fallback
        return str(s)


def signature(args, statics: Tuple = ()) -> Tuple:
    """Hashable shape signature of a dispatch's argument pytree."""
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    return (tuple(statics),
            tuple((tuple(getattr(x, "shape", ())),
                   str(getattr(x, "dtype", type(x).__name__)),
                   _sharding_key(x)) for x in leaves))


def _shapes_only(sig: Tuple) -> Tuple:
    statics, leaves = sig
    return (statics, tuple((shape, dt) for shape, dt, _ in leaves))


class RetraceSentinel:
    """Trace-signature sentinel (module docstring).  Thread-safe: the
    serve plane's dispatch thread and a caller's drain may observe
    concurrently."""

    def __init__(self, metrics=None, strict: bool = True):
        from agnes_tpu.utils.metrics import Metrics

        self.metrics = metrics if metrics is not None else Metrics()
        self.strict = strict
        self.armed = False
        self.expected: Set[Tuple] = set()
        self.unexpected: List[Tuple] = []
        #: (entry, statics+shapes) -> set of full signatures; >1 full
        #: signature per key == same graph traced under two shardings
        self._variants: Dict[Tuple, Set[Tuple]] = {}
        self._observed: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- recording ----------------------------------------------------------

    def expect(self, entry: str, sig: Tuple) -> None:
        """Register one expected (entry, signature) — the warmup plan
        calls this through observe() while unarmed."""
        with self._lock:
            self._expect_locked(entry, sig)

    def _expect_locked(self, entry: str, sig: Tuple) -> None:
        from agnes_tpu.utils.metrics import ANALYSIS_ENTRIES_AUDITED

        if (entry, sig) not in self.expected:
            self.expected.add((entry, sig))
            # each distinct vetted signature is one audited entry
            # shape — hardware rounds export this alongside
            # retrace_unexpected so "the audit ran clean" is a
            # recorded fact, not a vibe
            self.metrics.count(ANALYSIS_ENTRIES_AUDITED)

    def observe(self, entry: str, sig: Tuple) -> None:
        """Record a dispatch signature; raise (and count
        `retrace_unexpected`) on any trace outside the expected set
        once armed, or on a sharding-variant duplicate at any time."""
        from agnes_tpu.utils.metrics import RETRACE_UNEXPECTED

        key = (entry, _shapes_only(sig))
        with self._lock:
            self._observed[entry] = self._observed.get(entry, 0) + 1
            variants = self._variants.setdefault(key, set())
            is_new_variant = sig not in variants and bool(variants)
            variants.add(sig)
            if is_new_variant:
                self.unexpected.append((entry, sig))
                self.metrics.count(RETRACE_UNEXPECTED)
                if self.strict:
                    raise RetraceError(
                        f"entry {entry!r} dispatched with the SAME "
                        f"shapes under {len(variants)} different "
                        f"shardings — the same graph will trace/"
                        f"compile once per variant (the PR 3 "
                        f"double-compile class; commit the driver "
                        f"state once, e.g. place_step_state)")
                return
            if not self.armed:
                self._expect_locked(entry, sig)
                return
            if (entry, sig) not in self.expected:
                self.unexpected.append((entry, sig))
                self.metrics.count(RETRACE_UNEXPECTED)
                if self.strict:
                    raise RetraceError(
                        f"unexpected trace: entry {entry!r} dispatched "
                        f"with a signature outside the warmed set "
                        f"({len(self.expected)} expected) — an "
                        f"off-ladder shape or an unwarmed phase count "
                        f"would compile LIVE on the serve path")

    def arm(self) -> "RetraceSentinel":
        """Close the expected set: every signature observed so far is
        legal, anything else fails loudly."""
        with self._lock:
            self.armed = True
        return self

    # -- reporting ----------------------------------------------------------

    def report(self) -> dict:
        with self._lock:
            return {
                "armed": self.armed,
                "entries_observed": dict(self._observed),
                "expected_signatures": len(self.expected),
                "unexpected": len(self.unexpected),
            }


# -- static warmup-coverage proof (CLI retrace pass) --------------------------

def dispatchable_shapes(ladder, dense: bool = False,
                        dedup: bool = False,
                        ) -> Set[Tuple]:
    """Every (P, rung) shape the serve pipeline CAN dispatch on the
    signed path, derived from its build policy without building
    anything: builds are capped at the top rung and padded onto a
    ladder rung (packed-lane mode; `lane_floor = min_rung`), and the
    entry-prepend policy makes the step-sequence length P = 1 entry +
    {1, 2} vote classes.  Dense mode's compile key is (P, I, V) — rung
    is not part of it, so the rung slot is None.

    With `dedup` (ISSUE 5 split-rung dispatch) the pre-verified stream
    additionally dispatches the UNSIGNED sequence entries — their
    compile key carries no lane rung at all (dense [P, I, V] phases).
    P in {2, 3} is a HARD bound, not a hope: pre-verified builds are
    chunked to at most two vote phases per dispatch with the entry
    phase prepended on every chunk
    (ServePipeline._stage_preverified) — a cache-hit burst spanning
    rounds or equivocation layers stages several chunks rather than
    one long unwarmed sequence."""
    ps = (2, 3)
    out: Set[Tuple] = ({(p, None) for p in ps} if dense
                       else {(p, r) for p in ps for r in ladder.rungs})
    if dedup:
        out |= {("unsigned", p) for p in ps}
    return out


def warmup_shapes(ladder, n_phases=(2, 3), dense: bool = False,
                  dedup: bool = False,
                  ) -> Set[Tuple]:
    """The (P, rung) set ServePipeline.warmup(n_phases) precompiles
    (mirrors its loop structure; see pipeline.warmup docstring).  With
    `dedup` the warmup also compiles the unsigned sequence entries,
    one shape per P (the cache-enabled warmup loop)."""
    if isinstance(n_phases, int):
        n_phases = (n_phases,)
    out: Set[Tuple] = ({(p, None) for p in n_phases} if dense
                       else {(p, r) for p in n_phases
                             for r in ladder.rungs})
    if dedup:
        out |= {("unsigned", p) for p in n_phases}
    return out


def warmup_covers(ladder, n_phases=(2, 3), dense: bool = False,
                  dedup: bool = False) -> bool:
    """True iff every dispatchable signed shape is warmed — the
    no-live-compile invariant, provable statically."""
    return dispatchable_shapes(ladder, dense, dedup) <= warmup_shapes(
        ladder, n_phases, dense, dedup)


def coverage_findings(ladder, n_phases=(2, 3), dense: bool = False,
                      dedup: bool = False) -> List:
    """Finding list form of warmup_covers for the CLI."""
    from agnes_tpu.analysis.jaxpr_audit import Finding

    missing = dispatchable_shapes(ladder, dense, dedup) - warmup_shapes(
        ladder, n_phases, dense, dedup)
    if not missing:
        return []
    return [Finding(
        "retrace", "RET001", "ServePipeline.warmup",
        f"dispatchable signed shapes not covered by the warmup plan "
        f"{tuple(n_phases)}: {sorted(missing, key=repr)} — each would "
        f"compile LIVE mid-service")]
