"""Repo lint: AST rules for the mistakes that cost device time.

  LINT001  host sync inside a serve/pipeline hot path —
           ``.block_until_ready()``, ``np.asarray(...)`` or
           ``float(...)`` in the functions that run between dispatches
           forces a device fetch (or at best a host copy) on the path
           whose whole point is to never wait on the device.  Known-
           benign uses (host-built arrays, the documented fetch-mode
           fallback) carry a ``# lint: allow`` pragma with the reason.
  LINT002  import-time ``jax.jit`` outside the sanctioned registries —
           a module-level jit entry that is NOT registered in
           device/registry.py is an entry the jaxpr auditor cannot
           enumerate and the retrace tripwire cannot name.  Checked by
           IDENTITY against the live registry (import the module, look
           the object up), so a registration in any form satisfies it.
  LINT003  unhashable static-argnum candidate — a list/dict/set
           literal passed to a known static argname at a call site
           raises ``TypeError: unhashable`` only at runtime, usually
           minutes into a TPU round; flag it at review time.
  LINT004  raw native C-API call outside the audited wrappers — the
           ``ag_*`` ctypes surface (core/native/) takes raw pointers
           and trusts its callers' length/shape screens; every call
           must go through an AUDITED wrapper module
           (core/native.py, bridge/native_ingest.py,
           serve/native_admission.py) where those screens live.  A
           hot-path ``_lib().ag_...`` sprinkled elsewhere bypasses
           them — an OOB read two layers below the first test that
           would notice.  Paired with lockcheck's LOCK005 (no
           ``ag_*`` call under the admission lock): together they
           pin the ISSUE-14 GIL-release contract statically.
  LINT005  bare ``threading.Thread(...)`` outside the thread-wrapper
           modules — a thread spawned anywhere else bypasses the
           host's failure containment (serve/threaded.py `_guard`
           fails the whole host closed when a loop dies; a bare
           daemon thread dies SILENTLY) and is invisible to the
           schedule checker, whose `thread_factory` seam can only
           serialize threads created through it.  Spawn through
           ThreadedVoteService / FlightRecorder / the metrics
           exporter, or annotate ``# lint: allow-thread (reason)``
           anywhere in the call span for the rare justified case
           (the schedule checker's own turnstile workers are one).

Pragma: ``# lint: allow`` on the offending line (reason after the
marker), mirroring lockcheck's; LINT005 uses the more specific
``# lint: allow-thread`` so a generic allow cannot silence it.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from agnes_tpu.analysis.jaxpr_audit import Finding

PRAGMA = "lint: allow"

#: hot-path functions per file (repo-relative): the code that runs
#: between device dispatches on the serve plane
HOT_PATHS: Dict[str, Set[str]] = {
    "agnes_tpu/serve/pipeline.py": {
        "stage", "_build_all", "_build_one", "dispatch_staged", "pump",
        "_sync_window", "_entry_phase",
    },
    "agnes_tpu/serve/service.py": {
        "submit", "pump", "_close_batch", "_pump_batch",
    },
    "agnes_tpu/serve/threaded.py": {
        "submit", "_submit_loop", "_dispatch_loop",
    },
    "agnes_tpu/serve/native_admission.py": {
        "submit", "drain",
    },
    "agnes_tpu/harness/device_driver.py": {
        "step_async",
    },
    # ISSUE 15: the multi-host serve plane's between-dispatch code —
    # the pod front door's screen/rebase, the lifted dispatch
    # closures, and the local-block output views all run while a pod
    # step is in flight on every host
    "agnes_tpu/distributed/shard.py": {
        "submit", "submit_local", "pump",
    },
    # ISSUE 17: the elastic tick's host-side work — front-door
    # routing (mine/adopted/foreign), held-gossip bookkeeping, frame
    # pack/unpack feeding the per-tick allgather — all runs between
    # negotiated dispatches on every host
    "agnes_tpu/distributed/elastic.py": {
        "submit", "tick", "_hold", "_take_reroute",
        "_ingest_reroute", "_local_decision_frame",
    },
    "agnes_tpu/distributed/driver.py": {
        "_lift", "_dense_dispatch_fn", "_make_sharded_seq",
        "step_async", "_agree", "_plan_sig",
    },
}

#: static argnames across the registered entries (device/registry.py);
#: call sites passing unhashable literals to these are LINT003
STATIC_KWARGS = frozenset({
    "axis_name", "advance_height", "verify_chunk", "heights", "donate",
    "pallas_field",
})

#: modules sanctioned to DEFINE import-time jits; everything they
#: define must still be registered (identity check)
SANCTIONED_JIT_MODULES = ("agnes_tpu/device/step.py",
                          "agnes_tpu/parallel/sharded.py")

#: the audited ctypes wrapper modules — the ONLY places a raw
#: ``ag_*`` C-API call may appear (LINT004); each pairs every call
#: with the length/shape screens the raw ABI trusts its caller for
AUDITED_CAPI_MODULES = frozenset({
    "agnes_tpu/core/native.py",
    "agnes_tpu/bridge/native_ingest.py",
    "agnes_tpu/serve/native_admission.py",
})

#: LINT005 pragma — deliberately NOT the generic PRAGMA: a thread
#: spawn is a structural decision, so the annotation must name it
THREAD_PRAGMA = "lint: allow-thread"

#: the modules that may construct OS threads directly — each wraps
#: its threads in a containment story (the serve host's `_guard`
#: fails closed, the flight recorder's writer is crash-isolated, the
#: metrics exporter owns its server thread's lifecycle).  Everything
#: else spawns through these or carries the LINT005 pragma.
THREAD_WRAPPER_MODULES = frozenset({
    "agnes_tpu/serve/threaded.py",
    "agnes_tpu/utils/flightrec.py",
    "agnes_tpu/utils/metrics_http.py",
})


def _has_pragma(lines, lineno: int) -> bool:
    return lineno - 1 < len(lines) and PRAGMA in lines[lineno - 1]


def package_modules(repo_root: str) -> List[str]:
    """Every .py file of the package tree, repo-relative, sorted —
    THE scan-root derivation every repo-wide pass shares (ISSUE 9:
    scan roots used to be hand-maintained per pass, and the post-PR4
    modules — analysis/admission_mc.py, utils/flightrec.py,
    utils/metrics_http.py — silently fell outside lockcheck's list;
    deriving from the tree means a new module is scanned the moment
    the file exists)."""
    pkg_root = os.path.join(repo_root, "agnes_tpu")
    out: List[str] = []
    for root, dirs, names in os.walk(pkg_root):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        out.extend(os.path.relpath(os.path.join(root, n), repo_root)
                   for n in names if n.endswith(".py"))
    return sorted(out)


# -- LINT001: host syncs in hot paths ----------------------------------------

class _HotPathVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str, source: str, hot: Set[str]):
        self.relpath = relpath
        self.lines = source.splitlines()
        self.hot = hot
        self.findings: List[Finding] = []
        self._depth = 0                # inside a hot function?

    def _find(self, node, what: str) -> None:
        if _has_pragma(self.lines, node.lineno):
            return
        self.findings.append(Finding(
            "lint", "LINT001", f"{self.relpath}:{node.lineno}",
            f"{what} inside serve hot path — a host sync on the "
            f"never-wait-on-device path (annotate `# {PRAGMA} "
            f"(reason)` if provably host-side)"))

    def visit_FunctionDef(self, node) -> None:
        inside = node.name in self.hot
        if inside:
            self._depth += 1
        self.generic_visit(node)
        if inside:
            self._depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        if self._depth:
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr == "block_until_ready":
                    self._find(node, ".block_until_ready()")
                elif (f.attr == "asarray"
                      and isinstance(f.value, ast.Name)
                      and f.value.id in ("np", "numpy")):
                    self._find(node, "np.asarray(...)")
            elif isinstance(f, ast.Name) and f.id == "float" \
                    and node.args:
                self._find(node, "float(...) on a possibly-device value")
        self.generic_visit(node)


def check_hot_paths(repo_root: str,
                    hot_paths: Optional[Dict[str, Set[str]]] = None
                    ) -> List[Finding]:
    """LINT001 needs per-FUNCTION knowledge (which bodies run between
    dispatches), so HOT_PATHS stays a curated map — but a key naming a
    module that no longer exists is silent rot, reported as a finding
    instead of skipped."""
    findings: List[Finding] = []
    for rel, hot in (hot_paths or HOT_PATHS).items():
        path = os.path.join(repo_root, rel)
        if not os.path.exists(path):
            findings.append(Finding(
                "lint", "LINT001", rel,
                "HOT_PATHS names a module that does not exist — the "
                "curated hot-path map has rotted; update lint.HOT_PATHS"))
            continue
        with open(path) as fh:
            src = fh.read()
        v = _HotPathVisitor(rel, src, hot)
        v.visit(ast.parse(src, filename=rel))
        findings.extend(v.findings)
    return findings


# -- LINT002: unregistered import-time jits ----------------------------------

def _is_jit_call(node) -> bool:
    """ast matches `jax.jit(...)` or `functools.partial(jax.jit, ...)`
    / `partial(jax.jit, ...)`."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "jit" \
            and isinstance(f.value, ast.Name) and f.value.id == "jax":
        return True
    is_partial = (isinstance(f, ast.Name) and f.id == "partial") or \
        (isinstance(f, ast.Attribute) and f.attr == "partial")
    if is_partial and node.args:
        a = node.args[0]
        return (isinstance(a, ast.Attribute) and a.attr == "jit"
                and isinstance(a.value, ast.Name)
                and a.value.id == "jax")
    return False


def _module_level_jits(tree) -> List[Tuple[str, int]]:
    """(name, lineno) of import-time jit objects: module-level
    `name = jax.jit(...)` assignments and `@jax.jit`-family decorated
    module-level defs."""
    out: List[Tuple[str, int]] = []
    for node in tree.body:
        if isinstance(node, ast.Assign) and _is_jit_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.append((t.id, node.lineno))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_call(dec) or (
                        isinstance(dec, ast.Attribute)
                        and dec.attr == "jit"
                        and isinstance(dec.value, ast.Name)
                        and dec.value.id == "jax"):
                    out.append((node.name, node.lineno))
    return out


def check_import_time_jits(repo_root: str,
                           registered_check=None,
                           importer=None) -> List[Finding]:
    """Every module-level jit under agnes_tpu/ must be a REGISTERED
    entry (identity against device/registry.py).  `registered_check`
    and `importer` are injectable for fixtures; they default to the
    live registry (after importing the canonical modules) and
    importlib."""
    import importlib

    if registered_check is None:
        from agnes_tpu.device import registry

        registry.ensure_populated()
        registered_check = registry.is_registered_jit
    if importer is None:
        importer = importlib.import_module

    findings: List[Finding] = []
    for rel in package_modules(repo_root):
        with open(os.path.join(repo_root, rel)) as fh:
            src = fh.read()
        jits = _module_level_jits(ast.parse(src, filename=rel))
        if not jits:
            continue
        mod_name = rel[:-3].replace(os.sep, ".")
        try:
            mod = importer(mod_name)
        except Exception as e:  # noqa: BLE001 — unimportable module
            findings.append(Finding(
                "lint", "LINT002", rel,
                f"module defines import-time jit(s) but failed to "
                f"import for registration check: {e!r}"))
            continue
        for jname, lineno in jits:
            obj = getattr(mod, jname, None)
            if obj is None or not registered_check(obj):
                findings.append(Finding(
                    "lint", "LINT002", f"{rel}:{lineno}",
                    f"import-time jit {jname!r} is not a "
                    f"registered entry (device/registry.py) — the "
                    f"jaxpr auditor cannot enumerate it"))
    return findings


# -- LINT004: raw C-API calls outside the audited wrappers -------------------

class _CapiVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.lines = source.splitlines()
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr.startswith("ag_") \
                and not _has_pragma(self.lines, node.lineno):
            self.findings.append(Finding(
                "lint", "LINT004", f"{self.relpath}:{node.lineno}",
                f"raw native C-API call .{f.attr}() outside the "
                f"audited wrapper modules — the ctypes surface takes "
                f"raw pointers and trusts its caller's length/shape "
                f"screens (route through core/native.py, "
                f"bridge/native_ingest.py or "
                f"serve/native_admission.py)"))
        self.generic_visit(node)


def check_capi_wrappers(repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel in package_modules(repo_root):
        if rel.replace(os.sep, "/") in AUDITED_CAPI_MODULES:
            continue
        with open(os.path.join(repo_root, rel)) as fh:
            src = fh.read()
        v = _CapiVisitor(rel, src)
        v.visit(ast.parse(src, filename=rel))
        findings.extend(v.findings)
    return findings


# -- LINT005: bare thread construction outside the wrapper modules -----------

def _span_pragma(lines, node, pragma: str) -> bool:
    """Pragma anywhere in the call's line span — thread spawns are
    routinely multi-line calls with the annotation on the closing
    argument line."""
    end = getattr(node, "end_lineno", None) or node.lineno
    return any(pragma in lines[i]
               for i in range(node.lineno - 1, min(end, len(lines))))


class _ThreadVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.lines = source.splitlines()
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "Thread" \
                and isinstance(f.value, ast.Name) \
                and f.value.id == "threading" \
                and not _span_pragma(self.lines, node, THREAD_PRAGMA):
            self.findings.append(Finding(
                "lint", "LINT005", f"{self.relpath}:{node.lineno}",
                f"bare threading.Thread(...) outside the thread-"
                f"wrapper modules — bypasses failure containment "
                f"(a dead daemon thread is silent; serve/threaded.py "
                f"fails closed) and the schedule checker's "
                f"thread_factory seam cannot serialize it (annotate "
                f"`# {THREAD_PRAGMA} (reason)` if the spawn owns its "
                f"own containment)"))
        self.generic_visit(node)


def check_threads(repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel in package_modules(repo_root):
        if rel.replace(os.sep, "/") in THREAD_WRAPPER_MODULES:
            continue
        with open(os.path.join(repo_root, rel)) as fh:
            src = fh.read()
        v = _ThreadVisitor(rel, src)
        v.visit(ast.parse(src, filename=rel))
        findings.extend(v.findings)
    return findings


# -- LINT003: unhashable static candidates -----------------------------------

class _StaticKwVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.lines = source.splitlines()
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        for kw in node.keywords:
            if kw.arg in STATIC_KWARGS and isinstance(
                    kw.value, (ast.List, ast.Dict, ast.Set)) \
                    and not _has_pragma(self.lines, node.lineno):
                self.findings.append(Finding(
                    "lint", "LINT003",
                    f"{self.relpath}:{node.lineno}",
                    f"unhashable {type(kw.value).__name__.lower()} "
                    f"literal passed to static argname "
                    f"{kw.arg!r} — TypeError at trace time"))
        self.generic_visit(node)


def check_static_kwargs(repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel in package_modules(repo_root):
        with open(os.path.join(repo_root, rel)) as fh:
            src = fh.read()
        v = _StaticKwVisitor(rel, src)
        v.visit(ast.parse(src, filename=rel))
        findings.extend(v.findings)
    return findings


def check_repo(repo_root: str) -> List[Finding]:
    """All five rules over the repo."""
    return (check_hot_paths(repo_root)
            + check_import_time_jits(repo_root)
            + check_static_kwargs(repo_root)
            + check_capi_wrappers(repo_root)
            + check_threads(repo_root))
