"""Exhaustive bounded model checker for the consensus core (ISSUE 6).

The reference's whole design argument is that the consensus core is
pure and I/O-free precisely so its logic can be checked without
networking or signatures (README.md:8-14) — yet until this module the
only guard on the *semantics* was a 100-random-seed fuzz
(tests/test_cross_plane.py).  TOB-SVD (arXiv 2310.11331) catalogues
exactly the class of adversarial participation/schedule corners that
sampled fuzzing misses.  This checker closes the gap for SMALL SCOPES:
it exhaustively enumerates every delivery/timeout/partition schedule of
the host plane (harness/simulator.py step mode) within explicit bounds
and checks spec-level property monitors on every reachable state.

Soundness envelope — what "exhaustive" means here
-------------------------------------------------

Exhaustive WITHIN the bounds of an `MCConfig`, nothing beyond them:

  * N nodes with a fixed behavior assignment (honest / silent /
    equivocator / nil_flood — the simulator's fault models), one
    optional partition/heal cycle;
  * schedule length <= `depth` actions;
  * rounds <= `max_round` (rounds only advance off TIMEOUT_PRECOMMIT
    fires, which the action enumerator caps);
  * heights <= `max_height` (states where every node has advanced past
    the bound stop expanding);
  * a FIXED validator-set epoch schedule (`epochs`, ISSUE 9): per-
    height-boundary power tables mirroring the device plane's
    `set_validators` contract — tallies, DecisionCerts and the quorum
    monitor are all indexed by the epoch live at the vote's height;
  * at most `churn_budget` sleepy-churn naps (ISSUE 9, TOB-SVD's
    sleepy model): ("s", j)/("w", j) actions — deliveries to an
    asleep node hold, its timers freeze, a wake releases both —
    budgeted exactly the way faults are.

Within that envelope every interleaving is covered: the explorer is a
depth-bounded DFS over the step-mode transition system with

  * canonical state hashing (`Network.mc_digest` over int-only
    canonical forms — deadline-free timers, dead-timer erasure, history
    erasure) so converging interleavings merge, and
  * partial-order reduction: deliveries/timeouts targeting DISTINCT
    nodes commute (they touch disjoint node state and disjoint channel
    heads), so after exploring independent action `a` from a state, the
    lower-ordered independent siblings already explored from that state
    are put to sleep in `a`'s subtree — the pruned interleaving's
    successor is exactly the state the sibling-first branch reaches.
    Partition/heal are global (never slept).  `por=False` disables the
    reduction; tests assert por/no-por reach the SAME state set.

Property monitors (checked on every new state / transition):

  agreement      no two nodes decide different values at a height
                 (every node runs honest executor logic — byzantine
                 behaviors are router policies — so ALL nodes count)
  validity       every decided value was carried by some WireProposal
                 of that height
  quorum         every decision's DecisionCert (core/executor.py)
                 shows +2/3 precommit weight — no decide without quorum
  monotonic      per node, (height, round, step) never decreases
  evidence       every schedule-injected equivocation pair that was
                 delivered-and-counted is surfaced by round_votes
                 (`all_equivocations`)

Any violation is delta-debug-minimized (`minimize`) to a short
schedule; `run_schedule` skips not-enabled actions, which is what makes
arbitrary ddmin subsets replayable.  A minimized counterexample is
serialized as a corpus entry (tests/corpus/*.json) and can be replayed
through the PRODUCTION device plane (`device_replay_entry`:
VoteBatcher -> fused step via harness/replay.py) so a semantic
counterexample immediately becomes a cross-plane differential case.

The checker itself is pure CPU, ZERO jax imports, ZERO XLA compiles —
it runs in the same pre-test ci.sh gate slot as agnes_lint, with the
same frontier-sharded spawn-worker parallelism (`run_scope`) and the
same deadline-bounded real-value-or-sentinel contract.

Mutation self-test (`self_test` / `--self-test`): doctored executors
— deciding without quorum, dropping equivocation evidence, counting
heads instead of power, tallying against the PREVIOUS validator-set
epoch, treating a wake as a reboot — must each be caught, minimized,
and must vanish when the same schedule replays on the honest
executor; violations living past a height boundary (DEEP_MUTANTS)
are walk-discovered on the doctored executor and share the same
drill.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from agnes_tpu.core.executor import ConsensusExecutor
from agnes_tpu.core import state_machine as sm
from agnes_tpu.harness.simulator import Network, NodeSpec
from agnes_tpu.types import VoteType

PROPERTIES = ("agreement", "validity", "quorum", "monotonic", "evidence")


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MCConfig:
    """One bounded-exploration task: a behavior assignment plus the
    exhaustiveness envelope.  JSON-able (spawn workers, corpus files).

    `powers` assigns per-node voting power (original-index order, like
    `behaviors`; None = all 1).  Asymmetric vectors move every +2/3
    quorum boundary — the committee-weight territory of PAPERS.md
    2004.12990 — and the monitors check the WEIGHTED predicates
    (DecisionCert weight vs total power), so a tally that counts heads
    instead of power is a catchable bug (the weight-blind mutant).

    `epochs` (ISSUE 9) is a validator-set epoch schedule:
    ((boundary_height, (power, ...)), ...) in original-index order —
    at every height the tally weights/totals come from the epoch with
    the largest boundary <= height (genesis `powers` below the first
    boundary), mirroring the device plane's `set_validators`
    height-boundary contract.  A boundary at height 0 models a set
    rotated in at genesis whose table differs from the one the
    rotation was seeded with — the cheapest scope in which a
    stale-epoch tally is a reachable, catchable bug.

    `churn_budget`/`churnable` open TOB-SVD's sleepy-participation
    schedule space (arXiv 2310.11331): ("s", j)/("w", j) actions join
    the explored alphabet, bounded exactly the way faults are — at
    most `churn_budget` sleeps, `churnable` (sorted-set indices, like
    `partition`) naming the nodes allowed to nap (None = every honest
    node).

    The three new knobs serialize ONLY when non-default so every
    pre-epoch corpus entry regenerates bit-identical."""

    name: str
    n: int = 4
    behaviors: Tuple[str, ...] = ("honest",) * 4
    depth: int = 10
    max_round: int = 1
    max_height: int = 0
    partition: Optional[Tuple[Tuple[int, ...], ...]] = None
    get_value_base: int = 100
    powers: Optional[Tuple[int, ...]] = None
    epochs: Optional[Tuple[Tuple[int, Tuple[int, ...]], ...]] = None
    churn_budget: int = 0
    churnable: Optional[Tuple[int, ...]] = None

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["behaviors"] = list(self.behaviors)
        d["partition"] = None if self.partition is None else \
            [list(g) for g in self.partition]
        if self.powers is not None:
            d["powers"] = list(self.powers)
        # bit-stable serialization: pre-ISSUE-9 configs must produce
        # the exact JSON they always did (corpus regeneration contract)
        if self.epochs is None:
            d.pop("epochs")
        else:
            d["epochs"] = [[h, list(pw)] for h, pw in self.epochs]
        if not self.churn_budget:
            d.pop("churn_budget")
        if self.churnable is None:
            d.pop("churnable")
        else:
            d["churnable"] = list(self.churnable)
        return d

    @classmethod
    def from_json(cls, d: dict) -> "MCConfig":
        d = dict(d)
        d["behaviors"] = tuple(d["behaviors"])
        if d.get("partition") is not None:
            d["partition"] = tuple(tuple(g) for g in d["partition"])
        if d.get("powers") is not None:
            d["powers"] = tuple(d["powers"])
        if d.get("epochs") is not None:
            d["epochs"] = tuple((int(h), tuple(pw))
                                for h, pw in d["epochs"])
        if d.get("churnable") is not None:
            d["churnable"] = tuple(d["churnable"])
        return cls(**d)

    def epochs_dict(self) -> Optional[Dict[int, Tuple[int, ...]]]:
        """The schedule as the {boundary: powers} dict Network takes."""
        return None if self.epochs is None else dict(self.epochs)


def build_network(cfg: MCConfig,
                  executor_cls: Optional[type] = None,
                  sign: bool = False,
                  verify: Optional[bool] = None,
                  start: bool = True) -> Network:
    """A step-mode Network for `cfg`.  The checker runs unsigned +
    unverified (crypto is differential-tested elsewhere; the schedule
    space is about consensus logic); corpus replay rebuilds the SAME
    config signed + verifying for production parity (sign=True)."""
    base = cfg.get_value_base
    powers = cfg.powers or (1,) * cfg.n
    net = Network(
        n=cfg.n,
        specs=[NodeSpec(behavior=b, power=p)
               for b, p in zip(cfg.behaviors, powers)],
        get_value=lambda h: base + h,
        verify_signatures=sign if verify is None else verify,
        sign_messages=sign,
        executor_cls=executor_cls or ConsensusExecutor,
        epochs=cfg.epochs_dict())
    net.enable_step_mode(partition_groups=cfg.partition,
                         max_height=cfg.max_height,
                         churn_budget=cfg.churn_budget,
                         churnable=cfg.churnable)
    if start:
        net.mc_start()
    return net


# ---------------------------------------------------------------------------
# Property monitors
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Violation:
    property: str
    node: int                  # -1 for global properties
    detail: str


def _edge_snapshot(net: Network) -> list:
    """The per-node facts the transition monitors compare across one
    action: position, decision/cert counts."""
    return [((nd.height, nd.state.round, int(nd.state.step)),
             len(nd.decisions), len(nd.decision_certs))
            for nd in net.nodes]


def _edge_violations(net: Network, snap: list) -> List[Violation]:
    """Monotonicity + quorum certificates, checked on the transition
    from the state `snap` was taken in to `net`'s current state."""
    out: List[Violation] = []
    for j, nd in enumerate(net.nodes):
        pos0, n_dec0, _n_cert0 = snap[j]
        pos = (nd.height, nd.state.round, int(nd.state.step))
        if pos < pos0:
            out.append(Violation(
                "monotonic", j,
                f"(height, round, step) went {pos0} -> {pos}"))
        for i in range(n_dec0, len(nd.decisions)):
            d = nd.decisions[i]
            if i >= len(nd.decision_certs):
                out.append(Violation(
                    "quorum", j,
                    f"decision {d} recorded without a quorum "
                    f"certificate"))
                continue
            c = nd.decision_certs[i]
            epoch_total = net.epoch_total_at(d.height)
            if (c.height, c.round, c.value) != (d.height, d.round,
                                                d.value):
                out.append(Violation(
                    "quorum", j,
                    f"certificate {c} does not match decision {d}"))
            elif c.total != epoch_total:
                # epoch-indexed check (ISSUE 9): the quorum must be
                # denominated in the validator set LIVE at the vote's
                # height — a cert totalled against any other epoch is
                # the stale-epoch tally bug even if its own arithmetic
                # clears +2/3
                out.append(Violation(
                    "quorum", j,
                    f"decided {d.value} at (h={d.height}, r={d.round}) "
                    f"with a certificate denominated {c.weight}/"
                    f"{c.total} against a stale validator-set epoch "
                    f"(live epoch total: {epoch_total})"))
            elif not 3 * c.weight > 2 * c.total:
                out.append(Violation(
                    "quorum", j,
                    f"decided {d.value} at (h={d.height}, r={d.round}) "
                    f"on precommit weight {c.weight}/{c.total} "
                    f"(< +2/3)"))
    return out


def _state_violations(net: Network) -> List[Violation]:
    """Agreement, validity, evidence completeness — state predicates."""
    out: List[Violation] = []
    by_height: Dict[int, Dict[int, int]] = {}
    for j, nd in enumerate(net.nodes):
        for h, d in nd.decided.items():
            by_height.setdefault(h, {})[j] = d.value
    for h, m in sorted(by_height.items()):
        if len(set(m.values())) > 1:
            out.append(Violation(
                "agreement", -1,
                f"height {h} decided as {sorted(m.items())}"))
        proposed = net._proposed.get(h, ())
        for j, v in sorted(m.items()):
            if v not in proposed:
                out.append(Violation(
                    "validity", j,
                    f"node {j} decided unproposed value {v} at "
                    f"height {h} (proposed: {sorted(proposed)})"))
    for j, nd in enumerate(net.nodes):
        expected = net._expected_ev[j]
        if not expected:
            continue
        have = {(e.validator, e.height, e.round, int(e.typ))
                for e in nd.all_equivocations()}
        missing = expected - have
        if missing:
            out.append(Violation(
                "evidence", j,
                f"node {j} counted conflicting vote pairs "
                f"{sorted(missing)} but surfaced no equivocation "
                f"evidence for them"))
    return out


# ---------------------------------------------------------------------------
# Symmetry reduction (ISSUE 7 tentpole axis 1)
# ---------------------------------------------------------------------------


class SymmetryCapError(AssertionError):
    """A state escaped the envelope the symmetry group was built for
    (a node's height exceeded `h_cap` or its round exceeded
    `max_round`).  Orbit merges made under that assumption would be
    unsound, so the exploration fails LOUD instead of silently
    reporting a reduced-but-wrong state count.  The fix is a larger
    cap (more fixed proposer slots, less reduction), never ignoring
    the error."""


def relabel_action(act: tuple, perm: Sequence[int]) -> tuple:
    """An action's name under a node relabeling: deliveries carry
    (src, dst), timeouts and sleep/wake a node index; partition/heal
    are global."""
    k = act[0]
    if k == "d":
        return ("d", perm[act[1]], perm[act[2]])
    if k == "t":
        return ("t", perm[act[1]], *act[2:])
    if k in ("s", "w"):
        return (k, perm[act[1]])
    return act


@dataclasses.dataclass(frozen=True)
class Symmetry:
    """A sound node-permutation group for one MCConfig.

    Honest nodes are interchangeable — relabeling them induces a
    bisimulation — PROVIDED the permutation fixes everything the
    transition relation can tell nodes apart by:

      * behavior (byzantine policies are per-node),
      * voting power in EVERY epoch window live inside the envelope
        (weights feed every quorum predicate, per height —
        validator-set epochs make power a function of height),
      * sleepy-churn eligibility (a churnable node's enabled alphabet
        includes ("s", j); relabeling it onto a pinned-awake node
        would not be a bisimulation),
      * partition group (the ("p",) action's shape is fixed),
      * every proposer slot queryable inside the envelope: heights
        <= `h_cap`, rounds <= `max_round` (proposer identity is the
        ONE asymmetry in honest logic).  `h_cap` comes from a sound
        decision lower bound (`_decision_bound`): when the schedule
        budget cannot possibly produce a decision, no node ever
        leaves height 0 and only height-0 proposers need fixing —
        which is what makes the n=7 scopes collapse by orbits of the
        5 interchangeable non-proposers.

    `digest()` re-checks the envelope on every state (SymmetryCapError
    on escape), so the reduction is self-verifying rather than
    trusted.  Only meaningful on unsigned networks (the checker's
    build): per-node signing keys would distinguish relabeled nodes.
    """

    perms: Tuple[Tuple[int, ...], ...]     # identity first
    h_cap: int
    max_round: int

    def check(self, net: Network) -> None:
        for nd in net.nodes:
            if nd.height > self.h_cap:
                raise SymmetryCapError(
                    f"node at height {nd.height} > symmetry h_cap "
                    f"{self.h_cap}: orbit merges would be unsound")
            if nd.state.round > self.max_round:
                raise SymmetryCapError(
                    f"node at round {nd.state.round} > symmetry round "
                    f"cap {self.max_round}")

    def digest(self, net: Network) -> Tuple[bytes,
                                            Optional[Tuple[int, ...]]]:
        """(least orbit digest, canonicalizing perm or None for
        identity) — the visited key and the frame's action-name
        translation (the rec[] bookkeeping must compare actions in ONE
        labeling per orbit)."""
        self.check(net)
        best = net.mc_digest()
        best_p: Optional[Tuple[int, ...]] = None
        for p in self.perms[1:]:
            d = net.mc_digest(p)
            if d < best:
                best, best_p = d, p
        return best, best_p


def _decision_bound(net: Network, max_height: int = 0) -> int:
    """A sound LOWER bound on the schedule length of any decision:
    the decider needs q-1 delivered value-precommits (q = fewest
    validators, heaviest first, whose power clears +2/3), and each of
    those q-1 precommitters needed q-1 delivered prevotes for its
    polka — all distinct delivery actions.  Behaviors only remove
    messages, first-vote dedup blocks double counting, and sleepy
    churn only withholds deliveries, so no fault or churn schedule
    shortens this.  With validator-set epochs the quorum size varies
    per height, so the bound is the MINIMUM over every epoch live
    within the envelope (heights 0..max_height+1) — a decision at any
    reachable height needs at least its own epoch's q*(q-1).  Holds
    for the HONEST quorum rule only — a doctored executor may decide
    cheaper, so mutant explorations must not lean on it
    (build_symmetry keeps their h_cap conservative)."""
    def bound_at(height: int) -> int:
        powers = sorted(net.epoch_powers_at(height), reverse=True)
        total = sum(powers)
        acc = q = 0
        for w in powers:
            acc += w
            q += 1
            if 3 * acc > 2 * total:
                break
        return q * (q - 1)

    return min(bound_at(h) for h in range(max_height + 2))


def build_symmetry(cfg: MCConfig,
                   executor_cls: Optional[type] = None,
                   max_perms: int = 24) -> Symmetry:
    """The symmetry group for `cfg` (sorted-index space).  Buckets the
    honest, non-proposer-slot nodes by their full distinguishing
    profile and permutes within buckets; the group size is capped at
    `max_perms` (canonicalization costs one digest per perm per state)
    by fixing lowest-index members of the largest bucket first —
    deterministic, less reduction, never unsound.

    PER-EPOCH construction (ISSUE 9): interchangeable nodes must agree
    on everything the transition relation can tell them apart by in
    EVERY epoch window reachable inside the envelope — genesis power,
    the power vector of each epoch live at heights <= h_cap, partition
    group, proposer slots, and sleepy-churn eligibility (a churnable
    node and a pinned-awake one have different enabled alphabets, so
    relabeling across that line would not be a bisimulation).  The
    epoch profile is read through `Network.epoch_powers_at` — the same
    config-derived ground truth the monitors use — never through a
    (possibly doctored) executor."""
    import itertools
    import math

    net = build_network(cfg, executor_cls)
    mutant = executor_cls is not None \
        and executor_cls is not ConsensusExecutor
    if mutant or cfg.depth >= _decision_bound(net, cfg.max_height):
        h_cap = cfg.max_height + 1
    else:
        h_cap = 0            # no decision fits the budget: heights pin
    probe = net.nodes[0]
    fixed = {probe.proposer(h, r)
             for h in range(h_cap + 1)
             for r in range(cfg.max_round + 1)}
    gid: List[Optional[int]] = [None] * cfg.n
    if cfg.partition is not None:
        for g, members in enumerate(cfg.partition):
            for i in members:
                gid[i] = g
    buckets_by_key: Dict[tuple, List[int]] = {}
    for i in range(cfg.n):
        if i in fixed or net.specs[i].behavior != "honest":
            continue
        epoch_profile = tuple(net.epoch_powers_at(h)[i]
                              for h in range(h_cap + 1))
        churn_ok = (i in net._churnable) if cfg.churn_budget else None
        key = (net.specs[i].power, epoch_profile, gid[i], churn_ok)
        buckets_by_key.setdefault(key, []).append(i)
    buckets = [b for b in buckets_by_key.values() if len(b) >= 2]

    def group_size(bs):
        return math.prod(math.factorial(len(b)) for b in bs)

    while buckets and group_size(buckets) > max_perms:
        max(buckets, key=len).pop(0)
        buckets = [b for b in buckets if len(b) >= 2]

    ident = tuple(range(cfg.n))
    perms = [ident]
    for b in buckets:                      # buckets are disjoint
        perms = [_compose(p, b, order)
                 for p in perms
                 for order in itertools.permutations(b)]
    perms = [ident] + sorted(p for p in set(perms) if p != ident)
    return Symmetry(perms=tuple(perms), h_cap=h_cap,
                    max_round=cfg.max_round)


def _compose(base: Tuple[int, ...], bucket: List[int],
             order: Tuple[int, ...]) -> Tuple[int, ...]:
    p = list(base)
    for src, dst in zip(bucket, order):
        p[src] = dst
    return tuple(p)


# ---------------------------------------------------------------------------
# The explorer
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Counterexample:
    config: object                 # MCConfig | admission config
    violation: Violation
    schedule: List[tuple]          # action tuples from the initial state
    minimized: Optional[List[tuple]] = None
    codec: type = Network          # owns action_to_json/from_json

    def to_json(self) -> dict:
        sched = self.minimized if self.minimized is not None \
            else self.schedule
        return {
            "config": self.config.to_json(),
            "property": self.violation.property,
            "node": self.violation.node,
            "detail": self.violation.detail,
            "schedule": [self.codec.action_to_json(a) for a in sched],
            "schedule_unminimized":
                [self.codec.action_to_json(a) for a in self.schedule],
        }


@dataclasses.dataclass
class Report:
    config: object
    states: int = 0
    transitions: int = 0
    violations: List[Counterexample] = dataclasses.field(
        default_factory=list)
    near_misses: Dict[str, list] = dataclasses.field(default_factory=dict)
    complete: bool = True
    deepest: int = 0
    seconds: float = 0.0
    # filled only when explore(collect_digests=True): the exact visited
    # key set (canonical ORBIT digests under symmetry reduction), for
    # the POR/symmetry-soundness equivalence tests
    digests: Optional[set] = None
    # filled only when explore(collect_orbit_digests=True): the orbit
    # digest of every visited state — lets an UNREDUCED run state its
    # orbit coverage for comparison against a reduced run
    orbit_digests: Optional[set] = None
    # symmetry-group size the exploration ran under (1 = unreduced)
    sym_perms: int = 1
    codec: type = Network

    def to_json(self) -> dict:
        return {
            "config": self.config.name,
            "states": self.states,
            "transitions": self.transitions,
            "violations": [c.to_json() for c in self.violations],
            "near_misses": {k: [self.codec.action_to_json(a) for a in v]
                            for k, v in self.near_misses.items()},
            "complete": self.complete,
            "deepest": self.deepest,
            "sym_perms": self.sym_perms,
            "seconds": round(self.seconds, 1),
        }


def _target(act: tuple) -> Optional[int]:
    """The node an action mutates, None for global actions."""
    if act[0] == "d":
        return act[2]
    if act[0] in ("t", "s", "w"):
        return act[1]
    return None


def _indep(a: tuple, b: tuple) -> bool:
    if a[0] in ("s", "w") and b[0] in ("s", "w"):
        # the shared churn budget couples churn actions: with one
        # sleep left in the budget, taking ("s", j) DISABLES the
        # sibling ("s", k) — the commuting diamond the sleep-set
        # argument needs never closes, so churn-churn pairs stay
        # dependent (deliveries/timeouts never touch the budget, so
        # the distinct-target rule below remains exact for them)
        return False
    ta, tb = _target(a), _target(b)
    return ta is not None and tb is not None and ta != tb


class _Frame:
    __slots__ = ("net", "digest", "depth", "snap", "todo", "idx",
                 "sleep", "cperm")

    def __init__(self, net, digest, depth, snap, todo, sleep, cperm):
        self.net = net
        self.digest = digest
        self.depth = depth
        self.snap = snap
        self.todo = todo
        self.idx = 0
        self.sleep = sleep
        self.cperm = cperm      # canonicalizing perm (None = identity)


def _expandable(net: Network, cfg: MCConfig) -> bool:
    """Height bound: stop once EVERY node is past max_height (partial
    advancement keeps exploring — laggards must still be deliverable)."""
    return any(nd.height <= cfg.max_height for nd in net.nodes)


@dataclasses.dataclass
class Domain:
    """The exhaustive engine's pluggable surface (ISSUE 7: the one DFS
    drives both the consensus Network and the serve-plane admission
    model).  A system object must provide mc_clone / mc_apply /
    mc_digest; everything domain-specific — enabling, monitors, POR
    independence, bounds — arrives as hooks."""

    enabled: Callable[[object], List[tuple]]
    expandable: Callable[[object], bool]
    state_violations: Callable[[object], List[Violation]]
    edge_snapshot: Callable[[object], object]
    edge_violations: Callable[[object, object], List[Violation]]
    indep: Callable[[tuple, tuple], bool]
    near_miss: Optional[Callable[[object, list, "Report"], None]] = None
    symmetry: Optional[Symmetry] = None    # orbit-reduced visited keys
    codec: type = Network


def _explore_domain(root, cfg, dom: Domain, *,
                    por: bool = True,
                    deadline_at: Optional[float] = None,
                    max_states: Optional[int] = None,
                    stop_on_violation: bool = True,
                    collect_digests: bool = False,
                    collect_orbit_digests: bool = False,
                    orbit_sym: Optional[Symmetry] = None) -> Report:
    """Depth-bounded exhaustive DFS over `cfg`'s schedule space
    (`cfg.depth` bounds it; `deadline_at` is an absolute time.time()
    instant past which exploration stops cleanly with complete=False —
    the gate's sentinel half).  Returns on the first violation
    (minimized by the caller).

    Symmetry composition (dom.symmetry): the visited key is the LEAST
    ORBIT digest, and — because different orbit members name the same
    action differently — the per-orbit explored-action bookkeeping
    (`rec[1]`, the sleep-set/state-caching repair) stores and compares
    action names translated into the orbit's canonical labeling via
    each frame's canonicalizing perm.  Concrete frames are never
    relabeled, so counterexample schedules stay root-replayable, and
    POR's sleep sets (path-local, concrete labels) compose unchanged.

    `orbit_sym` makes an UNREDUCED run also record the orbit digest of
    every visited state (Report.orbit_digests) so tests can prove the
    reduced search covers the identical orbit set."""
    t0 = time.perf_counter()
    rep = Report(config=cfg, codec=dom.codec)
    sym = dom.symmetry
    if sym is not None:
        rep.sym_perms = len(sym.perms)
    viols = dom.state_violations(root)
    if viols:
        rep.violations.append(
            Counterexample(cfg, viols[0], [], codec=dom.codec))
        rep.states = 1
        rep.complete = False        # truncated at the root
        rep.seconds = time.perf_counter() - t0
        return rep

    # visited key -> [min_depth_seen, explored action set (canonical
    # labels under symmetry)]
    visited: Dict[bytes, list] = {}
    # raw digest -> (orbit digest, canonicalizing perm): revisits of a
    # raw-identical state skip the |perms| canonicalization loop
    orbit_memo: Dict[bytes, tuple] = {}
    path: List[tuple] = []
    orbit_digests: Optional[set] = set() if orbit_sym is not None \
        else None

    def state_key(net):
        if sym is None and orbit_sym is None:
            return net.mc_digest(), None
        raw = net.mc_digest()
        hit = orbit_memo.get(raw)
        if hit is None:
            hit = orbit_memo[raw] = (sym or orbit_sym).digest(net)
        orbit, cperm = hit
        if orbit_digests is not None:
            orbit_digests.add(orbit)
        if sym is None:
            return raw, None
        return orbit, cperm

    def canon_act(act, cperm):
        return act if cperm is None else relabel_action(act, cperm)

    def make_frame(net, digest, depth, sleep, cperm):
        enabled = dom.enabled(net)
        rec = visited.get(digest)
        if rec is None:
            rec = visited[digest] = [depth, set()]
        elif depth < rec[0]:
            # shallower re-visit: the earlier subtree had less depth
            # budget — re-explore everything from here
            rec[0] = depth
            rec[1] = set()
        todo = [a for a in enabled
                if a not in sleep and canon_act(a, cperm) not in rec[1]]
        rec[1].update(canon_act(a, cperm) for a in todo)
        return _Frame(net, digest, depth, dom.edge_snapshot(net),
                      todo, sleep, cperm)

    root_digest, root_cperm = state_key(root)
    stack = [make_frame(root, root_digest, 0, frozenset(), root_cperm)]
    check_tick = 0

    while stack:
        f = stack[-1]
        if f.idx >= len(f.todo) or f.depth >= cfg.depth \
                or not dom.expandable(f.net):
            stack.pop()
            if path:
                path.pop()
            continue
        act = f.todo[f.idx]
        f.idx += 1

        check_tick += 1
        if deadline_at is not None and check_tick % 256 == 0 \
                and time.time() > deadline_at:
            rep.complete = False
            break
        if max_states is not None and len(visited) >= max_states:
            rep.complete = False
            break

        child = f.net.mc_clone()
        applied = child.mc_apply(act)
        assert applied, (act, "enabled action failed to apply")
        rep.transitions += 1
        depth = f.depth + 1
        rep.deepest = max(rep.deepest, depth)
        sched = path + [act]

        for v in dom.edge_violations(child, f.snap):
            rep.violations.append(
                Counterexample(cfg, v, sched, codec=dom.codec))
        digest, cperm = state_key(child)
        rec = visited.get(digest)
        new_state = rec is None
        if new_state:
            # register EVERY distinct state — including the depth-bound
            # frontier, which never gets a frame: states_explored must
            # count it and the monitors must not re-run per path to it
            visited[digest] = [depth, set()]
            for v in dom.state_violations(child):
                rep.violations.append(
                    Counterexample(cfg, v, sched, codec=dom.codec))
            if dom.near_miss is not None:
                dom.near_miss(child, sched, rep)
        if rep.violations and stop_on_violation:
            rep.complete = False    # truncated, not exhausted
            break

        if depth >= cfg.depth:
            continue
        needs_visit = new_state or depth < rec[0]
        sleep = None
        if not needs_visit:
            # already visited at <= this depth; only new actions (ones
            # neither explored nor slept before) warrant a re-push
            enabled = dom.enabled(child)
            sleep = _child_sleep(f, act, por, dom)
            needs_visit = any(a not in sleep
                              and canon_act(a, cperm) not in rec[1]
                              for a in enabled)
        if needs_visit:
            if sleep is None:
                sleep = _child_sleep(f, act, por, dom)
            nf = make_frame(child, digest, depth, sleep, cperm)
            if nf.todo:
                stack.append(nf)
                path.append(act)

    rep.states = len(visited)
    if collect_digests:
        rep.digests = set(visited)
    if orbit_digests is not None:
        rep.orbit_digests = orbit_digests
    elif sym is not None and collect_orbit_digests:
        # under symmetry the visited keys ARE the orbit digests — the
        # field's contract holds in both modes
        rep.orbit_digests = set(visited)
    rep.seconds = time.perf_counter() - t0
    return rep


def _child_sleep(f: "_Frame", act: tuple, por: bool,
                 dom: Domain) -> frozenset:
    """Sleep set for `act`'s subtree: lower-ordered independent actions
    already explored from `f`'s state — their both-orders diamond
    closes, so re-exploring them under `act` only re-reaches the state
    the sibling-first branch already covers (module docstring)."""
    if not por:
        return frozenset()
    explored = f.todo[:f.idx - 1]
    inherited = f.sleep
    return frozenset(
        b for b in (*explored, *inherited)
        if dom.indep(b, act) and b < act)


def _consensus_domain(cfg: MCConfig,
                      symmetry: Optional[Symmetry] = None) -> Domain:
    return Domain(
        enabled=lambda net: net.mc_enabled(max_round=cfg.max_round),
        expandable=lambda net: _expandable(net, cfg),
        state_violations=_state_violations,
        edge_snapshot=_edge_snapshot,
        edge_violations=_edge_violations,
        indep=_indep,
        near_miss=_classify_near_miss,
        symmetry=symmetry,
        codec=Network)


def explore(cfg: MCConfig,
            executor_cls: Optional[type] = None,
            por: bool = True,
            deadline_at: Optional[float] = None,
            max_states: Optional[int] = None,
            stop_on_violation: bool = True,
            collect_digests: bool = False,
            sym: bool = False,
            collect_orbit_digests: bool = False) -> Report:
    """Depth-bounded exhaustive DFS over `cfg`'s schedule space (the
    consensus domain; _explore_domain is the engine).  `sym=True`
    composes symmetry reduction with POR: states dedup on least-orbit
    digests (build_symmetry's group), cutting visited states by up to
    |group| while reaching the identical orbit set — the smoke gate
    runs with it on.  `collect_orbit_digests` makes an unreduced run
    record its orbit coverage for the equivalence tests."""
    symmetry = build_symmetry(cfg, executor_cls) if sym else None
    orbit_sym = build_symmetry(cfg, executor_cls) \
        if (collect_orbit_digests and not sym) else None
    root = build_network(cfg, executor_cls)
    return _explore_domain(
        root, cfg, _consensus_domain(cfg, symmetry), por=por,
        deadline_at=deadline_at, max_states=max_states,
        stop_on_violation=stop_on_violation,
        collect_digests=collect_digests,
        collect_orbit_digests=collect_orbit_digests,
        orbit_sym=orbit_sym)


def _classify_near_miss(net: Network, sched: List[tuple],
                        rep: Report) -> None:
    """Tag interesting first-reached states; the schedules seed the
    regression corpus (kept as-reached; corpus emission minimizes)."""
    def put(tag):
        if tag not in rep.near_misses:
            rep.near_misses[tag] = list(sched)

    if all(0 in nd.decided for nd in net.nodes):
        put("all_decided")
        if any(nd.decided[0].round >= 1 for nd in net.nodes):
            put("multi_round_decision")
        if net._partition_cycles and net._group is None:
            put("healed_then_decided")
    if any(nd.all_equivocations() for nd in net.nodes):
        put("evidence_surfaced")


# ---------------------------------------------------------------------------
# Deterministic replay + delta-debug minimization
# ---------------------------------------------------------------------------


def run_with_monitors(cfg: MCConfig, actions: Sequence,
                      executor_cls: Optional[type] = None,
                      sign: bool = False) -> Tuple[Network,
                                                   List[Violation]]:
    """Replay `actions` (tuple or JSON form; not-enabled ones skip) on
    a fresh network, running every monitor after every applied action —
    the reproduction predicate for minimization and the corpus tests."""
    net = build_network(cfg, executor_cls, sign=sign)
    viols: List[Violation] = list(_state_violations(net))
    snap = [_edge_snapshot(net)]

    def on_action(_k, _act, ok):
        if ok:
            viols.extend(_edge_violations(net, snap[0]))
            viols.extend(_state_violations(net))
        snap[0] = _edge_snapshot(net)

    net.run_schedule(actions, on_action=on_action)
    return net, viols


def reproduces(cfg: MCConfig, actions: Sequence, prop: str,
               executor_cls: Optional[type] = None) -> bool:
    _, viols = run_with_monitors(cfg, actions, executor_cls)
    return any(v.property == prop for v in viols)


def minimize_schedule(cfg: MCConfig, actions: Sequence[tuple],
                      pred: Callable[[Network, List[Violation]], bool],
                      executor_cls: Optional[type] = None) -> List[tuple]:
    """ddmin (Zeller) over the action sequence, then a greedy
    one-at-a-time pass: a short schedule whose deterministic replay
    still satisfies `pred(net, violations)`.  Replay-with-skip keeps
    every subset well-defined."""
    def pred_acts(acts: List[tuple]) -> bool:
        return pred(*run_with_monitors(cfg, acts, executor_cls))

    return _ddmin(list(actions), pred_acts)


def minimize(cfg: MCConfig, actions: Sequence[tuple], prop: str,
             executor_cls: Optional[type] = None) -> List[tuple]:
    """Shortest (under ddmin) schedule still violating `prop`."""
    return minimize_schedule(
        cfg, actions,
        lambda _net, viols: any(v.property == prop for v in viols),
        executor_cls)


def _ddmin(acts: List[tuple], pred: Callable[[List[tuple]], bool]
           ) -> List[tuple]:
    assert pred(acts), "minimize called on a non-reproducing schedule"
    n = 2
    while len(acts) >= 2:
        chunk = max(1, len(acts) // n)
        reduced = False
        for i in range(0, len(acts), chunk):
            trial = acts[:i] + acts[i + chunk:]
            if trial and pred(trial):
                acts = trial
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if n >= len(acts):
                break
            n = min(len(acts), 2 * n)
    # greedy 1-minimal pass
    i = 0
    while i < len(acts):
        trial = acts[:i] + acts[i + 1:]
        if trial and pred(trial):
            acts = trial
        else:
            i += 1
    return acts


# ---------------------------------------------------------------------------
# Corpus entries (tests/corpus/*.json) + device-plane replay
# ---------------------------------------------------------------------------


def corpus_entry(name: str, cfg: MCConfig, actions: Sequence[tuple],
                 origin: str) -> dict:
    """Serialize a schedule as a regression-corpus entry, stamping the
    honest host plane's outcome (decisions + evidence counts) so the
    replay test asserts bit-stable semantics, not just liveness.
    Multi-height schedules (the epoch-boundary milestones) stamp every
    height's decision under `decided_heights`; the key is OMITTED for
    height-0-only entries so the pre-epoch corpus regenerates
    bit-identical."""
    net, viols = run_with_monitors(cfg, actions)
    entry = {
        "name": name,
        "origin": origin,
        "config": cfg.to_json(),
        "actions": [Network.action_to_json(tuple(a)) for a in actions],
        "expect": {
            "violations": sorted({v.property for v in viols}),
            "decided": {
                str(j): [nd.decided[0].round, nd.decided[0].value]
                for j, nd in enumerate(net.nodes) if 0 in nd.decided},
            "evidence": {
                str(j): len(nd.all_equivocations())
                for j, nd in enumerate(net.nodes)
                if nd.all_equivocations()},
        },
    }
    if any(h != 0 for nd in net.nodes for h in nd.decided):
        entry["expect"]["decided_heights"] = {
            str(j): {str(h): [d.round, d.value]
                     for h, d in sorted(nd.decided.items())}
            for j, nd in enumerate(net.nodes) if nd.decided}
    return entry


def load_corpus(directory: str) -> List[dict]:
    out = []
    if os.path.isdir(directory):
        for fn in sorted(os.listdir(directory)):
            if fn.endswith(".json"):
                with open(os.path.join(directory, fn)) as f:
                    out.append(json.load(f))
    return out


def replay_corpus_entry(entry: dict,
                        sign: bool = False) -> Tuple[Network,
                                                     List[Violation]]:
    """Host-plane deterministic replay of a corpus entry; asserts the
    stamped expectations (decisions, evidence, property verdicts)."""
    cfg = MCConfig.from_json(entry["config"])
    net, viols = run_with_monitors(cfg, entry["actions"], sign=sign)
    exp = entry["expect"]
    got_decided = {str(j): [nd.decided[0].round, nd.decided[0].value]
                   for j, nd in enumerate(net.nodes) if 0 in nd.decided}
    assert got_decided == exp["decided"], (
        f"{entry['name']}: decisions diverged: {got_decided} != "
        f"{exp['decided']}")
    got_ev = {str(j): len(nd.all_equivocations())
              for j, nd in enumerate(net.nodes) if nd.all_equivocations()}
    assert got_ev == exp["evidence"], (
        f"{entry['name']}: evidence diverged: {got_ev} != "
        f"{exp['evidence']}")
    if "decided_heights" in exp:
        got_hs = {str(j): {str(h): [d.round, d.value]
                           for h, d in sorted(nd.decided.items())}
                  for j, nd in enumerate(net.nodes) if nd.decided}
        assert got_hs == exp["decided_heights"], (
            f"{entry['name']}: per-height decisions diverged: "
            f"{got_hs} != {exp['decided_heights']}")
    assert sorted({v.property for v in viols}) == exp["violations"], (
        f"{entry['name']}: property verdicts diverged")
    return net, viols


def device_replay_entry(entry: dict) -> list:
    """Replay a corpus entry's schedule through the PRODUCTION device
    plane: run the signed host network under trace taps, then push each
    node's exact processing stream through VoteBatcher -> fused device
    step (harness/replay.py).  Returns (host net, [(node, {height:
    host Decision}, ReplayResult)]).  Weighted configs hand the sorted
    per-validator power vector to the replay so the device tally
    counts the same quorum boundaries the host did; EPOCH configs
    (ISSUE 9) hand the full height->powers table — the replay installs
    each epoch through the real `set_validators` boundary calls
    (driver + batcher) as the device advances heights, so host ==
    device holds THROUGH a validator-set change.  This is the ONLY
    modelcheck path that touches jax — imported lazily, never from
    the CLI gate."""
    from agnes_tpu.harness.replay import replay_trace, trace_network

    cfg = MCConfig.from_json(entry["config"])
    net = build_network(cfg, sign=True, verify=True, start=False)
    powers = None
    if any(v.voting_power != 1 for v in net.vset):
        powers = net.vset.device_arrays()[1]
    epochs = None
    if net.epochs:
        # sorted-index epoch tables, exactly what the device planes eat
        epochs = {h: list(pw) for h, pw in net.epochs.items()}
    traces = trace_network(net)
    net.run_schedule(entry["actions"])
    out = []
    for j, nd in enumerate(net.nodes):
        rep = replay_trace(traces[j], n_validators=net.n, powers=powers,
                           epochs=epochs)
        out.append((j, dict(nd.decided), rep))
    return net, out


def _walk_until(cfg: MCConfig,
                pred: Callable[[Network], bool],
                seed: int, max_steps: int = 600,
                deliver_bias: Optional[float] = None,
                executor_cls: Optional[type] = None
                ) -> Optional[List[tuple]]:
    """Seeded guided random walk to a predicate state — the corpus
    generator's probe for goals DEEPER than the exhaustive bounds (a
    full 4-node decision takes ~25 deliveries; the explorer's smoke
    depth stops well short).  Deterministic given (cfg, seed).
    `deliver_bias` is the probability of considering non-delivery
    actions at all — large N needs delivery-heavy walks (uniform
    timeout churn wedges at the round cap before a quorum forms).
    `executor_cls` runs the walk on a doctored executor — the
    discovery probe for mutants whose violation lives past a height
    boundary, beyond any exhaustively explorable depth."""
    import random

    rng = random.Random(seed)
    net = build_network(cfg, executor_cls)
    sched: List[tuple] = []
    for _ in range(max_steps):
        if pred(net):
            return sched
        acts = net.mc_enabled(max_round=cfg.max_round)
        if not acts:
            return None
        if deliver_bias is not None:
            dels = [a for a in acts if a[0] == "d"]
            if dels and rng.random() > deliver_bias:
                acts = dels
        act = rng.choice(acts)
        assert net.mc_apply(act)
        sched.append(act)
    return sched if pred(net) else None


def _all_decided(net: Network) -> bool:
    return all(0 in nd.decided for nd in net.nodes)


def _all_decided_through_height_1(net: Network) -> bool:
    return all(0 in nd.decided and 1 in nd.decided for nd in net.nodes)


def _sleepy_recovery_decided(net: Network) -> bool:
    """Everyone decided, at least one real nap happened, nobody is
    still asleep (the woken node's decision proves it caught up on
    the traffic the nap withheld)."""
    return (_all_decided(net) and net._churn_used > 0
            and not any(net._asleep))


#: name -> (config, goal predicate, walk seed, deliver bias): the
#: shipped regression corpus (tests/corpus/).  Each goal is a coverage
#: milestone the cross-plane differential should replay forever: full
#: decisions under each fault model, surfaced equivocation evidence, a
#: partition/heal recovery, a multi-round decision, and an N=7
#: decision.  Seeds are the first that reach the goal; depth is unused
#: by replay (0 marks these as walk configs, not exploration bounds).
CORPUS_GOALS: Dict[str, tuple] = {
    "mc_n4_honest_decides": (
        MCConfig(name="n4_honest", depth=0, max_round=2),
        _all_decided, 1, None),
    "mc_n4_multi_round_decides": (
        MCConfig(name="n4_honest_r1", depth=0, max_round=2),
        lambda net: (_all_decided(net)
                     and any(nd.decided[0].round >= 1
                             for nd in net.nodes)), 0, None),
    "mc_n4_equivocator_evidence": (
        MCConfig(name="n4_equivocator", depth=0, max_round=2,
                 behaviors=("equivocator", "honest", "honest", "honest")),
        lambda net: (_all_decided(net)
                     and any(nd.all_equivocations()
                             for nd in net.nodes)), 3, None),
    "mc_n4_nil_flood_decides": (
        MCConfig(name="n4_nil_flood", depth=0, max_round=2,
                 behaviors=("nil_flood", "honest", "honest", "honest")),
        _all_decided, 8, None),
    "mc_n4_partition_heal_decides": (
        MCConfig(name="n4_partition_heal", depth=0, max_round=2,
                 partition=((0, 1), (2, 3))),
        lambda net: (_all_decided(net) and net._partition_cycles > 0
                     and net._group is None), 2, None),
    "mc_n7_honest_decides": (
        MCConfig(name="n7_honest", n=7, depth=0, max_round=2,
                 behaviors=("honest",) * 7),
        _all_decided, 0, 0.05),
    # weighted milestones (ISSUE 7): decisions whose +2/3 boundary
    # falls between vote counts — the heavy validator is REQUIRED for
    # any quorum (lights alone hold 3/6), so the replayed device tally
    # must weight it correctly or the decision vanishes
    "mc_n4_weighted_decides": (
        MCConfig(name="n4_weighted", depth=0, max_round=2,
                 powers=(1, 1, 1, 3)),
        _all_decided, 1, None),
    "mc_n4_weighted_evidence": (
        MCConfig(name="n4_weighted_equiv", depth=0, max_round=2,
                 behaviors=("equivocator", "honest", "honest", "honest"),
                 powers=(1, 1, 1, 3)),
        lambda net: (_all_decided(net)
                     and any(nd.all_equivocations()
                             for nd in net.nodes)), 1, None),
    # symmetry milestone: a decision in the orbit-richest smoke config
    # (n=7, five interchangeable non-proposers) — replayed forever so
    # the orbit-merged envelope keeps a deterministic deep witness
    "mc_n7_weighted_decides": (
        MCConfig(name="n7_weighted", n=7, depth=0, max_round=2,
                 behaviors=("honest",) * 7,
                 powers=(1, 1, 1, 1, 1, 2, 3)),
        _all_decided, 0, 0.05),
    # epoch milestone (ISSUE 9): decisions at height 0 (genesis
    # equal-weight set) AND height 1 (the (1, 3, 1, 1) epoch — heavy
    # validator REQUIRED for any height-1 quorum), so the device
    # replay crosses a real set_validators boundary: host == device
    # must hold through the set change or the height-1 decision
    # vanishes
    "mc_epoch_set_change_decides": (
        MCConfig(name="n4_epoch_boundary", depth=0, max_round=2,
                 max_height=1, epochs=((1, (1, 3, 1, 1)),)),
        _all_decided_through_height_1, 0, 0.1),
    # sleepy-churn milestone (TOB-SVD): a full decision on a schedule
    # carrying a real sleep/wake cycle — the serialized ("s", j)/
    # ("w", j) actions ride the corpus codec, the deterministic host
    # replay, and the device-plane trace replay forever
    "mc_churn_sleepy_recovery_decides": (
        MCConfig(name="n4_sleepy", depth=0, max_round=2,
                 churn_budget=2),
        _sleepy_recovery_decided, 0, 0.3),
}


def emit_corpus(directory: str, include_mutants: bool = True) -> List[str]:
    """(Re)generate the regression corpus: a ddmin-minimized schedule
    per CORPUS_GOALS milestone, plus the mutation self-test
    counterexamples replayed on the honest executor (they stay
    interesting as device-plane differential cases even where the
    honest host plane is clean), plus the serve-plane admission corpus
    under `directory`/admission (analysis/admission_mc.py; replayed by
    tests/test_admission_mc.py through the real stubbed ServePipeline).
    Deterministic; committed as tests/corpus/ and replayed by
    tests/test_cross_plane.py."""
    os.makedirs(directory, exist_ok=True)
    written = []
    for name, (cfg, pred, seed, bias) in CORPUS_GOALS.items():
        sched = _walk_until(cfg, pred, seed, max_steps=1500,
                            deliver_bias=bias)
        assert sched is not None, f"corpus goal {name} unreachable"
        sched = minimize_schedule(cfg, sched,
                                  lambda net, _v, p=pred: p(net))
        entry = corpus_entry(name, cfg, sched,
                             origin=f"emit_corpus goal walk seed={seed}, "
                                    f"ddmin-minimized")
        path = os.path.join(directory, f"{name}.json")
        with open(path, "w") as f:
            json.dump(entry, f, indent=1, sort_keys=True)
            f.write("\n")
        written.append(path)
    if include_mutants:
        for mname, r in self_test().items():
            ce = r["counterexample"]
            cfg = MCConfig.from_json(ce["config"])
            acts = [Network.action_from_json(a) for a in ce["schedule"]]
            entry = corpus_entry(
                f"mc_mut_{mname}", cfg, acts,
                origin=f"minimized {mname} mutation counterexample "
                       f"(honest replay: near-miss)")
            path = os.path.join(directory, f"mc_mut_{mname}.json")
            with open(path, "w") as f:
                json.dump(entry, f, indent=1, sort_keys=True)
                f.write("\n")
            written.append(path)
    from agnes_tpu.analysis import admission_mc as am
    from agnes_tpu.analysis import membership_mc as mm

    written += am.emit_admission_corpus(
        os.path.join(directory, "admission"),
        include_mutants=include_mutants)
    written += mm.emit_membership_corpus(
        os.path.join(directory, "membership"),
        include_mutants=include_mutants)
    return written


# ---------------------------------------------------------------------------
# Mutation self-test: doctored executors the monitors MUST catch
# ---------------------------------------------------------------------------


class QuorumlessExecutor(ConsensusExecutor):
    """Doctored: treats a single precommit-for-value as a +2/3 quorum
    (the classic miscounted-threshold bug).  Method-override only, so
    ConsensusExecutor.clone() stays subclass-safe."""

    def _on_vote(self, v) -> None:
        super()._on_vote(v)
        if (v.typ == VoteType.PRECOMMIT and v.value is not None
                and (v.height is None or v.height == self.height)
                and self.state.step != sm.Step.COMMIT):
            self._apply_event(v.round, sm.Event.precommit_value(v.value))


class EvidenceDroppingExecutor(ConsensusExecutor):
    """Doctored: the slashing surface goes blind — equivocations are
    tallied (first vote counts, conflicts ignored) but never reported."""

    def all_equivocations(self) -> list:
        return []


class WeightBlindExecutor(ConsensusExecutor):
    """Doctored: counts validator HEADS instead of voting power (every
    vote weighs 1 against a total of n) — the committee-weight bug
    class of PAPERS.md 2004.12990.  On an asymmetric power vector
    where the +2/3 boundary falls between vote counts (three weight-1
    validators out of four are a head-count quorum but only 3/6 of
    the power), it decides without a real quorum; the cert monitor
    sees the counted weight against the TRUE total power and fires."""

    def _new_votes(self, height: int):
        from agnes_tpu.core.vote_executor import VoteExecutor

        return VoteExecutor(height=height, total_weight=len(self.vset),
                            edge_triggered=True)

    def _vote_weight(self, v) -> int:
        return 1


class StaleEpochExecutor(ConsensusExecutor):
    """Doctored: tallies every height against the PREVIOUS validator-
    set epoch — `epoch_powers` looks one height back, so precommits
    are counted with the powers (and denominated in the total) of the
    set that was live BEFORE the boundary.  The exact bug class the
    device plane's `set_validators` height-boundary contract exists to
    prevent (harness/device_driver.py: "mid-height changes would mix
    quorum denominators").  On a config whose epoch shifts weight onto
    one validator, the light validators' old-set quorum no longer
    clears the live set's +2/3 — the epoch-indexed cert monitor sees a
    certificate denominated against the wrong epoch and fires."""

    def epoch_powers(self, height: int):
        return super().epoch_powers(height - 1)


class WakeResetExecutor(ConsensusExecutor):
    """Doctored: treats waking from a sleepy-churn nap as a REBOOT —
    fresh round-0 state for the current height, lock and valid value
    shredded, (round, step) position regressed.  The churn-blind
    recovery bug class of TOB-SVD's sleepy model (a waking validator
    must resume, not restart: restarting un-locks it and re-opens
    equivocation/agreement windows the protocol had closed).  Caught
    by the per-edge monotonicity monitor on the ("w", j) action."""

    def on_wake(self) -> None:
        self.state = sm.State.new(self.height)


#: mutant name -> (executor class, property the monitors must catch it
#: with, config the violation is reachable in).  The weight-blind
#: config puts power 3 on one validator (original index 3 -> sorted
#: index 2, the round-0 proposer under the weighted rotation): the
#: three weight-1 validators form a head-count quorum (3 of 4) that
#: holds only 3 of 6 power — the violation needs the full 11-action
#: three-light protocol, hence the deeper bound.  The stale-epoch
#: config rotates a (1, 3, 1, 1) set in AT height 0 (original index 1
#: -> sorted index 0, a pinned proposer): the genesis table the
#: rotation was seeded with is equal-weight, so a tally stuck one
#: epoch back counts three lights as 3/4 when the live set makes them
#: 3/6 — again the full three-light protocol, depth 11.  The
#: wake-reset config needs only churn_budget=1: any position-advanced
#: node that sleeps and wakes regresses immediately.
MUTANTS: Dict[str, tuple] = {
    "decide_without_quorum": (
        QuorumlessExecutor, "quorum",
        MCConfig(name="mut_quorumless", n=4,
                 behaviors=("honest",) * 4, depth=8, max_round=1)),
    "drop_equivocation_evidence": (
        EvidenceDroppingExecutor, "evidence",
        MCConfig(name="mut_evidence", n=4,
                 behaviors=("equivocator", "honest", "honest", "honest"),
                 depth=6, max_round=1)),
    "decide_weight_blind_quorum": (
        WeightBlindExecutor, "quorum",
        MCConfig(name="mut_weight_blind", n=4,
                 behaviors=("honest",) * 4, powers=(1, 1, 1, 3),
                 depth=11, max_round=1)),
    "decide_stale_epoch_quorum": (
        StaleEpochExecutor, "quorum",
        MCConfig(name="mut_stale_epoch", n=4,
                 behaviors=("honest",) * 4,
                 epochs=((0, (1, 3, 1, 1)),), depth=11, max_round=1)),
    "wake_resets_round_state": (
        WakeResetExecutor, "monotonic",
        MCConfig(name="mut_wake_reset", n=4,
                 behaviors=("honest",) * 4, churn_budget=1,
                 depth=4, max_round=1)),
}

#: Deep-mutant registry: violations that live PAST a height boundary
#: — beyond any exhaustively explorable depth (a height-0 decision
#: alone costs ~25 actions) — discovered by a seeded guided walk on
#: the doctored executor instead of the DFS, then ddmin-minimized and
#: honest-replayed exactly like the explored mutants.  name ->
#: (executor class, property, config, goal predicate, seed, bias).
#: The cross-boundary stale-epoch drill: heights decide under the
#: genesis set, then the (1, 3, 1, 1) epoch lands at height 1 and the
#: stale tally keeps counting the old equal-weight set — its
#: height-1 decision carries a cert denominated 3/4 against a live
#: total of 6.
DEEP_MUTANTS: Dict[str, tuple] = {
    "stale_epoch_across_boundary": (
        StaleEpochExecutor, "quorum",
        MCConfig(name="mut_stale_epoch_deep", n=4, depth=0, max_round=2,
                 max_height=1, epochs=((1, (1, 3, 1, 1)),)),
        lambda net: any(1 in nd.decided for nd in net.nodes), 0, 0.1),
}


def self_test(por: bool = True) -> dict:
    """Prove the monitors have teeth: each doctored executor must be
    caught, its counterexample must delta-minimize, and the minimized
    schedule must run CLEAN on the honest executor (the violation is
    the mutation's, not the checker's).  Explored mutants (MUTANTS)
    are caught by the exhaustive DFS; deep mutants (DEEP_MUTANTS,
    violations past a height boundary) by a seeded guided walk on the
    doctored executor — both then share the exact
    minimize/reproduce/honest-replay drill."""
    out = {}
    for name, (mut_cls, prop, cfg) in MUTANTS.items():
        rep = explore(cfg, executor_cls=mut_cls, por=por)
        caught = [c for c in rep.violations
                  if c.violation.property == prop]
        assert caught, (
            f"mutant {name}: no {prop} violation in "
            f"{rep.states} states")
        ce = caught[0]
        out[name] = _finish_mutant_record(
            name, mut_cls, prop, cfg, ce, states=rep.states,
            discovery="dfs")
    for name, (mut_cls, prop, cfg, goal, seed, bias) in \
            DEEP_MUTANTS.items():
        sched = _walk_until(cfg, goal, seed, max_steps=1500,
                            deliver_bias=bias, executor_cls=mut_cls)
        assert sched is not None, f"deep mutant {name}: goal unreachable"
        assert reproduces(cfg, sched, prop, executor_cls=mut_cls), (
            f"deep mutant {name}: goal state shows no {prop} violation")
        ce = Counterexample(cfg, Violation(prop, -1,
                                           f"walk-discovered {name}"),
                            list(sched))
        out[name] = _finish_mutant_record(
            name, mut_cls, prop, cfg, ce, states=len(sched),
            discovery="walk")
    return out


def _finish_mutant_record(name: str, mut_cls: type, prop: str,
                          cfg: MCConfig, ce: Counterexample,
                          states: int, discovery: str = "dfs") -> dict:
    ce.minimized = minimize(cfg, ce.schedule, prop, executor_cls=mut_cls)
    assert reproduces(cfg, ce.minimized, prop, executor_cls=mut_cls)
    _, honest_viols = run_with_monitors(cfg, ce.minimized)
    assert not honest_viols, (
        f"mutant {name}: minimized schedule also violates on the "
        f"honest executor: {honest_viols}")
    return {
        "property": prop,
        "discovery": discovery,
        # explored-state count for DFS-caught mutants; for the walk-
        # discovered deep mutants the probe has no state count, so
        # this is the walk's schedule length (see `discovery`)
        "states_to_detection": states,
        "schedule_len": len(ce.schedule),
        "minimized_len": len(ce.minimized),
        "counterexample": ce.to_json(),
    }


# ---------------------------------------------------------------------------
# Scopes + frontier-sharded workers + CLI
# ---------------------------------------------------------------------------

#: The smoke scope: the ci.sh gate's envelope.  Sized for the 2-CPU CI
#: box — must EXHAUST (complete=True) well inside the gate timeout
#: while clearing the per-shard state floors the gate asserts.  One
#: config per fault model plus a partition/heal drill, an N=7 shallow
#: sweep, (ISSUE 7) two WEIGHTED configs whose +2/3 boundary falls
#: between vote counts (power 3 on original index 3 -> sorted index 2:
#: three weight-1 validators are a head-count majority with only 3/6
#: of the power), and (ISSUE 9) two validator-set EPOCH shards plus a
#: sleepy-CHURN shard; every one stays within f < n/3 by weight in
#: every live epoch.
SMOKE_SCOPE: Tuple[MCConfig, ...] = (
    MCConfig(name="n4_honest", depth=10, max_round=1),
    MCConfig(name="n4_silent", depth=11, max_round=1,
             behaviors=("silent", "honest", "honest", "honest")),
    MCConfig(name="n4_equivocator", depth=9, max_round=1,
             behaviors=("equivocator", "honest", "honest", "honest")),
    MCConfig(name="n4_nil_flood", depth=9, max_round=1,
             behaviors=("nil_flood", "honest", "honest", "honest")),
    MCConfig(name="n4_partition_heal", depth=9, max_round=1,
             partition=((0, 1), (2, 3))),
    MCConfig(name="n7_honest", n=7, behaviors=("honest",) * 7,
             depth=5, max_round=1),
    MCConfig(name="n4_weighted", powers=(1, 1, 1, 3), depth=10,
             max_round=1),
    MCConfig(name="n4_weighted_equiv", powers=(1, 1, 1, 3), depth=9,
             max_round=1,
             behaviors=("equivocator", "honest", "honest", "honest")),
    # ISSUE 9 epoch shards: validator-set epochs live inside the
    # envelope.  n4_epoch_shift rotates weight 3 onto original index 0
    # at the height-1 boundary — original 0 sorts to index 1, a PINNED
    # proposer slot, so sorted nodes {2, 3} stay interchangeable in
    # BOTH epochs and the per-epoch symmetry group is real (|G| = 2).
    # n4_epoch_genesis rotates (1, 3, 1, 1) in AT height 0 (the
    # stale-epoch mutant's scope): the live set differs from the
    # genesis table the network was seeded with from the first vote.
    MCConfig(name="n4_epoch_shift", depth=10, max_round=1,
             epochs=((1, (3, 1, 1, 1)),)),
    MCConfig(name="n4_epoch_genesis", depth=9, max_round=1,
             epochs=((0, (1, 3, 1, 1)),)),
    # ISSUE 9 churn shard: TOB-SVD sleepy participation — one sleep in
    # the budget opens ("s", j) for every honest node plus the paired
    # wake, the largest alphabet extension in the scope
    MCConfig(name="n4_churn1", depth=9, max_round=1, churn_budget=1),
)

#: PR 6's measured unreduced (por-only) visit counts on the shared
#: smoke configs — the denominator-side baseline for the
#: `modelcheck_sym_orbit_reduction` metric.  These are DETERMINISTIC
#: (same config -> same visited set); regenerate with
#: `explore(cfg, sym=False)` after any semantic change to the core or
#: the enumerator (the floor assertions in ci.sh will catch a silent
#: drift).
SYM_BASELINE_STATES: Dict[str, int] = {
    "n4_honest": 94_290,
    "n4_silent": 11_019,
    "n4_equivocator": 62_570,
    "n4_nil_flood": 50_932,
    "n4_partition_heal": 88_057,
    "n7_honest": 74_873,
    # ISSUE 9: unreduced visit counts of the epoch/churn shards —
    # the denominators of the PER-EPOCH orbit-reduction metric
    # (`modelcheck_epoch_orbit_reduction` reads only the epoch ones)
    "n4_epoch_shift": 94_290,
    "n4_epoch_genesis": 46_252,
    "n4_churn1": 164_617,
}

#: Unit-test / CLI-smoke scope: seconds, not minutes.
TINY_SCOPE: Tuple[MCConfig, ...] = (
    MCConfig(name="tiny_honest", depth=6, max_round=1),
    MCConfig(name="tiny_equivocator", depth=5, max_round=1,
             behaviors=("equivocator", "honest", "honest", "honest")),
    MCConfig(name="tiny_weighted", powers=(1, 1, 1, 3), depth=6,
             max_round=1),
    MCConfig(name="tiny_epoch", depth=6, max_round=1,
             epochs=((1, (3, 1, 1, 1)),)),
    MCConfig(name="tiny_churn", depth=5, max_round=1, churn_budget=1),
)

#: Deep scope for workstation runs (not CI-gated): more rounds, deeper
#: schedules, a second fault in the n=7 set, a weighted n=7.
FULL_SCOPE: Tuple[MCConfig, ...] = SMOKE_SCOPE + (
    MCConfig(name="n4_honest_deep", depth=12, max_round=2),
    MCConfig(name="n4_equivocator_deep", depth=11, max_round=2,
             behaviors=("equivocator", "honest", "honest", "honest")),
    MCConfig(name="n7_two_faults", n=7, depth=6, max_round=1,
             behaviors=("equivocator", "silent", "honest", "honest",
                        "honest", "honest", "honest")),
    MCConfig(name="n7_weighted", n=7, depth=5, max_round=1,
             behaviors=("honest",) * 7, powers=(1, 1, 1, 1, 1, 2, 3)),
    MCConfig(name="n4_churn2", depth=8, max_round=1, churn_budget=2),
    MCConfig(name="n4_epoch_churn", depth=8, max_round=1,
             epochs=((1, (3, 1, 1, 1)),), churn_budget=1),
)

SCOPES = {"tiny": TINY_SCOPE, "smoke": SMOKE_SCOPE, "full": FULL_SCOPE}


def _scope_worker(task: dict) -> dict:
    """One exploration shard in a spawned interpreter (the agnes_lint
    --pass all pattern): configs are independent, so they parallelize
    across cores; JSON-able dicts cross the process boundary.  `kind`
    routes between the consensus domain, the serve-plane admission
    domain (analysis/admission_mc.py) and the pod-membership domain
    (analysis/membership_mc.py) — same engine, same record shape."""
    if task["config"].get("kind") == "membership":
        from agnes_tpu.analysis import membership_mc as mm

        cfg = mm.MembershipMCConfig.from_json(task["config"])
        rep = mm.explore_membership(cfg,
                                    deadline_at=task["deadline_at"],
                                    max_states=task.get("max_states"))
        for ce in rep.violations:
            try:
                ce.minimized = mm.minimize_membership(
                    cfg, ce.schedule, ce.violation.property)
            except AssertionError:
                ce.minimized = None
        out = rep.to_json()
        out["kind"] = "membership"
        return out
    if task["config"].get("kind") == "admission":
        from agnes_tpu.analysis import admission_mc as am

        cfg = am.AdmissionMCConfig.from_json(task["config"])
        rep = am.explore_admission(cfg,
                                   deadline_at=task["deadline_at"],
                                   max_states=task.get("max_states"))
        for ce in rep.violations:
            try:
                ce.minimized = am.minimize_admission(
                    cfg, ce.schedule, ce.violation.property)
            except AssertionError:
                ce.minimized = None
        out = rep.to_json()
        out["kind"] = "admission"
        return out
    cfg = MCConfig.from_json(task["config"])
    rep = explore(cfg, por=task["por"], sym=task.get("sym", False),
                  deadline_at=task["deadline_at"],
                  max_states=task.get("max_states"))
    for ce in rep.violations:
        try:
            ce.minimized = minimize(cfg, ce.schedule,
                                    ce.violation.property)
        except AssertionError:
            ce.minimized = None     # non-deterministic repro: report raw
    out = rep.to_json()
    out["kind"] = "consensus"
    return out


def run_scope(scope: str, workers: Optional[int] = None, por: bool = True,
              deadline_at: Optional[float] = None,
              max_states: Optional[int] = None,
              sym: bool = True) -> dict:
    """Explore every config of `scope` — the consensus envelope, the
    serve-plane admission envelope (admission_mc.ADMISSION_SCOPES)
    AND the pod-membership envelope (ISSUE 17,
    membership_mc.MEMBERSHIP_SCOPES) — frontier-sharded over spawned
    workers; aggregate states/violations (the CLI/gate record).
    Consensus shards run symmetry-reduced by default (`sym`); the
    aggregate report carries the measured orbit reduction against the
    PR 6 unreduced baseline (`SYM_BASELINE_STATES`) and the
    admission/membership-model state totals."""
    from agnes_tpu.analysis.admission_mc import ADMISSION_SCOPES
    from agnes_tpu.analysis.membership_mc import MEMBERSHIP_SCOPES

    configs = SCOPES[scope]
    adm_configs = ADMISSION_SCOPES.get(scope, ())
    mem_configs = MEMBERSHIP_SCOPES.get(scope, ())
    tasks = [{"config": c.to_json(), "por": por, "sym": sym,
              "deadline_at": deadline_at, "max_states": max_states}
             for c in configs]
    tasks += [{"config": c.to_json(), "por": por,
               "deadline_at": deadline_at, "max_states": max_states}
              for c in (*adm_configs, *mem_configs)]
    t0 = time.perf_counter()
    if workers is None:
        workers = min(len(tasks), max(2, os.cpu_count() or 2))
    if workers <= 1 or len(tasks) == 1:
        results = [_scope_worker(t) for t in tasks]
    else:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")       # no forked interpreter state
        with ctx.Pool(processes=workers) as pool:
            results = pool.map(_scope_worker, tasks)
    by_name = {c.name: c for c in configs}
    report = {
        "scope": scope,
        "por": por,
        "sym": sym,
        "configs": {r["config"]: r for r in results},
        "states_explored": sum(r["states"] for r in results),
        "transitions": sum(r["transitions"] for r in results),
        "violations": sum(len(r["violations"]) for r in results),
        "complete": all(r["complete"] for r in results),
        "consensus_states": sum(r["states"] for r in results
                                if r["kind"] == "consensus"),
        "admission_states": sum(r["states"] for r in results
                                if r["kind"] == "admission"),
        "membership_states": sum(r["states"] for r in results
                                 if r["kind"] == "membership"),
        # ISSUE 9 domain splits: canonical states visited by the shards
        # carrying validator-set epochs / a sleepy-churn budget (a shard
        # can be in both; the ci.sh gate floors the COMBINED count)
        "epoch_states": sum(
            r["states"] for r in results if r["kind"] == "consensus"
            and by_name[r["config"]].epochs is not None),
        "churn_states": sum(
            r["states"] for r in results if r["kind"] == "consensus"
            and by_name[r["config"]].churn_budget > 0),
        "seconds": round(time.perf_counter() - t0, 1),
    }
    # measured orbit reduction on the baselined configs — overall, and
    # the PER-EPOCH slice (epoch shards only: the group there must be
    # sound in EVERY epoch window, so its measured bite is its own
    # metric).  Only meaningful where shards EXHAUSTED under symmetry.
    base = reduced = ep_base = ep_reduced = 0
    for r in results:
        if r["kind"] == "consensus" and r["complete"] and sym \
                and r["config"] in SYM_BASELINE_STATES:
            base += SYM_BASELINE_STATES[r["config"]]
            reduced += r["states"]
            if by_name[r["config"]].epochs is not None:
                ep_base += SYM_BASELINE_STATES[r["config"]]
                ep_reduced += r["states"]
    report["sym_orbit_reduction"] = \
        round(base / reduced, 2) if reduced else -1
    report["epoch_orbit_reduction"] = \
        round(ep_base / ep_reduced, 2) if ep_reduced else -1
    report["ok"] = report["violations"] == 0
    return report


def main(argv: Optional[List[str]] = None) -> int:
    """CLI (scripts/agnes_modelcheck.py + the agnes-modelcheck console
    script).  Pure CPU, zero XLA compiles; honors the enclosing
    timeout budget (utils/budget.Deadline discovery) so the ci.sh gate
    always gets a parseable record — complete=False is the sentinel
    half of the real-value-or-sentinel contract."""
    import argparse

    from agnes_tpu.utils.budget import Deadline

    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--scope", default="smoke", choices=sorted(SCOPES),
                    help="bounded exploration envelope (default: smoke)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--no-por", action="store_true",
                    help="disable partial-order reduction (debug aid)")
    ap.add_argument("--no-sym", action="store_true",
                    help="disable symmetry reduction (debug aid; the "
                         "orbit-reduction metric reads -1)")
    ap.add_argument("--self-test", action="store_true",
                    help="run the doctored-executor AND admission-"
                         "mutant self-tests")
    ap.add_argument("--emit-corpus", metavar="DIR", default=None,
                    help="(re)generate the regression corpus into DIR")
    ap.add_argument("--max-states", type=int, default=None)
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="wall budget; default: discovered from "
                         "AGNES_MODELCHECK_DEADLINE_S or the enclosing "
                         "`timeout N`")
    args = ap.parse_args(argv)

    if args.deadline_s is not None:
        deadline = Deadline.after(args.deadline_s)
    else:
        deadline = Deadline.discover(
            env_var="AGNES_MODELCHECK_DEADLINE_S")
    rem = deadline.remaining()
    # leave a report-assembly margin before the enclosing kill; the
    # 1s floor only guards an already-blown budget (the sentinel path)
    deadline_at = None if deadline.at is None \
        else time.time() + max(1.0, rem - min(20.0, rem * 0.2))

    t0 = time.perf_counter()
    if args.self_test:
        from agnes_tpu.analysis.admission_mc import self_test_admission
        from agnes_tpu.analysis.membership_mc import (
            self_test_membership,
        )

        mut = self_test(por=not args.no_por)
        report = {"self_test": mut,
                  "self_test_admission": self_test_admission(),
                  "self_test_membership": self_test_membership(),
                  "ok": True,
                  "seconds": round(time.perf_counter() - t0, 1)}
        print(json.dumps(report, sort_keys=True), flush=True)
        return 0
    if args.emit_corpus:
        written = emit_corpus(args.emit_corpus)
        print(json.dumps({"ok": True, "corpus": written,
                          "seconds": round(time.perf_counter() - t0, 1)},
                         sort_keys=True), flush=True)
        return 0

    report = run_scope(args.scope, workers=args.workers,
                       por=not args.no_por, deadline_at=deadline_at,
                       max_states=args.max_states,
                       sym=not args.no_sym)
    from agnes_tpu.utils.metrics import (
        MODELCHECK_ADMISSION_STATES,
        MODELCHECK_CHURN_STATES,
        MODELCHECK_EPOCH_ORBIT_REDUCTION,
        MODELCHECK_EPOCH_STATES,
        MODELCHECK_MEMBERSHIP_STATES,
        MODELCHECK_STATES_EXPLORED,
        MODELCHECK_SYM_ORBIT_REDUCTION,
        MODELCHECK_VIOLATIONS,
    )

    report["metrics"] = {
        MODELCHECK_STATES_EXPLORED: report["states_explored"],
        MODELCHECK_VIOLATIONS: report["violations"],
        MODELCHECK_SYM_ORBIT_REDUCTION: report["sym_orbit_reduction"],
        MODELCHECK_ADMISSION_STATES: report["admission_states"],
        MODELCHECK_MEMBERSHIP_STATES: report["membership_states"],
        MODELCHECK_EPOCH_STATES: report["epoch_states"],
        MODELCHECK_CHURN_STATES: report["churn_states"],
        MODELCHECK_EPOCH_ORBIT_REDUCTION:
            report["epoch_orbit_reduction"],
    }
    report["deadline"] = {"source": deadline.source,
                          "budget_s": None if rem == float("inf")
                          else round(rem, 1)}
    if not args.json:
        for name, r in report["configs"].items():
            status = "EXHAUSTED" if r["complete"] else "partial"
            print(f"[agnes_modelcheck] {name}: {r['states']} states / "
                  f"{r['transitions']} transitions {status} "
                  f"({r['seconds']}s), {len(r['violations'])} "
                  f"violation(s)", flush=True)
    print(json.dumps(report, sort_keys=True), flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
