"""Serve lock-order lint: the threaded host's two-lock discipline,
checked at the AST (plus a runtime instrumented-lock mode for tests).

serve/threaded.py promises `submit` is wait-free relative to in-flight
XLA dispatch.  The whole promise is a lock discipline no test can see
break until it deadlocks or stalls in production:

  LOCK001  bare ``.acquire()``/``.release()`` — a raised exception
           between the two leaks the lock forever; every acquisition
           must be a ``with`` block
  LOCK002  inconsistent order — the device lock acquired OUTSIDE an
           admission acquisition anywhere means two call paths can
           deadlock; the global order is admission -> device
  LOCK003  admission lock held across a device dispatch / XLA call —
           the exact stall the _close_batch/_pump_batch split removed:
           a multi-second XLA call under the admission lock blocks
           every producer
  LOCK004  admission lock held across a device-lock ACQUISITION —
           even in the right order, holding admission while waiting
           on the device lock serializes submit behind device work
  LOCK005  native C-API call (``ag_*`` — the ingest loop's and the
           admission front-end's ctypes surface) under the admission
           lock — ctypes releases the GIL for the foreign call's
           whole span, so a Python lock held across it blocks every
           other thread that wants the lock for the full native call;
           the native handles carry their own mutexes precisely so no
           Python lock is needed (ISSUE 14: ThreadedVoteService
           ELIDES the admission lock around a native queue).  Paired
           with lint's LINT004, which keeps every ``ag_*`` call
           inside the audited wrapper modules.

Suppressions are explicit and greppable: a ``# lockcheck: allow``
comment on the ``with`` line (reason after the marker).  The one
sanctioned use is ThreadedVoteService.drain's quiescent section —
both loop threads are joined before it runs, so holding both locks is
deliberate (the pass SURFACED that hold; review concluded quiescence,
and the pragma records it).

Runtime mode: `InstrumentedLock` wraps the two locks with a per-thread
held-stack that asserts the same order discipline on every real
acquisition — the threaded tests run their concurrency scenarios over
`instrument()`-ed services, so the static rule and the runtime
behavior cannot drift apart.
"""

from __future__ import annotations

import ast
import threading
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from agnes_tpu.analysis.jaxpr_audit import Finding

#: attribute names of the two serve locks
ADMISSION_LOCKS = frozenset({"_admission"})
DEVICE_LOCKS = frozenset({"_device"})

#: attribute calls that are (or directly wrap) device dispatch / XLA
#: work — forbidden under the admission lock
DISPATCH_CALLS = frozenset({
    "step", "step_seq", "step_seq_signed", "step_seq_signed_dense",
    "step_async", "run_heights_fused", "pump", "_pump_batch",
    "dispatch_staged", "settle", "collect", "block_until_ready",
    "warmup", "drain", "poll_decisions", "device_put",
})

PRAGMA = "lockcheck: allow"

#: the native C ABI's symbol prefix (core/native/*.cpp — every
#: exported symbol is ``ag_*``, including ag_apply and the
#: ag_ed25519_* batch entries): a call on an attribute with this
#: prefix IS a GIL-releasing ctypes call — LOCK005 forbids it under
#: the admission lock, exactly as the LINT004 docs promise
NATIVE_CAPI_PREFIXES = ("ag_",)


def _lock_name(node) -> Optional[str]:
    """The lock attribute acquired by a with-item expression, if any."""
    if isinstance(node, ast.Attribute) and \
            node.attr in (ADMISSION_LOCKS | DEVICE_LOCKS):
        return node.attr
    return None


def _has_pragma(source_lines, lineno: int) -> bool:
    line = source_lines[lineno - 1] if lineno - 1 < len(source_lines) \
        else ""
    return PRAGMA in line


class _LockVisitor(ast.NodeVisitor):
    def __init__(self, filename: str, source: str):
        self.filename = filename
        self.lines = source.splitlines()
        self.findings: List[Finding] = []
        self.held: List[str] = []          # lock attrs held, outer first

    def _find(self, code: str, node, msg: str) -> None:
        self.findings.append(Finding(
            "locks", code, f"{self.filename}:{node.lineno}", msg))

    # -- bare acquire/release ------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in ("acquire",
                                                       "release"):
            if not _has_pragma(self.lines, node.lineno):
                self._find(
                    "LOCK001", node,
                    f"bare .{f.attr}() — an exception between acquire "
                    f"and release leaks the lock; use a `with` block")
        self._check_dispatch(node)
        self._check_native(node)
        self.generic_visit(node)

    def _check_native(self, node: ast.Call) -> None:
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr.startswith(NATIVE_CAPI_PREFIXES)):
            return
        if any(h in ADMISSION_LOCKS for h in self.held) \
                and not _has_pragma(self.lines, node.lineno):
            self._find(
                "LOCK005", node,
                f".{f.attr}() under the admission lock — the ctypes "
                f"call releases the GIL for its whole span, so every "
                f"thread contending this lock blocks for the full "
                f"native call; the handle has its own mutex, elide "
                f"the Python lock (serve/threaded.py ISSUE 14)")

    def _check_dispatch(self, node: ast.Call) -> None:
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in DISPATCH_CALLS):
            return
        admission_held = any(h in ADMISSION_LOCKS for h in self.held)
        device_held = any(h in DEVICE_LOCKS for h in self.held)
        if admission_held and not device_held \
                and not _has_pragma(self.lines, node.lineno):
            self._find(
                "LOCK003", node,
                f".{f.attr}() under the admission lock — a device/"
                f"XLA call here blocks every producer for its whole "
                f"duration (move it under the device lock; see "
                f"VoteService._close_batch/_pump_batch)")

    # -- with blocks ---------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        acquired = [n for n in
                    (_lock_name(item.context_expr)
                     for item in node.items) if n]
        allow = _has_pragma(self.lines, node.lineno)
        pushed = 0
        for name in acquired:
            if not allow:
                self._order_check(name, node)
            self.held.append(name)
            pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - pushed:]

    def _order_check(self, name: str, node) -> None:
        admission_held = any(h in ADMISSION_LOCKS for h in self.held)
        if name in ADMISSION_LOCKS and \
                any(h in DEVICE_LOCKS for h in self.held):
            self._find(
                "LOCK002", node,
                "admission lock acquired while holding the device "
                "lock — inverts the global admission -> device order "
                "(deadlock with any in-order path)")
        if name in DEVICE_LOCKS and admission_held:
            self._find(
                "LOCK004", node,
                "device lock acquired while holding the admission "
                "lock — submit serializes behind device work for the "
                "whole wait (quiescent shutdown sections may annotate "
                f"`# {PRAGMA} (reason)`)")


def check_source(source: str, filename: str = "<string>"
                 ) -> List[Finding]:
    tree = ast.parse(source, filename=filename)
    v = _LockVisitor(filename, source)
    v.visit(tree)
    return v.findings


def check_paths(paths) -> List[Finding]:
    """Lint every .py file under the given files/directories."""
    import os

    findings: List[Finding] = []
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, names in os.walk(p):
                files.extend(os.path.join(root, n) for n in names
                             if n.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    for fpath in sorted(files):
        with open(fpath, "r") as fh:
            src = fh.read()
        findings.extend(check_source(src, filename=fpath))
    return findings


def default_paths(repo_root: str) -> List[str]:
    """EVERY module of the package tree (lint.package_modules — the
    shared scan-root derivation).  The old hand-maintained list (the
    serve dir + utils/metrics.py) silently missed every threaded
    module added after it was written — utils/flightrec.py's heartbeat
    thread, utils/metrics_http.py's server, analysis/admission_mc.py —
    exactly the modules where a bare .acquire() or an order inversion
    would hide.  The rules are attribute-name-scoped (`_admission`/
    `_device`) and pragma-tolerant, so the widened scan stays
    false-positive-free; a new module is covered the moment the file
    exists."""
    import os

    from agnes_tpu.analysis.lint import package_modules

    return [os.path.join(repo_root, rel)
            for rel in package_modules(repo_root)]


# -- runtime instrumented-lock mode -------------------------------------------

@dataclass
class LockOrderState:
    """Shared recorder for a set of InstrumentedLocks: per-thread held
    stack + violation log (thread-safe)."""

    violations: List[str] = field(default_factory=list)
    acquisitions: int = 0
    _tls: threading.local = field(default_factory=threading.local)
    _mu: threading.Lock = field(default_factory=threading.Lock)

    def stack(self) -> List[Tuple[str, int]]:
        if not hasattr(self._tls, "held"):
            self._tls.held = []
        return self._tls.held


class InstrumentedLock:
    """A threading.Lock that asserts the global acquisition order at
    runtime.  `rank` orders the locks (admission=0 < device=1 < the
    rank-2 leaf mutexes); an acquisition while holding an equal-or-
    higher rank is a violation — recorded, and raised when `strict`.

    The acquire/release steps route through `_raw_acquire` /
    `_raw_release` and announce themselves via `_sched_point` — the
    SchedPoint seam (ISSUE 19): the schedule checker subclasses this
    lock to make every acquisition a serialized, explorable yield
    point while REUSING the order bookkeeping below verbatim.  All
    three hooks are trivial here, so the test-mode lock stays what it
    always was."""

    def __init__(self, name: str, rank: int, state: LockOrderState,
                 strict: bool = True):
        self.name = name
        self.rank = rank
        self.state = state
        self.strict = strict
        self._lock = threading.Lock()

    # -- SchedPoint seam (overridden by schedcheck's SchedLock) -----------
    def _sched_point(self, event: str) -> None:
        """Called before acquire ('acquire') and after release
        ('release'); a no-op outside the schedule checker."""

    def _raw_acquire(self) -> None:
        self._lock.acquire()  # lockcheck: allow (the wrapper IS the with)

    def _raw_release(self) -> None:
        self._lock.release()  # lockcheck: allow (wrapper __exit__)

    # -- order bookkeeping (shared with SchedLock) ------------------------
    def _order_check(self) -> None:
        held = self.state.stack()
        bad = [n for n, r in held if r >= self.rank]
        if bad:
            msg = (f"lock order violation: acquiring {self.name!r} "
                   f"(rank {self.rank}) while holding {bad}")
            with self.state._mu:
                self.state.violations.append(msg)
            if self.strict:
                raise AssertionError(msg)

    def __enter__(self):
        self._order_check()
        self._sched_point("acquire")
        self._raw_acquire()
        self.state.stack().append((self.name, self.rank))
        with self.state._mu:
            self.state.acquisitions += 1
        return self

    def __exit__(self, *exc):
        self.state.stack().remove((self.name, self.rank))
        self._raw_release()
        self._sched_point("release")
        return False

    # the bare-call API stays available for foreign code, but counts
    # as a violation — the static rule LOCK001 made executable
    def acquire(self, *a, **kw):
        with self.state._mu:
            self.state.violations.append(
                f"bare acquire() on {self.name!r}")
        return self._lock.acquire(*a, **kw)  # lockcheck: allow (delegate)

    def release(self):
        return self._lock.release()  # lockcheck: allow (delegate)


#: `threading.Lock` is a factory function (not a type) on CPython —
#: the resolver's isinstance check needs the real lock type
_LOCK_TYPE = type(threading.Lock())


def _leaf(*path: str):
    """Resolver for a rank-2 leaf mutex at threaded_service.<path>._mu
    (getattr-safe: absent anywhere along the path means the deployment
    has no such lock and the registry entry is skipped)."""
    def resolve(t):
        obj = t
        for attr in path:
            obj = getattr(obj, attr, None)
            if obj is None:
                return None
        return (obj, "_mu") if isinstance(
            getattr(obj, "_mu", None), _LOCK_TYPE) else None
    return resolve


#: the runtime-instrumented lock SET, derived here instead of
#: hand-listed in instrument() (the ISSUE 19 satellite): every entry
#: is (name, rank, resolver) where resolver(threaded_service) returns
#: the (holder, attribute) to swap — or None when that deployment has
#: no such lock (no cache, no BLS lane, no flight recorder, a
#: duck-typed test stub).  Ranks: the two serve locks keep their
#: admission(0) -> device(1) order; every leaf mutex held for dict/
#: ring operations only is rank 2 — acquirable under anything,
#: NEVER while holding another leaf.
LOCK_REGISTRY: Tuple = (
    ("_admission", 0, lambda t: (t, "_admission")),
    ("_device", 1, lambda t: (t, "_device")),
    ("cache._mu", 2, _leaf("service", "queue", "cache")),
    ("bls_table._mu", 2, _leaf("service", "queue", "bls_table")),
    ("flightrec._mu", 2, _leaf("service", "flightrec")),
)

#: the NATIVE side of the lock order (ISSUE 20): the C++ mutexes the
#: sharded admission front-end holds below everything Python.  These
#: cannot be runtime-swapped (they live inside the handle), so this
#: table is the documented contract the TSan lane
#: (tests/native/tsan_admission_stress.cpp) exercises and a drift
#: test greps the C source against.  Entries are (name, rank, rule):
#:
#:   AdmQ::mu        per-shard leaf — one per shard; when a group
#:                   operation must hold SEVERAL (the k-way merged
#:                   drain, the atomic export) they are acquired in
#:                   ASCENDING shard order, always all-or-nothing
#:   AdmShards::route_mu   routing-table leaf (seq -> shard route for
#:                   mark_verified) — never nested with any AdmQ::mu
#:                   in either direction: submit stores the route
#:                   AFTER every per-shard screen returned, the mark
#:                   moves the route OUT under route_mu before any
#:                   shard back-walk
#:
#: Both sit strictly below the Python locks: every ag_adms_* entry
#: point acquires them inside one GIL-released span and returns with
#: none held, which is WHY LOCK005 can demand the admission lock be
#: elided — there is no lock-order edge from Python into the handle.
NATIVE_LOCK_ORDER: Tuple = (
    ("AdmQ::mu", 2, "per-shard leaf; multi-shard holds ascending"),
    ("AdmShards::route_mu", 2, "routing leaf; never nested with mu"),
)


def instrument(threaded_service, strict: bool = True,
               lock_factory=None) -> LockOrderState:
    """Swap a ThreadedVoteService's locks — ALL of LOCK_REGISTRY that
    resolve on this deployment, not just the two serve locks — for
    instrumented ones (BEFORE start()); returns the shared order state
    the test asserts on.  `lock_factory(name, rank, state, strict)`
    lets the schedule checker substitute its cooperative SchedLock
    while keeping this registry as the single source of the lock
    set."""
    factory = lock_factory or InstrumentedLock
    state = LockOrderState()
    for name, rank, resolve in LOCK_REGISTRY:
        target = resolve(threaded_service)
        if target is None:
            continue
        holder, attr = target
        setattr(holder, attr, factory(name, rank, state, strict))
    return state
