"""Per-backend Pallas lowering-support audit (ISSUE 18).

A Pallas kernel that lowers on TPU may not lower on Triton-GPU (and
vice versa): memory spaces, iota rank rules and scatter support all
differ per backend, and the first place a bad assumption surfaces by
default is a LIVE dispatch on the new backend.  This pass makes the
support set an audited REGISTRY RECORD instead of tribal knowledge:

* every registered entry that is Pallas-bearing — a `pallas_call` in
  its defining module, or the `pallas_field` kernel-lane static on
  its signature (the BLS serve entries, whose traced graph contains
  the field kernels when the lane is on) — must carry a non-empty
  `EntrySpec.pallas_backends` tuple;
* every claim must be a known backend name
  (`registry.PALLAS_BACKENDS`); and
* a record on a NON-Pallas entry is itself a finding — a stale claim
  is as misleading as a missing one.

The GPU bench lane (ROADMAP) consumes this table: kernels claiming
"triton" are its known-good starting set, and the claim may only be
added together with a real lowering (test or hardware run), never
speculatively.

Codes: PAL001 missing record, PAL002 record on a non-Pallas entry,
PAL003 unknown backend name.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Tuple

from agnes_tpu.analysis.jaxpr_audit import Finding

PASS = "pallas"

_PALLAS_MODULES = ("jax.experimental.pallas",
                   "jax.experimental.pallas.tpu")


def _is_pallas_bearing(spec) -> bool:
    """The defining module imports `jax.experimental.pallas` (the
    registration-next-to-kernels idiom of `pallas_verify.py`), or the
    kernel-lane static rides the signature (the BLS serve entries,
    whose traced graph holds the field kernels when the lane is on).
    Checked against the module NAMESPACE, not its source text — a
    docstring merely mentioning pallas must not create a claim
    obligation — and never by tracing (the audit stays cheap)."""
    if "pallas_field" in spec.statics:
        return True
    fn = spec.factory if spec.sharded else spec.fn
    mod = sys.modules.get(getattr(fn, "__module__", "") or "")
    return mod is not None and any(
        getattr(v, "__name__", None) in _PALLAS_MODULES
        for v in vars(mod).values())


def check() -> List[Finding]:
    from agnes_tpu.device import registry

    findings: List[Finding] = []
    for spec in registry.entries():
        bearing = _is_pallas_bearing(spec)
        rec = spec.pallas_backends
        if bearing and not rec:
            findings.append(Finding(
                PASS, "PAL001", spec.name,
                "Pallas-bearing entry registered without a "
                "per-backend lowering-support record (add "
                "pallas_backends=(...) to its EntrySpec)"))
        elif rec and not bearing:
            findings.append(Finding(
                PASS, "PAL002", spec.name,
                "pallas_backends recorded on an entry with no "
                "Pallas kernel in reach — stale claim, drop it"))
        if rec:
            bad = sorted(set(rec) - set(registry.PALLAS_BACKENDS))
            if bad:
                findings.append(Finding(
                    PASS, "PAL003", spec.name,
                    f"unknown pallas backend claim(s) {bad}; known: "
                    f"{list(registry.PALLAS_BACKENDS)}"))
    return findings


def support_table() -> Dict[str, Tuple[str, ...]]:
    """{entry -> recorded backends} for every entry carrying a
    record — the report detail the GPU lane (and README's support
    table) reads."""
    from agnes_tpu.device import registry

    return {s.name: tuple(s.pallas_backends)
            for s in registry.entries()
            if s.pallas_backends is not None}
