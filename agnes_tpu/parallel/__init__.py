"""Mesh/sharding layer: instance-DP x validator-TP over XLA collectives.

The reference has no parallelism or communication backend of any kind
(SURVEY.md §2.7 — zero deps, single synchronous call chain); these are
new first-class components.  The two scaling axes of a consensus fleet
are *instances* (independent (height, round) machines — embarrassingly
parallel, sharded as data parallelism) and *validators* (the tally /
signature axis — sharded as tensor parallelism whose quorum reductions
are `psum`s over the mesh axis, riding ICI intra-slice and DCN across
slices).
"""

from agnes_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    VAL_AXIS,
    make_mesh,
)
from agnes_tpu.parallel.sharded import (  # noqa: F401
    make_sharded_step,
    shard_step_args,
)
