"""Mesh/sharding layer: instance-DP x validator-TP over XLA collectives.

The reference has no parallelism or communication backend of any kind
(SURVEY.md §2.7 — zero deps, single synchronous call chain); these are
new first-class components.  The two scaling axes of a consensus fleet
are *instances* (independent (height, round) machines — embarrassingly
parallel, sharded as data parallelism) and *validators* (the tally /
signature axis — sharded as tensor parallelism whose quorum reductions
are `psum`s over the mesh axis, riding ICI intra-slice and DCN across
slices).

Multi-slice is first-class: `make_hierarchical_mesh` builds a
(slice, data, val) mesh whose outer axis models the DCN boundary —
instances shard across slices (no collectives cross it, ever), quorum
psums stay on the intra-slice val axis.  The sharded step detects the
slice axis and widens its instance-dimension specs automatically.
"""

from agnes_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    SLICE_AXIS,
    VAL_AXIS,
    make_hierarchical_mesh,
    make_mesh,
)
from agnes_tpu.parallel.sharded import (  # noqa: F401
    make_sharded_honest_heights,
    make_sharded_step,
    make_sharded_step_seq,
    make_sharded_step_seq_signed,
    place_step_state,
    shard_step_args,
)
