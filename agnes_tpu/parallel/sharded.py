"""shard_map-wrapped consensus step: dp over instances, tp over validators.

Sharding layout (I = instances, V = validators, W = rounds, S = slots;
``data*`` is the instance-dimension axis set — ("data",) on a flat
mesh, ("slice", "data") on a hierarchical multi-slice mesh, where the
outer slice axis crosses DCN and carries no collectives at all):

  =================  ==================  =========================
  array              shape               PartitionSpec
  =================  ==================  =========================
  DeviceState.*      [I]                 (data*,)
  tally.weights      [I, W, 2, S+1]      (data*,)       replicated over val
  tally.voted        [I, W, 2, V]        (data*,,,val)  the per-validator record
  tally.emitted      [I, W, 2]           (data*,)
  tally.skipped      [I, W]              (data*,)
  tally.equiv        [I, V]              (data*, val)
  ExtEvent.*         [I]                 (data*,)
  phase.round/typ    [I]                 (data*,)
  phase.slots/mask   [I, V]              (data*, val)
  powers             [V]                 (val,)
  total_power        []                  ()
  proposer_flag      [I, W]              (data*,)
  propose_value      [I]                 (data*,)
  msgs out           [n_stages, I]       (None, data*)
  =================  ==================  =========================

Only the tally's two validator reductions communicate (psum over
``val``, see device/tally.py); the state machine replicates over the
val axis — its per-instance state is a handful of ints, so replicating
beats communicating.  Nothing ever reduces over ``slice`` or ``data``:
instance parallelism is embarrassingly parallel by design, which is
what makes the multi-slice story work — DCN only ever carries the
initial shard placement, never a per-step collective (SURVEY.md §2.7
comm-backend row: ICI for quorum psums, DCN for instance DP).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from agnes_tpu.device.step import (
    DenseSignedPhases,
    ExtEvent,
    SignedStepOutputs,
    StepOutputs,
    VotePhase,
    consensus_step,
    consensus_step_seq,
    consensus_step_seq_signed_dense,
    honest_heights,
)
from agnes_tpu.device.tally import TallyState
from agnes_tpu.parallel.mesh import DATA_AXIS, SLICE_AXIS, VAL_AXIS

_SCALAR = P()

#: memoized jitted step factories: (factory name, mesh, statics) -> fn.
#: Two DeviceDrivers over one mesh historically each built their OWN
#: jit object for the identical shard_map'd step, so a differential
#: (offline driver vs serve driver) paid the multi-minute XLA trace
#: TWICE for one graph.  Mesh is hashable (axis names + device grid),
#: so the factory result can be shared process-wide — the serve plane
#: and the offline path then hit one compiled executable, which is
#: also what makes their bit-identity differentials cheap to run.
_FACTORY_CACHE: dict = {}


def _memo(key, build):
    try:
        hash(key)
    except TypeError:          # unhashable exotic mesh: just rebuild
        return build()
    fn = _FACTORY_CACHE.get(key)
    if fn is None:
        fn = _FACTORY_CACHE[key] = build()
    return fn


def _shard_map(fn, *, mesh, in_specs, out_specs, check_vma):
    """shard_map across the JAX API generations this framework meets:
    `jax.shard_map(check_vma=...)` (>= 0.6) when present, else
    `jax.experimental.shard_map.shard_map(check_rep=...)` (0.4.x —
    check_rep is that API's static replication validator; same
    guarantee surface, weaker analysis).  Without this shim the whole
    sharded layer raises AttributeError on 0.4.x."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def _data_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The axis set sharding the instance dimension: widened with the
    slice axis on hierarchical meshes."""
    return ((SLICE_AXIS, DATA_AXIS) if SLICE_AXIS in mesh.axis_names
            else (DATA_AXIS,))


def _in_specs(da: Tuple[str, ...]):
    """One source of truth for the step's argument shardings — used both
    by shard_map and by shard_step_args placement, so they cannot
    silently disagree."""
    data = P(da)
    state_spec = _state_spec(da)
    tally_spec = TallyState(
        weights=data,
        voted=P(da, None, None, VAL_AXIS),
        emitted=data,
        skipped=data,
        equiv=P(da, VAL_AXIS),
        q_round=data,
        q_step=data,
        pc_done=data,
        skip_w=data,
        base_round=data,
    )
    ext_spec = ExtEvent(tag=data, round=data, value=data, pol_round=data)
    phase_spec = VotePhase(round=data, typ=data,
                           slots=P(da, VAL_AXIS),
                           mask=P(da, VAL_AXIS),
                           height=data)
    return (state_spec, tally_spec, ext_spec, phase_spec,
            P(VAL_AXIS), _SCALAR, data, data)


def _state_spec(da: Tuple[str, ...]):
    from agnes_tpu.device.encoding import DeviceState

    return DeviceState(*([P(da)] * len(DeviceState._fields)))


def seq_in_specs(mesh: Mesh):
    """The stacked step-sequence argument specs — (state, tally,
    exts_st, phases_st, powers, total, proposer_flag, propose_value)
    with the leading replicated sequence axis on exts/phases.  Public
    because the multi-host driver (distributed/driver.py) assembles
    GLOBAL arrays from process-local blocks against exactly these
    specs — one source of truth with the shard_map wrappers below."""
    da = _data_axes(mesh)
    s = _in_specs(da)
    return (s[0], s[1], _prepend_none(s[2]), _prepend_none(s[3]),
            s[4], s[5], s[6], s[7])


def dense_lane_specs(mesh: Mesh) -> DenseSignedPhases:
    """Sharding specs of the dense signed-lane tensors (the
    make_sharded_step_seq_signed layout), shared with the multi-host
    lift for the same reason as seq_in_specs."""
    da = _data_axes(mesh)
    return DenseSignedPhases(
        pub=P(VAL_AXIS),
        sig=P(None, da, VAL_AXIS),
        blocks=P(None, da, VAL_AXIS))


def make_sharded_step(mesh: Mesh, advance_height: bool = False):
    """A jitted consensus_step sharded over `mesh` (flat data x val or
    hierarchical slice x data x val); call with arrays already placed
    by `shard_step_args` (or let jit reshard).  Memoized per (mesh,
    statics) — see _FACTORY_CACHE.

    check_vma=True: shard_map statically validates the replication
    claims of every output spec (VERDICT r2 weak #6); the bitwise
    sharded-vs-unsharded scenario suite in tests/test_sharded.py checks
    the values on top."""

    def build():
        da = _data_axes(mesh)
        specs = _in_specs(da)
        out_specs = StepOutputs(state=_state_spec(da),
                                tally=specs[1],
                                msgs=P(None, da))
        fn = _shard_map(
            partial(consensus_step, axis_name=VAL_AXIS,
                    advance_height=advance_height),
            mesh=mesh, in_specs=specs, out_specs=out_specs,
            check_vma=True)
        return jax.jit(fn)

    return _memo(("step", mesh, advance_height), build)


def _prepend_none(spec_tree):
    """Widen every PartitionSpec in a tree with a leading replicated
    axis — the sequence axis of stacked exts/phases ([P, ...] leaves)."""
    return jax.tree.map(lambda s: P(*((None,) + tuple(s))), spec_tree,
                        is_leaf=lambda x: isinstance(x, P))


def make_sharded_step_seq(mesh: Mesh, advance_height: bool = False,
                          donate: bool = False):
    """consensus_step_seq sharded over `mesh`: P phases in ONE sharded
    dispatch (the same fused-sequence rationale as the single-device
    path — device/step.py — with the quorum psums riding the val axis
    inside the scanned body).  exts/phases carry a leading replicated
    sequence axis; msgs come back [P, n_stages, I] sharded on I.

    `donate=True` is the serve plane's async twin (the mesh analogue
    of consensus_step_seq_donated_jit): state/tally are donated so the
    continuous dispatch loop updates them in place.  A separate jit
    entry for the same reason as the single-device pair — donation is
    part of the executable's buffer aliasing, and the non-donating
    entry keeps its historical reuse semantics."""

    def build():
        da = _data_axes(mesh)
        s = _in_specs(da)
        in_specs = seq_in_specs(mesh)
        out_specs = StepOutputs(state=_state_spec(da), tally=s[1],
                                msgs=P(None, None, da))
        fn = _shard_map(
            partial(consensus_step_seq, axis_name=VAL_AXIS,
                    advance_height=advance_height),
            mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=True)
        return (jax.jit(fn, donate_argnums=(0, 1)) if donate
                else jax.jit(fn))

    return _memo(("step_seq", mesh, advance_height, donate), build)


def make_sharded_step_seq_signed(mesh: Mesh, advance_height: bool = False,
                                 verify_chunk: int | None = None,
                                 donate: bool = False):
    """consensus_step_seq_signed_dense sharded over `mesh`: the FUSED
    verify+step sequence multi-chip.  The dense lane tensors shard
    like the phase masks (data x val), the pubkey table like powers
    (val), so each device runs the Ed25519 kernel on its local
    (instance, validator) cells — fused verification adds ZERO
    collectives; the tally's quorum psums stay the only communication.
    n_rejected comes back [I] (sharded on the data axes, psum'd over
    val inside).

    `verify_chunk` (LOCAL instance rows per verify microbatch —
    utils/budget.plan_dense_verify on the per-device shape) bounds the
    verify workspace per chunk; the chunk loop is a shard-local
    `lax.map`, so the zero-added-collectives property holds PER CHUNK
    — nothing new crosses the mesh between tiles.

    `donate=True` is the mesh serve plane's dispatch entry (the
    sharded analogue of consensus_step_seq_signed_dense_donated_jit):
    the streaming pipeline's continuous dispatch updates state/tally
    in place across chips."""

    def build():
        da = _data_axes(mesh)
        s = _in_specs(da)
        dense_spec = dense_lane_specs(mesh)
        sq = seq_in_specs(mesh)
        in_specs = (sq[0], sq[1], sq[2], sq[3],
                    dense_spec, s[4], s[5], s[6], s[7])
        out_specs = SignedStepOutputs(state=_state_spec(da), tally=s[1],
                                      msgs=P(None, None, da),
                                      n_rejected=P(da))
        # check_vma=False here (alone among the wrappers): the SHA-512
        # compression scan inside the verify kernel carries its
        # replicated H0 init constants into a varying loop, which the
        # static VMA checker rejects (scan carry in/out vma mismatch)
        # even though the computation is elementwise-local per cell.
        # The static guarantee is restored by the SHAPE GRID
        # differential instead (tests/test_step_signed.py
        # test_dense_sharded_matches_unsharded: flat + hierarchical
        # meshes x chunked/unchunked x ragged tiles, bitwise against
        # the single-device path — the values the static pass would
        # have vouched for, VERDICT r5 weak #6).
        fn = _shard_map(
            partial(consensus_step_seq_signed_dense, axis_name=VAL_AXIS,
                    advance_height=advance_height,
                    verify_chunk=verify_chunk),
            mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False)
        return (jax.jit(fn, donate_argnums=(0, 1)) if donate
                else jax.jit(fn))

    return _memo(("seq_signed", mesh, advance_height, verify_chunk,
                  donate), build)


def make_sharded_honest_heights(mesh: Mesh, heights: int):
    """honest_heights sharded over `mesh`: H full honest heights in ONE
    sharded dispatch; msgs come back [H, 3, n_stages, I] sharded on I."""

    def build():
        da = _data_axes(mesh)
        s = _in_specs(da)
        iv = P(da, VAL_AXIS)
        in_specs = (s[0], s[1], iv, iv, s[4], s[5], s[6], s[7])
        out_specs = StepOutputs(state=_state_spec(da), tally=s[1],
                                msgs=P(None, None, None, da))
        fn = _shard_map(
            partial(honest_heights, heights=heights, axis_name=VAL_AXIS),
            mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=True)
        return jax.jit(fn)

    return _memo(("honest_heights", mesh, heights), build)


# -- entry registry -----------------------------------------------------------
# The sharded factories register alongside the single-device entries
# (device/registry.py): the auditor builds each over a CPU mesh and
# abstractly traces it (collective census + donation), and the driver
# resolves the factories through one table.  Factory statics are the
# keyword arguments each factory takes.

from agnes_tpu.device import registry as _registry  # noqa: E402

_registry.register(_registry.EntrySpec(
    name="sharded_step", fn=consensus_step, factory=make_sharded_step,
    statics=("advance_height",), sharded=True))
_registry.register(_registry.EntrySpec(
    name="sharded_step_seq", fn=consensus_step_seq,
    factory=make_sharded_step_seq,
    statics=("advance_height", "donate"), sharded=True))
_registry.register(_registry.EntrySpec(
    name="sharded_step_seq_signed", fn=consensus_step_seq_signed_dense,
    factory=make_sharded_step_seq_signed,
    statics=("advance_height", "verify_chunk", "donate"), sharded=True))
_registry.register(_registry.EntrySpec(
    name="sharded_honest_heights", fn=honest_heights,
    factory=make_sharded_honest_heights,
    statics=("heights",), sharded=True))


def place_step_state(mesh: Mesh, state, tally):
    """Commit state/tally onto `mesh` per the layout table.  The jit
    cache keys on input shardings: a driver whose FIRST dispatch
    passes fresh uncommitted host arrays and whose later dispatches
    pass the committed sharded outputs compiles the SAME graph twice
    (minutes each with the persistent cache off) — and the serve
    plane's warmup would only ever warm the uncommitted variant, so
    the second real batch of a service would stall on a live compile.
    Committing at driver construction pins one sharding for the whole
    lifetime: one compile, warmup that actually covers the steady
    state, and donation that is in-place from the first call."""
    da = _data_axes(mesh)
    specs = _in_specs(da)

    def place(tree, spec):
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            tree, spec, is_leaf=lambda x: x is None)

    return place(state, specs[0]), place(tally, specs[1])


def shard_step_args(mesh: Mesh, state, tally, ext, phase, powers,
                    total_power, proposer_flag, propose_value):
    """Place the step arguments on the mesh per the layout table."""
    args = (state, tally, ext, phase, powers, total_power,
            proposer_flag, propose_value)
    return tuple(
        jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            a, spec, is_leaf=lambda x: x is None)
        for a, spec in zip(args, _in_specs(_data_axes(mesh))))
