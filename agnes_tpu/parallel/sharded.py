"""shard_map-wrapped consensus step: dp over instances, tp over validators.

Sharding layout (I = instances, V = validators, W = rounds, S = slots):

  =================  ==================  =========================
  array              shape               PartitionSpec
  =================  ==================  =========================
  DeviceState.*      [I]                 (data,)
  tally.weights      [I, W, 2, S+1]      (data,)        replicated over val
  tally.voted        [I, W, 2, V]        (data,,,val)   the per-validator record
  tally.emitted      [I, W, 2]           (data,)
  tally.skipped      [I, W]              (data,)
  tally.equiv        [I, V]              (data, val)
  ExtEvent.*         [I]                 (data,)
  phase.round/typ    [I]                 (data,)
  phase.slots/mask   [I, V]              (data, val)
  powers             [V]                 (val,)
  total_power        []                  ()
  proposer_flag      [I, W]              (data,)
  propose_value      [I]                 (data,)
  msgs out           [n_stages, I]       (None, data)
  =================  ==================  =========================

Only the tally's two validator reductions communicate (psum over
``val``, see device/tally.py); the state machine replicates over the
val axis — its per-instance state is a handful of ints, so replicating
beats communicating.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from agnes_tpu.device.step import (
    ExtEvent,
    StepOutputs,
    VotePhase,
    consensus_step,
)
from agnes_tpu.device.tally import TallyState
from agnes_tpu.parallel.mesh import DATA_AXIS, VAL_AXIS

_DATA = P(DATA_AXIS)
_SCALAR = P()

_STATE_SPEC_LEAF = _DATA
_TALLY_SPEC = TallyState(
    weights=_DATA,
    voted=P(DATA_AXIS, None, None, VAL_AXIS),
    emitted=_DATA,
    skipped=_DATA,
    equiv=P(DATA_AXIS, VAL_AXIS),
    q_round=_DATA,
    q_step=_DATA,
    pc_done=_DATA,
    skip_w=_DATA,
    base_round=_DATA,
)
_EXT_SPEC = ExtEvent(tag=_DATA, round=_DATA, value=_DATA, pol_round=_DATA)
_PHASE_SPEC = VotePhase(round=_DATA, typ=_DATA,
                        slots=P(DATA_AXIS, VAL_AXIS),
                        mask=P(DATA_AXIS, VAL_AXIS),
                        height=_DATA)


def _state_spec():
    from agnes_tpu.device.encoding import DeviceState

    return DeviceState(*([_STATE_SPEC_LEAF] * len(DeviceState._fields)))


def _in_specs():
    """One source of truth for the step's argument shardings — used both
    by shard_map and by shard_step_args placement, so they cannot
    silently disagree."""
    return (_state_spec(), _TALLY_SPEC, _EXT_SPEC, _PHASE_SPEC,
            P(VAL_AXIS), _SCALAR, _DATA, _DATA)


def make_sharded_step(mesh: Mesh, advance_height: bool = False):
    """A jitted consensus_step sharded over `mesh`; call with arrays
    already placed by `shard_step_args` (or let jit reshard).

    check_vma=True: shard_map statically validates the replication
    claims of every output spec (VERDICT r2 weak #6); the bitwise
    sharded-vs-unsharded scenario suite in tests/test_sharded.py checks
    the values on top."""
    out_specs = StepOutputs(state=_state_spec(), tally=_TALLY_SPEC,
                            msgs=P(None, DATA_AXIS))
    fn = jax.shard_map(
        partial(consensus_step, axis_name=VAL_AXIS,
                advance_height=advance_height),
        mesh=mesh, in_specs=_in_specs(), out_specs=out_specs,
        check_vma=True)
    return jax.jit(fn)


def shard_step_args(mesh: Mesh, state, tally, ext, phase, powers,
                    total_power, proposer_flag, propose_value):
    """Place the step arguments on the mesh per the layout table."""
    args = (state, tally, ext, phase, powers, total_power,
            proposer_flag, propose_value)
    return tuple(
        jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            a, spec, is_leaf=lambda x: x is None)
        for a, spec in zip(args, _in_specs()))
