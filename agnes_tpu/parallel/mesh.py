"""Device mesh construction for the consensus data plane.

Axes:
  * ``data`` — instance axis: independent consensus instances, no
    cross-talk, pure data parallelism.
  * ``val``  — validator axis: the vote tally's reduction axis; partial
    tallies are combined with `psum` (SURVEY.md §2.3 "TPU mapping").

On a real slice, lay ``val`` on the innermost (fastest-ICI) mesh dim —
it carries the per-phase quorum psums; ``data`` shards never
communicate, so they can span DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
VAL_AXIS = "val"


def make_mesh(n_data: int, n_val: int,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A (data=n_data, val=n_val) mesh over the given (default: all)
    devices."""
    if devices is None:
        devices = jax.devices()
    need = n_data * n_val
    if len(devices) < need:
        raise ValueError(
            f"mesh {n_data}x{n_val} needs {need} devices, have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_data, n_val)
    return Mesh(grid, (DATA_AXIS, VAL_AXIS))
