"""Device mesh construction for the consensus data plane.

Axes:
  * ``slice`` — (hierarchical meshes only) the multi-slice axis: one
    shard per TPU slice/host-group, connected by DCN.  Carries ONLY
    instance data parallelism — nothing in the step communicates over
    it, so slice-to-slice bandwidth never gates throughput.
  * ``data`` — instance axis within a slice: independent consensus
    instances, no cross-talk, pure data parallelism.
  * ``val``  — validator axis: the vote tally's reduction axis; partial
    tallies are combined with `psum` (SURVEY.md §2.3 "TPU mapping").

On a real slice, lay ``val`` on the innermost (fastest-ICI) mesh dim —
it carries the per-phase quorum psums; ``data`` shards never
communicate, so they can span DCN.  On real multi-slice hardware build
the hierarchical mesh's device grid with
`jax.experimental.mesh_utils.create_hybrid_device_mesh` so the outer
axis actually follows slice boundaries; `make_hierarchical_mesh` takes
any device list (the virtual CPU mesh in tests, the driver's dryrun)
and reshapes it (slice, data, val) slice-major, which matches the
hybrid layout when devices are enumerated slice-by-slice (JAX's
default enumeration order).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SLICE_AXIS = "slice"
DATA_AXIS = "data"
VAL_AXIS = "val"


def make_mesh(n_data: int, n_val: int,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A (data=n_data, val=n_val) mesh over the given (default: all)
    devices."""
    if devices is None:
        devices = jax.devices()
    need = n_data * n_val
    if len(devices) < need:
        raise ValueError(
            f"mesh {n_data}x{n_val} needs {need} devices, have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_data, n_val)
    return Mesh(grid, (DATA_AXIS, VAL_AXIS))


def make_hierarchical_mesh(n_slices: int, n_data: int, n_val: int,
                           devices: Optional[Sequence[jax.Device]] = None
                           ) -> Mesh:
    """A (slice=n_slices, data=n_data, val=n_val) hierarchical mesh:
    instances shard over slice x data (slice crosses DCN), the tally's
    psum reduction stays on val (intra-slice ICI).  sharded.py detects
    the slice axis and widens its instance-dimension specs to
    ("slice", "data") automatically."""
    if devices is None:
        devices = jax.devices()
    need = n_slices * n_data * n_val
    if len(devices) < need:
        raise ValueError(
            f"mesh {n_slices}x{n_data}x{n_val} needs {need} devices, "
            f"have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_slices, n_data, n_val)
    return Mesh(grid, (SLICE_AXIS, DATA_AXIS, VAL_AXIS))
