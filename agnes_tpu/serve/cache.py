"""VerifiedCache: the serve plane's verified-vote dedup layer.

In committee-based BFT the cost center is signature verification, and
gossip delivers every vote O(peers) times — under realistic duplication
factors of 8-32x most of the device's Ed25519 lanes re-verify bytes it
already vouched for.  This module is the fix (ISSUE 5 tentpole): a
bounded, thread-safe map keyed by the SHA-256 of the 96-byte wire
record, consulted at ADMISSION (serve/queue.py):

* **hit**  — the exact bytes were device-verified before: the record is
  admitted *pre-verified* and later dispatched on the verify-free
  unsigned step entries (``consensus_step_seq_*``; the split-rung
  dispatch in serve/pipeline.py), skipping the Ed25519 lane entirely.
* **miss** — the record flows to the fused device verify exactly as
  before.

Poisoning safety is the whole design:

* Entries are inserted only AFTER the device verify of that dispatch
  lands clean (`ServePipeline.settle`): a forged duplicate can never
  pre-populate the cache, because its bytes only become a key once a
  dispatch carrying them reported **zero** rejected lanes.  Granularity
  is per dispatch — the device reports a rejected-lane *count*, not a
  per-lane verdict, so a batch containing ANY rejected signature caches
  nothing (counted in ``insert_skipped_rejected``).  Honest steady
  state rejects nothing, so the cache fills; an adversary replaying a
  *rejected* signature re-pays the device verify on every replay and
  stays uncached forever.
* A hit therefore proves "identical bytes passed the device verify" —
  and verification is a pure function of the record's bytes (message,
  signature and pubkey index all come from the record), so replaying
  the hit through the unsigned step cannot change any verdict.

Bounded two ways:

* **LRU byte budget** (`max_bytes`): inserts evict least-recently-hit
  entries first.  `ENTRY_BYTES` is the accounted per-entry cost (the
  32-byte digest plus dict/tuple bookkeeping, rounded up).
* **decided-height pruning** (`prune_decided`): a vote for a height an
  instance has decided can never reach a verify lane again (the
  batcher's stale-height screen drops it first), so its entry is dead
  weight — the service prunes on its poll cadence.

Pure stdlib + numpy, no jax; the internal mutex is a leaf lock held
for dict operations only — admission (under the threaded host's
admission lock) and settle (under the device lock) may touch the cache
concurrently without ever ordering against each other.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

import numpy as np

#: accounted bytes per entry: 32-byte digest key + dict slot + the
#: (instance, height) value tuple — rounded up so the budget errs
#: toward smaller, not larger, resident size
ENTRY_BYTES = 128

#: default budget: ~512k entries — a few full north-star ticks of
#: distinct votes, far above any honest per-height working set
DEFAULT_MAX_BYTES = 64 * 1024 * 1024


class VerifiedCache:
    """Bounded thread-safe digest -> (instance, height) LRU map
    (module docstring).  All arrays are host numpy; every method is a
    short critical section under one leaf mutex."""

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES):
        if int(max_bytes) < ENTRY_BYTES:
            raise ValueError(
                f"max_bytes must hold at least one entry "
                f"({ENTRY_BYTES}): {max_bytes}")
        self.max_bytes = int(max_bytes)
        self._mu = threading.Lock()
        # digest bytes -> (instance, height); order = LRU (oldest first)
        self._entries: "collections.OrderedDict[bytes, tuple]" = \
            collections.OrderedDict()
        # instance -> height -> set of keys: the pruning index, so
        # dropping a decided height is O(entries pruned), never a full
        # cache walk under the mutex (admission lookups share it)
        self._by_inst: dict = {}
        self.counters = {
            "hits": 0, "misses": 0, "inserted": 0, "evicted": 0,
            "pruned_height": 0, "insert_skipped_rejected": 0,
            "insert_skipped_noverdict": 0,
        }
        self._last_prune: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return len(self._entries)      # len(dict) is atomic

    @property
    def bytes(self) -> int:
        """Accounted resident size (ENTRY_BYTES per entry)."""
        return len(self._entries) * ENTRY_BYTES

    # -- admission-side -------------------------------------------------------

    def lookup(self, digests: np.ndarray) -> np.ndarray:
        """[N] bool hit mask for [N, 32] uint8 digests.  Hits refresh
        LRU recency; hit/miss counters move per record.  Key bytes are
        materialized BEFORE the mutex — the critical section is dict
        ops only."""
        n = len(digests)
        out = np.zeros(n, bool)
        if n == 0:
            return out
        keys = [digests[j].tobytes() for j in range(n)]
        with self._mu:
            entries = self._entries
            for j, key in enumerate(keys):
                if key in entries:
                    entries.move_to_end(key)
                    out[j] = True
            hits = int(out.sum())
            self.counters["hits"] += hits
            self.counters["misses"] += n - hits
        return out

    # -- settle-side ----------------------------------------------------------

    def insert(self, digests: np.ndarray, instances: np.ndarray,
               heights: np.ndarray) -> int:
        """Insert device-verified records (call ONLY after the dispatch
        that carried them settled with zero rejected lanes — the
        caller-side contract that keeps the cache poisoning-safe).
        Returns entries newly inserted; evicts LRU past `max_bytes`."""
        n = len(digests)
        if n == 0:
            return 0
        budget = self.max_bytes // ENTRY_BYTES
        # materialize keys/values outside the mutex (the numpy ->
        # bytes/int conversions are the bulk of the per-record cost)
        items = [(digests[j].tobytes(),
                  (int(instances[j]), int(heights[j])))
                 for j in range(n)]
        with self._mu:
            entries = self._entries
            new = 0
            for key, val in items:
                old = entries.get(key)
                if old is None:
                    new += 1
                elif old != val:
                    self._index_discard(key, old)
                entries[key] = val
                entries.move_to_end(key)
                if old is None or old != val:
                    self._by_inst.setdefault(val[0], {}) \
                        .setdefault(val[1], set()).add(key)
            evicted = 0
            while len(entries) > budget:
                key, val = entries.popitem(last=False)
                self._index_discard(key, val)
                evicted += 1
            self.counters["inserted"] += new
            self.counters["evicted"] += evicted
        return new

    def _index_discard(self, key: bytes, val: tuple) -> None:
        """Drop one key from the pruning index (mutex held)."""
        hts = self._by_inst.get(val[0])
        if hts is None:
            return
        bucket = hts.get(val[1])
        if bucket is not None:
            bucket.discard(key)
            if not bucket:
                del hts[val[1]]
        if not hts:
            del self._by_inst[val[0]]

    def note_rejected_batch(self) -> None:
        """Record that a settled dispatch carried rejected lanes and
        its candidate entries were (all) discarded."""
        with self._mu:
            self.counters["insert_skipped_rejected"] += 1

    def note_unverified_batch(self) -> None:
        """Record that a settled signed dispatch carried NO reject
        verdict (fail-closed skip: never insert on a missing
        verdict)."""
        with self._mu:
            self.counters["insert_skipped_noverdict"] += 1

    # -- state-space surface (analysis/admission_mc.py) -----------------------

    def mc_clone(self) -> "VerifiedCache":
        """Copy for state-space branching (the admission model
        checker): fresh leaf mutex, duplicated entry map (LRU order
        preserved) and pruning index."""
        c = VerifiedCache(self.max_bytes)
        with self._mu:
            c._entries = collections.OrderedDict(self._entries)
            c._by_inst = {i: {h: set(s) for h, s in hts.items()}
                          for i, hts in self._by_inst.items()}
            c.counters = dict(self.counters)
            c._last_prune = None if self._last_prune is None \
                else self._last_prune.copy()
        return c

    def mc_canonical(self) -> tuple:
        """Canonical form: entries in LRU order (recency is behavior —
        it picks eviction victims).  Counters are monotone history,
        not behavior, and stay out (see AdmissionQueue.mc_canonical)."""
        with self._mu:
            return tuple((k, v[0], v[1])
                         for k, v in self._entries.items())

    # -- pruning --------------------------------------------------------------

    def prune_decided(self, heights: np.ndarray) -> int:
        """Drop entries whose height is below their instance's current
        height (stale-height screened: they can never reach a verify
        lane again).  `heights` is the batcher's [I] per-instance
        height view; out-of-range instances are left untouched.

        O(entries pruned), never a full-cache walk: the per-instance
        height index (`_by_inst`, maintained by insert/evict) names
        exactly the dead buckets, and instances whose height did not
        move since the last call are skipped entirely — callers may
        prune on every poll/settle tick without blocking concurrent
        admission lookups for more than the pruned entries' dict
        ops."""
        hts = np.asarray(heights)
        n_inst = len(hts)
        pruned = 0
        with self._mu:
            prev = self._last_prune
            for inst, buckets in list(self._by_inst.items()):
                if not 0 <= inst < n_inst:
                    continue
                h_now = int(hts[inst])
                if prev is not None and inst < len(prev) \
                        and int(prev[inst]) == h_now:
                    continue                  # no advance: skip
                for h in [h for h in buckets if h < h_now]:
                    for key in buckets.pop(h):
                        self._entries.pop(key, None)
                        pruned += 1
                if not buckets:
                    del self._by_inst[inst]
            self._last_prune = hts.copy()
            self.counters["pruned_height"] += pruned
        return pruned

    # -- reporting ------------------------------------------------------------

    @property
    def hit_rate(self) -> float:
        with self._mu:
            h, m = self.counters["hits"], self.counters["misses"]
        return h / (h + m) if h + m else 0.0

    def snapshot(self) -> dict:
        with self._mu:
            out = dict(self.counters)
            out["entries"] = len(self._entries)
        out["bytes"] = out["entries"] * ENTRY_BYTES
        out["hit_rate"] = round(
            out["hits"] / (out["hits"] + out["misses"]), 4) \
            if out["hits"] + out["misses"] else 0.0
        return out
