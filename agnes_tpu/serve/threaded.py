"""ThreadedVoteService: the host event loop above VoteService.

VoteService is single-threaded by contract: one caller alternates
submit / pump / poll.  A real frontend cannot — bytes arrive on socket
threads while the dispatch loop must keep the chip fed.  This module
is that layer: two daemon threads over one VoteService,

    submit thread    drains a socket-shaped Inbox (serve/queue.py)
                     into the bounded AdmissionQueue
    dispatch thread  pumps service ticks continuously: closes
                     micro-batches, densifies, queues fused device
                     dispatches

with a two-lock discipline chosen so the caller-facing `submit` is
WAIT-FREE relative to in-flight XLA dispatch:

* ``_admission`` guards the AdmissionQueue + MicroBatcher state.  It
  is held across `queue.submit` (submit thread) and `micro.poll`
  (dispatch thread) — both microseconds of numpy — and NEVER across a
  device dispatch.
* ``_device`` guards the pipeline + driver (densify, dispatch,
  collection).  Only the dispatch thread and the caller's
  poll_decisions/drain take it; the submit thread never does.

`submit()` itself takes NEITHER lock — it appends to the Inbox (its
own nanosecond mutex).  So a socket thread can always hand bytes off,
even while the dispatch thread sits inside a multi-second XLA call.

With the NATIVE admission front-end (ISSUE 14,
serve/native_admission.py) the admission lock is elided entirely: the
C++ queue handle holds its own mutex, queue.submit/drain are single
GIL-releasing ctypes calls, and holding a Python lock across a
GIL-release span would let a second Python thread block on that lock
for the whole native call (the nesting lockcheck's LOCK005 forbids on
the C-API surface).  The submit thread's work becomes a memcpy into
the native inbox; everything else it touches (Metrics, the cache's
leaf mutex, the flight recorder's ring) is thread-safe on its own.
The same elision covers the SHARDED native front-end (ISSUE 20,
NativeAdmissionShards): the shard group's handle synchronizes
internally (per-shard leaf mutexes + a routing-table mutex), carries
the same ``native = True`` class attribute this module keys on, and
its submit is the same single GIL-releasing ctypes call — so N socket
threads spread across shards without ever meeting a Python lock.
The verified-vote dedup lookup (ISSUE 5, serve/cache.py) runs inside
`queue.submit` on the SUBMIT thread under the admission lock — never
under the device lock — and the cache's own leaf mutex is held for
dict operations only, so dedup adds nothing to the wait-free story
(settle-side insertion happens under the device lock, ordered against
the cache only through that leaf mutex).

Observability (per-thread depth/utilization, the ISSUE-3 satellite):
`serve_inbox_depth`, `serve_submit_busy_frac` and
`serve_dispatch_busy_frac` gauges — each loop's busy time over wall
time, windowed per gauge interval — plus the `serve_inbox_dropped`
counter, all on the service's (thread-safe) Metrics registry.

Shutdown is drain-then-join, loss-free for admitted work: `drain()`
stops intake, lets the submit thread finish the inbox, joins both
threads, then runs VoteService.drain() (flush + held re-entry +
settle) on the calling thread and returns its report.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import List, Optional, TYPE_CHECKING

from agnes_tpu.serve.queue import Inbox
#: metric names come from utils/metrics.py, NOT serve/service.py:
#: this module is jax-free at import by contract, so the schedule
#: checker (analysis/schedcheck.py, ISSUE 19) can run the real loop
#: code below in the zero-XLA interpreter every checker here uses.
#: VoteService itself is only needed by the threaded_service()
#: assembler, which imports it lazily.
from agnes_tpu.utils.metrics import (
    SERVE_DISPATCH_BUSY_FRAC,
    SERVE_INBOX_DEPTH,
    SERVE_INBOX_DROPPED,
    SERVE_SUBMIT_BUSY_FRAC,
    SERVE_THREAD_FAILURES,
)

if TYPE_CHECKING:  # annotation only — keep the module jax-free
    from agnes_tpu.serve.service import VoteService


class ThreadedVoteService:
    """Submit/dispatch threads over a VoteService (module docstring).

    ``idle_wait_s`` bounds how long either loop sleeps when it finds
    no work (the inbox get timeout and the dispatch idle nap);
    ``gauge_interval_s`` is the busy-fraction gauge window."""

    def __init__(self, service: VoteService, *,
                 inbox_capacity: int = 1024,
                 idle_wait_s: float = 0.0005,
                 gauge_interval_s: float = 0.05,
                 clock=time.monotonic,
                 thread_factory=threading.Thread,
                 sleep=time.sleep):
        self.service = service
        self.inbox = Inbox(inbox_capacity)
        self.idle_wait_s = float(idle_wait_s)
        self.gauge_interval_s = float(gauge_interval_s)
        self._clock = clock
        #: SchedPoint seams (ISSUE 19): the schedule checker passes a
        #: cooperative thread factory + logical sleep so it can
        #: serialize every yield point of these REAL loops.  Production
        #: keeps the defaults — a plain attribute read, zero overhead.
        self._sleep = sleep
        self._admission = threading.Lock()
        self._device = threading.Lock()
        #: native admission (ISSUE 14): the queue's handle holds its
        #: own mutex, so the admission lock is ELIDED around submit
        #: and the micro-batch close — the GIL-releasing C call must
        #: never run under a Python lock another thread waits on
        #: (lockcheck LOCK005 polices the nesting on the C-API
        #: surface; everything the lock otherwise guards is either
        #: inside the native handle or thread-safe on its own)
        self._native = bool(getattr(service.queue, "native", False))
        #: monotone per-loop busy seconds (single writer each) +
        #: the shared sample window sample_busy_gauges() closes —
        #: the busy-frac gauges used to refresh only when a loop's
        #: PRIVATE window rolled, so the final partial window was
        #: dropped at drain and a heartbeat between rolls read stale
        #: values (the ISSUE 14 satellite fix)
        self._busy_totals = {"submit": 0.0, "dispatch": 0.0}
        #: start instant of a loop's call currently in flight (None =
        #: idle), so a mid-call sample attributes the elapsed span to
        #: the CURRENT window — without it a 60 s XLA compile looked
        #: idle for 60 heartbeat samples and then landed whole in one
        #: 1 s window as busy_frac = 60
        self._busy_inflight = {"submit": None, "dispatch": None}
        self._busy_sample = {"t": None, "submit": 0.0, "dispatch": 0.0}
        self._busy_mu = threading.Lock()
        self._stop = threading.Event()       # stop intake, finish work
        self._started = False
        #: first exception that killed a loop (None = healthy).  A
        #: dead loop FAILS CLOSED: the guard closes the inbox (so
        #: submit refuses) and stops the twin loop; drain() surfaces
        #: the exception in its report under "thread_failure".
        self.failure: Optional[BaseException] = None
        self._submit_t = thread_factory(
            target=lambda: self._guard(self._submit_loop), daemon=True,
            name="agnes-serve-submit")  # lint: allow-thread (the contained-loop wrapper itself: _guard fails closed)
        self._dispatch_t = thread_factory(
            target=lambda: self._guard(self._dispatch_loop),
            daemon=True, name="agnes-serve-dispatch")  # lint: allow-thread (the contained-loop wrapper itself: _guard fails closed)

    def _guard(self, loop) -> None:
        """Exception containment for a loop thread: without it, a
        runtime error mid-pump (XLA OOM, a densify bug) would kill
        the daemon thread SILENTLY — submit would keep accepting work
        nothing will ever dispatch.  Instead the first failure is
        recorded, counted, and the whole host fails closed."""
        try:
            loop()
        except BaseException as e:  # noqa: BLE001 — fail closed on ANY
            if self.failure is None:
                self.failure = e
            self.service.metrics.count(SERVE_THREAD_FAILURES)
            fr = getattr(self.service, "flightrec", None)
            if fr is not None:
                # the crash-surviving trail names the dead loop — a
                # wedged host's heartbeat dates and attributes it
                fr.event("thread_failure",
                         thread=threading.current_thread().name,
                         error=repr(e))
            self._stop.set()
            self.inbox.close()       # refuse producers immediately

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ThreadedVoteService":
        if not self._started:
            self._started = True
            self._submit_t.start()
            self._dispatch_t.start()
        return self

    # -- ingress (any thread; wait-free wrt device work) ---------------------

    def submit(self, wire_bytes) -> bool:
        """Hand a wire blob to the event loop.  Returns False (and
        counts `serve_inbox_dropped`) when the inbox is full, closed
        (draining or a loop thread died) — fail closed, never block:
        backpressure surfaces to the network peer as a refusal, not a
        stall.  The inbox is the ONE refusal authority, so its
        `dropped` count and the metric cannot diverge."""
        if not self.inbox.put(wire_bytes):
            self.service.metrics.count(SERVE_INBOX_DROPPED)
            return False
        return True

    # -- the loops -----------------------------------------------------------

    def busy_seconds(self) -> dict:
        """Lifetime busy seconds per loop (monotone totals — the
        sampler's source).  A probe divides by its own measured span
        for a whole-run busy fraction instead of whatever the last
        gauge window happened to cover."""
        return dict(self._busy_totals)

    def sample_busy_gauges(self, now: Optional[float] = None) -> None:
        """Refresh `serve_submit_busy_frac` / `serve_dispatch_busy_frac`
        from the loops' monotone busy totals over ONE shared sample
        window (the ISSUE 14 satellite fix).  Callable from any thread
        — the loops call it on their gauge cadence, poll_decisions and
        drain call it so the final partial window still lands, and a
        bench heartbeat source may call it so the native-vs-Python
        busy comparison reads live between loop wakeups."""
        m = self.service.metrics
        with self._busy_mu:
            now = self._clock() if now is None else now
            t0 = self._busy_sample["t"]
            if t0 is None:
                self._busy_sample["t"] = now
                for name in ("submit", "dispatch"):
                    self._busy_sample[name] = self._observed(name, now)
                return
            dt = now - t0
            if dt <= 0:
                return
            for name, gauge in (("submit", SERVE_SUBMIT_BUSY_FRAC),
                                ("dispatch", SERVE_DISPATCH_BUSY_FRAC)):
                observed = self._observed(name, now)
                # clamp: attribution keeps windows consistent, the
                # min() only absorbs clock-read jitter at the edges
                m.gauge(gauge, min(
                    1.0, (observed - self._busy_sample[name]) / dt))
                self._busy_sample[name] = observed
            self._busy_sample["t"] = now

    def _observed(self, name: str, now: float) -> float:
        """Busy seconds observable at `now`: the completed total plus
        the elapsed span of any call still in flight (callers hold
        _busy_mu).  A loop sitting in a minutes-long device call is
        BUSY for every window the call spans, not idle-then-60x."""
        start = self._busy_inflight[name]
        inflight = max(0.0, now - start) if start is not None else 0.0
        return self._busy_totals[name] + inflight

    @contextlib.contextmanager
    def _busy(self, name: str):
        """Busy-span bookkeeping for one loop call: mark in flight so
        mid-call samples attribute the elapsed span to their window,
        accumulate + clear on the way out — in a finally, so a raising
        call never leaves a dead thread reading 100% busy forever."""
        t0 = self._clock()
        with self._busy_mu:
            self._busy_inflight[name] = t0
        try:
            yield
        finally:
            with self._busy_mu:
                self._busy_totals[name] += self._clock() - t0
                self._busy_inflight[name] = None

    def _submit_loop(self) -> None:
        m = self.service.metrics
        if self.service.tracer is not None:
            # label this row in chrome-trace (stable-id metadata —
            # the ISSUE 8 tracer satellite)
            self.service.tracer.name_thread(self._submit_t.name)
        self.sample_busy_gauges()        # open the shared window
        win_t0 = self._clock()
        while not (self._stop.is_set() and self.inbox.depth == 0):
            blob = self.inbox.get(timeout=self.idle_wait_s)
            if blob is not None:
                with self._busy("submit"):
                    if self._native:
                        # internally-synchronized native queue: the
                        # GIL-releasing C call runs LOCK-FREE (ISSUE 14)
                        self.service.submit(blob)
                    else:
                        with self._admission:
                            self.service.submit(blob)
            now = self._clock()
            if now - win_t0 >= self.gauge_interval_s:
                self.sample_busy_gauges(now)
                m.gauge(SERVE_INBOX_DEPTH, self.inbox.depth)
                win_t0 = now

    def _dispatch_loop(self) -> None:
        if self.service.tracer is not None:
            self.service.tracer.name_thread(self._dispatch_t.name)
        win_t0 = self._clock()
        while True:
            if self._native:
                batch = self.service._close_batch()
            else:
                with self._admission:
                    batch = self.service._close_batch()
            # pump when there is a closed batch OR builds staged by a
            # previous tick wait for their dispatch (reading the FIFO's
            # truthiness unlocked is benign: worst case one extra tick)
            # OR a BLS aggregate class would close (ISSUE 10: classes
            # are polled inside _pump_batch, so without this gate a
            # BLS-only — or Ed25519-quiet — deployment would strand
            # deadline-expired classes until drain)
            if (batch is not None or self.service.pipeline._staged
                    or (self.service.bls is not None
                        and self.service.bls.ready())):
                with self._busy("dispatch"):
                    with self._device:
                        self.service._pump_batch(batch)
            elif self._stop.is_set():
                break          # idle AND draining: nothing left to pump
            else:
                self._sleep(self.idle_wait_s)
            now = self._clock()
            if now - win_t0 >= self.gauge_interval_s:
                self.sample_busy_gauges(now)
                win_t0 = now

    # -- egress (calling thread) ----------------------------------------------

    def poll_decisions(self) -> List:
        """Newly latched decisions (VoteService.poll_decisions under
        the device lock — serialized against the dispatch thread's
        pipeline work, never against submit).  Also refreshes the
        busy-fraction gauges on the shared sample window, so a poll
        cadence keeps them live even when the loops sit in long
        device calls."""
        self.sample_busy_gauges()
        with self._device:
            return self.service.poll_decisions()

    # -- shutdown -------------------------------------------------------------

    def drain(self, timeout_s: Optional[float] = 60.0) -> dict:
        """Graceful shutdown: close intake, join both threads, flush
        any inbox residue through admission, then run the service's
        own drain (queue flush + held-vote re-entry + settle) and
        return its final report (plus inbox accounting).

        Loss-free for accepted work: `inbox.close()` atomically
        orders every racing `submit` against the final flush — a
        producer that slipped past the stop flag and appended after
        the submit loop exited still gets its blob admitted here; a
        producer arriving after the close gets False (counted).

        `timeout_s` is HONEST: a thread that does not quiesce in time
        (e.g. the dispatch thread inside a multi-minute XLA trace)
        raises TimeoutError instead of silently blocking on the
        device lock for however long the trace takes — retry with a
        larger budget once the compile has had time to finish."""
        self._stop.set()
        self.inbox.close()
        if self._started:
            # ONE shared deadline across both joins, so the promised
            # bound is timeout_s total, not per thread
            t_end = (None if timeout_s is None
                     else self._clock() + timeout_s)
            for t in (self._submit_t, self._dispatch_t):
                t.join(timeout=None if t_end is None
                       else max(0.0, t_end - self._clock()))
            stuck = [t.name for t in (self._submit_t, self._dispatch_t)
                     if t.is_alive()]
            if stuck:
                raise TimeoutError(
                    f"serve threads did not quiesce within "
                    f"{timeout_s}s: {', '.join(stuck)} (an in-flight "
                    f"XLA trace can hold the dispatch thread for "
                    f"minutes; retry drain with a larger timeout_s)")
        # flush the final partial busy window: without this, the last
        # < gauge_interval_s of loop work never reached the gauges and
        # a short-lived service reported busy fractions of 0
        self.sample_busy_gauges()
        # Surfaced by analysis/lockcheck.py (LOCK004): holding the
        # admission lock across the device-lock acquisition is exactly
        # what the two-lock discipline forbids on the serve path.
        # HERE it is deliberate and safe — both loop threads are
        # joined (or were never started) by this point, so this is a
        # quiescent section: nothing can contend, and the final flush
        # + service drain NEED both domains atomically.
        with self._admission, self._device:  # lockcheck: allow (quiescent: loops joined above)
            try:
                # schedcheck: atomic (residue flush: every inbox blob
                # accepted before close() must be admitted here —
                # schedcheck's conservation monitor proves the span)
                while True:     # TOCTOU residue (docstring)
                    blob = self.inbox.get(timeout=0)
                    if blob is None:
                        break
                    self.service.submit(blob)
                report = self.service.drain()
            except BaseException as e:  # noqa: BLE001
                # the service drain re-dispatches queued work through
                # the same driver a loop thread may have died on; for
                # a FAILED host the promised contract is a report
                # carrying thread_failure, not a second raise.  A
                # healthy host's drain error is a real bug: re-raise.
                if self.failure is None:
                    raise
                report = {"drain_error": repr(e),
                          "metrics": self.service.metrics.snapshot()}
        report["inbox"] = {"enqueued": self.inbox.enqueued,
                           "dropped": self.inbox.dropped,
                           "depth_at_drain": self.inbox.depth}
        report["thread_failure"] = (repr(self.failure)
                                    if self.failure is not None else None)
        return report


def threaded_service(driver, batcher, pubkeys=None, *,
                     inbox_capacity: int = 1024,
                     idle_wait_s: float = 0.0005,
                     **service_kw) -> ThreadedVoteService:
    """Convenience assembler: VoteService + ThreadedVoteService,
    started.  `service_kw` passes through to VoteService (ladder,
    capacity, window_predictor, donate, ...)."""
    from agnes_tpu.serve.service import VoteService  # lazy: jax-backed

    svc = VoteService(driver, batcher, pubkeys, **service_kw)
    return ThreadedVoteService(svc, inbox_capacity=inbox_capacity,
                               idle_wait_s=idle_wait_s).start()
