"""Double-buffered densify/dispatch loop of the serve plane.

The stage that keeps the chip busy: while the device executes the
async fused signed step on batch k (DeviceDriver.step_async — deferred
collection, donated state/tally buffers), the host densifies batch
k+1 (VoteBatcher.add_arrays -> build_phases_device — or the dense
builder on a mesh: the EXISTING offline densify stages, reused
verbatim so streaming and offline builds cannot diverge).  One staged
slot — a FIFO when a tick's window-aware split stages several capped
builds (class docstring) — is the whole buffer discipline:

    pump(batch):
      1. DISPATCH the staged (already densified) batch     [device]
      2. DENSIFY `batch` into the staged slot              [host]

so step 2's host work overlaps step 1's device work through JAX async
dispatch, and the device never waits on densify of the batch after
next.  This is the serve twin of bench.py's `_pipeline_fused` loop.

Window discipline: densify needs the batcher synced to the device's
(base_round, heights) — fetching those serializes host behind device
(the fetch completes only after the in-flight step).  Production
honest-path serving therefore passes `window_predictor` (the same
prediction bench._pipeline_fused uses: honest pipeline -> round 0,
height h) and keeps the loop fetch-free; without one the pipeline
fetches per stage — always CORRECT, measurably slower ("the
measured-overhead baseline", as with the host-verified build).

Entry phases: the offline per-height loop prepends one empty entry
phase (round entry + self-proposal) per height.  The pipeline does
the same automatically whenever the window heights advance past the
last entry it dispatched (and on the first dispatch), so honest
streamed traffic reproduces the offline step sequence exactly —
that's what makes the serve-vs-offline differential bit-identical.

Degenerate ticks fail SOFT and CHEAP: a zero-vote batch, an all-held
(future-round) batch, an all-stale batch — anything that densifies to
zero phases — skips dispatch entirely (a counted no-op; no fresh
compile, no crash) instead of pushing an empty step shape through jit.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from agnes_tpu.device.encoding import I32
from agnes_tpu.device.step import VotePhase
from agnes_tpu.serve.batcher import ShapeLadder
from agnes_tpu.serve.queue import PhaseBuildState, WireColumns
from agnes_tpu.types import NIL_ID
from agnes_tpu.utils.tracing import Tracer


@dataclass
class _StagedBatch:
    """A densified batch waiting for its device dispatch."""

    phases: list               # [(VotePhase, n_votes)]
    lanes: object              # SignedLanes | None (host-verified)
    entry: bool                # entry phase prepended?
    entry_heights: Optional[np.ndarray]
    n_votes: int
    t_first: float             # earliest admission instant
    # dedup-cache insertion candidates of a signed (device-verify)
    # build: (digest [N,32], instance [N], height [N]) of its real
    # lanes, inserted at settle iff the dispatch rejected zero lanes
    cache_keys: Optional[tuple] = None
    preverified: bool = False  # unsigned build of dedup-cache hits
    tick: int = 0              # monotonic lifecycle id (ISSUE 8)


@dataclass
class _Inflight:
    t_first: float
    n_votes: int
    t_dispatch: float
    cache_keys: Optional[tuple] = None
    rejects: object = None     # deferred device rejected-lane count
    tick: int = 0


class ServePipeline:
    """Densify + dispatch with one staged slot (module docstring).

    Two dispatch modes, chosen by the driver:

    * **packed-lane** (single-device): `build_phases_device` packs the
      emitted votes into SignedLanes padded onto a ladder rung — the
      compile key includes the lane count, so the ladder IS the shape
      discipline.
    * **dense** (driver has a mesh; forceable via `dense=True`):
      `build_phases_device_dense` scatters the Ed25519 inputs to
      [Ps, I, V] DenseSignedPhases, the layout that shards under
      shard_map — `step_async` dispatches the sharded fused signed
      step with donated buffers, each device verifying its local
      cells.  The compile key is (P, I, V) — fixed by the deployment —
      so the ladder's rungs only pace votes per micro-batch
      (ShapeLadder.plan_dense validates the per-device budget).

    Builds are CAPPED at the ladder's top rung and held-vote re-entry
    builds separately from the fresh batch (window-aware split): a
    held future-round burst entering the window in the same tick as a
    full batch used to drain into ONE build above the top rung — a
    pow2 but UNWARMED lane shape, i.e. a live multi-minute compile
    stall counted in `offladder_builds`.  Since the split, every build
    lands on a warmed rung and the counter is a regression alarm, not
    an accepted cost.  A tick can therefore stage SEVERAL builds; the
    staged slot is a FIFO and `dispatch_staged` queues them all
    back-to-back (async dispatch — the device never waits)."""

    def __init__(self, driver, batcher, pubkeys: Optional[np.ndarray],
                 ladder: ShapeLadder,
                 window_predictor: Optional[Callable] = None,
                 donate: bool = True,
                 dense: Optional[bool] = None,
                 cache=None,
                 bls_lane=None,
                 tracer: Optional[Tracer] = None,
                 metrics=None,
                 flightrec=None,
                 clock=time.monotonic):
        """`cache` (serve/cache.VerifiedCache, shared with the
        AdmissionQueue) enables the SPLIT-RUNG dispatch (ISSUE 5):
        every tick's pending votes partition into a FRESH stream
        (built signed, dispatched on the fused verify entries at a
        now-smaller ladder rung) and a PRE-VERIFIED stream of
        dedup-cache hits (built unsigned, dispatched on the verify-
        free ``consensus_step_seq_*`` entries), interleaved under the
        same double buffer; settle() inserts each signed dispatch's
        wire digests into the cache iff its device verify rejected
        zero lanes."""
        self.driver = driver
        self.batcher = batcher
        self.pubkeys = pubkeys          # None = unsigned deployment
        self.ladder = ladder
        self.window_predictor = window_predictor
        self.donate = donate
        self.cache = cache
        # BLS aggregate lane (ISSUE 10, serve/bls_lane.BlsLane):
        # pump() hands it closed classes, stage_bls() aggregates them
        # on device, pairing-checks on host and feeds the cleared
        # rows down the SAME split-rung unsigned path as dedup-cache
        # hits — one warmed-shape discipline for both
        self.bls_lane = bls_lane
        self.dense = (dense if dense is not None
                      else getattr(driver, "mesh", None) is not None)
        self.tracer = tracer
        self.flightrec = flightrec
        # observability plane (ISSUE 8): a monotonic TICK id per staged
        # build, threaded through dispatch (step_async) and settle so
        # the tracer's flow events and the flight recorder's
        # tick_open/tick_close events name one connected lifecycle; and
        # the dispatch/settle wall histograms on the shared registry
        self.tick_seq = 0
        if metrics is not None:
            from agnes_tpu.utils.metrics import (
                SERVE_DISPATCH_WALL_S,
                SERVE_SETTLE_WALL_S,
            )
            self._h_dispatch = metrics.histogram(SERVE_DISPATCH_WALL_S)
            self._h_settle = metrics.histogram(SERVE_SETTLE_WALL_S)
        else:
            self._h_dispatch = self._h_settle = None
        self._clock = clock
        self._staged: List[_StagedBatch] = []
        self._inflight: List[_Inflight] = []
        self._entry_h: Optional[np.ndarray] = None
        # slot->value decode captured at each instance's FIRST height
        # advance: sync_device resets an advanced instance's slot map,
        # and the double buffer stages h+1 before h's decision
        # messages are collected — so the FIRST (latched) decision of
        # an instance must decode against the table that existed when
        # it was made, not whatever a later height interned into the
        # same slot (service.poll_decisions consumes this)
        self.first_advance_decode: dict = {}
        # ... and the HEIGHT the instance was on before that first
        # advance — i.e. the height its latched first decision decided
        # (the pod decision gather stamps frames with it; reading the
        # batcher's CURRENT height instead would mis-stamp any
        # decision polled after later-height traffic moved the window)
        self.first_advance_height: dict = {}
        self.dispatched_batches = 0
        self.dispatched_votes = 0
        self.noop_ticks = 0
        self.host_fallback_builds = 0
        # split-rung dispatch accounting: builds/votes that rode the
        # verify-free unsigned entries because every record was a
        # dedup-cache hit (dispatched_* above count BOTH streams)
        self.preverified_builds = 0
        self.preverified_votes = 0
        # BLS aggregate lane accounting: votes that entered via a
        # pairing-cleared class / the per-share fallback (subsets of
        # preverified_votes — lane rows ride the unsigned stream)
        self.bls_votes = 0
        # lane shapes above the ladder's top rung.  Historically: a
        # held future-round burst entering the window in the same
        # round as a full new batch drained into one build — a pow2
        # but UNWARMED shape, i.e. a live compile stall.  The
        # window-aware split (stage/_build_all: held re-entry builds
        # separately, every build capped at max_rung votes) PREVENTS
        # this; the counter stays as the regression alarm (tests
        # assert it is 0)
        self.offladder_builds = 0
        # zero-copy densify (ISSUE 20): builds adopted straight from a
        # native phase drain — no add_arrays, no build_phases_device;
        # the C++ drain already produced the device-build arrays.  The
        # numpy pubkey table handed to the drain is cached here because
        # self.pubkeys may be device-resident (one fetch, not one per
        # drain).
        self.native_phase_builds = 0
        self._pk_np: Optional[np.ndarray] = None
        # elastic-pod negotiation support (ISSUE 17): warmup() records
        # every (kind, P[, rung]) it compiled so the negotiation layer
        # can PROVE a padded plan lands on a warmed shape before
        # dispatching it (`warmup_covers`); pad_staged_to /
        # stage_padding are the padding primitives and these counters
        # their audit trail
        self.warmed_keys: set = set()
        self.padded_phases = 0         # empty phases appended by pads
        self.pad_builds = 0            # pure-padding builds staged

    def _span(self, name: str):
        import contextlib

        return (self.tracer.span(name) if self.tracer is not None
                else contextlib.nullcontext())

    def _event(self, kind: str, **fields) -> None:
        if self.flightrec is not None:
            self.flightrec.event(kind, **fields)

    def _next_tick(self) -> int:
        self.tick_seq += 1
        return self.tick_seq

    # -- window --------------------------------------------------------------

    def _sync_window(self) -> np.ndarray:
        """Adopt the target (base_round, heights) into the batcher;
        returns the heights.  Predictor mode is fetch-free; device
        mode forces a host<->device sync (docstring)."""
        if self.window_predictor is not None:
            base, hts = self.window_predictor()
            base = np.asarray(base, np.int64)  # lint: allow (host predictor output)
            hts = np.asarray(hts, np.int64)  # lint: allow (host predictor output)
        else:
            base = np.asarray(self.driver.tally.base_round,  # lint: allow (documented fetch-mode fallback: correct, measurably slower)
                              ).astype(np.int64)
            hts = np.asarray(self.driver.state.height).astype(np.int64)  # lint: allow (documented fetch-mode fallback)
        for i in np.nonzero(hts > self.batcher.heights)[0]:
            if int(i) not in self.first_advance_decode:
                self.first_advance_decode[int(i)] = {
                    s: self.batcher.decode_slot(int(i), s)
                    for s in range(self.batcher.slots.n_slots)}
                self.first_advance_height[int(i)] = \
                    int(self.batcher.heights[i])
        self.batcher.sync_device(base, hts)
        return hts

    def native_phase_state(self) -> Optional[PhaseBuildState]:
        """The PhaseBuildState a native zero-copy phase drain densifies
        against (ISSUE 20), or None when this deployment cannot adopt
        one — no window predictor (the drain runs BEFORE _sync_window,
        so only a predicted window can be densified against without a
        device fetch), unsigned, dense dispatch mode, or MSM verify
        mode.  The service wires this as the native queue's
        `phase_state` hook; it runs once per drain, on the drain's
        thread, and must stay cheap (the predictor is the honest-path
        host computation _sync_window already trusts).  stage()
        re-validates the prediction against the just-synced window and
        falls back to add_arrays on the plain columns if a rotation
        landed in between — correctness never rests on the prediction,
        only the zero-copy fast path does."""
        if (self.window_predictor is None or self.pubkeys is None
                or self.dense or self.batcher.verify_mode != "lanes"):
            return None
        base, hts = self.window_predictor()
        if self._pk_np is None:
            self._pk_np = np.ascontiguousarray(
                np.asarray(self.pubkeys), np.uint8)  # lint: allow (one-time pubkey table snapshot)
        return PhaseBuildState(
            heights=np.asarray(hts, np.int64),  # lint: allow (host predictor output)
            base_round=np.asarray(base, np.int64),  # lint: allow (host predictor output)
            window=self.batcher.W,
            slot_lut=self.batcher.slots.dense,
            pubkeys=self._pk_np,
            n_validators=self.batcher.V,
            lane_floor=self.ladder.min_rung,
            max_votes=self.ladder.max_rung,
            phase_offset=1)

    def _entry_phase(self, heights: np.ndarray) -> VotePhase:
        """The round-entry phase, built from HOST heights so nothing
        in a donated dispatch aliases the driver's live state
        (DeviceDriver.step_async's donation contract)."""
        I, V = self.driver.I, self.driver.V
        return VotePhase(
            round=jnp.zeros(I, I32),
            typ=jnp.zeros(I, I32),
            slots=jnp.full((I, V), NIL_ID, I32),
            mask=jnp.zeros((I, V), bool),
            height=jnp.asarray(heights, I32))

    # -- stages --------------------------------------------------------------

    def stage(self, batch: Optional[WireColumns],
              sync: bool = True) -> bool:
        """Densify into the staged FIFO (host work — overlaps the
        in-flight device step).  Returns True when something was
        staged; a tick whose traffic densifies to nothing (all held /
        stale / rejected) is a counted no-op.  With batch None,
        whatever the batcher already holds pending is built instead
        (the drain path's held-vote re-entry; `sync=False` when the
        caller just synced) — a no-batch no-pending call is a plain
        idle tick.

        Window-aware split (class docstring): held votes that
        re-entered on this tick's sync — anything already pending —
        build BEFORE the fresh batch is even added, and every build is
        capped at the ladder's top rung, so no single build can ever
        exceed a warmed shape."""
        n_new = len(batch) if batch is not None else 0
        if n_new == 0 and self.batcher.pending_votes == 0:
            return False
        with self._span("serve.densify"):
            hts = (self._sync_window() if sync
                   else self.batcher.heights.copy())
            staged_any = False
            if self.batcher.pending_votes:
                staged_any |= self._build_all(hts, self._clock())
            if n_new:
                ph = batch.native_phases
                if (ph is not None
                        and self.batcher.pending_votes == 0
                        and self.pubkeys is not None and not self.dense
                        and self.batcher.verify_mode == "lanes"
                        and np.array_equal(ph.heights,
                                           self.batcher.heights)
                        and np.array_equal(ph.base_round,
                                           self.batcher.base_round)):
                    # zero-copy adopt (ISSUE 20): the native drain
                    # already produced this batch's device-build
                    # arrays, and the window it densified against IS
                    # the window just synced — skip add_arrays and
                    # build_phases_device entirely.  Any mismatch (a
                    # rotation landed between drain and stage, held
                    # re-entry left rows pending, a mode flip) falls
                    # through to the plain columns, which are always
                    # filled.
                    phases, lanes = self.batcher.adopt_native_phases(
                        batch, ph, self.pubkeys)
                    keys = (self.batcher.last_build_keys
                            if self.cache is not None else None)
                    self.native_phase_builds += 1
                    staged_any |= self._stage_signed(
                        phases, lanes, hts, batch.t_first, keys,
                        native=True)
                else:
                    self.batcher.add_arrays(batch.instance,
                                            batch.validator,
                                            batch.height, batch.round_,
                                            batch.typ, batch.value,
                                            batch.signatures,
                                            verified=batch.verified,
                                            digest=batch.digest)
                    staged_any |= self._build_all(hts, batch.t_first)
        if not staged_any:
            self.noop_ticks += 1
        return staged_any

    def _build_all(self, hts: np.ndarray, t_first: float) -> bool:
        """Drain everything pending into staged builds, at most
        `ladder.max_rung` votes per build (each build consumes its cap
        from the pending queue, so the loop strictly progresses even
        when a build densifies to nothing — held/stale votes leave
        `pending` too).

        Split-rung dispatch (class docstring): on a signed deployment
        the pending queue first partitions by the dedup-cache verified
        flag — fresh rows build signed (smaller rungs once duplicates
        are carved out), pre-verified rows build UNSIGNED afterwards
        and ride the verify-free entries.  The partition lives in the
        batcher (`split_pending_verified`) so held future-round votes
        re-entering on a later tick keep their stream: a fresh vote can
        never slip into an unsigned build."""
        staged = False
        # gate on the CACHE or the BLS LANE, not merely a signed
        # deployment: without either, no admission path ever sets the
        # verified column, so the split would be a per-tick no-op walk
        # — and a stray verified=True row fed directly to the batcher
        # must not ride an unsigned build that neither a cache hit nor
        # a cleared pairing vouched for
        pre = (self.batcher.split_pending_verified()
               if (self.cache is not None
                   or self.bls_lane is not None) else [])
        while self.batcher.pending_votes > 0:
            before = self.batcher.pending_votes
            staged |= self._build_one(hts, t_first)
            if self.batcher.pending_votes >= before:  # defensive: a
                break          # non-draining build must not spin
        if pre:
            # fail CLOSED on the security invariant: if the fresh loop
            # exited via its defensive non-draining break, unverified
            # rows are still pending — building "pre-verified" from
            # that queue would drain them into an UNSIGNED dispatch.
            # Re-park the verified rows instead (their flag survives;
            # the next tick's split reclaims them) and only build when
            # the queue holds nothing but cache hits.
            leftover = self.batcher.pending_votes
            self.batcher.adopt_pending(pre)
            while leftover == 0 and self.batcher.pending_votes > 0:
                before = self.batcher.pending_votes
                staged |= self._build_one(hts, t_first,
                                          preverified=True)
                if self.batcher.pending_votes >= before:
                    break
        return staged

    def _build_one(self, hts: np.ndarray, t_first: float,
                   preverified: bool = False) -> bool:
        """One capped build -> staged FIFO entry (False = densified to
        nothing).  `preverified` builds carry only dedup-cache hits:
        identical bytes already device-verified, so they build through
        the UNSIGNED phase path (no lanes, no verify) and dispatch on
        the plain sequence entries."""
        cap = self.ladder.max_rung
        keys = None
        if preverified:
            return self._stage_preverified(hts, t_first, cap)
        if self.pubkeys is not None:
            if self.dense:
                phases, lanes = self.batcher.build_phases_device_dense(
                    self.pubkeys, max_votes=cap)
            else:
                phases, lanes = self.batcher.build_phases_device(
                    self.pubkeys, phase_offset=1,
                    lane_floor=self.ladder.min_rung, max_votes=cap)
            if self.cache is not None and lanes is not None:
                keys = self.batcher.last_build_keys
        else:
            phases, lanes = self.batcher.build_phases(max_votes=cap), \
                None
        if self.pubkeys is not None and lanes is None and phases:
            # ineligible traffic (equivocation layers, mixed
            # rounds, MSM mode): the batcher host-verified instead
            self.host_fallback_builds += 1
        return self._stage_signed(phases, lanes, hts, t_first, keys)

    def _stage_signed(self, phases, lanes, hts: np.ndarray,
                      t_first: float, keys,
                      native: bool = False) -> bool:
        """The staging tail shared by _build_one and stage()'s native
        adopt path: off-ladder alarm, tick lifecycle, entry policy,
        staged-FIFO append.  `phases` is [(VotePhase, n_votes)]; lanes
        may be None (host-verified/unsigned builds)."""
        if (not self.dense and lanes is not None
                and int(lanes.pub.shape[0]) > self.ladder.max_rung):
            # unreachable since the max_votes cap (lanes <= votes and
            # the cap is itself a pow2 rung) — kept as the production
            # regression alarm the ISSUE-2 ROADMAP item promised
            self.offladder_builds += 1
        if not phases:
            return False
        tick = self._next_tick()
        # rung chosen for this build: the padded lane count on the
        # packed-lane signed path, else the vote count (dense/unsigned
        # compile keys carry no rung)
        rung = (int(lanes.pub.shape[0])
                if (not self.dense and lanes is not None) else None)
        extra = {"native": True} if native else {}
        self._event("tick_open", tick=tick,
                    votes=sum(n for _, n in phases), rung=rung,
                    signed=lanes is not None, **extra)
        # Entry policy: signed builds ALWAYS prepend the empty entry
        # phase (their lanes were packed with phase_offset=1, and the
        # honest steady state advances heights every batch anyway —
        # exactly the offline per-height loop's shape); unsigned
        # builds prepend when the window heights advanced past the
        # last entry dispatched (or on the first dispatch).  An extra
        # empty step on an instance mid-round is a state-machine no-op
        # (the driver's canned scenarios rely on the same property).
        entry = (lanes is not None or self.dense or self._entry_h is None
                 or bool((hts > self._entry_h).any()))
        if entry:
            self._entry_h = hts.copy()
        n_votes = sum(n for _, n in phases)
        self._staged.append(_StagedBatch(
            phases=[p for p, _ in phases], lanes=lanes, entry=entry,
            entry_heights=hts if entry else None,
            n_votes=n_votes, t_first=t_first, cache_keys=keys,
            tick=tick))
        return True

    def _stage_preverified(self, hts: np.ndarray, t_first: float,
                           cap: int) -> bool:
        """Stage the pending PRE-VERIFIED rows (dedup-cache hits) as
        unsigned builds, CHUNKED to at most two vote phases per staged
        dispatch.  The chunking is the unsigned twin of the signed
        path's eligibility gate: a cache-hit burst spanning several
        rounds or equivocation layers densifies to one phase per
        (round, class, layer), and an uncapped step sequence would
        dispatch a P outside the warmed {2, 3} set — a live compile
        stall (and, armed, a retrace failure) on exactly the path the
        dedup layer exists to keep cheap.  Splitting a phase list
        across sequential dispatches is semantics-preserving (a P-step
        sequence IS P sequential steps), and every chunk dispatches
        entry + <= 2 phases, a warmed shape.  The entry phase prepends
        on every chunk: an extra empty step mid-round is a
        state-machine no-op."""
        groups = self.batcher.build_phases(max_votes=cap)
        if not groups:
            return False
        for k in range(0, len(groups), 2):
            chunk = groups[k:k + 2]
            n_votes = sum(n for _, n in chunk)
            self._entry_h = hts.copy()
            tick = self._next_tick()
            self._event("tick_open", tick=tick, votes=n_votes,
                        rung=None, signed=False, preverified=True)
            self._staged.append(_StagedBatch(
                phases=[p for p, _ in chunk], lanes=None, entry=True,
                entry_heights=hts, n_votes=n_votes, t_first=t_first,
                preverified=True, tick=tick))
        return True

    # -- elastic-pod padding (ISSUE 17) --------------------------------------
    #
    # Per-tick plan negotiation pads every host of a pod to the tick's
    # MAX build shape so `PodCoordinator.agree` sees identical plans
    # under honest heterogeneity.  Both primitives reuse the warmup
    # properties the steady state already depends on: an empty vote
    # phase (the entry phase IS one — mask all False) is a
    # state-machine no-op on every instance, and an all-zero dense
    # lane row is the exact all-padding encoding warmup compiles —
    # so padding changes neither state nor the compile-key set.

    def warmup_covers(self, kind: str, n_phases: int,
                      rung: int = 0) -> bool:
        """True iff warmup() compiled exactly this build shape —
        (kind, total P incl. entry[, padded lane rung]).  The
        negotiation layer calls this BEFORE dispatching a padded plan:
        a merged plan outside the warmed set is a deployment error
        (fail loudly), never a silent live compile."""
        key = (("signed", int(n_phases), int(rung)) if kind == "signed"
               else (kind, int(n_phases)))
        return key in self.warmed_keys

    def pad_staged_to(self, st: _StagedBatch, n_phases: int) -> int:
        """Pad one staged build UP to a total step-sequence length of
        `n_phases` (entry included) by appending empty vote phases —
        and, on a dense signed build, all-zero lane rows so the
        DenseSignedPhases leading axis tracks the phase count.
        Returns the phases appended (0 = already at least that long).
        Dense / unsigned builds only: a packed-lane build's compile
        key carries its rung, so the pod plane (which is dense) is the
        only caller that ever needs phase padding."""
        cur = len(st.phases) + (1 if st.entry else 0)
        extra = int(n_phases) - cur
        if extra <= 0:
            return 0
        if st.lanes is not None and not self.dense:
            raise ValueError(
                "phase padding is defined for dense/unsigned builds "
                "only (packed-lane keys carry a rung, not a P)")
        hts = (st.entry_heights if st.entry_heights is not None
               else self.batcher.heights.copy())
        st.phases = list(st.phases) + [self._entry_phase(hts)] * extra
        if st.lanes is not None:
            from agnes_tpu.device.step import DenseSignedPhases

            lanes = st.lanes
            st.lanes = DenseSignedPhases(
                pub=lanes.pub,
                sig=jnp.concatenate(
                    [lanes.sig,
                     jnp.zeros((extra,) + lanes.sig.shape[1:],
                               lanes.sig.dtype)]),
                blocks=jnp.concatenate(
                    [lanes.blocks,
                     jnp.zeros((extra,) + lanes.blocks.shape[1:],
                               lanes.blocks.dtype)]))
        self.padded_phases += extra
        self._event("tick_pad", tick=st.tick, phases=extra,
                    n_phases=int(n_phases))
        return extra

    def stage_padding(self, n_phases: int, signed: bool = True) -> int:
        """Stage one PURE-padding build — entry + empty phases +
        (signed) all-zero dense lanes: byte-for-byte the shape
        warmup() compiled for this P, and a state-machine no-op on
        every instance.  What a host dispatches for a negotiated tick
        slot it has no traffic for, so the pod's collective order
        stays lockstep.  Returns the tick id.

        `n_phases` is honored EXACTLY (total P at dispatch, entry
        included): a negotiated slot is the per-tick max of the pod's
        staged builds, and padding to any OTHER P would hand
        PodCoordinator.agree differing plans on an honest-
        heterogeneity tick — a spurious pod abort.  n_phases=1 stages
        a pure-entry build (no vote phases, no lanes: the entry
        carries none, warmup's own convention)."""
        if int(n_phases) < 1:
            raise ValueError(
                f"a padding build needs n_phases >= 1: {n_phases}")
        hts = self.batcher.heights.copy()
        Ps = int(n_phases) - 1
        phases = [self._entry_phase(hts)] * Ps
        lanes = None
        if Ps and signed and self.pubkeys is not None and self.dense:
            from agnes_tpu.device.step import DenseSignedPhases

            d = self.driver
            lanes = DenseSignedPhases(
                pub=jnp.zeros((d.V, 32), jnp.int32),
                sig=jnp.zeros((Ps, d.I, d.V, 64), jnp.int32),
                blocks=jnp.zeros((Ps, d.I, d.V, 1, 32), jnp.uint32))
        tick = self._next_tick()
        self._event("tick_open", tick=tick, votes=0, rung=None,
                    signed=lanes is not None, padding=True)
        self._staged.append(_StagedBatch(
            phases=phases, lanes=lanes, entry=True, entry_heights=hts,
            n_votes=0, t_first=self._clock(), tick=tick))
        self.pad_builds += 1
        return tick

    def dispatch_staged(self) -> int:
        """Queue every staged build's fused step on the device (async;
        never fetches; back-to-back queueing — the split builds of one
        tick ride consecutive dispatches).  Returns the votes
        dispatched (0 = no-op).  If a dispatch RAISES (transient XLA
        error), the failing build and everything after it go back on
        the staged FIFO before the exception propagates — a caller
        that catches and retries loses no staged vote (the
        admitted == dispatched + counted-drops conservation the tests
        assert)."""
        staged, self._staged = self._staged, []
        total = 0
        for k, st in enumerate(staged):
            try:
                t0 = self._clock()
                with self._span("serve.dispatch"):
                    if self.tracer is not None:
                        # flow step: this tick crossed onto the
                        # dispatch thread (submit emitted the start)
                        self.tracer.flow("tick", st.tick, "t")
                    phases = st.phases
                    if st.entry:
                        phases = [self._entry_phase(st.entry_heights)] \
                            + phases
                    self.driver.step_async(phases, st.lanes,
                                           donate=self.donate,
                                           tick=st.tick)
            except BaseException:
                self._staged = staged[k:] + self._staged
                raise
            if self._h_dispatch is not None:
                self._h_dispatch.record(self._clock() - t0)
            self._inflight.append(_Inflight(
                t_first=st.t_first, n_votes=st.n_votes,
                t_dispatch=self._clock(), cache_keys=st.cache_keys,
                rejects=getattr(self.driver, "last_step_rejects",
                                None),
                tick=st.tick))
            self.dispatched_batches += 1
            self.dispatched_votes += st.n_votes
            if st.preverified:
                # counted at DISPATCH (not staging): the metric's name
                # promises dispatched votes, and a staged build can be
                # requeued by a transient dispatch failure
                self.preverified_builds += 1
                self.preverified_votes += st.n_votes
            total += st.n_votes
        return total

    def stage_bls(self, classes) -> bool:
        """Aggregate-lane staging (ISSUE 10): device-MSM + pairing-
        check the closed classes (BlsLane.clear_classes), then feed
        every surviving row — pairing-cleared class members and
        per-share fallback survivors alike — into the batcher as
        PRE-VERIFIED votes and build them through the same split-rung
        unsigned path as dedup-cache hits.  Forged shares died inside
        the lane (counted there); nothing unverified can reach an
        unsigned entry through this path."""
        if not classes or self.bls_lane is None:
            return False
        with self._span("serve.bls_clear"):
            rows = self.bls_lane.clear_classes(classes)
        if rows is None:
            self.noop_ticks += 1
            return False
        n = len(rows["instance"])
        with self._span("serve.densify"):
            hts = self._sync_window()
            self.batcher.add_class_votes(
                rows["instance"], rows["validator"], rows["height"],
                rows["round_"], rows["typ"], rows["value"])
            self.bls_votes += n
            staged = self._build_all(
                hts, rows["t_first"] if rows["t_first"] is not None
                else self._clock())
        if not staged:
            self.noop_ticks += 1
        return staged

    def pump(self, batch: Optional[WireColumns],
             bls_classes=None) -> Tuple[int, bool]:
        """One pipeline tick: dispatch what was staged, then densify
        `batch` (and any closed BLS classes) while the device runs.
        Returns (votes dispatched, staged?)."""
        dispatched = self.dispatch_staged()
        staged = self.stage(batch)
        if bls_classes:
            staged |= self.stage_bls(bls_classes)
        return dispatched, staged

    # -- settle --------------------------------------------------------------

    def settle(self) -> List[_Inflight]:
        """Collect every queued message batch (the one host<->device
        sync point) and hand back the in-flight batch metadata so the
        caller (service) can derive end-to-end latency.

        Dedup-cache insertion happens HERE, after collect() has forced
        every settled dispatch's outputs: a signed dispatch's wire
        digests become cache entries iff its device verify rejected
        ZERO lanes.  The device reports a rejected-lane count, not a
        per-lane verdict, so a batch containing any forged signature
        caches nothing — which is exactly what keeps an adversarial
        replay of a REJECTED signature uncacheable forever."""
        t0 = self._clock()
        with self._span("serve.collect"):
            self.driver.collect()
        if self._h_settle is not None:
            self._h_settle.record(self._clock() - t0)
        done, self._inflight = self._inflight, []
        now = self._clock()
        for b in done:
            if self.tracer is not None:
                self.tracer.flow("tick", b.tick, "f")   # lifecycle end
            self._event("tick_close", tick=b.tick, votes=b.n_votes,
                        e2e_s=round(now - b.t_first, 6))
        if self.cache is not None:
            for b in done:
                if b.cache_keys is None:
                    continue
                if b.rejects is None:
                    # no reject verdict for a signed dispatch (a
                    # driver double that never set last_step_rejects):
                    # the cache gate fails CLOSED — skip insertion
                    # rather than assume the verify was clean
                    self.cache.note_unverified_batch()
                    continue
                n_rej = int(np.asarray(b.rejects).sum())
                if n_rej == 0:
                    dig, inst, heights = b.cache_keys
                    self.cache.insert(dig, inst, heights)
                else:
                    self.cache.note_rejected_batch()
        return done

    def warmup(self, n_phases=(2, 3), arm: bool = True) -> int:
        """Precompile every fused-step shape the steady state will
        dispatch, so the first real batch of each is not a minutes-
        long trace stall mid-service.  Runs the EXACT runtime entry
        (donated or not, mesh-sharded or not, same dtypes, same
        verify-chunk resolution) on all-padding synthetic lanes
        against throwaway COPIES of the driver state — outputs are
        discarded, so the live state/tally are untouched even under
        donation.  `n_phases` is the step-sequence length(s) to warm:
        signed builds always prepend the entry phase, so the honest
        shapes are P=3 (entry + both vote classes, size-closed
        batches) AND P=2 (entry + ONE class — a deadline-closed batch
        that caught only the round's prevotes), hence the (2, 3)
        default.  Packed-lane mode warms one shape per (P, ladder
        rung); dense mode warms one per P — the dense compile key is
        (P, I, V), rung-independent.  Returns shapes warmed.  Signed
        deployments only (unsigned phase sequences have data-dependent
        layer counts).

        When the driver carries a retrace sentinel
        (DeviceDriver(audit=True), analysis/retrace.py) every warmed
        shape is observed into the sentinel's expected-trace set and
        — with `arm` (default) — the set is CLOSED afterwards: any
        serve dispatch whose (entry, shape-signature) was not warmed
        fails loudly and bumps `retrace_unexpected`, instead of
        stalling the service on a live multi-minute compile."""
        if self.pubkeys is None and self.bls_lane is None:
            return 0
        import jax

        from agnes_tpu.device import registry
        from agnes_tpu.device.step import DenseSignedPhases, SignedLanes

        if isinstance(n_phases, int):
            n_phases = (n_phases,)
        d = self.driver
        zero_hts = np.zeros(d.I, np.int64)

        def copies():
            # through the driver hook: the pod driver must copy via a
            # jitted pod computation (DeviceDriver.state_copies)
            return d.state_copies()

        warmed = 0
        for P in n_phases:
            phases = [self._entry_phase(zero_hts)] * P
            exts = [d.ext()] * P
            phases_st = jax.tree.map(lambda *xs: jnp.stack(xs), *phases)
            exts_st = jax.tree.map(lambda *xs: jnp.stack(xs), *exts)
            if self.pubkeys is None:
                pass                      # BLS-only: no signed rungs
            elif self.dense:
                Ps = max(P - 1, 1)           # entry carries no lanes
                dense = DenseSignedPhases(
                    pub=jnp.zeros((d.V, 32), jnp.int32),
                    sig=jnp.zeros((Ps, d.I, d.V, 64), jnp.int32),
                    blocks=jnp.zeros((Ps, d.I, d.V, 1, 32), jnp.uint32))
                fn = d._dense_dispatch_fn(Ps, donate=self.donate)
                out = fn(*copies(), exts_st, phases_st, dense)
                jax.block_until_ready(out.state)
                self.warmed_keys.add(("dense_signed", P))
                warmed += 1
            else:
                name = ("consensus_step_seq_signed_donated"
                        if self.donate else "consensus_step_seq_signed")
                fn = registry.timed_entry(name)
                for r in self.ladder.rungs:
                    lanes = SignedLanes(
                        pub=jnp.zeros((r, 32), jnp.int32),
                        sig=jnp.zeros((r, 64), jnp.int32),
                        blocks=jnp.zeros((r, 1, 32), jnp.uint32),
                        phase_idx=jnp.full(r, P, jnp.int32),  # dropped
                        inst=jnp.zeros(r, jnp.int32),
                        val=jnp.zeros(r, jnp.int32),
                        real=jnp.zeros(r, bool))
                    chunk = d._resolve_lane_chunk(r)
                    args = (*copies(), exts_st, phases_st, lanes,
                            d.powers, d.total, d.proposer_flag,
                            d.propose_value)
                    d._observe(name, args, (d.advance_height, chunk))
                    out = fn(*args, advance_height=d.advance_height,
                             verify_chunk=chunk)
                    jax.block_until_ready(out.state)
                    self.warmed_keys.add(("signed", P, r))
                    warmed += 1
            if self.cache is not None or self.bls_lane is not None:
                # split-rung dispatch (ISSUE 5 + ISSUE 10):
                # pre-verified builds — dedup-cache hits AND
                # pairing-cleared BLS class rows — ride the UNSIGNED
                # sequence entries; warm (and tripwire-arm) those at
                # the same P, so a burst of either can never stall
                # the service on a live unsigned-entry trace.  Their
                # compile key carries no lane rung (phases are dense
                # [P, I, V]): one shape per P, sharing this loop's
                # stacked phases/exts.
                args = (*copies(), exts_st, phases_st, d.powers,
                        d.total, d.proposer_flag, d.propose_value)
                if d.mesh is not None:
                    d._observe("sharded_step_seq", args,
                               (d.advance_height, self.donate))
                    fn = d._make_sharded_seq(
                        d.mesh, advance_height=d.advance_height,
                        donate=self.donate)
                    out = fn(*args)
                else:
                    name = ("consensus_step_seq_donated" if self.donate
                            else "consensus_step_seq")
                    d._observe(name, args, (d.advance_height,))
                    out = registry.timed_entry(name)(
                        *args, advance_height=d.advance_height)
                jax.block_until_ready(out.state)
                self.warmed_keys.add(("unsigned", P))
                warmed += 1
        if self.bls_lane is not None and self.ladder.bls_rungs:
            # the aggregate lane's MSM entry: one compiled shape per
            # BLS rung (all-zero inputs with weight 0 — the padding
            # encoding — build the exact runtime shapes)
            from agnes_tpu.crypto import bls_jax as _bj

            fn = registry.timed_entry("bls_aggregate")
            nw = self.bls_lane.registry.n_windows
            # ISSUE 18: the field-kernel lane is resolved ONCE here
            # and rides the retrace statics — serving with a different
            # lane than was warmed raises at the armed sentinel, never
            # as a live mid-serve compile of the other lane.
            pf = self.bls_lane.uses_pallas_field
            for r in self.ladder.bls_rungs:
                args = (jnp.zeros((r, 2, _bj.NLIMBS), jnp.int32),
                        jnp.zeros((r, 4, _bj.NLIMBS), jnp.int32),
                        jnp.zeros((r, _bj.W_LIMBS), jnp.int32))
                d._observe("bls_aggregate", args, statics=(nw, pf))
                out = fn(*args, n_windows=nw, pallas_field=pf)
                jax.block_until_ready(out[0].x)
                warmed += 1
        if (self.bls_lane is not None and self.ladder.bls_class_rungs
                and self.bls_lane.uses_device_pairing):
            # the device pairing entry (ISSUE 13): one compiled shape
            # per CLASS rung.  All-zero inputs are all-identity
            # padding classes — the exact runtime padding encoding
            from agnes_tpu.crypto import bls_jax as _bj  # noqa: F811
            from agnes_tpu.crypto import bls_pairing_jax  # noqa: F401
            #                      ^ import = entry registration

            fn = registry.timed_entry("bls_pairing_product")
            pf = self.bls_lane.uses_pallas_field
            for r in self.ladder.bls_class_rungs:
                args = (jnp.zeros((r, 2, 3, _bj.NLIMBS), jnp.int32),
                        jnp.zeros((r, 2, 3, 2, _bj.NLIMBS),
                                  jnp.int32))
                d._observe("bls_pairing_product", args, statics=(pf,))
                jax.block_until_ready(fn(*args, pallas_field=pf))
                warmed += 1
        if arm and getattr(d, "sentinel", None) is not None:
            d.sentinel.arm()
        return warmed
