"""BLS aggregate-precommit lane: one pairing per vote class.

The serve plane's decisions/sec ceiling is per-vote Ed25519
verification — the fused signed step pays one verify per lane no
matter how many precommits agree on the same (height, round, value).
PAPERS.md 2302.00418 quantifies the alternative this lane implements:
BLS verification is ~10x slower per signature but AGGREGATES, so a
whole vote class costs ONE aggregate check:

  wire shares ──submit_bls──> fold into AggregateClass buckets
      (per (instance, height, round, typ, value): signer bitmap +
       share table; PoP-less / unknown / duplicate / malformed
       shares rejected and counted at admission)
  class closes (size-or-deadline, the micro-batcher discipline)
      ──> O(N) on DEVICE: `bls_aggregate` (crypto/bls_jax) MSMs the
          signer pubkeys (G1, stake-weighted) and shares (G2) onto a
          padded ladder rung — one compiled shape per rung, queued
          async back-to-back for every closing class
      ──> O(1) on DEVICE (ISSUE 13): ALL closed classes' pairing
          checks in ONE `bls_pairing_product` dispatch on a padded
          class rung (`ShapeLadder.bls_class_rungs`) consuming the
          MSM outputs in place — zero host crypto, only a [C] bool
          vector crosses back; verdicts memoized per
          (class key, epoch, signer set), memos pruned on epoch
          advance (`bls_memo_evictions`)
  pairing clears ──> the class densifies to ONE dense phase row per
      signer set (VoteBatcher.add_class_votes, verified=True) and
      dispatches down the verify-free UNSIGNED step entries — the
      insert-after-verify discipline of the dedup cache: nothing
      reaches an unsigned entry without a cleared pairing behind it
  pairing fails ──> per-share fallback: every share is verified
      individually against the `bls_ref` HOST oracle (the oracle's
      remaining production role, alongside the differential tests);
      good shares still dispatch (host-verified, the
      `host_fallback_builds` analogue), forged shares are dropped
      and counted — one forged share can never poison the class, and
      can never suppress honest shares.  The device pairing is
      REJECT-safe on degenerate/wrong-subgroup aggregates
      (bls_pairing_jax docstring), so soundness never rests on it:
      a device False only ever costs this oracle sweep.

Host-pairing mode (`device_pairing=False`, or no pairing class rungs
planned): the PR 10 path — per-class MSM fetch + oracle pairing —
kept for the bench's device-vs-host comparison and for hosts whose
ladder never warmed the pairing entry.

Rogue-key defense (the satellite): `BlsKeyRegistry` only folds shares
from validators with a verified proof-of-possession
(`bls_ref.pop_prove`/`pop_verify`); shares from PoP-less validators
are rejected at admission and counted as `bls_pop_missing`.  README
"BLS aggregate lane" carries the full threat model.

Host side is numpy + stdlib; jax enters only at the `clear_classes`
device dispatch (lazy import — admission stays jax-free)."""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from agnes_tpu.utils.metrics import BLS_DEVICE_PAIRING_DISPATCHES

#: wire record: the 96-byte Ed25519 record's 32-byte header followed
#: by a 192-byte UNCOMPRESSED G2 share (bls_ref.g2_to_bytes layout) —
#: uncompressed so admission never pays an Fp2 square root per share
BLS_HEADER = 32
BLS_SIG_BYTES = 192
BLS_REC_SIZE = BLS_HEADER + BLS_SIG_BYTES


def pack_bls_wire(instance, validator, height, round_, typ, value,
                  shares: np.ndarray) -> bytes:
    """Column arrays + [N, 192] share bytes -> packed BLS wire records
    (same header layout as `native_ingest.pack_wire_votes`)."""
    n = len(np.asarray(instance))
    rec = np.zeros((n, BLS_REC_SIZE), np.uint8)
    rec[:, 0:4] = np.asarray(instance, np.uint32)[:, None].view(
        np.uint8).reshape(n, 4)
    rec[:, 4:8] = np.asarray(validator, np.uint32)[:, None].view(
        np.uint8).reshape(n, 4)
    rec[:, 8:16] = np.asarray(height, np.int64)[:, None].view(
        np.uint8).reshape(n, 8)
    rec[:, 16:20] = np.asarray(round_, np.int32)[:, None].view(
        np.uint8).reshape(n, 4)
    rec[:, 20] = np.asarray(typ, np.uint8)
    val = np.asarray(value, np.int64)
    rec[:, 21] = (val >= 0).astype(np.uint8)
    rec[:, 24:32] = np.maximum(val, 0)[:, None].view(
        np.uint8).reshape(n, 8)
    rec[:, BLS_HEADER:] = np.asarray(shares, np.uint8).reshape(
        n, BLS_SIG_BYTES)
    return rec.tobytes()


def unpack_bls_wire(wire) -> Tuple[np.ndarray, ...]:
    """Packed BLS records -> (instance, validator, height, round, typ,
    value, shares [N, 192]); trailing partial record dropped (counted
    by the caller via len % BLS_REC_SIZE)."""
    buf = np.frombuffer(wire, np.uint8) \
        if isinstance(wire, (bytes, bytearray, memoryview)) \
        else np.asarray(wire, np.uint8).ravel()
    n = len(buf) // BLS_REC_SIZE
    rec = buf[:n * BLS_REC_SIZE].reshape(n, BLS_REC_SIZE)

    def field(lo, hi, dt):
        return np.ascontiguousarray(rec[:, lo:hi]).view(dt)[:, 0]

    inst = field(0, 4, np.uint32).astype(np.int64)
    val = field(4, 8, np.uint32).astype(np.int64)
    height = field(8, 16, np.int64).copy()
    round_ = field(16, 20, np.int32).astype(np.int64)
    typ = rec[:, 20].astype(np.int64)
    nonnil = rec[:, 21] != 0
    value = np.where(nonnil, field(24, 32, np.int64), -1)
    shares = np.ascontiguousarray(rec[:, BLS_HEADER:])
    return inst, val, height, round_, typ, value, shares


class BlsKeyRegistry:
    """Validator BLS key table + proof-of-possession ledger.

    Construction decompresses (and subgroup-checks) every pubkey once;
    `register_pop` verifies a validator's PoP against the oracle and
    unlocks them for aggregation.  `mark_trusted` is the deployment
    trust-root seam (keys whose PoPs were verified out of band, e.g. a
    genesis file) — folding NEVER happens for a validator that is in
    neither state, counted `bls_pop_missing`."""

    def __init__(self, pubkeys, powers=None):
        from agnes_tpu.crypto import bls_jax as BJ
        from agnes_tpu.crypto import bls_ref as ref

        pk = np.asarray(pubkeys, np.uint8)
        if pk.ndim != 2 or pk.shape[1] != 48:
            raise ValueError(f"pubkeys must be [V, 48]: {pk.shape}")
        self.V = pk.shape[0]
        self.pk_bytes = [bytes(pk[v]) for v in range(self.V)]
        self.pk_points = [ref.g1_decompress(b) for b in self.pk_bytes]
        pw = (np.asarray(powers, np.int64) if powers is not None
              else np.ones(self.V, np.int64))
        if pw.shape != (self.V,):
            raise ValueError(f"powers must be [{self.V}]: {pw.shape}")
        if (pw < 0).any() or (pw >= (1 << BJ.W_BITS)).any():
            raise ValueError(
                f"powers must fit {BJ.W_BITS} bits (the MSM weight "
                f"width)")
        self.powers = pw
        #: the deployment's weight WIDTH, fixed at construction: the
        #: MSM's window count (a STATIC compile-key component,
        #: bls_jax.n_windows_for) derives from it, so set_powers must
        #: stay inside it — uniform-stake deployments (w_bits=1) pay
        #: one window's bucket scan per class instead of six
        self.w_bits = max(1, int(pw.max()).bit_length()) \
            if self.V else 1
        #: [V, 2, NLIMBS] int32 — the G1 MSM's pubkey rows, packed once
        self.pk_limbs = BJ.pack_g1_rows(self.pk_points)
        self.pop_ok = np.zeros(self.V, bool)
        #: liveness defense (README threat model): per-validator count
        #: of shares the fallback PROVED forged, and the quarantine
        #: flag the lane raises after `BlsLane.quarantine_after`
        #: strikes — a quarantined validator's folds are rejected at
        #: admission (`bls_quarantined`), so a PoP-verified-but-
        #: malicious validator cannot re-bill the per-share pairing
        #: sweep forever by minting fresh garbage points per class
        self.forged_strikes = np.zeros(self.V, np.int64)
        self.quarantined = np.zeros(self.V, bool)
        #: bumped by set_powers — pairing memo keys carry it so a
        #: validator-set epoch can never reuse a stale verdict
        self.epoch = 0

    @property
    def n_windows(self) -> int:
        from agnes_tpu.crypto import bls_jax as BJ

        return BJ.n_windows_for(self.w_bits)

    def register_pop(self, validator: int, pop_bytes: bytes) -> bool:
        """Verify + record a proof of possession; False (and no state
        change) on a bad proof."""
        from agnes_tpu.crypto import bls_ref as ref

        v = int(validator)
        if not 0 <= v < self.V:
            return False
        if not ref.pop_verify(self.pk_bytes[v], pop_bytes):
            return False
        self.pop_ok[v] = True
        return True

    def mark_trusted(self, validators) -> None:
        """Trust-root seam: mark validators whose PoPs were verified
        out of band (module docstring)."""
        self.pop_ok[np.asarray(validators, np.int64)] = True

    def set_powers(self, powers) -> None:
        """Validator-set epoch: adopt new voting powers at a height
        boundary (the `set_validators` contract) and advance the
        epoch, invalidating every memoized pairing verdict."""
        from agnes_tpu.crypto import bls_jax as BJ

        pw = np.asarray(powers, np.int64)
        if pw.shape != (self.V,):
            raise ValueError(f"powers must be [{self.V}]: {pw.shape}")
        if (pw < 0).any() or (pw >= (1 << BJ.W_BITS)).any():
            raise ValueError(f"powers must fit {BJ.W_BITS} bits")
        new_bits = max(1, int(pw.max()).bit_length()) if self.V else 1
        if BJ.n_windows_for(new_bits) > self.n_windows:
            # the window COUNT is a warmed compile-key component: an
            # epoch needing more windows would dispatch an uncompiled
            # shape mid-serve (widths within the same 4-bit window
            # granularity are fine)
            raise ValueError(
                f"epoch powers need "
                f"{BJ.n_windows_for(new_bits)} MSM windows > the "
                f"deployment's warmed {self.n_windows} "
                f"(construct the registry with the widest epoch)")
        self.powers = pw
        self.epoch += 1


@dataclasses.dataclass
class AggregateClass:
    """One (instance, height, round, typ, value) precommit class:
    signer bitmap + raw shares, growing until the lane closes it."""

    key: Tuple[int, int, int, int, int]
    signers: np.ndarray                 # [V] bool
    shares: Dict[int, bytes]            # validator -> 192-byte share
    weight: int
    t_first: float

    @property
    def n_signers(self) -> int:
        return len(self.shares)


class BlsClassTable:
    """Admission-side class-bucket store (the AdmissionQueue's
    class-bucketing mode delegates here).  Bounded fail-closed like
    the record queue: at most `max_classes` open classes, at most one
    share per (class, validator), shares only from PoP-verified
    validators.  Thread-safe under one leaf mutex (the threaded host's
    submit and dispatch threads may fold and poll concurrently)."""

    def __init__(self, registry: BlsKeyRegistry, n_instances: int,
                 max_classes: int = 256,
                 clock=time.monotonic):
        if max_classes <= 0:
            raise ValueError(f"max_classes must be positive: "
                             f"{max_classes}")
        self.registry = registry
        self.I = int(n_instances)
        self.max_classes = int(max_classes)
        self._clock = clock
        self._mu = threading.Lock()
        #: opt-in native header screen (ISSUE 14): `fold`'s pass-1
        #: range/PoP/quarantine screens run in C++
        #: (serve/native_admission.bls_screen) and the Python loop
        #: touches only the survivors (which still pay the on-curve
        #: decode — the oracle stays the authority on point validity).
        #: VoteService(native_admission=True) flips this on; the
        #: taxonomy is identical either way (differential-tested).
        self.native_screen = False
        self.classes: Dict[tuple, AggregateClass] = {}
        self.counters = {
            "bls_shares_submitted": 0, "bls_shares_folded": 0,
            "bls_malformed": 0, "bls_unknown_validator": 0,
            "bls_pop_missing": 0, "bls_duplicate_share": 0,
            "bls_class_overflow": 0, "bls_quarantined": 0,
        }

    # -- admission -----------------------------------------------------------

    def fold(self, wire_bytes, decode: bool = True) -> dict:
        """Fold packed BLS wire records into class buckets; returns
        the per-cause counts of this submit.  `decode=False` skips the
        on-curve share screen (the admission model checker's seam —
        its shares are opaque tokens)."""
        raw_len = len(wire_bytes)
        n = raw_len // BLS_REC_SIZE
        res = {k: 0 for k in ("folded", "malformed",
                              "unknown_validator", "pop_missing",
                              "duplicate", "overflow",
                              "quarantined")}
        tail = 1 if raw_len % BLS_REC_SIZE else 0
        res["malformed"] = tail
        cols = unpack_bls_wire(wire_bytes)
        inst, val, height, round_, typ, value, shares = cols
        now = self._clock()
        reg = self.registry
        # pass 1, LOCK-FREE: range/PoP screens + the on-curve decode
        # (a pure-python Fp2 check per share — holding the mutex
        # across it would block the pipeline thread's poll() for the
        # whole submit in the threaded host).  With the native screen
        # on (ISSUE 14), the header screens run in ONE C call and the
        # Python loop walks only the survivors; the reject counts come
        # from a bincount over the native verdict codes — same
        # first-failing-screen-wins taxonomy, differential-tested.
        if self.native_screen and n:
            from agnes_tpu.serve.native_admission import bls_screen

            codes = bls_screen(wire_bytes, self.I, reg.V, reg.pop_ok,
                               reg.quarantined)
            bc = np.bincount(codes, minlength=5)
            res["malformed"] += int(bc[1])
            res["unknown_validator"] += int(bc[2])
            res["pop_missing"] += int(bc[3])
            res["quarantined"] += int(bc[4])
            candidates = np.flatnonzero(codes == 0)
        else:
            candidates = None
        staged = []
        for j in (range(n) if candidates is None else candidates):
            j = int(j)
            i, v = int(inst[j]), int(val[j])
            if candidates is None:
                if not (0 <= i < self.I and 0 <= typ[j] <= 1):
                    res["malformed"] += 1
                    continue
                if not 0 <= v < reg.V:
                    res["unknown_validator"] += 1
                    continue
                if not reg.pop_ok[v]:
                    # rogue-key defense: no verified proof of
                    # possession, no aggregation — ever
                    res["pop_missing"] += 1
                    continue
                if reg.quarantined[v]:
                    # proven-forger liveness defense: this validator's
                    # shares have failed the per-share fallback
                    # repeatedly — stop paying pairings for them
                    res["quarantined"] += 1
                    continue
            share = shares[j].tobytes()
            if decode:
                from agnes_tpu.crypto import bls_ref as ref

                try:
                    if ref.g2_from_bytes(share) is None:
                        raise ValueError("identity share")
                except ValueError:
                    res["malformed"] += 1
                    continue
            staged.append(((i, int(height[j]), int(round_[j]),
                            int(typ[j]), int(value[j])), v, share))
        # pass 2, under the mutex: class-dict mutation only
        with self._mu:
            self.counters["bls_shares_submitted"] += n + tail
            for key, v, share in staged:
                cls = self.classes.get(key)
                if cls is None:
                    if len(self.classes) >= self.max_classes:
                        res["overflow"] += 1
                        continue
                    cls = self.classes[key] = AggregateClass(
                        key=key, signers=np.zeros(reg.V, bool),
                        shares={}, weight=0, t_first=now)
                if v in cls.shares:
                    res["duplicate"] += 1
                    continue
                cls.shares[v] = share
                cls.signers[v] = True
                cls.weight += int(reg.powers[v])
                res["folded"] += 1
            self.counters["bls_shares_folded"] += res["folded"]
            self.counters["bls_malformed"] += res["malformed"]
            self.counters["bls_unknown_validator"] += \
                res["unknown_validator"]
            self.counters["bls_pop_missing"] += res["pop_missing"]
            self.counters["bls_duplicate_share"] += res["duplicate"]
            self.counters["bls_class_overflow"] += res["overflow"]
            self.counters["bls_quarantined"] += res["quarantined"]
        return res

    # -- close ---------------------------------------------------------------

    def poll(self, now: Optional[float] = None,
             target_signers: Optional[int] = None,
             max_delay_s: float = 0.005) -> List[AggregateClass]:
        """Remove and return the classes ready to aggregate:
        size-closed (signers >= target, default the full validator
        set) or deadline-closed (oldest share older than
        max_delay_s) — the micro-batcher's size-or-deadline dial
        applied to classes."""
        tgt = (int(target_signers) if target_signers is not None
               else self.registry.V)
        out: List[AggregateClass] = []
        with self._mu:
            now = self._clock() if now is None else now
            for key in list(self.classes):
                cls = self.classes[key]
                if cls.n_signers >= tgt \
                        or now - cls.t_first >= max_delay_s:
                    out.append(self.classes.pop(key))
        return out

    def ready(self, now: Optional[float] = None,
              target_signers: Optional[int] = None,
              max_delay_s: float = 0.005) -> bool:
        """Non-destructive poll(): would any class close right now?
        The threaded host's dispatch loop gates its pump on this (a
        destructive peek would strand classes outside the pump's lock
        domain)."""
        tgt = (int(target_signers) if target_signers is not None
               else self.registry.V)
        with self._mu:
            now = self._clock() if now is None else now
            return any(c.n_signers >= tgt
                       or now - c.t_first >= max_delay_s
                       for c in self.classes.values())

    def flush(self) -> List[AggregateClass]:
        """Remove and return every open class (drain path)."""
        with self._mu:
            out = list(self.classes.values())
            self.classes.clear()
        return out

    @property
    def open_classes(self) -> int:
        return len(self.classes)

    @property
    def pending_shares(self) -> int:
        with self._mu:
            return sum(c.n_signers for c in self.classes.values())

    # -- state-space surface (analysis/admission_mc.py) ----------------------

    def mc_clone(self) -> "BlsClassTable":
        t = type(self).__new__(type(self))
        t.registry = self.registry
        t.I = self.I
        t.max_classes = self.max_classes
        t._clock = self._clock
        t.native_screen = self.native_screen
        t._mu = threading.Lock()
        with self._mu:
            t.classes = {
                k: AggregateClass(key=c.key, signers=c.signers.copy(),
                                  shares=dict(c.shares),
                                  weight=c.weight, t_first=c.t_first)
                for k, c in self.classes.items()}
            t.counters = dict(self.counters)
        return t

    def mc_canonical(self) -> tuple:
        """Canonical int-only bucket content (counters excluded —
        monotone history, AdmissionQueue.mc_canonical's argument)."""
        with self._mu:
            return tuple(sorted(
                (c.key, tuple(sorted(c.shares)), c.weight)
                for c in self.classes.values()))

    def snapshot(self) -> dict:
        with self._mu:
            out = dict(self.counters)
            out["open_classes"] = len(self.classes)
        return out


class BlsLane:
    """The pipeline-side half: device aggregation + memoized pairing +
    forged-share fallback (module docstring).  Constructed around a
    BlsKeyRegistry; `bind()` wires the driver (dispatch + retrace
    observation), metrics registry and ladder in at service setup."""

    def __init__(self, registry: BlsKeyRegistry, n_instances: int,
                 max_classes: int = 256,
                 target_signers: Optional[int] = None,
                 max_delay_s: float = 0.005,
                 quarantine_after: int = 3,
                 device_pairing: Optional[bool] = None,
                 pallas_field=None,
                 clock=time.monotonic):
        self.registry = registry
        self.table = BlsClassTable(registry, n_instances,
                                   max_classes=max_classes,
                                   clock=clock)
        self.target_signers = target_signers
        self.max_delay_s = float(max_delay_s)
        #: strikes before a proven forger's folds are refused at
        #: admission (registry docstring; <= 0 disables quarantine)
        self.quarantine_after = int(quarantine_after)
        #: ISSUE 13: None = auto (device pairing iff the bound ladder
        #: planned pairing class rungs — a host that never warmed the
        #: pairing entry must not trip a live compile); True/False
        #: forces it (the bench's device-vs-host comparison)
        self.device_pairing = device_pairing
        #: ISSUE 18: None = auto (field kernels iff the default JAX
        #: backend is a TPU — the only backend with a real Mosaic
        #: lowering); False/True/"interpret" forces the lane.  The
        #: resolved value (`uses_pallas_field`) is a STATIC: it rides
        #: the retrace statics of every BLS observe/dispatch, so
        #: warming one lane and serving the other trips the armed
        #: sentinel instead of a live mid-serve compile.
        self.pallas_field = pallas_field
        self._clock = clock
        self.driver = None
        self.metrics = None
        self.ladder = None
        self._h_pairing = None
        #: memoized per-class-message G2 hash and pairing verdicts
        self._msg_memo: Dict[tuple, object] = {}
        self._pair_memo: Dict[tuple, bool] = {}
        #: per-SHARE verdicts from fallback isolation, keyed by
        #: (validator, epoch, message key, share bytes) — a forged
        #: share replayed into a later class costs a dict hit, not a
        #: ~2s host pairing; without this a single malicious
        #: PoP-verified validator could re-bill the pairing per tick
        self._share_memo: Dict[tuple, bool] = {}
        #: the epoch the verdict/share memos were built under: an
        #: epoch advance (set_powers / set_validators) prunes BOTH —
        #: the keys already carry the epoch (no stale verdict could
        #: ever be REUSED), but without the prune a long-lived
        #: service's memos grow one dead generation per epoch,
        #: unboundedly (the ISSUE 13 fix satellite)
        self._memo_epoch = registry.epoch
        self.counters = {
            "agg_classes": 0, "agg_votes": 0,
            "fallback_classes": 0, "fallback_votes": 0,
            "rejected_share_signature": 0,
            "pairing_memo_hits": 0,
            BLS_DEVICE_PAIRING_DISPATCHES: 0,
            "bls_memo_evictions": 0,
        }

    def bind(self, driver, metrics=None, ladder=None) -> None:
        from agnes_tpu.utils.metrics import BLS_PAIRING_WALL_S

        self.driver = driver
        self.metrics = metrics
        self.ladder = ladder
        if metrics is not None:
            self._h_pairing = metrics.histogram(BLS_PAIRING_WALL_S)

    # -- admission passthrough ----------------------------------------------

    def poll(self, now: Optional[float] = None) -> List[AggregateClass]:
        return self.table.poll(now, self.target_signers,
                               self.max_delay_s)

    def ready(self, now: Optional[float] = None) -> bool:
        """Would poll() return anything?  (Non-destructive; the
        threaded host's pump gate.)"""
        return self.table.ready(now, self.target_signers,
                                self.max_delay_s)

    def flush(self) -> List[AggregateClass]:
        return self.table.flush()

    # -- aggregation + verification ------------------------------------------

    def _rung_for(self, n: int) -> int:
        from agnes_tpu.serve.batcher import _ceil_pow2

        if self.ladder is not None and self.ladder.bls_rungs:
            return self.ladder.bls_rung_for(n)
        return _ceil_pow2(n)

    def _class_msg_point(self, key: tuple):
        """hash_to_g2 of the class's canonical signing message —
        the SAME bytes an Ed25519 vote would sign
        (crypto.encoding.vote_signing_bytes), memoized per class."""
        mk = key[1:]                      # (height, round, typ, value)
        pt = self._msg_memo.get(mk)
        if pt is None:
            from agnes_tpu.crypto import bls_ref as ref
            from agnes_tpu.crypto.encoding import vote_signing_bytes

            h, r, t, val = mk
            pt = ref.hash_to_g2(vote_signing_bytes(
                h, r, t, None if val < 0 else val))
            if len(self._msg_memo) >= 4096:
                self._msg_memo.clear()
            self._msg_memo[mk] = pt
        return pt

    @property
    def uses_device_pairing(self) -> bool:
        """Resolved pairing mode (constructor docstring): forced, or
        auto = the bound ladder planned pairing class rungs."""
        if self.device_pairing is not None:
            return bool(self.device_pairing)
        return (self.ladder is not None
                and bool(self.ladder.bls_class_rungs))

    @property
    def uses_pallas_field(self):
        """Resolved field-kernel lane (constructor docstring): forced
        (False/True/"interpret"), or auto = kernels iff serving on a
        TPU.  One resolution, used by warmup AND every dispatch — the
        value is part of each BLS entry's retrace statics, so the two
        can never silently disagree (a mismatch raises RetraceError at
        the first observe, not a live compile mid-serve)."""
        if self.pallas_field is not None:
            return self.pallas_field
        import jax

        return jax.default_backend() == "tpu"

    def _prune_epoch_memos(self) -> None:
        """Epoch advance (set_powers / the service's set_validators
        path) -> drop every memoized pairing/share verdict of the old
        generation (constructor docstring; counted
        `bls_memo_evictions`).  The message-point memo survives: the
        class message is epoch-independent."""
        ep = self.registry.epoch
        if ep == self._memo_epoch:
            return
        n = len(self._pair_memo) + len(self._share_memo)
        self._pair_memo.clear()
        self._share_memo.clear()
        self._memo_epoch = ep
        if n:
            self.counters["bls_memo_evictions"] += n

    def _msm_dispatch(self, cls: AggregateClass, signers):
        """Queue one class's O(N) MSMs on a padded ladder rung;
        returns the aggregated (G1P, G2P) as DEVICE pytrees — no
        fetch, so consecutive classes' dispatches queue back-to-back
        through JAX async dispatch.  Retrace-observed like every
        other device entry."""
        import jax.numpy as jnp

        from agnes_tpu.crypto import bls_jax as BJ
        from agnes_tpu.crypto import bls_ref as ref
        from agnes_tpu.device import registry as _registry

        n = len(signers)
        rung = self._rung_for(n)
        pk_rows = np.zeros((rung, 2, BJ.NLIMBS), np.int32)
        sig_rows = np.zeros((rung, 4, BJ.NLIMBS), np.int32)
        w = np.zeros(rung, np.int64)
        pk_rows[:n] = self.registry.pk_limbs[signers]
        sig_rows[:n] = BJ.pack_g2_rows(
            [ref.g2_from_bytes(cls.shares[v]) for v in signers])
        w[:n] = self.registry.powers[signers]
        args = (jnp.asarray(pk_rows), jnp.asarray(sig_rows),
                jnp.asarray(BJ.pack_weights(w)))
        nw = self.registry.n_windows
        pf = self.uses_pallas_field
        if self.driver is not None:
            self.driver._observe("bls_aggregate", args,
                                 statics=(nw, pf))
        return _registry.timed_entry("bls_aggregate")(
            *args, n_windows=nw, pallas_field=pf)

    def _aggregate_device(self, cls: AggregateClass, signers):
        """Host-pairing mode's aggregation: MSM dispatch + the ONE
        host<->device sync of that mode (class-close boundary, O(1)
        per class — not a per-vote sync); returns bls_ref affine
        points for the oracle pairing."""
        import jax

        from agnes_tpu.crypto import bls_jax as BJ

        agg_pk, agg_sig = self._msm_dispatch(cls, signers)
        agg_pk = jax.tree.map(np.asarray, agg_pk)  # lint: allow (class-close boundary fetch)
        agg_sig = jax.tree.map(np.asarray, agg_sig)  # lint: allow (class-close boundary fetch)
        return BJ.g1_from_device(agg_pk), BJ.g2_from_device(agg_sig)

    def _host_pairing_sweep(self, pending) -> Dict[tuple, bool]:
        """The PR 10 path: per class, fetch the aggregates and pay
        one oracle pairing-product (~seconds of pure python each).
        The histogram times EXACTLY the pairing-product (not the MSM
        or a cold hash-to-curve)."""
        from agnes_tpu.crypto import bls_ref as ref

        out: Dict[tuple, bool] = {}
        for memo_key, cls, signers, msg_pt in pending:
            agg_pk, agg_sig = self._aggregate_device(cls, signers)
            t0 = self._clock()
            out[memo_key] = ref.pairing_product_is_one(
                [(ref.point_neg(ref.G1), agg_sig),
                 (agg_pk, msg_pt)])
            if self._h_pairing is not None:
                self._h_pairing.record(self._clock() - t0)
        return out

    def _device_pairing_sweep(self, pending) -> Dict[tuple, bool]:
        """ISSUE 13 steady state — ZERO host crypto: every pending
        class's MSMs queue async back-to-back, their device outputs
        feed ONE `bls_pairing_product` dispatch per padded class rung
        (chunked above the top rung), and only the [C] bool verdicts
        cross back to the host.  The histogram records the pairing
        dispatch wall divided over its classes (the per-class cost
        the old host path reported in seconds)."""
        import jax
        import jax.numpy as jnp

        from agnes_tpu.crypto import bls_pairing_jax as BP
        from agnes_tpu.device import registry as _registry

        if self.ladder is None or not self.ladder.bls_class_rungs:
            # forced device_pairing=True without planned pairing
            # class rungs: every dispatch would hit an UNWARMED
            # ad-hoc shape — a live multi-minute XLA compile (and a
            # retrace trip) mid-serve.  Fail loudly at the first use
            # instead (auto mode never gets here: it resolves to the
            # host path when no rungs are planned).
            raise ValueError(
                "device pairing needs planned bls_class_rungs "
                "(ShapeLadder.with_bls) — bind a ladder with pairing "
                "rungs or construct the lane with "
                "device_pairing=False")
        cap = self.ladder.bls_class_rungs[-1]
        pf = self.uses_pallas_field
        out: Dict[tuple, bool] = {}
        neg_g1 = jnp.asarray(BP.NEG_G1_LIMBS)
        for k0 in range(0, len(pending), cap):
            chunk = pending[k0:k0 + cap]
            p_rows, q_rows = [], []
            for _mk, cls, signers, msg_pt in chunk:
                agg_pk, agg_sig = self._msm_dispatch(cls, signers)
                p_rows.append(jnp.stack(
                    [neg_g1,
                     jnp.stack([agg_pk.x, agg_pk.y, agg_pk.z])]))
                q_rows.append(jnp.stack(
                    [jnp.stack([agg_sig.x, agg_sig.y, agg_sig.z]),
                     jnp.asarray(BP.pack_g2_proj(msg_pt))]))
            C = len(chunk)
            rung = self.ladder.bls_class_rung_for(C)
            pad = rung - C
            p = jnp.stack(p_rows + [jnp.zeros_like(p_rows[0])] * pad)
            q = jnp.stack(q_rows + [jnp.zeros_like(q_rows[0])] * pad)
            if self.driver is not None:
                self.driver._observe("bls_pairing_product", (p, q),
                                     statics=(pf,))
            # force the queued MSMs first so the histogram times the
            # pairing dispatch itself, comparable to the host mode's
            # pairing-product wall (the bench's speedup ratio)
            jax.block_until_ready((p, q))  # lint: allow (class-close boundary; timing fence)
            t0 = self._clock()
            ok = np.asarray(_registry.timed_entry(
                "bls_pairing_product")(p, q, pallas_field=pf))  # lint: allow (class-close boundary fetch: the [C] bool verdicts)
            wall = self._clock() - t0
            if self._h_pairing is not None:
                self._h_pairing.record(wall / C, n=C)
            self.counters[BLS_DEVICE_PAIRING_DISPATCHES] += 1
            if self.metrics is not None:
                self.metrics.count(BLS_DEVICE_PAIRING_DISPATCHES)
            fr = getattr(self.driver, "flightrec", None) \
                if self.driver is not None else None
            if fr is not None:
                fr.event(BLS_DEVICE_PAIRING_DISPATCHES, classes=C,
                         rung=rung, wall_s=round(wall, 4))
            for (mk, *_rest), verdict in zip(chunk, ok[:C]):
                out[mk] = bool(verdict)
        return out

    def clear_classes(self, classes: List[AggregateClass]
                      ) -> Optional[dict]:
        """Aggregate + verify a batch of closed classes; returns the
        verified row columns (all verified=True — the unsigned-entry
        contract) or None when nothing survived.  In the steady state
        every un-memoized class rides ONE device pairing dispatch
        (`_device_pairing_sweep`); a class whose pairing fails falls
        back to per-share oracle verification: good shares still
        dispatch, forged shares are dropped and counted
        (`rejected_share_signature`)."""
        from agnes_tpu.crypto import bls_ref as ref

        self._prune_epoch_memos()
        entries: List[tuple] = []
        pending: List[tuple] = []
        # verdicts for THIS batch, resolved at lookup/sweep time —
        # never re-read from _pair_memo below: the memo's capacity
        # clear (4096 entries) may fire mid-update, and a memo-HIT
        # class re-read after the clear would default to False and
        # take a spurious host fallback sweep
        verdicts: Dict[tuple, bool] = {}
        for cls in classes:
            signers = np.nonzero(cls.signers)[0]
            if not len(signers):
                continue
            memo_key = (cls.key, self.registry.epoch,
                        signers.tobytes())
            msg_pt = self._class_msg_point(cls.key)
            entries.append((cls, signers, memo_key, msg_pt))
            hit = self._pair_memo.get(memo_key)
            if hit is not None:
                self.counters["pairing_memo_hits"] += 1
                verdicts[memo_key] = hit
            else:
                pending.append((memo_key, cls, signers, msg_pt))
        if pending:
            sweep = (self._device_pairing_sweep
                     if self.uses_device_pairing
                     else self._host_pairing_sweep)
            swept = sweep(pending)
            verdicts.update(swept)
            for mk, verdict in swept.items():
                if len(self._pair_memo) >= 4096:
                    self._pair_memo.clear()
                self._pair_memo[mk] = verdict
        out: List[tuple] = []
        t_first = None
        for cls, signers, memo_key, msg_pt in entries:
            key = cls.key
            ok = verdicts[memo_key]
            if ok:
                good = signers
                self.counters["agg_classes"] += 1
                self.counters["agg_votes"] += len(signers)
            else:
                # forged share(s) somewhere in the class: isolate
                # per share against the oracle; honest shares still
                # count, forged ones are dropped forever.  Verdicts
                # memoize per share so replays cost a lookup.
                reg = self.registry
                good_list = []
                for v in signers:
                    sk = (int(v), reg.epoch, key[1:],
                          cls.shares[v])
                    ok_s = self._share_memo.get(sk)
                    if ok_s is None:
                        ok_s = ref.verify_share(
                            reg.pk_points[v], msg_pt,
                            ref.g2_from_bytes(cls.shares[v]))
                        if len(self._share_memo) >= 8192:
                            self._share_memo.clear()
                        self._share_memo[sk] = ok_s
                        if not ok_s:
                            # PROVEN forgery (not a replay): strike
                            # the signer; past the threshold their
                            # folds are refused at admission, so
                            # fresh-garbage-per-class cannot re-bill
                            # the pairing sweep forever
                            reg.forged_strikes[v] += 1
                            if 0 < self.quarantine_after \
                                    <= reg.forged_strikes[v]:
                                reg.quarantined[v] = True
                    if ok_s:
                        good_list.append(v)
                good = np.asarray(good_list, np.int64)
                self.counters["fallback_classes"] += 1
                self.counters["fallback_votes"] += len(good)
                self.counters["rejected_share_signature"] += \
                    len(signers) - len(good)
            if len(good):
                out.append((key, good))
                t_first = cls.t_first if t_first is None \
                    else min(t_first, cls.t_first)
        if not out:
            return None
        inst = np.concatenate([np.full(len(g), k[0], np.int64)
                               for k, g in out])
        vals = np.concatenate([g for _k, g in out])
        height = np.concatenate([np.full(len(g), k[1], np.int64)
                                 for k, g in out])
        round_ = np.concatenate([np.full(len(g), k[2], np.int64)
                                 for k, g in out])
        typ = np.concatenate([np.full(len(g), k[3], np.int64)
                              for k, g in out])
        value = np.concatenate([np.full(len(g), k[4], np.int64)
                                for k, g in out])
        return {"instance": inst, "validator": vals, "height": height,
                "round_": round_, "typ": typ, "value": value,
                "t_first": t_first}

    def snapshot(self) -> dict:
        out = dict(self.counters)
        out.update(self.table.snapshot())
        return out
