"""NativeAdmissionQueue: the C++ admission front-end (ISSUE 14).

The drop-in twin of `serve.queue.AdmissionQueue`, with the per-record
hot path — wire parse, malformed/fairness/capacity screens, overload
policy, SHA-256 dedup-cache digests, densify-to-columns — behind ONE
ctypes call per submit and per drain (core/native/admission.cpp).
ctypes releases the GIL for every foreign call, so the threaded host's
submit thread spends its time in native code instead of serializing
every producer behind the interpreter: `submit` is a memcpy into the
native inbox plus the (vectorized) Python cache lookup.

What stays in Python, deliberately:

* **The VerifiedCache itself** (serve/cache.py).  The cache's insert
  side is driven by settle (device-verify outcomes) and its poisoning
  contract is subtle; the native side computes the digests (the
  per-record cost) and the wrapper does one vectorized `lookup` per
  submit, so hit/miss counters match the Python queue per record.
* **BLS share decode** (bls_ref.g2_from_bytes).  The class-bucket
  HEADER screens run natively (`bls_screen`, used by
  `BlsClassTable.fold` when its `native_screen` flag is set); the
  on-curve check stays with the oracle.
* **Everything downstream.**  `drain` returns the same `WireColumns`
  the Python queue yields — VoteBatcher/pipeline/dispatch are shared,
  which is what makes the native-ON == native-OFF differential
  (tests/test_native_admission.py) leaf-for-leaf.

Thread safety: the native handle holds its own mutex, so submit and
drain may race — ThreadedVoteService detects `queue.native` and drops
the Python admission lock around both (the GIL-release span must never
nest under that lock; analysis/lockcheck.py LOCK005 polices the
inverse, and LINT004 keeps every `ag_*` C-API call inside this audited
wrapper).  Behavioral parity with AdmissionQueue is specified by the
admission model checker's corpus; where the two could disagree,
serve/queue.py is the specification.

Pure numpy + stdlib + ctypes at import; building the shared library
happens on first use (core/native_build.py).
"""

from __future__ import annotations

import ctypes
import math
import time
from typing import Optional

import numpy as np

from agnes_tpu.bridge.native_ingest import REC_SIZE
from agnes_tpu.core.native_build import lib as _build_lib
from agnes_tpu.serve.queue import (
    AdmitResult,
    DROP_OLDEST,
    REJECT_NEWEST,
    WireColumns,
)

_configured = False


def _lib() -> ctypes.CDLL:
    global _configured
    L = _build_lib()
    if not _configured:
        c = ctypes
        L.ag_adm_new.restype = c.c_void_p
        L.ag_adm_new.argtypes = [c.c_int64, c.c_int64, c.c_int64,
                                 c.c_int32, c.c_int32]
        L.ag_adm_free.argtypes = [c.c_void_p]
        L.ag_adm_submit.restype = c.c_int64
        L.ag_adm_submit.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                    c.c_void_p, c.c_void_p]
        L.ag_adm_set_chunk_ts.argtypes = [c.c_void_p, c.c_int64,
                                          c.c_double]
        L.ag_adm_mark_verified.argtypes = [c.c_void_p, c.c_int64,
                                           c.c_char_p, c.c_int64]
        L.ag_adm_depth.restype = c.c_int64
        L.ag_adm_depth.argtypes = [c.c_void_p]
        L.ag_adm_instance_depth.restype = c.c_int64
        L.ag_adm_instance_depth.argtypes = [c.c_void_p, c.c_int64]
        L.ag_adm_oldest_ts.restype = c.c_double
        L.ag_adm_oldest_ts.argtypes = [c.c_void_p]
        L.ag_adm_counters.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_adm_add_counters.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_adm_drain.restype = c.c_int64
        L.ag_adm_drain.argtypes = [c.c_void_p, c.c_int64] + \
            [c.c_void_p] * 10
        L.ag_adm_export.restype = c.c_int64
        L.ag_adm_export.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                    c.c_int64]
        L.ag_adm_bls_screen.restype = c.c_int64
        L.ag_adm_bls_screen.argtypes = [c.c_char_p, c.c_int64,
                                        c.c_int64, c.c_int64,
                                        c.c_void_p, c.c_void_p,
                                        c.c_void_p]
        _configured = True
    return L


def bls_screen(wire_bytes, n_instances: int, n_validators: int,
               pop_ok: np.ndarray, quarantined: np.ndarray
               ) -> np.ndarray:
    """Native BLS class-bucket header screen: per whole record the
    first failing screen's code (0 ok, 1 malformed, 2 unknown
    validator, 3 PoP missing, 4 quarantined) in BlsClassTable.fold's
    screen order.  The trailing-partial-record count stays with the
    caller (len % BLS_REC_SIZE, as for the Python fold)."""
    from agnes_tpu.serve.bls_lane import BLS_REC_SIZE

    raw = bytes(wire_bytes)
    n = len(raw) // BLS_REC_SIZE
    codes = np.empty(max(n, 1), np.uint8)
    pop = np.ascontiguousarray(pop_ok, np.uint8)
    quar = np.ascontiguousarray(quarantined, np.uint8)
    if pop.shape != (int(n_validators),) or quar.shape != pop.shape:
        raise ValueError(
            f"pop_ok/quarantined must be [{n_validators}]: "
            f"{pop.shape}/{quar.shape}")
    got = _lib().ag_adm_bls_screen(
        raw, len(raw), int(n_instances), int(n_validators),
        pop.ctypes.data, quar.ctypes.data, codes.ctypes.data)
    return codes[:got]


class NativeAdmissionQueue:
    """C++-backed FIFO of admitted wire records — AdmissionQueue's
    interface (submit / submit_bls / drain / counters / depth /
    oldest_ts / instance_depth / wait_hist), native hot path (module
    docstring)."""

    #: the threaded host's lock-elision marker: this queue is
    #: internally synchronized, so holding the Python admission lock
    #: across its GIL-releasing calls is exactly the nesting LOCK005
    #: forbids
    native = True

    def __init__(self, n_instances: int, capacity: int,
                 instance_cap: Optional[int] = None,
                 policy: str = REJECT_NEWEST,
                 cache=None,
                 bls_table=None,
                 clock=time.monotonic):
        # the same validation + defaulting as AdmissionQueue.__init__
        if capacity <= 0:
            raise ValueError(f"capacity must be positive: {capacity}")
        if policy not in (REJECT_NEWEST, DROP_OLDEST):
            raise ValueError(f"unknown overload policy: {policy}")
        self.I = int(n_instances)
        self.capacity = int(capacity)
        self.instance_cap = (int(instance_cap)
                             if instance_cap is not None
                             else max(1, (2 * self.capacity) // self.I))
        if self.instance_cap <= 0:
            raise ValueError(
                f"instance_cap must be positive: {instance_cap}")
        self.policy = policy
        #: digest computation is FROZEN into the native handle at
        #: construction — the cache property's setter enforces it
        self._digests = cache is not None
        self._cache = cache
        self.bls_table = bls_table
        self.wait_hist = None        # duck-typed .record(s, n) sink
        #: drain wall-clock sink (serve_native_drain_wall_s): the
        #: service wires the shared registry's histogram in
        self.drain_hist = None
        self._clock = clock
        L = _lib()
        self._h = L.ag_adm_new(
            self.I, self.capacity, self.instance_cap,
            0 if policy == REJECT_NEWEST else 1,
            1 if cache is not None else 0)
        if not self._h:
            # the C side fails closed (NULL) on hostile dimensions
            raise ValueError(
                f"invalid admission dimensions: I={n_instances} "
                f"capacity={capacity} instance_cap={instance_cap}")
        self._free = L.ag_adm_free   # bound now: module globals are
        #                              gone at interpreter shutdown

    def __del__(self):
        if getattr(self, "_h", None):
            self._free(self._h)
            self._h = None

    @property
    def cache(self):
        return self._cache

    @cache.setter
    def cache(self, value) -> None:
        # AdmissionQueue reads self.cache per submit, but the native
        # handle freezes its digest flag at construction: attaching a
        # cache to a digest-less handle would hand cache.lookup
        # uninitialized digest bytes and settle all-zero keys.  Fail
        # loudly instead of silently diverging from the twin contract.
        # (Detaching — or re-attaching on a digest-enabled handle — is
        # fine: the C side keeps hashing either way.)
        if value is not None and not self._digests:
            raise ValueError(
                "NativeAdmissionQueue cannot attach a dedup cache "
                "after construction: the native handle was created "
                "without digest computation (pass cache= to "
                "__init__)")
        self._cache = value

    # -- admission -----------------------------------------------------------

    def submit(self, wire_bytes) -> AdmitResult:
        """Admit packed wire records: parse/screen/fairness/policy/
        digest in ONE GIL-releasing native call, then (cache attached)
        one vectorized lookup + one native mark-back.  Counts are
        byte-compatible with AdmissionQueue.submit."""
        raw = wire_bytes if isinstance(wire_bytes, bytes) \
            else bytes(wire_bytes)
        n_whole = len(raw) // REC_SIZE
        counts = np.zeros(5, np.int64)
        # snapshot: submit runs LOCK-FREE on the threaded host's
        # submit thread while the setter blesses runtime detach /
        # re-attach — one read, used throughout, or a re-attach
        # landing mid-submit pairs `cache is not None` with dig=None
        cache = self.cache
        dig = (np.empty((n_whole, 32), np.uint8)
               if cache is not None and n_whole else None)
        seq = _lib().ag_adm_submit(
            self._h, raw, len(raw), counts.ctypes.data,
            dig.ctypes.data if dig is not None else None)
        accepted = int(counts[0])
        if accepted:
            # the Python queue reads its clock once per ACCEPTED
            # submit, after admission — fake-clock differentials count
            # invocations, so the native path keeps that discipline
            _lib().ag_adm_set_chunk_ts(self._h, seq, self._clock())
        pre_verified = 0
        if cache is not None and accepted:
            # the lookup covers exactly the admitted records, so the
            # cache's hit + miss counters still sum to `admitted`
            ver = cache.lookup(dig[:accepted])
            pre_verified = int(ver.sum())
            if pre_verified:
                _lib().ag_adm_mark_verified(
                    self._h, seq,
                    np.ascontiguousarray(ver, np.uint8).tobytes(),
                    accepted)
        return AdmitResult(accepted, int(counts[1]), int(counts[2]),
                           int(counts[3]), int(counts[4]), pre_verified)

    def submit_bls(self, wire_bytes) -> AdmitResult:
        """Class-bucketing admission: the fold itself lives with the
        BlsClassTable (which runs the native header screen when its
        `native_screen` flag is set); the reject taxonomy maps onto
        this queue's counters exactly like AdmissionQueue.submit_bls."""
        if self.bls_table is None:
            raise ValueError(
                "submit_bls on a queue without a bls_table (pass "
                "BlsClassTable/BlsLane at construction)")
        res = self.bls_table.fold(wire_bytes)
        fairness = (res["pop_missing"] + res["unknown_validator"]
                    + res["duplicate"] + res["quarantined"])
        deltas = np.asarray(
            [res["folded"] + fairness + res["malformed"]
             + res["overflow"],
             res["folded"], res["overflow"], fairness,
             res["malformed"]], np.int64)
        _lib().ag_adm_add_counters(self._h, deltas.ctypes.data)
        return AdmitResult(res["folded"], res["overflow"], fairness,
                           res["malformed"], 0)

    # -- introspection -------------------------------------------------------

    @property
    def depth(self) -> int:
        return int(_lib().ag_adm_depth(self._h))

    @property
    def oldest_ts(self) -> Optional[float]:
        """Admission instant of the oldest queued record, None when
        empty — with one documented transient: the front record can be
        drained-visible between a lock-free submit and its
        set_chunk_ts stamp, in which case its ts is still NaN and this
        reads None while depth > 0.  MicroBatcher.poll treats that as
        "no deadline anchor yet" and just defers the deadline close by
        one poll; the next read sees the stamp.  Never taken
        single-threaded, so differentials are unaffected."""
        v = _lib().ag_adm_oldest_ts(self._h)
        return None if math.isnan(v) else v

    def instance_depth(self, instance: int) -> int:
        return int(_lib().ag_adm_instance_depth(self._h, int(instance)))

    @property
    def counters(self) -> dict:
        buf = np.empty(7, np.int64)
        _lib().ag_adm_counters(self._h, buf.ctypes.data)
        return {"submitted": int(buf[0]), "admitted": int(buf[1]),
                "rejected_overflow": int(buf[2]),
                "rejected_fairness": int(buf[3]),
                "rejected_malformed": int(buf[4]),
                "evicted": int(buf[5]), "drained": int(buf[6])}

    def native_snapshot(self) -> dict:
        """The drain report's native-admission section."""
        out = self.counters
        out["depth"] = self.depth
        return out

    # -- state-space surface -------------------------------------------------

    def mc_canonical(self) -> tuple:
        """AdmissionQueue.mc_canonical's row format, rebuilt from the
        native FIFO export — the native-vs-Python queue-content
        differential.  (No mc_clone: state-space BRANCHING stays with
        the Python queue the model checker explores.)"""
        from agnes_tpu.bridge.native_ingest import unpack_wire_votes

        n = self.depth
        raw = np.empty((max(n, 1), REC_SIZE), np.uint8)
        ver = np.empty(max(n, 1), np.uint8)
        # cap = the buffers' size: a concurrent submit may have grown
        # the queue since the depth read above; the C side clamps
        n = int(_lib().ag_adm_export(self._h, raw.ctypes.data,
                                     ver.ctypes.data, n))
        inst, val, hts, rnd, typ, value, _sigs = unpack_wire_votes(
            raw[:n].tobytes())
        rows = [(int(inst[j]), int(val[j]), int(hts[j]), int(rnd[j]),
                 int(typ[j]), int(value[j]), int(ver[j]))
                for j in range(n)]
        return (tuple(rows), n)

    # -- drain ---------------------------------------------------------------

    def drain(self, max_records: Optional[int] = None
              ) -> Optional[WireColumns]:
        """Pop up to `max_records` oldest records, densified to the
        WireColumns arrays in ONE GIL-releasing native call (None when
        empty).  The batch is sized from the native call's RETURN
        value, not the pre-read depth — the queue may shrink between
        the two under concurrent drains.  Wait-histogram recording
        keeps the Python queue's chunk granularity: records of one
        submit share one admission instant, so the run-length groups
        of the ts column ARE the chunks (two submits stamped with an
        identical coarse-clock value merge into one record() call —
        histogram contents identical, invocation count not)."""
        n = self.depth
        if n == 0:
            return None
        if max_records is not None:
            n = min(n, int(max_records))
            if n <= 0:
                # zero/negative cap: None, matching AdmissionQueue
                # (np.empty(n < 0) would raise; the C side clamps >= 0)
                return None
        inst = np.empty(n, np.int64)
        val = np.empty(n, np.int64)
        hts = np.empty(n, np.int64)
        rnd = np.empty(n, np.int64)
        typ = np.empty(n, np.int64)
        value = np.empty(n, np.int64)
        sigs = np.empty((n, 64), np.uint8)
        ver = np.empty(n, np.uint8)
        dig = (np.empty((n, 32), np.uint8)
               if self.cache is not None else None)
        ts = np.empty(n, np.float64)
        t0 = time.perf_counter()
        got = int(_lib().ag_adm_drain(
            self._h, n, inst.ctypes.data, val.ctypes.data,
            hts.ctypes.data, rnd.ctypes.data, typ.ctypes.data,
            value.ctypes.data, sigs.ctypes.data, ver.ctypes.data,
            dig.ctypes.data if dig is not None else None,
            ts.ctypes.data))
        wall = time.perf_counter() - t0
        # the C side clamps n to the LIVE queue size under its mutex —
        # a concurrent drain (or anything else shrinking the queue)
        # between the unlocked depth read above and the native call
        # means rows past `got` are uninitialized np.empty memory and
        # must never reach VoteBatcher
        if got == 0:
            return None
        if got < n:
            n = got
            inst, val, hts, rnd, typ, value, ts = (
                a[:n] for a in (inst, val, hts, rnd, typ, value, ts))
            sigs, ver = sigs[:n], ver[:n]
            if dig is not None:
                dig = dig[:n]
        if self.drain_hist is not None:
            self.drain_hist.record(wall, n)
        # a record popped between a lock-free submit and its
        # set_chunk_ts stamp carries NaN — substitute "admitted just
        # now" so neither the wait histogram nor t_first (and the
        # batch-close-age histogram downstream of it) ever sees an
        # epoch-scale outlier.  Never taken single-threaded, so the
        # fake-clock invocation parity of the differentials holds.
        nan = np.isnan(ts)
        if nan.any():
            ts[nan] = self._clock()
        if self.wait_hist is not None:
            # one clock read, and ONLY with a histogram attached —
            # AdmissionQueue.drain's exact clock discipline
            now = self._clock()
            edges = np.flatnonzero(np.diff(ts)) + 1
            starts = np.concatenate(([0], edges))
            ends = np.concatenate((edges, [n]))
            for s, e in zip(starts, ends):
                self.wait_hist.record(now - ts[s].item(), int(e - s))
        return WireColumns(inst, val, hts, rnd, typ, value, sigs,
                           ver.astype(bool), digest=dig,
                           t_first=ts.min().item())
