"""NativeAdmissionQueue: the C++ admission front-end (ISSUE 14).

The drop-in twin of `serve.queue.AdmissionQueue`, with the per-record
hot path — wire parse, malformed/fairness/capacity screens, overload
policy, SHA-256 dedup-cache digests, densify-to-columns — behind ONE
ctypes call per submit and per drain (core/native/admission.cpp).
ctypes releases the GIL for every foreign call, so the threaded host's
submit thread spends its time in native code instead of serializing
every producer behind the interpreter: `submit` is a memcpy into the
native inbox plus the (vectorized) Python cache lookup.

What stays in Python, deliberately:

* **The VerifiedCache itself** (serve/cache.py).  The cache's insert
  side is driven by settle (device-verify outcomes) and its poisoning
  contract is subtle; the native side computes the digests (the
  per-record cost) and the wrapper does one vectorized `lookup` per
  submit, so hit/miss counters match the Python queue per record.
* **BLS share decode** (bls_ref.g2_from_bytes).  The class-bucket
  HEADER screens run natively (`bls_screen`, used by
  `BlsClassTable.fold` when its `native_screen` flag is set); the
  on-curve check stays with the oracle.
* **Everything downstream.**  `drain` returns the same `WireColumns`
  the Python queue yields — VoteBatcher/pipeline/dispatch are shared,
  which is what makes the native-ON == native-OFF differential
  (tests/test_native_admission.py) leaf-for-leaf.

Thread safety: the native handle holds its own mutex, so submit and
drain may race — ThreadedVoteService detects `queue.native` and drops
the Python admission lock around both (the GIL-release span must never
nest under that lock; analysis/lockcheck.py LOCK005 polices the
inverse, and LINT004 keeps every `ag_*` C-API call inside this audited
wrapper).  Behavioral parity with AdmissionQueue is specified by the
admission model checker's corpus; where the two could disagree,
serve/queue.py is the specification.

Pure numpy + stdlib + ctypes at import; building the shared library
happens on first use (core/native_build.py).
"""

from __future__ import annotations

import ctypes
import math
import time
from typing import Optional

import numpy as np

from agnes_tpu.bridge.native_ingest import REC_SIZE
from agnes_tpu.core.native_build import lib as _build_lib
from agnes_tpu.serve.queue import (
    AdmitResult,
    DROP_OLDEST,
    NativePhases,
    REJECT_NEWEST,
    WireColumns,
)

_configured = False


def _lib() -> ctypes.CDLL:
    global _configured
    L = _build_lib()
    if not _configured:
        c = ctypes
        L.ag_adm_new.restype = c.c_void_p
        L.ag_adm_new.argtypes = [c.c_int64, c.c_int64, c.c_int64,
                                 c.c_int32, c.c_int32]
        L.ag_adm_free.argtypes = [c.c_void_p]
        L.ag_adm_submit.restype = c.c_int64
        L.ag_adm_submit.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                    c.c_void_p, c.c_void_p]
        L.ag_adm_set_chunk_ts.argtypes = [c.c_void_p, c.c_int64,
                                          c.c_double]
        L.ag_adm_mark_verified.argtypes = [c.c_void_p, c.c_int64,
                                           c.c_char_p, c.c_int64]
        L.ag_adm_depth.restype = c.c_int64
        L.ag_adm_depth.argtypes = [c.c_void_p]
        L.ag_adm_instance_depth.restype = c.c_int64
        L.ag_adm_instance_depth.argtypes = [c.c_void_p, c.c_int64]
        L.ag_adm_oldest_ts.restype = c.c_double
        L.ag_adm_oldest_ts.argtypes = [c.c_void_p]
        L.ag_adm_counters.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_adm_add_counters.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_adm_drain.restype = c.c_int64
        L.ag_adm_drain.argtypes = [c.c_void_p, c.c_int64] + \
            [c.c_void_p] * 10
        L.ag_adm_export.restype = c.c_int64
        L.ag_adm_export.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                    c.c_int64]
        L.ag_adm_bls_screen.restype = c.c_int64
        L.ag_adm_bls_screen.argtypes = [c.c_char_p, c.c_int64,
                                        c.c_int64, c.c_int64,
                                        c.c_void_p, c.c_void_p,
                                        c.c_void_p]
        # zero-copy densify drain (ISSUE 20): handle, n, 10 column
        # pointers, then the PhaseBuildState scalars/pointers, then the
        # 13 phase/lane output pointers
        _phase_args = ([c.c_void_p, c.c_int64] + [c.c_void_p] * 10
                       + [c.c_void_p, c.c_void_p, c.c_int64, c.c_void_p,
                          c.c_int64, c.c_int64, c.c_void_p, c.c_int64,
                          c.c_int64, c.c_int64, c.c_int64]
                       + [c.c_void_p] * 13)
        L.ag_adm_drain_phases.restype = c.c_int64
        L.ag_adm_drain_phases.argtypes = _phase_args
        # sharded group (ISSUE 20): the ag_adm_* twins under ag_adms_
        L.ag_adms_new.restype = c.c_void_p
        L.ag_adms_new.argtypes = [c.c_int64, c.c_int64, c.c_int64,
                                  c.c_int64, c.c_int32, c.c_int32]
        L.ag_adms_free.argtypes = [c.c_void_p]
        L.ag_adms_n_shards.restype = c.c_int64
        L.ag_adms_n_shards.argtypes = [c.c_void_p]
        L.ag_adms_submit.restype = c.c_int64
        L.ag_adms_submit.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                     c.c_void_p, c.c_void_p]
        L.ag_adms_set_chunk_ts.argtypes = [c.c_void_p, c.c_int64,
                                           c.c_double]
        L.ag_adms_mark_verified.argtypes = [c.c_void_p, c.c_int64,
                                            c.c_char_p, c.c_int64]
        L.ag_adms_depth.restype = c.c_int64
        L.ag_adms_depth.argtypes = [c.c_void_p]
        L.ag_adms_shard_depth.restype = c.c_int64
        L.ag_adms_shard_depth.argtypes = [c.c_void_p, c.c_int64]
        L.ag_adms_instance_depth.restype = c.c_int64
        L.ag_adms_instance_depth.argtypes = [c.c_void_p, c.c_int64]
        L.ag_adms_oldest_ts.restype = c.c_double
        L.ag_adms_oldest_ts.argtypes = [c.c_void_p]
        L.ag_adms_counters.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_adms_shard_counters.argtypes = [c.c_void_p, c.c_int64,
                                             c.c_void_p]
        L.ag_adms_add_counters.argtypes = [c.c_void_p, c.c_void_p]
        L.ag_adms_drain.restype = c.c_int64
        L.ag_adms_drain.argtypes = [c.c_void_p, c.c_int64] + \
            [c.c_void_p] * 10
        L.ag_adms_drain_phases.restype = c.c_int64
        L.ag_adms_drain_phases.argtypes = _phase_args
        L.ag_adms_export.restype = c.c_int64
        L.ag_adms_export.argtypes = [c.c_void_p, c.c_void_p,
                                     c.c_void_p, c.c_int64]
        _configured = True
    return L


def bls_screen(wire_bytes, n_instances: int, n_validators: int,
               pop_ok: np.ndarray, quarantined: np.ndarray
               ) -> np.ndarray:
    """Native BLS class-bucket header screen: per whole record the
    first failing screen's code (0 ok, 1 malformed, 2 unknown
    validator, 3 PoP missing, 4 quarantined) in BlsClassTable.fold's
    screen order.  The trailing-partial-record count stays with the
    caller (len % BLS_REC_SIZE, as for the Python fold)."""
    from agnes_tpu.serve.bls_lane import BLS_REC_SIZE

    raw = bytes(wire_bytes)
    n = len(raw) // BLS_REC_SIZE
    codes = np.empty(max(n, 1), np.uint8)
    pop = np.ascontiguousarray(pop_ok, np.uint8)
    quar = np.ascontiguousarray(quarantined, np.uint8)
    if pop.shape != (int(n_validators),) or quar.shape != pop.shape:
        raise ValueError(
            f"pop_ok/quarantined must be [{n_validators}]: "
            f"{pop.shape}/{quar.shape}")
    got = _lib().ag_adm_bls_screen(
        raw, len(raw), int(n_instances), int(n_validators),
        pop.ctypes.data, quar.ctypes.data, codes.ctypes.data)
    return codes[:got]


def _native_drain(q, drain_fn, phases_fn, max_records):
    """The shared drain body of NativeAdmissionQueue and
    NativeAdmissionShards (`q` supplies _h/I/cache/_clock and the
    histogram/hook attributes; the fns are the handle-flavored C entry
    points).

    Plain path: pop up to `max_records` oldest records, densified to
    the WireColumns arrays in ONE GIL-releasing native call (None when
    empty).  The batch is sized from the native call's RETURN value,
    not the pre-read depth — the queue may shrink between the two
    under concurrent drains.

    Phases path (ISSUE 20): when the pipeline wired a `phase_state`
    hook and it yields a PhaseBuildState, the same single call ALSO
    fills the padded device-build arrays (slot/mask planes + signed
    lanes) when the rows are device-verify eligible; the batch then
    carries a NativePhases bundle and the pipeline skips add_arrays
    entirely.  Ineligible rows (multi-round, held/past, stale,
    pre-verified, uninterned value, ...) fill only the plain columns —
    the Python path owns every screen and split, so the dispatch
    stream is leaf-identical either way.

    Wait-histogram recording keeps the Python queue's chunk
    granularity: records of one submit share one admission instant, so
    the run-length groups of the ts column ARE the chunks (two submits
    stamped with an identical coarse-clock value merge into one
    record() call — histogram contents identical, invocation count
    not)."""
    n = q.depth
    if n == 0:
        return None
    if max_records is not None:
        n = min(n, int(max_records))
        if n <= 0:
            # zero/negative cap: None, matching AdmissionQueue
            # (np.empty(n < 0) would raise; the C side clamps >= 0)
            return None
    st = None
    if phases_fn is not None and q.phase_state is not None:
        st = q.phase_state()
        if st is not None and n > int(st.max_votes):
            # the batcher would _defer_pending-split this batch: let
            # the Python path own the split (and skip the plane
            # allocation for a build that must bail)
            st = None
    inst = np.empty(n, np.int64)
    val = np.empty(n, np.int64)
    hts = np.empty(n, np.int64)
    rnd = np.empty(n, np.int64)
    typ = np.empty(n, np.int64)
    value = np.empty(n, np.int64)
    sigs = np.empty((n, 64), np.uint8)
    ver = np.empty(n, np.uint8)
    dig = (np.empty((n, 32), np.uint8)
           if q.cache is not None else None)
    ts = np.empty(n, np.float64)
    cols = (inst.ctypes.data, val.ctypes.data, hts.ctypes.data,
            rnd.ctypes.data, typ.ctypes.data, value.ctypes.data,
            sigs.ctypes.data, ver.ctypes.data,
            dig.ctypes.data if dig is not None else None,
            ts.ctypes.data)
    ph = None
    t0 = time.perf_counter()
    if st is None:
        got = int(drain_fn(q._h, n, *cols))
    else:
        I = q.I
        S = int(st.slot_lut.shape[1])
        V = int(st.n_validators)
        pad_cap = 1
        while pad_cap < n:
            pad_cap <<= 1
        pad_cap = max(pad_cap, int(st.lane_floor))
        ph_slots = np.empty((2, I, V), np.int32)
        ph_mask = np.empty((2, I, V), np.bool_)
        ph_typ = np.empty(2, np.int64)
        ph_counts = np.empty(2, np.int64)
        l_pub = np.empty((pad_cap, 32), np.int32)
        l_sig = np.empty((pad_cap, 64), np.int32)
        l_blocks = np.empty((pad_cap, 32), np.uint32)
        l_pidx = np.empty(pad_cap, np.int32)
        l_inst = np.empty(pad_cap, np.int32)
        l_val = np.empty(pad_cap, np.int32)
        l_real = np.empty(pad_cap, np.bool_)
        l_rows = np.empty(n, np.int64)
        meta = np.zeros(5, np.int64)
        win_h = np.ascontiguousarray(st.heights, np.int64)
        win_b = np.ascontiguousarray(st.base_round, np.int64)
        lut = np.ascontiguousarray(st.slot_lut, np.int64)
        pk = np.ascontiguousarray(st.pubkeys, np.uint8)
        got = int(phases_fn(
            q._h, n, *cols, win_h.ctypes.data, win_b.ctypes.data,
            int(st.window), lut.ctypes.data, S, V, pk.ctypes.data,
            int(st.lane_floor), int(st.max_votes),
            int(st.phase_offset), pad_cap, ph_slots.ctypes.data,
            ph_mask.ctypes.data, ph_typ.ctypes.data,
            ph_counts.ctypes.data, l_pub.ctypes.data,
            l_sig.ctypes.data, l_blocks.ctypes.data,
            l_pidx.ctypes.data, l_inst.ctypes.data, l_val.ctypes.data,
            l_real.ctypes.data, l_rows.ctypes.data, meta.ctypes.data))
        if meta[0] == 1:
            n_ph, n_ln, n_pad = int(meta[1]), int(meta[2]), int(meta[3])
            ph = NativePhases(
                n_phases=n_ph, n_lanes=n_ln, n_pad=n_pad,
                round_=int(meta[4]), typ=ph_typ[:n_ph],
                counts=ph_counts[:n_ph], slots=ph_slots[:n_ph],
                mask=ph_mask[:n_ph], pub=l_pub[:n_pad],
                sig=l_sig[:n_pad],
                blocks=l_blocks[:n_pad].reshape(n_pad, 1, 32),
                phase_idx=l_pidx[:n_pad], inst=l_inst[:n_pad],
                val=l_val[:n_pad], real=l_real[:n_pad],
                lane_rows=l_rows[:n_ln],
                heights=win_h, base_round=win_b)
            q.phase_fill += 1
        else:
            q.phase_bail += 1
    wall = time.perf_counter() - t0
    # the C side clamps n to the LIVE queue size under its mutex —
    # a concurrent drain (or anything else shrinking the queue)
    # between the unlocked depth read above and the native call
    # means rows past `got` are uninitialized np.empty memory and
    # must never reach VoteBatcher
    if got == 0:
        return None
    if got < n:
        n = got
        inst, val, hts, rnd, typ, value, ts = (
            a[:n] for a in (inst, val, hts, rnd, typ, value, ts))
        sigs, ver = sigs[:n], ver[:n]
        if dig is not None:
            dig = dig[:n]
    if q.drain_hist is not None:
        q.drain_hist.record(wall, n)
    if ph is not None and q.densify_hist is not None:
        q.densify_hist.record(wall, n)
    # a record popped between a lock-free submit and its
    # set_chunk_ts stamp carries NaN — substitute "admitted just
    # now" so neither the wait histogram nor t_first (and the
    # batch-close-age histogram downstream of it) ever sees an
    # epoch-scale outlier.  Never taken single-threaded, so the
    # fake-clock invocation parity of the differentials holds.
    nan = np.isnan(ts)
    if nan.any():
        ts[nan] = q._clock()
    if q.wait_hist is not None:
        # one clock read, and ONLY with a histogram attached —
        # AdmissionQueue.drain's exact clock discipline
        now = q._clock()
        edges = np.flatnonzero(np.diff(ts)) + 1
        starts = np.concatenate(([0], edges))
        ends = np.concatenate((edges, [n]))
        for s, e in zip(starts, ends):
            q.wait_hist.record(now - ts[s].item(), int(e - s))
    return WireColumns(inst, val, hts, rnd, typ, value, sigs,
                       ver.astype(bool), digest=dig,
                       t_first=ts.min().item(), native_phases=ph)


class NativeAdmissionQueue:
    """C++-backed FIFO of admitted wire records — AdmissionQueue's
    interface (submit / submit_bls / drain / counters / depth /
    oldest_ts / instance_depth / wait_hist), native hot path (module
    docstring)."""

    #: the threaded host's lock-elision marker: this queue is
    #: internally synchronized, so holding the Python admission lock
    #: across its GIL-releasing calls is exactly the nesting LOCK005
    #: forbids
    native = True

    def __init__(self, n_instances: int, capacity: int,
                 instance_cap: Optional[int] = None,
                 policy: str = REJECT_NEWEST,
                 cache=None,
                 bls_table=None,
                 clock=time.monotonic):
        # the same validation + defaulting as AdmissionQueue.__init__
        if capacity <= 0:
            raise ValueError(f"capacity must be positive: {capacity}")
        if policy not in (REJECT_NEWEST, DROP_OLDEST):
            raise ValueError(f"unknown overload policy: {policy}")
        self.I = int(n_instances)
        self.capacity = int(capacity)
        self.instance_cap = (int(instance_cap)
                             if instance_cap is not None
                             else max(1, (2 * self.capacity) // self.I))
        if self.instance_cap <= 0:
            raise ValueError(
                f"instance_cap must be positive: {instance_cap}")
        self.policy = policy
        #: digest computation is FROZEN into the native handle at
        #: construction — the cache property's setter enforces it
        self._digests = cache is not None
        self._cache = cache
        self.bls_table = bls_table
        self.wait_hist = None        # duck-typed .record(s, n) sink
        #: drain wall-clock sink (serve_native_drain_wall_s): the
        #: service wires the shared registry's histogram in
        self.drain_hist = None
        #: zero-copy densify (ISSUE 20): the pipeline wires
        #: phase_state = ServePipeline.native_phase_state so drain can
        #: fill the device-build arrays natively; densify_hist is the
        #: serve_native_densify_wall_s sink.  phase_fill/phase_bail
        #: count eligible vs bailed-to-Python phase drains.
        self.phase_state = None
        self.densify_hist = None
        self.phase_fill = 0
        self.phase_bail = 0
        self._clock = clock
        L = _lib()
        self._h = L.ag_adm_new(
            self.I, self.capacity, self.instance_cap,
            0 if policy == REJECT_NEWEST else 1,
            1 if cache is not None else 0)
        if not self._h:
            # the C side fails closed (NULL) on hostile dimensions
            raise ValueError(
                f"invalid admission dimensions: I={n_instances} "
                f"capacity={capacity} instance_cap={instance_cap}")
        self._free = L.ag_adm_free   # bound now: module globals are
        #                              gone at interpreter shutdown

    def __del__(self):
        if getattr(self, "_h", None):
            self._free(self._h)
            self._h = None

    @property
    def cache(self):
        return self._cache

    @cache.setter
    def cache(self, value) -> None:
        # AdmissionQueue reads self.cache per submit, but the native
        # handle freezes its digest flag at construction: attaching a
        # cache to a digest-less handle would hand cache.lookup
        # uninitialized digest bytes and settle all-zero keys.  Fail
        # loudly instead of silently diverging from the twin contract.
        # (Detaching — or re-attaching on a digest-enabled handle — is
        # fine: the C side keeps hashing either way.)
        if value is not None and not self._digests:
            raise ValueError(
                "NativeAdmissionQueue cannot attach a dedup cache "
                "after construction: the native handle was created "
                "without digest computation (pass cache= to "
                "__init__)")
        self._cache = value

    # -- admission -----------------------------------------------------------

    def submit(self, wire_bytes) -> AdmitResult:
        """Admit packed wire records: parse/screen/fairness/policy/
        digest in ONE GIL-releasing native call, then (cache attached)
        one vectorized lookup + one native mark-back.  Counts are
        byte-compatible with AdmissionQueue.submit."""
        raw = wire_bytes if isinstance(wire_bytes, bytes) \
            else bytes(wire_bytes)
        n_whole = len(raw) // REC_SIZE
        counts = np.zeros(5, np.int64)
        # snapshot: submit runs LOCK-FREE on the threaded host's
        # submit thread while the setter blesses runtime detach /
        # re-attach — one read, used throughout, or a re-attach
        # landing mid-submit pairs `cache is not None` with dig=None
        cache = self.cache
        dig = (np.empty((n_whole, 32), np.uint8)
               if cache is not None and n_whole else None)
        seq = _lib().ag_adm_submit(
            self._h, raw, len(raw), counts.ctypes.data,
            dig.ctypes.data if dig is not None else None)
        accepted = int(counts[0])
        if accepted:
            # the Python queue reads its clock once per ACCEPTED
            # submit, after admission — fake-clock differentials count
            # invocations, so the native path keeps that discipline
            _lib().ag_adm_set_chunk_ts(self._h, seq, self._clock())
        pre_verified = 0
        if cache is not None and accepted:
            # the lookup covers exactly the admitted records, so the
            # cache's hit + miss counters still sum to `admitted`
            ver = cache.lookup(dig[:accepted])
            pre_verified = int(ver.sum())
            if pre_verified:
                _lib().ag_adm_mark_verified(
                    self._h, seq,
                    np.ascontiguousarray(ver, np.uint8).tobytes(),
                    accepted)
        return AdmitResult(accepted, int(counts[1]), int(counts[2]),
                           int(counts[3]), int(counts[4]), pre_verified)

    def submit_bls(self, wire_bytes) -> AdmitResult:
        """Class-bucketing admission: the fold itself lives with the
        BlsClassTable (which runs the native header screen when its
        `native_screen` flag is set); the reject taxonomy maps onto
        this queue's counters exactly like AdmissionQueue.submit_bls."""
        if self.bls_table is None:
            raise ValueError(
                "submit_bls on a queue without a bls_table (pass "
                "BlsClassTable/BlsLane at construction)")
        res = self.bls_table.fold(wire_bytes)
        fairness = (res["pop_missing"] + res["unknown_validator"]
                    + res["duplicate"] + res["quarantined"])
        deltas = np.asarray(
            [res["folded"] + fairness + res["malformed"]
             + res["overflow"],
             res["folded"], res["overflow"], fairness,
             res["malformed"]], np.int64)
        _lib().ag_adm_add_counters(self._h, deltas.ctypes.data)
        return AdmitResult(res["folded"], res["overflow"], fairness,
                           res["malformed"], 0)

    # -- introspection -------------------------------------------------------

    @property
    def depth(self) -> int:
        return int(_lib().ag_adm_depth(self._h))

    @property
    def oldest_ts(self) -> Optional[float]:
        """Admission instant of the oldest STAMPED queued record, None
        when empty or when nothing queued is stamped yet.  ISSUE 20
        fix for the PR 14 transient: the FRONT record can be unstamped
        (NaN) between a lock-free submit and its set_chunk_ts call
        while DEEPER records already carry stamps — the old front-only
        read handed MicroBatcher.poll a None even though stamped work
        was past its deadline, deferring the close arbitrarily under a
        sustained race.  The native side now takes a guarded min over
        the live records, so a stamped record's deadline is always
        visible; None still means "no deadline anchor yet", which poll
        treats as defer-one-poll.  Never taken single-threaded, so
        differentials are unaffected."""
        v = _lib().ag_adm_oldest_ts(self._h)
        return None if math.isnan(v) else v

    def instance_depth(self, instance: int) -> int:
        return int(_lib().ag_adm_instance_depth(self._h, int(instance)))

    @property
    def counters(self) -> dict:
        buf = np.empty(7, np.int64)
        _lib().ag_adm_counters(self._h, buf.ctypes.data)
        return {"submitted": int(buf[0]), "admitted": int(buf[1]),
                "rejected_overflow": int(buf[2]),
                "rejected_fairness": int(buf[3]),
                "rejected_malformed": int(buf[4]),
                "evicted": int(buf[5]), "drained": int(buf[6])}

    def native_snapshot(self) -> dict:
        """The drain report's native-admission section."""
        out = self.counters
        out["depth"] = self.depth
        out["phase_fill"] = self.phase_fill
        out["phase_bail"] = self.phase_bail
        return out

    # -- state-space surface -------------------------------------------------

    def mc_canonical(self) -> tuple:
        """AdmissionQueue.mc_canonical's row format, rebuilt from the
        native FIFO export — the native-vs-Python queue-content
        differential.  (No mc_clone: state-space BRANCHING stays with
        the Python queue the model checker explores.)"""
        from agnes_tpu.bridge.native_ingest import unpack_wire_votes

        n = self.depth
        raw = np.empty((max(n, 1), REC_SIZE), np.uint8)
        ver = np.empty(max(n, 1), np.uint8)
        # cap = the buffers' size: a concurrent submit may have grown
        # the queue since the depth read above; the C side clamps
        n = int(_lib().ag_adm_export(self._h, raw.ctypes.data,
                                     ver.ctypes.data, n))
        inst, val, hts, rnd, typ, value, _sigs = unpack_wire_votes(
            raw[:n].tobytes())
        rows = [(int(inst[j]), int(val[j]), int(hts[j]), int(rnd[j]),
                 int(typ[j]), int(value[j]), int(ver[j]))
                for j in range(n)]
        return (tuple(rows), n)

    # -- drain ---------------------------------------------------------------

    def drain(self, max_records: Optional[int] = None
              ) -> Optional[WireColumns]:
        """Pop up to `max_records` oldest records in ONE GIL-releasing
        native call — plain WireColumns, or columns + a NativePhases
        device build when the pipeline wired a phase_state hook and
        the rows are eligible (see _native_drain)."""
        L = _lib()
        return _native_drain(self, L.ag_adm_drain,
                             L.ag_adm_drain_phases, max_records)


class NativeAdmissionShards:
    """Sharded native ingest (ISSUE 20): N C++ admission shards behind
    the NativeAdmissionQueue interface — one handle (and one mutex)
    per shard, instance-range partitioned exactly like
    distributed/topology.HostPlan (shard s owns instances
    [s*L, (s+1)*L), L = I / n_shards), with ONE submit fan-in routing
    each 96-byte record by instance id and a deterministic k-way
    merged drain (global (seq, sub_idx) order — byte-identical to the
    single queue's stream whenever the accept decisions agree).

    Per-instance fairness is EXACT at any shard count (the partition
    key is the fairness key).  Capacity is split evenly across shards
    (capacity / n_shards each), so aggregate overflow near the ceiling
    can differ from a single queue when the instance mix is skewed —
    producers that stay below the per-shard ceiling see identical
    admission.  Construction therefore requires I % n_shards == 0 and
    capacity % n_shards == 0 (the C side's fail-closed screens,
    surfaced here as ValueError).

    One wrapper-contract difference from the single queue: when a
    dedup cache is attached, mark_verified is called for EVERY
    accepted submit (hits or not) — the native side holds a per-submit
    routing vector (global admission order -> owning shard) that the
    mark consumes."""

    native = True

    def __init__(self, n_instances: int, capacity: int,
                 instance_cap: Optional[int] = None,
                 policy: str = REJECT_NEWEST,
                 cache=None,
                 bls_table=None,
                 clock=time.monotonic,
                 n_shards: int = 1):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive: {capacity}")
        if policy not in (REJECT_NEWEST, DROP_OLDEST):
            raise ValueError(f"unknown overload policy: {policy}")
        self.n_shards = int(n_shards)
        self.I = int(n_instances)
        if self.n_shards <= 0:
            raise ValueError(
                f"n_shards must be positive: {n_shards}")
        if self.I % self.n_shards != 0:
            raise ValueError(
                f"n_instances={n_instances} not divisible by "
                f"n_shards={n_shards} (the HostPlan equal-range "
                f"contract)")
        self.capacity = int(capacity)
        if self.capacity % self.n_shards != 0:
            raise ValueError(
                f"capacity={capacity} not divisible by "
                f"n_shards={n_shards}: the per-shard ceiling must be "
                f"an integer (capacity splits evenly across shards)")
        self.L = self.I // self.n_shards
        self.instance_cap = (int(instance_cap)
                             if instance_cap is not None
                             else max(1, (2 * self.capacity) // self.I))
        if self.instance_cap <= 0:
            raise ValueError(
                f"instance_cap must be positive: {instance_cap}")
        self.policy = policy
        self._digests = cache is not None
        self._cache = cache
        self.bls_table = bls_table
        self.wait_hist = None
        self.drain_hist = None
        self.phase_state = None
        self.densify_hist = None
        self.phase_fill = 0
        self.phase_bail = 0
        self._clock = clock
        L = _lib()
        self._h = L.ag_adms_new(
            self.n_shards, self.I, self.capacity, self.instance_cap,
            0 if policy == REJECT_NEWEST else 1,
            1 if cache is not None else 0)
        if not self._h:
            raise ValueError(
                f"invalid admission dimensions: I={n_instances} "
                f"capacity={capacity} instance_cap={instance_cap} "
                f"n_shards={n_shards}")
        self._free = L.ag_adms_free

    def __del__(self):
        if getattr(self, "_h", None):
            self._free(self._h)
            self._h = None

    @property
    def cache(self):
        return self._cache

    @cache.setter
    def cache(self, value) -> None:
        # same frozen-digest contract as NativeAdmissionQueue.cache
        if value is not None and not self._digests:
            raise ValueError(
                "NativeAdmissionShards cannot attach a dedup cache "
                "after construction: the native handles were created "
                "without digest computation (pass cache= to "
                "__init__)")
        self._cache = value

    # -- admission -----------------------------------------------------------

    def submit(self, wire_bytes) -> AdmitResult:
        """Admit packed wire records through the shard fan-in: route
        by instance, screen per shard (no shared mutex), gather
        digests back into global admission order.  Counts are the
        summed per-shard taxonomy."""
        raw = wire_bytes if isinstance(wire_bytes, bytes) \
            else bytes(wire_bytes)
        n_whole = len(raw) // REC_SIZE
        counts = np.zeros(5, np.int64)
        cache = self.cache
        dig = (np.empty((n_whole, 32), np.uint8)
               if cache is not None and n_whole else None)
        seq = _lib().ag_adms_submit(
            self._h, raw, len(raw), counts.ctypes.data,
            dig.ctypes.data if dig is not None else None)
        accepted = int(counts[0])
        if accepted:
            # one clock read per ACCEPTED submit (broadcast to every
            # shard holding records of this seq) — the Python queue's
            # clock discipline
            _lib().ag_adms_set_chunk_ts(self._h, seq, self._clock())
        pre_verified = 0
        if cache is not None and accepted:
            ver = cache.lookup(dig[:accepted])
            pre_verified = int(ver.sum())
            # ALWAYS mark (even all-miss): the native side drops the
            # per-submit routing vector when consumed
            _lib().ag_adms_mark_verified(
                self._h, seq,
                np.ascontiguousarray(ver, np.uint8).tobytes(),
                accepted)
        return AdmitResult(accepted, int(counts[1]), int(counts[2]),
                           int(counts[3]), int(counts[4]), pre_verified)

    def submit_bls(self, wire_bytes) -> AdmitResult:
        """BlsClassTable fold + taxonomy mapping, exactly
        NativeAdmissionQueue.submit_bls (counter deltas land on
        shard 0 — the aggregate is what reports sum)."""
        if self.bls_table is None:
            raise ValueError(
                "submit_bls on a queue without a bls_table (pass "
                "BlsClassTable/BlsLane at construction)")
        res = self.bls_table.fold(wire_bytes)
        fairness = (res["pop_missing"] + res["unknown_validator"]
                    + res["duplicate"] + res["quarantined"])
        deltas = np.asarray(
            [res["folded"] + fairness + res["malformed"]
             + res["overflow"],
             res["folded"], res["overflow"], fairness,
             res["malformed"]], np.int64)
        _lib().ag_adms_add_counters(self._h, deltas.ctypes.data)
        return AdmitResult(res["folded"], res["overflow"], fairness,
                           res["malformed"], 0)

    # -- introspection -------------------------------------------------------

    @property
    def depth(self) -> int:
        return int(_lib().ag_adms_depth(self._h))

    def shard_depth(self, shard: int) -> int:
        return int(_lib().ag_adms_shard_depth(self._h, int(shard)))

    @property
    def oldest_ts(self) -> Optional[float]:
        """Guarded min over every shard's stamped records (the ISSUE 20
        oldest_ts fix, grouped) — None only when nothing stamped
        anywhere; see NativeAdmissionQueue.oldest_ts."""
        v = _lib().ag_adms_oldest_ts(self._h)
        return None if math.isnan(v) else v

    def instance_depth(self, instance: int) -> int:
        return int(_lib().ag_adms_instance_depth(self._h,
                                                 int(instance)))

    @property
    def counters(self) -> dict:
        buf = np.empty(7, np.int64)
        _lib().ag_adms_counters(self._h, buf.ctypes.data)
        return {"submitted": int(buf[0]), "admitted": int(buf[1]),
                "rejected_overflow": int(buf[2]),
                "rejected_fairness": int(buf[3]),
                "rejected_malformed": int(buf[4]),
                "evicted": int(buf[5]), "drained": int(buf[6])}

    def shard_counters(self, shard: int) -> dict:
        buf = np.empty(7, np.int64)
        _lib().ag_adms_shard_counters(self._h, int(shard),
                                      buf.ctypes.data)
        return {"submitted": int(buf[0]), "admitted": int(buf[1]),
                "rejected_overflow": int(buf[2]),
                "rejected_fairness": int(buf[3]),
                "rejected_malformed": int(buf[4]),
                "evicted": int(buf[5]), "drained": int(buf[6])}

    def native_snapshot(self) -> dict:
        """The drain report's native-admission section, with the
        per-shard breakdown alongside the aggregate."""
        out = self.counters
        out["depth"] = self.depth
        out["phase_fill"] = self.phase_fill
        out["phase_bail"] = self.phase_bail
        out["n_shards"] = self.n_shards
        shards = []
        for s in range(self.n_shards):
            c = self.shard_counters(s)
            c["depth"] = self.shard_depth(s)
            shards.append(c)
        out["shards"] = shards
        return out

    # -- state-space surface -------------------------------------------------

    def mc_canonical(self) -> tuple:
        """AdmissionQueue.mc_canonical's row format over the MERGED
        (seq, sub_idx) stream — the shard-group-vs-Python queue
        content differential."""
        from agnes_tpu.bridge.native_ingest import unpack_wire_votes

        n = self.depth
        raw = np.empty((max(n, 1), REC_SIZE), np.uint8)
        ver = np.empty(max(n, 1), np.uint8)
        n = int(_lib().ag_adms_export(self._h, raw.ctypes.data,
                                      ver.ctypes.data, n))
        inst, val, hts, rnd, typ, value, _sigs = unpack_wire_votes(
            raw[:n].tobytes())
        rows = [(int(inst[j]), int(val[j]), int(hts[j]), int(rnd[j]),
                 int(typ[j]), int(value[j]), int(ver[j]))
                for j in range(n)]
        return (tuple(rows), n)

    # -- drain ---------------------------------------------------------------

    def drain(self, max_records: Optional[int] = None
              ) -> Optional[WireColumns]:
        """K-way merged drain across the shards in ONE GIL-releasing
        native call — plain WireColumns, or columns + a NativePhases
        device build when eligible (see _native_drain)."""
        L = _lib()
        return _native_drain(self, L.ag_adms_drain,
                             L.ag_adms_drain_phases, max_records)
