"""VoteService: the streaming vote service plane's façade.

The one object a network frontend talks to.  Wires the four stages —
admission (queue.py), micro-batching (batcher.py), densify/dispatch
(pipeline.py), decision collection — into three calls:

    svc.submit(wire_bytes)   admit packed 96-byte wire records
    svc.pump()               advance the pipeline one tick (the event
                             loop calls this continuously; each tick
                             dispatches at most one batch and stages
                             the next)
    svc.poll_decisions()     newly decided instances (collects the
                             deferred device messages — the sync
                             point; call at the scrape/report cadence,
                             not per tick)
    svc.drain()              graceful shutdown: flush the queue and
                             the staged slot, re-enter held future-
                             round votes once, settle everything, and
                             return the final decision report

Observability: every stage feeds a utils.metrics.Metrics registry —
queue depth / batch fill / in-flight gauges, admission counters, and
WINDOWED serve rates (Metrics.interval_rate — lifetime rates trend to
zero on a long-lived service, the ISSUE-2 satellite) — and, given a
Tracer, wraps itself in per-stage chrome-trace spans
(serve.submit/densify/dispatch/collect).
"""

from __future__ import annotations

import time
from typing import List, NamedTuple, Optional

import numpy as np

from agnes_tpu.serve.batcher import MicroBatcher, ShapeLadder
from agnes_tpu.serve.queue import AdmissionQueue, AdmitResult, REJECT_NEWEST
from agnes_tpu.serve.pipeline import ServePipeline
from agnes_tpu.utils.metrics import (  # noqa: F401 — SERVE_* threaded-
    # host names are re-exports for back-compat; they are DEFINED in
    # utils/metrics.py so the threaded host (and the schedule checker
    # that runs its real loops, ISSUE 19) can import them without
    # pulling this module's jax-backed pipeline
    COMPILE_MS_PREFIX,
    Metrics,
    SERVE_ADMIT_WAIT_S,
    SERVE_BATCH_CLOSE_AGE_S,
    SERVE_DISPATCH_BUSY_FRAC,
    SERVE_E2E_DECISION_S,
    SERVE_INBOX_DEPTH,
    SERVE_INBOX_DROPPED,
    SERVE_NATIVE_DENSIFY_WALL_S,
    SERVE_NATIVE_DRAIN_WALL_S,
    SERVE_NATIVE_INBOX_DEPTH,
    SERVE_NATIVE_PHASE_BUILDS,
    SERVE_NATIVE_REJECTS_FAIRNESS,
    SERVE_NATIVE_REJECTS_MALFORMED,
    SERVE_NATIVE_REJECTS_OVERFLOW,
    SERVE_NATIVE_SHARD_DEPTH_PREFIX,
    SERVE_NATIVE_SHARD_REJECTS_PREFIX,
    SERVE_SUBMIT_BUSY_FRAC,
    SERVE_THREAD_FAILURES,
)
from agnes_tpu.utils.tracing import Tracer

# serve-plane metric names (counters unless noted)
SERVE_SUBMITTED = "serve_submitted"
SERVE_ADMITTED = "serve_admitted"
SERVE_REJECTED_OVERFLOW = "serve_rejected_overflow"
SERVE_REJECTED_FAIRNESS = "serve_rejected_fairness"
SERVE_REJECTED_MALFORMED = "serve_rejected_malformed"
SERVE_EVICTED = "serve_evicted"
SERVE_BATCHES = "serve_batches"
SERVE_NOOP_TICKS = "serve_noop_ticks"
SERVE_VOTES_DISPATCHED = "serve_votes_dispatched"
SERVE_DECISIONS = "serve_decisions"
#: gauges
SERVE_QUEUE_DEPTH = "serve_queue_depth"
SERVE_BATCH_FILL = "serve_batch_fill"
SERVE_INFLIGHT = "serve_inflight"
SERVE_E2E_LATENCY_S = "serve_e2e_latency_s"
SERVE_ADMIT_RATE = "serve_admit_rate_per_sec_window"
SERVE_DISPATCH_RATE = "serve_dispatch_rate_per_sec_window"
#: verified-vote dedup layer (ISSUE 5, serve/cache.py): admission
#: cache hits/misses (counters; hits + misses == admitted on a
#: cache-enabled service), LRU evictions (counter, reconciled from the
#: cache at settle), resident bytes (gauge), the WINDOWED hit-rate
#: gauge (via Metrics.interval_rate — a lifetime rate would bury a
#: traffic-pattern change), and votes dispatched on the verify-free
#: unsigned entries (counter)
SERVE_CACHE_HITS = "serve_cache_hits"
SERVE_CACHE_MISSES = "serve_cache_misses"
SERVE_CACHE_EVICTIONS = "serve_cache_evictions"
SERVE_CACHE_BYTES = "serve_cache_bytes"                  # gauge
SERVE_CACHE_HIT_RATE = "serve_cache_hit_rate_window"     # gauge
SERVE_PREVERIFIED_DISPATCHED = "serve_preverified_votes_dispatched"
#: BLS aggregate lane (ISSUE 10, serve/bls_lane.py): pairing-cleared
#: classes, votes that fell back to per-share verification after a
#: failed class pairing, and shares the admission fold rejected for a
#: missing proof of possession (rogue-key defense) — counters; the
#: pairing wall-clock histogram name lives in utils/metrics.py
SERVE_BLS_AGG_CLASSES = "serve_bls_agg_classes"
SERVE_BLS_FALLBACK_VOTES = "serve_bls_fallback_votes"
SERVE_BLS_POP_MISSING = "bls_pop_missing"
#: threaded-host gauges (serve/threaded.py): defined in
#: utils/metrics.py, re-exported via the module import above


#: compile-event fan-out (ISSUE 8): ONE registry observer for the
#: whole process, forwarding first-dispatch compile recordings to a
#: WeakSet of flight recorders — dead recorders fall out on GC (no
#: discarded service is retained), and the registry's observer list
#: never grows past one entry however many services come and go
_COMPILE_RECORDERS = None          # weakref.WeakSet, created lazily


def _notify_compile(name: str, ms: float) -> None:
    for rec in list(_COMPILE_RECORDERS or ()):
        rec.event("compile", entry=name, ms=round(ms, 1))


def _watch_compiles(flightrec) -> None:
    global _COMPILE_RECORDERS
    if _COMPILE_RECORDERS is None:
        import weakref

        from agnes_tpu.device import registry as _registry

        _COMPILE_RECORDERS = weakref.WeakSet()
        _registry.on_compile(_notify_compile)
    _COMPILE_RECORDERS.add(flightrec)


class Decision(NamedTuple):
    """One newly latched instance decision, decoded for the consumer
    boundary (slot -> value id via the batcher's slot map)."""

    instance: int
    value_slot: int
    value_id: Optional[int]
    round: int


class VoteService:
    """Assembles and drives the serve plane (module docstring)."""

    def __init__(self, driver, batcher,
                 pubkeys: Optional[np.ndarray] = None, *,
                 capacity: Optional[int] = None,
                 instance_cap: Optional[int] = None,
                 overload_policy: str = REJECT_NEWEST,
                 target_votes: Optional[int] = None,
                 max_delay_s: float = 0.005,
                 ladder: Optional[ShapeLadder] = None,
                 window_predictor=None,
                 donate: bool = True,
                 dedup_cache=None,
                 bls_lane=None,
                 native_admission: bool = False,
                 native_shards: int = 1,
                 metrics: Optional[Metrics] = None,
                 tracer: Optional[Tracer] = None,
                 flightrec=None,
                 clock=time.monotonic):
        """`flightrec` (utils/flightrec.FlightRecorder) arms the
        always-on observability trail (ISSUE 8): tick open/close,
        rung chosen, rejects by cause, retrace trips and thread
        failures land in its bounded ring, and a Heartbeat over the
        same recorder leaves a crash-surviving NDJSON trail.  The
        recorder is also handed to the driver (dispatch events).

        `bls_lane` (serve/bls_lane.BlsLane) attaches the BLS
        aggregate-precommit lane (ISSUE 10): `submit_bls` folds BLS
        wire shares into per-class buckets, pump() closes classes
        size-or-deadline, the pipeline aggregates them on device
        (`bls_aggregate`), pairing-checks through the bls_ref oracle
        and dispatches cleared classes down the verify-free unsigned
        entries; a failed pairing falls back to per-share
        verification so a forged share can neither be counted nor
        suppress honest shares.  Works beside OR without Ed25519
        `pubkeys` (a BLS-only deployment passes pubkeys=None).

        `dedup_cache` enables the verified-vote dedup layer
        (ISSUE 5): pass a serve/cache.VerifiedCache (or True for a
        default-budget one).  Admission then digest-screens every
        admitted record, cache hits dispatch on the verify-free
        unsigned entries (split-rung dispatch), and settled clean
        verifies populate the cache.  Off (None) by default: dedup is
        a pure throughput optimization — decisions are bit-identical
        either way (tests/test_serve_pipeline.py) — and an unsigned
        deployment has nothing to dedup.  Requires `pubkeys`.

        `native_admission` (ISSUE 14) swaps the admission queue for
        its C++ twin (serve/native_admission.NativeAdmissionQueue):
        per-record parse/screen/fairness/digest work runs behind one
        GIL-releasing ctypes call per submit and per drain, the BLS
        class table's header screens go native too
        (`BlsClassTable.native_screen`), and the threaded host elides
        its Python admission lock around the internally-synchronized
        handle.  Default OFF, pure opt-in like `dedup_cache`: the
        native path is byte-compatible with the Python queue
        (identical reject taxonomy, cache hit/miss counts and
        dispatch streams — tests/test_native_admission.py), so
        flipping it changes throughput, never decisions.

        `native_shards` (ISSUE 20, requires `native_admission`) splits
        the native front-end into N admission shards — one C++ queue
        (and one mutex) per shard, instance-range partitioned like
        distributed/topology.HostPlan — behind one submit fan-in and a
        deterministic k-way merged drain, so producer threads landing
        on different instance ranges never contend.  Needs
        I % native_shards == 0 and capacity % native_shards == 0 (the
        per-shard capacity ceiling must be an integer).  On a
        native_admission service the drain ALSO densifies eligible
        batches straight to the device-build phase/lane arrays
        (zero-copy densify — serve_native_densify_wall_s /
        serve_native_phase_builds measure it); both are throughput
        knobs, never decision changes."""
        I, V = driver.I, driver.V
        if dedup_cache is not None and dedup_cache is not False:
            from agnes_tpu.serve.cache import VerifiedCache

            if dedup_cache is True:
                dedup_cache = VerifiedCache()
            if pubkeys is None:
                raise ValueError(
                    "dedup_cache needs a signed deployment (pubkeys): "
                    "unsigned services never verify, so there is "
                    "nothing to dedup")
        else:
            dedup_cache = None
        self.cache = dedup_cache
        self.bls = bls_lane
        if ladder is None:
            if getattr(driver, "mesh", None) is not None:
                # dense dispatch mode: the compile shape is fixed by
                # the deployment; plan the budget against the
                # PER-DEVICE slice (tentpole: mesh serving)
                ladder = ShapeLadder.plan_dense(
                    I, V, local_shape=driver._local_shape())
            else:
                ladder = ShapeLadder.plan(I, V)
        if bls_lane is not None and not ladder.bls_rungs:
            # the aggregation MSM needs its own warmed rung set
            ladder = ladder.with_bls(V)
        self.metrics = metrics or Metrics()
        self.flightrec = flightrec
        # default queue: two full both-classes ticks — enough to
        # absorb a burst while one tick is in flight, small enough
        # that overload surfaces as rejects, not as unbounded memory
        capacity = capacity if capacity is not None else 4 * I * V
        self.native_admission = bool(native_admission)
        self.native_shards = int(native_shards)
        if self.native_shards < 1:
            raise ValueError(
                f"native_shards must be >= 1: {native_shards}")
        if self.native_shards > 1 and not self.native_admission:
            raise ValueError(
                "native_shards > 1 requires native_admission=True "
                "(sharding is a property of the C++ front-end)")
        qkw = {}
        if self.native_admission:
            if self.native_shards > 1:
                from agnes_tpu.serve.native_admission import (
                    NativeAdmissionShards,
                )

                queue_cls = NativeAdmissionShards
                qkw["n_shards"] = self.native_shards
            else:
                from agnes_tpu.serve.native_admission import (
                    NativeAdmissionQueue,
                )

                queue_cls = NativeAdmissionQueue
        else:
            queue_cls = AdmissionQueue
        # ONE construction site: the queues are byte-compatible twins,
        # so a config kwarg can never apply to one and not the others
        self.queue = queue_cls(
            I, capacity, instance_cap=instance_cap,
            policy=overload_policy, cache=self.cache,
            bls_table=(bls_lane.table if bls_lane is not None
                       else None),
            clock=clock, **qkw)
        # per-shard depth gauge names, precomputed (submit is the hot
        # path — no per-submit string building)
        self._shard_depth_names = [
            SERVE_NATIVE_SHARD_DEPTH_PREFIX + str(s)
            for s in range(self.native_shards)] \
            if self.native_shards > 1 else []
        if self.native_admission:
            # ISSUE 14 observability: wall of the GIL-releasing
            # drain-and-densify span, into the shared registry
            self.queue.drain_hist = self.metrics.histogram(
                SERVE_NATIVE_DRAIN_WALL_S)
            if bls_lane is not None:
                # the class table's header screens go native too
                bls_lane.table.native_screen = True
        # serve latency histograms (ISSUE 8): admission wait recorded
        # by the queue at drain; close age + submit->decision here;
        # dispatch/settle walls inside the pipeline — one registry
        self.queue.wait_hist = self.metrics.histogram(SERVE_ADMIT_WAIT_S)
        self._h_close_age = self.metrics.histogram(
            SERVE_BATCH_CLOSE_AGE_S)
        self._h_e2e = self.metrics.histogram(SERVE_E2E_DECISION_S)
        self.micro = MicroBatcher(self.queue, ladder,
                                  target_votes=target_votes,
                                  max_delay_s=max_delay_s, clock=clock)
        self.pipeline = ServePipeline(driver, batcher, pubkeys, ladder,
                                      window_predictor=window_predictor,
                                      donate=donate, cache=self.cache,
                                      bls_lane=bls_lane,
                                      tracer=tracer,
                                      metrics=self.metrics,
                                      flightrec=flightrec, clock=clock)
        if self.native_admission:
            # ISSUE 20 zero-copy densify: the native drain fills the
            # device-build phase/lane arrays against the pipeline's
            # predicted window (None hook result = plain drain; the
            # pipeline re-validates at stage time either way)
            self.queue.phase_state = self.pipeline.native_phase_state
            self.queue.densify_hist = self.metrics.histogram(
                SERVE_NATIVE_DENSIFY_WALL_S)
        if bls_lane is not None:
            bls_lane.bind(driver, metrics=self.metrics, ladder=ladder)
        self.driver = driver
        if flightrec is not None and \
                getattr(driver, "flightrec", None) is None:
            driver.flightrec = flightrec      # dispatch/retrace events
        if flightrec is not None:
            # first-dispatch compile walls are flight events too: the
            # heartbeat trail dates an unexpected mid-serve compile
            # (one process-wide observer + a recorder WeakSet — see
            # _watch_compiles; no duplicate events, no retention)
            _watch_compiles(flightrec)
        self.batcher = batcher
        self.tracer = tracer
        self._clock = clock
        self._reported = np.zeros(I, bool)
        self._draining = False

    # -- ingress -------------------------------------------------------------

    def submit(self, wire_bytes) -> AdmitResult:
        """Admit wire records (rejected records are counted + dropped;
        a draining service rejects everything — fail closed)."""
        if self._draining:
            from agnes_tpu.bridge.native_ingest import REC_SIZE

            n = len(wire_bytes) // REC_SIZE
            tail = 1 if len(wire_bytes) % REC_SIZE else 0
            # keep the submitted == admitted + rejected invariant on
            # this path too (and classify the truncated tail honestly)
            self.metrics.count(SERVE_SUBMITTED, n + tail)
            self.metrics.count(SERVE_REJECTED_OVERFLOW, n)
            self.metrics.count(SERVE_REJECTED_MALFORMED, tail)
            return AdmitResult(0, n, 0, tail, 0)
        if self.tracer is not None:
            with self.tracer.span("serve.submit"):
                # flow START for the tick these records will ride: the
                # pipeline's next staged build (an approximation under
                # concurrency — reading tick_seq unlocked is benign,
                # the arrow still lands on the right lifecycle for the
                # alternating submit/pump protocol the trace shows)
                self.tracer.flow("tick",
                                 self.pipeline.tick_seq + 1, "s")
                res = self.queue.submit(wire_bytes)
        else:
            res = self.queue.submit(wire_bytes)
        m = self.metrics
        m.count(SERVE_SUBMITTED, res.accepted + res.rejected)
        m.count(SERVE_ADMITTED, res.accepted)
        m.count(SERVE_REJECTED_OVERFLOW, res.rejected_overflow)
        m.count(SERVE_REJECTED_FAIRNESS, res.rejected_fairness)
        m.count(SERVE_REJECTED_MALFORMED, res.rejected_malformed)
        m.count(SERVE_EVICTED, res.evicted)
        if self.cache is not None and res.accepted:
            # hits + misses == admitted, per record, by construction
            # (the queue looks up exactly the admitted set)
            m.count(SERVE_CACHE_HITS, res.pre_verified)
            m.count(SERVE_CACHE_MISSES, res.accepted - res.pre_verified)
        if self.flightrec is not None and res.rejected:
            self.flightrec.event(
                "reject", overflow=res.rejected_overflow,
                fairness=res.rejected_fairness,
                malformed=res.rejected_malformed)
        depth = self.queue.depth
        if self.native_admission:
            # ISSUE 14: the native screens' reject taxonomy and the
            # native-inbox depth, mirrored beside the shared serve
            # counters so a native-vs-Python A/B reads off one scrape
            # (counter writes only when something was rejected — this
            # is the per-submit hot path)
            if res.rejected:
                m.count(SERVE_NATIVE_REJECTS_OVERFLOW,
                        res.rejected_overflow)
                m.count(SERVE_NATIVE_REJECTS_FAIRNESS,
                        res.rejected_fairness)
                m.count(SERVE_NATIVE_REJECTS_MALFORMED,
                        res.rejected_malformed)
            m.gauge(SERVE_NATIVE_INBOX_DEPTH, depth)
            for s, name in enumerate(self._shard_depth_names):
                # ISSUE 20: per-shard resident depth — a skewed
                # instance mix shows up here long before the aggregate
                # ceiling does
                m.gauge(name, self.queue.shard_depth(s))
        m.gauge(SERVE_QUEUE_DEPTH, depth)
        return res

    def submit_bls(self, wire_bytes) -> AdmitResult:
        """Admit packed BLS wire shares (serve/bls_lane wire ABI)
        into the class-bucketing lane; same fail-closed semantics as
        submit (a draining service rejects everything)."""
        if self.bls is None:
            raise ValueError("submit_bls on a service without a "
                             "bls_lane")
        from agnes_tpu.serve.bls_lane import BLS_REC_SIZE

        if self._draining:
            n = len(wire_bytes) // BLS_REC_SIZE
            tail = 1 if len(wire_bytes) % BLS_REC_SIZE else 0
            self.metrics.count(SERVE_SUBMITTED, n + tail)
            self.metrics.count(SERVE_REJECTED_OVERFLOW, n)
            self.metrics.count(SERVE_REJECTED_MALFORMED, tail)
            return AdmitResult(0, n, 0, tail, 0)
        res = self.queue.submit_bls(wire_bytes)
        m = self.metrics
        m.count(SERVE_SUBMITTED, res.accepted + res.rejected)
        m.count(SERVE_ADMITTED, res.accepted)
        m.count(SERVE_REJECTED_OVERFLOW, res.rejected_overflow)
        m.count(SERVE_REJECTED_FAIRNESS, res.rejected_fairness)
        m.count(SERVE_REJECTED_MALFORMED, res.rejected_malformed)
        # the rogue-key reject is its own well-known number: a fleet
        # suddenly dropping shares for missing PoPs is a registry
        # problem, not load
        m.gauge(SERVE_BLS_POP_MISSING,
                self.bls.table.counters["bls_pop_missing"])
        if self.flightrec is not None and res.rejected:
            self.flightrec.event(
                "reject", overflow=res.rejected_overflow,
                fairness=res.rejected_fairness,
                malformed=res.rejected_malformed, bls=True)
        return res

    def _mirror_bls_metrics(self) -> None:
        """Reconcile the lane's counters into the shared registry —
        called from every path that clears classes (pump ticks AND
        the drain flush), so scrapes/heartbeats/drain reports never
        under-report against the lane's own snapshot."""
        if self.bls is None:
            return
        c = self.bls.counters
        for name, key in ((SERVE_BLS_AGG_CLASSES, "agg_classes"),
                          (SERVE_BLS_FALLBACK_VOTES,
                           "fallback_votes")):
            delta = c[key] - self.metrics.counters.get(name, 0)
            if delta > 0:
                self.metrics.count(name, delta)

    # -- the event-loop tick -------------------------------------------------

    def pump(self, now: Optional[float] = None) -> dict:
        """One service tick: maybe close a micro-batch (size-or-
        deadline), dispatch the staged batch, densify the closed one.
        Never fetches from the device (collection happens in
        poll_decisions/drain).  Returns a small status dict.

        Split into `_close_batch` (admission/micro-batcher state — the
        part a threaded host guards with its admission lock) and
        `_pump_batch` (pipeline + device dispatch — guarded by the
        device lock), so ThreadedVoteService can hold the admission
        lock ONLY across the microseconds-of-numpy close, never across
        an XLA dispatch: that is what keeps `submit` wait-free
        relative to in-flight device work (serve/threaded.py)."""
        return self._pump_batch(self._close_batch(now))

    def _close_batch(self, now: Optional[float] = None):
        """Size-or-deadline micro-batch close (admission side only)."""
        return self.micro.poll(now)

    def _pump_batch(self, batch) -> dict:
        """Pipeline half of a tick: dispatch staged, densify `batch`
        (and any size-or-deadline-closed BLS classes — polled HERE,
        under the same lock domain as the pipeline, so the threaded
        host's split pump keeps working unchanged)."""
        n_batch = len(batch) if batch is not None else 0
        if n_batch:
            # oldest-record age at close (size- OR deadline-closed):
            # the batching delay component of end-to-end latency
            self._h_close_age.record(self._clock() - batch.t_first,
                                     n_batch)
        bls_classes = self.bls.poll() if self.bls is not None else None
        dispatched, staged = self.pipeline.pump(batch, bls_classes)
        self._mirror_bls_metrics()
        m = self.metrics
        if n_batch:
            m.count(SERVE_BATCHES)
            m.gauge(SERVE_BATCH_FILL, self.micro.fill(n_batch))
        if dispatched:
            m.count(SERVE_VOTES_DISPATCHED, dispatched)
        if batch is not None and not staged:
            m.count(SERVE_NOOP_TICKS)
        m.gauge(SERVE_QUEUE_DEPTH, self.queue.depth)
        m.gauge(SERVE_INFLIGHT, len(self.pipeline._inflight))
        return {"batch_votes": n_batch, "dispatched": dispatched,
                "staged": staged}

    # -- egress --------------------------------------------------------------

    def _settle(self) -> None:
        """Collect deferred device work + update latency/rate gauges."""
        done = self.pipeline.settle()
        if done:
            now = self._clock()
            # worst case end-to-end: oldest admitted record of the
            # settled batches to now (admission -> decision visible)
            self.metrics.gauge(SERVE_E2E_LATENCY_S,
                               now - min(b.t_first for b in done))
            # ... and the DISTRIBUTION (ISSUE 8): per settled batch,
            # oldest-record submit -> decisions visible, weighted by
            # the batch's votes — the p50/p99 the drain report and
            # bench verdicts carry
            for b in done:
                self._h_e2e.record(now - b.t_first, b.n_votes)
        self.metrics.gauge(SERVE_INFLIGHT, 0)
        if self.native_admission:
            m = self.metrics
            # ISSUE 20: adopted native phase builds into the registry
            # (delta-reconciled — settle is the one sync point)
            delta = (self.pipeline.native_phase_builds
                     - m.counters.get(SERVE_NATIVE_PHASE_BUILDS, 0))
            if delta > 0:
                m.count(SERVE_NATIVE_PHASE_BUILDS, delta)
            if self.native_shards > 1:
                # shard-summed reject taxonomy under the shard names,
                # so a shards-vs-single A/B reads off one scrape
                c = self.queue.counters
                for cause in ("overflow", "fairness", "malformed"):
                    name = SERVE_NATIVE_SHARD_REJECTS_PREFIX + cause
                    delta = (c["rejected_" + cause]
                             - m.counters.get(name, 0))
                    if delta > 0:
                        m.count(name, delta)
        self.metrics.gauge(SERVE_ADMIT_RATE,
                           self.metrics.interval_rate(SERVE_ADMITTED))
        self.metrics.gauge(
            SERVE_DISPATCH_RATE,
            self.metrics.interval_rate(SERVE_VOTES_DISPATCHED))
        if self.cache is not None:
            m = self.metrics
            # evictions happen inside the cache (insert-side): carry
            # the delta into the registry so scrapes see one source
            delta = (self.cache.counters["evicted"]
                     - m.counters.get(SERVE_CACHE_EVICTIONS, 0))
            if delta > 0:
                m.count(SERVE_CACHE_EVICTIONS, delta)
            delta = (self.pipeline.preverified_votes
                     - m.counters.get(SERVE_PREVERIFIED_DISPATCHED, 0))
            if delta > 0:
                m.count(SERVE_PREVERIFIED_DISPATCHED, delta)
            m.gauge(SERVE_CACHE_BYTES, self.cache.bytes)
            # WINDOWED hit rate: both interval windows span the same
            # stretch, so the per-second rates divide into a fraction
            rh = m.interval_rate(SERVE_CACHE_HITS)
            rm = m.interval_rate(SERVE_CACHE_MISSES)
            m.gauge(SERVE_CACHE_HIT_RATE,
                    rh / (rh + rm) if rh + rm > 0 else 0.0)
            # decided heights can never reach a verify lane again:
            # their entries are dead weight (poll-cadence prune)
            self.cache.prune_decided(self.batcher.heights)

    def poll_decisions(self) -> List[Decision]:
        """Newly latched first-decisions since the last poll (under
        advance_height the driver latches each instance's FIRST
        decision; decisions_total in the drain report counts all).
        This is the host<->device sync point."""
        self._settle()
        st = self.driver.stats
        new = st.decided & ~self._reported
        out: List[Decision] = []
        for i in np.nonzero(new)[0]:
            slot = int(st.decision_value[i])
            # the driver latches each instance's FIRST decision, and
            # sync_device rebuilt the slot map the moment that
            # instance's height advanced — decode via the snapshot the
            # pipeline took at that first advance (the live table is
            # a LATER height's interning); fall through to the live
            # table only when no advance ever happened
            snap = self.pipeline.first_advance_decode.get(int(i))
            if snap is not None and slot in snap:
                value_id = snap[slot]
            else:
                value_id = self.batcher.decode_slot(int(i), slot)
            out.append(Decision(
                instance=int(i), value_slot=slot, value_id=value_id,
                round=int(st.decision_round[i])))
        self._reported |= new
        if out:
            self.metrics.count(SERVE_DECISIONS, len(out))
        return out

    # -- shutdown ------------------------------------------------------------

    def drain(self) -> dict:
        """Graceful shutdown: stop admitting, push everything queued
        and staged through the device, re-enter held future-round
        votes whose window has arrived (ONE device-synced pass —
        still-future votes are reported, not spun on), settle, and
        return the final report."""
        self._draining = True
        # 1. flush the admission queue through the pipeline
        while self.queue.depth > 0:
            self.pipeline.pump(self.micro.flush())
        self.pipeline.pump(None)           # dispatch the last staged
        # 2. re-enter held future-round votes against the REAL device
        #    window (forces the sync fetch; we are shutting down),
        #    then build + dispatch them through the pipeline's own
        #    stages so the report/metrics/latency accounting sees them
        #    — stage() runs the same split-rung path as live ticks, so
        #    flushed PRE-VERIFIED votes ride the verify-free unsigned
        #    entries instead of paying a signed-rung dispatch at
        #    shutdown (the ISSUE 5 drain fix)
        if self.bls is not None:
            # flush every open class through the lane (aggregate +
            # pairing + dispatch), before held-vote re-entry
            open_cls = self.bls.flush()
            if open_cls:
                self.pipeline.pump(None, open_cls)
                self.pipeline.pump(None)
            self._mirror_bls_metrics()
        self.pipeline.window_predictor = None
        held_before = self.batcher.held_votes
        if held_before:
            self.driver.collect()
            self.pipeline._sync_window()       # re-enters held votes
            if self.pipeline.stage(None, sync=False):
                self.pipeline.dispatch_staged()
        # 3. settle everything and report.  Dispatches made on the
        # drain path above went around pump()'s counting — reconcile
        # the dispatched-votes counter against the pipeline's total so
        # the final snapshot (and its windowed rate) is complete.
        delta = (self.pipeline.dispatched_votes
                 - self.metrics.counters.get(SERVE_VOTES_DISPATCHED, 0))
        if delta > 0:
            self.metrics.count(SERVE_VOTES_DISPATCHED, delta)
        decisions = self.poll_decisions()
        # per-entry first-dispatch compile walls into the registry's
        # gauges so the final snapshot (and any scrape) carries them
        from agnes_tpu.device import registry as _registry

        for name, ms in _registry.compile_ms().items():
            self.metrics.gauge(COMPILE_MS_PREFIX + name, round(ms, 1))
        # WINDOWED final snapshot (the ISSUE 8 satellite): the shared
        # interval window, so a long-lived service's drain rates
        # describe the last window instead of a decayed lifetime
        # average; serve_rates_window is carved from the SAME snapshot
        # so the two can never disagree (bench's own verdict records
        # keep their lifetime semantics — they never read this)
        snap = self.metrics.snapshot(window=True)
        st = self.driver.stats
        report = {
            "decisions_total": st.decisions_total,
            "decided_instances": int(st.decided.sum()),
            "final_decisions": decisions,
            "held_flushed": held_before - self.batcher.held_votes,
            "held_remaining": self.batcher.held_votes,
            "late_quorums": self.batcher.drain_host_events(),
            "rejected_signature_device":
                self.driver.rejected_signature_device,
            "queue": dict(self.queue.counters),
            "noop_ticks": self.pipeline.noop_ticks,
            "host_fallback_builds": self.pipeline.host_fallback_builds,
            "offladder_builds": self.pipeline.offladder_builds,
            "dispatched_batches": self.pipeline.dispatched_batches,
            "dispatched_votes": self.pipeline.dispatched_votes,
            "preverified_builds": self.pipeline.preverified_builds,
            "preverified_votes": self.pipeline.preverified_votes,
            "serve_cache": (self.cache.snapshot()
                            if self.cache is not None else None),
            # ISSUE 14: the native front-end's counters + resident
            # depth (None = Python admission) — the drain report's
            # mirror of the serve_native_* registry names
            "native_admission": (self.queue.native_snapshot()
                                 if self.native_admission else None),
            # ISSUE 20: builds adopted straight from a native phase
            # drain (0 on a Python-admission or fetch-mode service)
            "native_phase_builds": self.pipeline.native_phase_builds,
            "bls": (self.bls.snapshot() if self.bls is not None
                    else None),
            "bls_votes": self.pipeline.bls_votes,
            "metrics": snap,
            "serve_rates_window": {k: v for k, v in snap.items()
                                   if k.endswith("_per_sec")},
            # the latency distributions, spelled out (p50/p90/p99/max/
            # count per histogram) — what a hardware round's artifact
            # quotes as its tail-latency numbers
            "latency": {name: h.snapshot()
                        for name, h in self.metrics.hists.items()},
        }
        return report

    # -- export surface (ISSUE 8) --------------------------------------------

    def start_metrics_server(self, port: int = 0,
                             host: str = "127.0.0.1"):
        """Attach a `/metrics` Prometheus endpoint over this service's
        registry (utils/metrics_http.MetricsServer, jax-free stdlib).
        Returns the started server; `server.port` is the bound port
        (port 0 = ephemeral), `server.stop()` shuts it down.  The
        scrape includes the per-entry `compile_ms_<entry>` gauges."""
        from agnes_tpu.device import registry as _registry
        from agnes_tpu.utils.metrics_http import MetricsServer

        server = MetricsServer(
            self.metrics, host=host, port=port,
            extra_sources=(_registry.compile_gauges,))
        server.start()
        return server
