"""Deadline-aware micro-batching over a precomputed shape ladder.

The stage between admission (serve/queue.py) and densify/dispatch
(serve/pipeline.py).  Two jobs:

* **When to close a batch** (`MicroBatcher`): size-OR-deadline.  A
  batch closes the moment the queue holds `target_votes` records
  (throughput mode: full device batches), or when the OLDEST queued
  record has waited `max_delay_s` (latency mode: a trickle of votes
  still reaches the chip promptly).  The classic latency/throughput
  dial of every serving system, applied to consensus votes.

* **What shapes may reach the device** (`ShapeLadder`): the fused
  signed step's compile key includes the lane count, and with the
  persistent compile cache deliberately off (utils/compile_cache.py)
  a fresh shape costs MINUTES of XLA trace on the tier-1 box — a
  request-dependent shape is a self-inflicted DoS.  The ladder is the
  full set of lane shapes the serve plane will ever emit: powers of
  two from `min_rung` to a top rung planned against the device HBM
  budget (utils/budget.plan_lane_verify — a rung whose resident
  verify operands cannot fit is dropped).  The pipeline passes
  `min_rung` as VoteBatcher's lane_floor, so every emitted batch pads
  onto a rung: at most len(rungs) compiles for the service's entire
  lifetime, each precompilable at startup (`ServePipeline.warmup`).

The batch-fill ratio (votes / rung) is the honest utilization number:
padding lanes do real device work, so sustained fill << 1 means the
deadline is too tight or the target too big for the offered load.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Tuple

from agnes_tpu.serve.queue import AdmissionQueue, WireColumns
from agnes_tpu.utils.budget import (
    BudgetError,
    plan_dense_verify,
    plan_lane_verify,
)


def _ceil_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class ShapeLadder:
    """Ascending power-of-two lane counts the serve plane may emit.

    `bls_rungs` is the MIXED-MODE extension (ISSUE 10): the BLS
    aggregate lane pads each vote class's signer count onto one of
    these rungs before the `bls_aggregate` MSM dispatch, so the
    aggregation kernel — like the fused verify — compiles a
    logarithmic number of shapes for the service's lifetime and every
    one of them is warmable (ServePipeline.warmup covers them when a
    lane is attached).  Empty = no BLS lane planned.

    `bls_class_rungs` (ISSUE 13) paces the DEVICE PAIRING the same
    way: `bls_pairing_product` clears all deadline-closed classes in
    one dispatch whose compile key is the padded CLASS count — the
    lane pads onto the smallest fitting rung (chunking above the top
    one), so the pairing entry too compiles a fixed, warmable shape
    set.  Empty = host-pairing lane (the PR 10 path)."""

    rungs: Tuple[int, ...]
    bls_rungs: Tuple[int, ...] = ()
    bls_class_rungs: Tuple[int, ...] = ()

    def __post_init__(self):
        if not self.rungs:
            raise ValueError("empty shape ladder")
        for r in self.rungs + self.bls_rungs + self.bls_class_rungs:
            if r & (r - 1) or r <= 0:
                raise ValueError(f"rungs must be powers of two: {r}")
        if list(self.rungs) != sorted(set(self.rungs)):
            raise ValueError(f"rungs must be ascending: {self.rungs}")
        if list(self.bls_rungs) != sorted(set(self.bls_rungs)):
            raise ValueError(
                f"bls_rungs must be ascending: {self.bls_rungs}")
        if list(self.bls_class_rungs) != sorted(set(
                self.bls_class_rungs)):
            raise ValueError(f"bls_class_rungs must be ascending: "
                             f"{self.bls_class_rungs}")

    @property
    def min_rung(self) -> int:
        return self.rungs[0]

    @property
    def max_rung(self) -> int:
        return self.rungs[-1]

    def rung_for(self, n_votes: int) -> int:
        """Smallest rung holding `n_votes` lanes (the shape a batch of
        that size pads onto).  n_votes above the top rung is a caller
        bug — the micro-batcher's target is clamped to max_rung."""
        for r in self.rungs:
            if n_votes <= r:
                return r
        raise ValueError(
            f"{n_votes} votes exceed the ladder's top rung "
            f"{self.max_rung} (close smaller batches)")

    @classmethod
    def plan(cls, n_instances: int, n_validators: int,
             max_votes: Optional[int] = None, min_rung: int = 256,
             hbm_bytes: Optional[int] = None) -> "ShapeLadder":
        """Build the ladder for a deployment shape: rungs from
        `min_rung` up to the smaller of `max_votes` (default: one full
        both-classes tick, 2*I*V — the largest honest micro-batch) and
        the largest rung whose resident verify operands fit the HBM
        budget at all (chunked execution handles the workspace; a rung
        plan_lane_verify cannot even size is dropped)."""
        top_want = 2 * n_instances * n_validators
        if max_votes is not None:
            top_want = min(top_want, int(max_votes))
        min_rung = _ceil_pow2(min_rung)
        top = max(_ceil_pow2(top_want), min_rung)
        rungs = []
        r = min_rung
        while r <= top:
            try:
                plan_lane_verify(r, hbm_bytes=hbm_bytes)
            except BudgetError:
                break          # larger rungs only get worse
            rungs.append(r)
            r <<= 1
        if not rungs:
            raise BudgetError(
                f"no ladder rung >= {min_rung} fits the HBM budget "
                f"(shape {n_instances}x{n_validators})")
        return cls(rungs=tuple(rungs))

    @classmethod
    def plan_dense(cls, n_instances: int, n_validators: int,
                   local_shape: Optional[Tuple[int, int]] = None,
                   n_classes: int = 2,
                   max_votes: Optional[int] = None, min_rung: int = 256,
                   hbm_bytes: Optional[int] = None,
                   n_hosts: int = 1,
                   n_live: Optional[int] = None) -> "ShapeLadder":
        """Ladder for the DENSE dispatch mode (mesh serving): the
        dense fused signed step's compile key is (P, I, V) — fixed by
        the deployment, NOT by the batch size — so rungs here only
        pace how many votes each micro-batch carries (host densify
        cost and latency), never which shapes compile.  What the
        budget must validate instead is the deployment itself: the
        dense verify of `n_classes` signed vote classes over the
        PER-DEVICE `local_shape` (utils/budget.mesh_local_shape) has
        to fit the per-device HBM slice at least chunked —
        plan_dense_verify raises BudgetError when it cannot, failing
        the service at plan time rather than live at first dispatch.

        `n_hosts` (ISSUE 15): on a pod, `n_instances` may be the
        GLOBAL deployment figure while each host's admission only
        ever feeds its own slice — rungs sized to the global tick
        would pace micro-batches n_hosts times too big (a per-host
        batch can never fill them, so every close is deadline-forced
        and fill sits at 1/n_hosts forever).  The top rung is planned
        against the instance slice ONE host actually owns.

        `n_live` (ISSUE 17): an elastic pod's LIVE membership can be
        smaller than the process count; the slice a surviving owner
        serves is n_instances / n_live, so both the even-split check
        and the top rung plan against the live count — re-planning at
        an epoch boundary with the new membership size is how a
        shrunken pod re-paces instead of under-claiming (the ladder is
        cheap frozen data; ElasticShard rebuilds it per epoch)."""
        nh = max(1, int(n_hosts))
        live = int(n_live) if n_live is not None else nh
        if not 1 <= live <= nh:
            raise ValueError(
                f"live membership {live} outside [1, {nh}]")
        if n_instances % nh:
            raise ValueError(
                f"{n_instances} instances do not shard evenly over "
                f"{n_hosts} hosts")
        if n_instances % live:
            raise ValueError(
                f"{n_instances} instances do not repartition evenly "
                f"over {live} live host(s)")
        li, lv = (local_shape if local_shape is not None
                  else (n_instances // live, n_validators))
        plan_dense_verify(n_classes, li, lv, hbm_bytes=hbm_bytes)
        top_want = 2 * (n_instances // live) * n_validators
        if max_votes is not None:
            top_want = min(top_want, int(max_votes))
        min_rung = _ceil_pow2(min_rung)
        top = max(_ceil_pow2(top_want), min_rung)
        rungs = []
        r = min_rung
        while r <= top:
            rungs.append(r)
            r <<= 1
        return cls(rungs=tuple(rungs))

    def with_bls(self, n_validators: int, min_rung: int = 16,
                 class_rungs: Tuple[int, ...] = (1, 4)
                 ) -> "ShapeLadder":
        """Extend with BLS aggregation rungs (powers of two from
        `min_rung` up to the validator count — a class can never hold
        more signers than validators) AND the device-pairing CLASS
        rungs (`class_rungs`, default one small + one burst shape:
        every pairing compile is a warmup-time cost, so the set stays
        tiny; closes above the top rung chunk)."""
        min_rung = _ceil_pow2(min_rung)
        top = max(_ceil_pow2(n_validators), min_rung)
        rungs = []
        r = min_rung
        while r <= top:
            rungs.append(r)
            r <<= 1
        return dataclasses.replace(
            self, bls_rungs=tuple(rungs),
            bls_class_rungs=tuple(sorted(set(class_rungs))))

    def bls_rung_for(self, n_signers: int) -> int:
        """Smallest BLS rung holding `n_signers` aggregation lanes."""
        for r in self.bls_rungs:
            if n_signers <= r:
                return r
        raise ValueError(
            f"{n_signers} signers exceed the top BLS rung "
            f"{self.bls_rungs[-1] if self.bls_rungs else 0}")

    def bls_class_rung_for(self, n_classes: int) -> int:
        """Smallest pairing class rung holding `n_classes`; callers
        CHUNK above the top rung (unlike lane shapes, a class batch
        splits freely across sequential pairing dispatches)."""
        for r in self.bls_class_rungs:
            if n_classes <= r:
                return r
        if not self.bls_class_rungs:
            raise ValueError("no bls_class_rungs planned")
        return self.bls_class_rungs[-1]

    def describe(self) -> str:
        out = ("shape ladder: " + " ".join(str(r) for r in self.rungs)
               + " lanes")
        if self.bls_rungs:
            out += (" | bls: "
                    + " ".join(str(r) for r in self.bls_rungs))
        if self.bls_class_rungs:
            out += (" | bls classes: "
                    + " ".join(str(r) for r in self.bls_class_rungs))
        return out


class MicroBatcher:
    """Size-or-deadline batch closer over an AdmissionQueue."""

    def __init__(self, queue: AdmissionQueue, ladder: ShapeLadder,
                 target_votes: Optional[int] = None,
                 max_delay_s: float = 0.005,
                 clock=time.monotonic):
        self.queue = queue
        self.ladder = ladder
        self.target = min(int(target_votes) if target_votes is not None
                          else ladder.max_rung, ladder.max_rung)
        if self.target <= 0:
            raise ValueError(f"target_votes must be positive: "
                             f"{target_votes}")
        if max_delay_s < 0:
            raise ValueError(f"max_delay_s must be >= 0: {max_delay_s}")
        self.max_delay_s = float(max_delay_s)
        self._clock = clock
        self.batches_closed = 0
        self.closed_by_size = 0
        self.closed_by_deadline = 0

    def poll(self, now: Optional[float] = None) -> Optional[WireColumns]:
        """Close and return a batch iff the size target is met or the
        oldest queued record's deadline has passed; else None (the
        caller's pump loop just comes back)."""
        if self.queue.depth <= 0:
            return None
        by_size = self.queue.depth >= self.target
        if not by_size:
            oldest = self.queue.oldest_ts
            now = self._clock() if now is None else now
            if oldest is None or now - oldest < self.max_delay_s:
                return None
        batch = self.queue.drain(self.target)
        if batch is not None:
            self.batches_closed += 1
            if by_size:
                self.closed_by_size += 1
            else:
                self.closed_by_deadline += 1
        return batch

    def flush(self) -> Optional[WireColumns]:
        """Close a batch regardless of size/deadline (drain path)."""
        batch = self.queue.drain(self.target)
        if batch is not None:
            self.batches_closed += 1
        return batch

    def fill(self, n_votes: int) -> float:
        """Batch-fill ratio: votes over the rung they pad onto."""
        return n_votes / self.ladder.rung_for(min(n_votes,
                                                  self.ladder.max_rung))
