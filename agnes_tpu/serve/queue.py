"""Bounded admission queue over packed 96-byte wire records.

The first stage of the streaming vote service plane (serve/): a
continuous network front pushes raw wire bytes in, the micro-batcher
drains FIFO column batches out.  Everything here is unauthenticated —
signature verification happens far downstream (fused on device) — so
this queue is the system's overload valve and its first DoS surface:

* **Bounded, fail-closed.**  `capacity` records, hard.  The default
  overload policy is **reject-newest** (a full queue refuses new work
  and tells the caller, who can push back on the network peer);
  `drop_oldest` is available for deployments that prefer freshest-
  vote semantics (old consensus votes age out of relevance anyway),
  at the cost of silently shedding admitted work.
* **Per-instance fairness.**  One flooded consensus instance must not
  starve the other 9,999: an instance may never occupy more than
  `instance_cap` queue slots, whatever the total depth.  Records
  beyond the cap are rejected at admission (counted, never queued) —
  the host-side twin of the device plane's value-flood containment
  (bench.bench_value_flood).
* **Cheap screens only.**  Records are parsed (vectorized
  `unpack_wire_votes`) and screened just enough to account fairness:
  truncated tails and out-of-range instance ids are rejected as
  malformed here; every deeper screen (validator range, vote type,
  height staleness, signatures) stays with VoteBatcher/device, where
  it already exists — duplicating it would create two drifting
  truths.
* **Verified-vote dedup** (ISSUE 5): with a `VerifiedCache`
  (serve/cache.py) attached, every ADMITTED record's 96-byte wire
  bytes are SHA-256'd and looked up — a hit (identical bytes already
  device-verified in a settled dispatch) marks the record
  *pre-verified*, and the pipeline's split-rung dispatch later routes
  it to the verify-free unsigned step entries.  The lookup happens
  here, at admission, because this is the last place the raw record
  bytes exist (everything downstream carries columns); misses carry
  their digest along so the pipeline can insert them once their
  device verify lands.  Rejected records (overflow/fairness/
  malformed) are never hashed or looked up.

Pure numpy + stdlib; no jax anywhere on the admission path.
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from dataclasses import dataclass
from typing import List, NamedTuple, Optional

import numpy as np

from agnes_tpu.bridge.native_ingest import REC_SIZE, unpack_wire_votes

#: overload policies
REJECT_NEWEST = "reject_newest"
DROP_OLDEST = "drop_oldest"


class AdmitResult(NamedTuple):
    """Per-submit admission verdict (counts of records)."""

    accepted: int
    rejected_overflow: int
    rejected_fairness: int
    rejected_malformed: int
    evicted: int               # drop_oldest only: old records shed
    pre_verified: int = 0      # dedup-cache hits among `accepted`

    @property
    def rejected(self) -> int:
        return (self.rejected_overflow + self.rejected_fairness
                + self.rejected_malformed)


class WireColumns(NamedTuple):
    """A drained FIFO batch as VoteBatcher.add_arrays columns."""

    instance: np.ndarray       # [N] int64
    validator: np.ndarray      # [N] int64
    height: np.ndarray         # [N] int64
    round_: np.ndarray         # [N] int64
    typ: np.ndarray            # [N] int64
    value: np.ndarray          # [N] int64 (-1 = nil)
    signatures: np.ndarray     # [N, 64] uint8
    verified: np.ndarray       # [N] bool — dedup-cache pre-verified
    digest: Optional[np.ndarray]  # [N, 32] uint8 wire SHA-256s (cache
    #                               attached) or None (dedup off)
    t_first: float             # earliest admission instant in the batch
    #: zero-copy densify (ISSUE 20): a NativePhases bundle when the
    #: native drain already built the phase/lane arrays for this batch
    #: (None on the Python queue and on any native bail-to-Python
    #: drain).  The columns above are ALWAYS filled regardless — the
    #: pipeline's adopt path still logs them as evidence, and a window
    #: mismatch at stage time falls back to add_arrays on them.
    native_phases: Optional["NativePhases"] = None

    def __len__(self) -> int:
        return len(self.instance)


class PhaseBuildState(NamedTuple):
    """Inputs a native phase drain needs to replay the batcher's
    device-verify build: the WINDOW the batch will be staged against
    (predicted — the drain runs before ServePipeline._sync_window, so
    the pipeline hands the post-sync window it will install and
    validates the prediction at stage time) plus the value-table and
    ladder geometry.  Built by ServePipeline.native_phase_state()."""

    heights: np.ndarray        # [I] int64 window heights (predicted)
    base_round: np.ndarray     # [I] int64 window base rounds
    window: int                # rounds per window (W)
    slot_lut: np.ndarray       # [I, S] int64 dense SlotMap export
    pubkeys: np.ndarray        # [V, 32] uint8 validator keys
    n_validators: int
    lane_floor: int            # ladder.min_rung (pad floor)
    max_votes: int             # ladder.max_rung (defer threshold)
    phase_offset: int          # entry-phase slot count (1)


@dataclass
class NativePhases:
    """The padded device-build arrays a native phase drain produced —
    exactly VoteBatcher.build_phases_device's output layout, filled by
    core/native/admission_phases.cpp into numpy buffers the pipeline
    wraps WITHOUT per-record Python work (jnp.asarray per ARRAY, not
    per record).  `heights`/`base_round` echo the PhaseBuildState the
    build assumed so the adopter can validate the window prediction."""

    n_phases: int
    n_lanes: int               # real lanes (== batch length)
    n_pad: int                 # padded lane rung
    round_: int                # the single round of the batch
    typ: np.ndarray            # [n_phases] int64 phase vote types
    counts: np.ndarray         # [n_phases] int64 votes per phase
    slots: np.ndarray          # [n_phases, I, V] int32 slot planes
    mask: np.ndarray           # [n_phases, I, V] bool
    pub: np.ndarray            # [n_pad, 32] int32 widened pubkeys
    sig: np.ndarray            # [n_pad, 64] int32 widened signatures
    blocks: np.ndarray         # [n_pad, 1, 32] uint32 SHA-512 words
    phase_idx: np.ndarray      # [n_pad] int32
    inst: np.ndarray           # [n_pad] int32
    val: np.ndarray            # [n_pad] int32
    real: np.ndarray           # [n_pad] bool pad mask
    lane_rows: np.ndarray      # [n_lanes] int64 lane -> drained-row
    #                            permutation (the phase-grouped cat
    #                            order; the adopter's last_build_keys
    #                            and log gathers)
    heights: np.ndarray        # [I] int64 window the build assumed
    base_round: np.ndarray     # [I] int64


def _record_digests(wire_bytes, idx: np.ndarray) -> np.ndarray:
    """[len(idx), 32] uint8 SHA-256 of the selected whole 96-byte wire
    records — the dedup cache key.  Hashed from the RAW bytes (not a
    canonical re-pack), so the key means exactly "these bytes were
    verified"; SHA-256 of 96 bytes is ~1us/record, admission-cheap."""
    mv = memoryview(bytes(wire_bytes))
    out = np.empty((len(idx), 32), np.uint8)
    for j, k in enumerate(idx):
        k = int(k)
        out[j] = np.frombuffer(
            hashlib.sha256(mv[k * REC_SIZE:(k + 1) * REC_SIZE]).digest(),
            np.uint8)
    return out


@dataclass
class _Chunk:
    """One admitted submit's (surviving) columns + admission time."""

    cols: tuple                # 8 arrays, WireColumns order sans
    #                            digest/t_first
    dig: Optional[np.ndarray]  # [N, 32] uint8 or None (dedup off)
    ts: float

    def __len__(self) -> int:
        return len(self.cols[0])

    def split(self, n: int):
        head = _Chunk(tuple(c[:n] for c in self.cols),
                      self.dig[:n] if self.dig is not None else None,
                      self.ts)
        tail = _Chunk(tuple(c[n:] for c in self.cols),
                      self.dig[n:] if self.dig is not None else None,
                      self.ts)
        return head, tail


def _cumcount(x: np.ndarray) -> np.ndarray:
    """[N] rank of each element within its value group, in arrival
    order (groupby-cumcount, vectorized)."""
    n = len(x)
    order = np.argsort(x, kind="stable")
    sx = x[order]
    new = np.ones(n, bool)
    new[1:] = sx[1:] != sx[:-1]
    starts = np.maximum.accumulate(np.where(new, np.arange(n), 0))
    out = np.empty(n, np.int64)
    out[order] = np.arange(n) - starts
    return out


class Inbox:
    """Socket-shaped thread-safe blob inbox for the threaded host
    (serve/threaded.py): network threads `put` raw wire-bytes blobs,
    the submit thread `get`s them and feeds the AdmissionQueue.

    This is the ONLY structure the caller-facing `submit` touches in
    the threaded host, and it shares no lock with anything device-
    side — a put is a bounded-deque append under a private mutex held
    for nanoseconds, so producers stay wait-free relative to in-flight
    XLA dispatch no matter what the pipeline is doing.  Bounded and
    fail-closed like the AdmissionQueue itself (a full inbox refuses
    the blob and counts it; unauthenticated bytes must never buffer
    unboundedly), but in BLOBS, not records: real record accounting —
    parse, fairness, overload policy — stays with AdmissionQueue,
    where it already exists."""

    def __init__(self, capacity: int = 1024):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive: {capacity}")
        self.capacity = int(capacity)
        self._q: collections.deque = collections.deque()
        self._mu = threading.Lock()
        self._not_empty = threading.Condition(self._mu)
        self.closed = False
        self.enqueued = 0
        self.dropped = 0

    @property
    def depth(self) -> int:
        return len(self._q)          # len(deque) is atomic

    def put(self, blob) -> bool:
        """Enqueue a wire blob; False (and counted) when full or
        closed."""
        with self._mu:
            # schedcheck: atomic (closed-check + append: the PR 3
            # close/put TOCTOU window — checking closed outside _mu
            # lets a blob land after the final drain flush)
            if self.closed or len(self._q) >= self.capacity:
                self.dropped += 1
                return False
            self._q.append(blob)
            self.enqueued += 1
            self._not_empty.notify()
        return True

    def close(self) -> None:
        """Atomically stop accepting blobs: every `put` that returned
        True happened-before this call and its blob is still in the
        deque (drainable); every later `put` returns False.  This is
        what lets the threaded host's drain close the submit/stop
        race loss-free — a stop FLAG checked outside the inbox mutex
        cannot order a racing put against the final flush."""
        with self._mu:
            # schedcheck: atomic (close orders every racing put
            # against the final flush — the other half of the PR 3
            # window)
            self.closed = True
            self._not_empty.notify_all()

    def get(self, timeout: Optional[float] = None):
        """Dequeue the oldest blob, waiting up to `timeout` seconds
        (None = block until a blob arrives or the inbox closes);
        returns None on timeout/empty-after-close.  `wait_for`
        absorbs spurious condition wakeups, so the block-forever
        contract of timeout=None actually holds."""
        with self._not_empty:
            # schedcheck: atomic (predicate + popleft under one hold:
            # a wakeup-then-reacquire that re-checks nothing would
            # double-pop against a racing get)
            self._not_empty.wait_for(lambda: self._q or self.closed,
                                     timeout)
            return self._q.popleft() if self._q else None


class AdmissionQueue:
    """FIFO of admitted wire records, bounded with per-instance
    fairness (module docstring).  `submit` admits, `drain` hands FIFO
    column batches to the micro-batcher.

    This class is the SPECIFICATION of the admission plane: the C++
    front-end (serve/native_admission.NativeAdmissionQueue, ISSUE 14)
    is a byte-compatible twin — identical reject taxonomy, counters,
    digest bytes and drained columns — differential-tested against it
    (tests/test_native_admission.py) and against the admission model
    checker's corpus."""

    #: NOT internally synchronized: the threaded host guards this
    #: queue with its admission lock.  The native twin overrides this
    #: (its handle holds its own mutex), which is what lets the host
    #: elide the Python lock around the GIL-releasing C calls.
    native = False

    def __init__(self, n_instances: int, capacity: int,
                 instance_cap: Optional[int] = None,
                 policy: str = REJECT_NEWEST,
                 cache=None,
                 bls_table=None,
                 clock=time.monotonic):
        """`cache` is an optional serve/cache.VerifiedCache: admitted
        records are digest-looked-up and hits marked pre-verified
        (module docstring); None = dedup off, zero added work.

        `bls_table` (serve/bls_lane.BlsClassTable) enables the
        CLASS-BUCKETING mode (ISSUE 10): `submit_bls` folds BLS wire
        shares into per-(instance, height, round, typ, value)
        aggregate classes instead of the record queue — the table is
        bounded and fail-closed on its own (max open classes, one
        share per signer, PoP-verified signers only), and its rejects
        surface through this queue's counters so the admission plane
        reports through one place."""
        if capacity <= 0:
            raise ValueError(f"capacity must be positive: {capacity}")
        if policy not in (REJECT_NEWEST, DROP_OLDEST):
            raise ValueError(f"unknown overload policy: {policy}")
        self.I = int(n_instances)
        self.capacity = int(capacity)
        # default: 2x the fair share — bursty-but-honest instances
        # breathe, a single flooder still can't take the whole queue
        # (for I >= 2 the cap is strictly below capacity)
        self.instance_cap = (int(instance_cap) if instance_cap is not None
                             else max(1, (2 * self.capacity) // self.I))
        if self.instance_cap <= 0:
            raise ValueError(
                f"instance_cap must be positive: {instance_cap}")
        self.policy = policy
        self.cache = cache
        self.bls_table = bls_table
        # optional utils.metrics.Histogram: submit -> drain wait per
        # drained chunk (ISSUE 8 `serve_admit_wait_s`; VoteService
        # wires the shared registry's histogram in).  A plain
        # duck-typed `.record(seconds, n)` sink — this module stays
        # numpy+stdlib either way.
        self.wait_hist = None
        self._clock = clock
        # deque: a realistic frontend submits a few records per peer
        # per call, so one micro-batch spans hundreds of chunks — a
        # list's pop(0) would make every drain quadratic
        self._chunks: collections.deque = collections.deque()
        self.depth = 0
        self._inst_counts = np.zeros(self.I, np.int64)
        self.counters = {
            "submitted": 0, "admitted": 0, "rejected_overflow": 0,
            "rejected_fairness": 0, "rejected_malformed": 0,
            "evicted": 0, "drained": 0,
        }

    @property
    def oldest_ts(self) -> Optional[float]:
        """Admission instant of the oldest queued record (None when
        empty) — the micro-batcher's deadline anchor."""
        return self._chunks[0].ts if self._chunks else None

    def instance_depth(self, instance: int) -> int:
        return int(self._inst_counts[instance])

    # -- admission -----------------------------------------------------------

    def submit(self, wire_bytes) -> AdmitResult:
        """Admit packed wire records (the serve plane's single entry
        from the network).  Returns per-record counts; rejected
        records are COUNTED and DROPPED, never queued."""
        raw_len = len(wire_bytes)
        n_whole = raw_len // REC_SIZE
        malformed = 1 if raw_len % REC_SIZE else 0   # truncated tail
        cols = unpack_wire_votes(wire_bytes)
        inst = cols[0]
        self.counters["submitted"] += n_whole + malformed
        if n_whole == 0:
            self.counters["rejected_malformed"] += malformed
            return AdmitResult(0, 0, 0, malformed, 0)

        # instance-range screen: fairness accounting needs a valid id
        # (everything else is screened downstream by the batcher)
        ok = (inst >= 0) & (inst < self.I)
        malformed += int(n_whole - ok.sum())
        keep = np.nonzero(ok)[0]

        # fairness: occupancy-so-far + rank-within-this-submit < cap
        inst_k = inst[keep]
        occ = self._inst_counts[inst_k] + _cumcount(inst_k)
        fair = occ < self.instance_cap
        rejected_fairness = int(len(keep) - fair.sum())
        keep = keep[fair]

        # capacity
        rejected_overflow = 0
        evicted = 0
        room = self.capacity - self.depth
        if len(keep) > room:
            if self.policy == REJECT_NEWEST:
                rejected_overflow = len(keep) - max(room, 0)
                keep = keep[:max(room, 0)]
            else:                                     # DROP_OLDEST
                # shed oldest queued records; if the submit alone
                # exceeds capacity, keep its newest `capacity` records
                if len(keep) > self.capacity:
                    rejected_overflow = len(keep) - self.capacity
                    keep = keep[len(keep) - self.capacity:]
                evicted = min(self.depth,
                              len(keep) - (self.capacity - self.depth))
                if evicted > 0:
                    self._pop(evicted, count_drained=False)
                    self.counters["evicted"] += evicted

        accepted = len(keep)
        pre_verified = 0
        if accepted:
            sub = tuple(c[keep] for c in cols)
            # dedup lookup LAST, on exactly the admitted records:
            # rejects never pay the hash, and cache hit/miss counters
            # add up to `admitted` (the accounting the metrics assert)
            if self.cache is not None:
                dig = _record_digests(wire_bytes, keep)
                ver = self.cache.lookup(dig)
                pre_verified = int(ver.sum())
            else:
                dig = None
                ver = np.zeros(accepted, bool)
            self._chunks.append(_Chunk(sub + (ver,), dig, self._clock()))
            self.depth += accepted
            np.add.at(self._inst_counts, sub[0], 1)

        self.counters["admitted"] += accepted
        self.counters["rejected_overflow"] += rejected_overflow
        self.counters["rejected_fairness"] += rejected_fairness
        self.counters["rejected_malformed"] += malformed
        return AdmitResult(accepted, rejected_overflow,
                           rejected_fairness, malformed, evicted,
                           pre_verified)

    def submit_bls(self, wire_bytes) -> AdmitResult:
        """Class-bucketing admission (ISSUE 10): fold packed BLS wire
        records (serve/bls_lane wire ABI) into the aggregate-class
        table.  Folded shares count as accepted; every reject cause
        maps onto this queue's counter taxonomy (PoP-missing, unknown
        validator, duplicate and quarantined-forger shares count as
        FAIRNESS rejects — they are per-identity admission refusals —
        class-table overflow as OVERFLOW, bad points/truncation as
        MALFORMED)."""
        if self.bls_table is None:
            raise ValueError(
                "submit_bls on a queue without a bls_table (pass "
                "BlsClassTable/BlsLane at construction)")
        res = self.bls_table.fold(wire_bytes)
        fairness = (res["pop_missing"] + res["unknown_validator"]
                    + res["duplicate"] + res["quarantined"])
        self.counters["submitted"] += (res["folded"] + fairness
                                       + res["malformed"]
                                       + res["overflow"])
        self.counters["admitted"] += res["folded"]
        self.counters["rejected_overflow"] += res["overflow"]
        self.counters["rejected_fairness"] += fairness
        self.counters["rejected_malformed"] += res["malformed"]
        return AdmitResult(res["folded"], res["overflow"], fairness,
                           res["malformed"], 0)

    # -- state-space surface (analysis/admission_mc.py) ----------------------

    def mc_clone(self) -> "AdmissionQueue":
        """O(live state) copy for state-space branching (the serve-
        plane admission model checker).  `_Chunk` objects are never
        mutated after construction (drain REPLACES the head chunk,
        split builds new ones), so the clone shares them; `cache` is
        shared too — the model re-points it at its own cache clone.
        Subclasses adding mutable state must extend this."""
        q = type(self).__new__(type(self))
        q.I = self.I
        q.capacity = self.capacity
        q.instance_cap = self.instance_cap
        q.policy = self.policy
        q.cache = self.cache
        q.bls_table = self.bls_table
        q._clock = self._clock
        q.wait_hist = self.wait_hist
        q._chunks = collections.deque(self._chunks)
        q.depth = self.depth
        q._inst_counts = self._inst_counts.copy()
        q.counters = dict(self.counters)
        return q

    def mc_canonical(self) -> tuple:
        """Canonical int-only form of the queued content — the model
        checker's dedup-key contribution.  Rows in FIFO order;
        signature bytes are excluded (the model's records are
        unsigned; identity lives in the value column).  Counters are
        deliberately NOT part of the canonical form: they are monotone
        history (two states with identical content but different
        reject histories behave identically), and including them would
        block every state merge the explorer depends on."""
        rows = []
        for c in self._chunks:
            inst, val, hts, rnd, typ, value = c.cols[:6]
            ver = c.cols[7]
            for j in range(len(c)):
                rows.append((int(inst[j]), int(val[j]), int(hts[j]),
                             int(rnd[j]), int(typ[j]), int(value[j]),
                             int(ver[j])))
        return (tuple(rows), self.depth)

    # -- drain ---------------------------------------------------------------

    def _pop(self, n: int, count_drained: bool = True) -> List[_Chunk]:
        """Remove the n oldest records (n <= depth), updating counts."""
        out: List[_Chunk] = []
        left = n
        while left > 0:
            c = self._chunks[0]
            if len(c) <= left:
                self._chunks.popleft()
                out.append(c)
                left -= len(c)
            else:
                head, tail = c.split(left)
                self._chunks[0] = tail
                out.append(head)
                left = 0
        for c in out:
            np.subtract.at(self._inst_counts, c.cols[0], 1)
        self.depth -= n
        if count_drained:
            self.counters["drained"] += n
        return out

    def drain(self, max_records: Optional[int] = None
              ) -> Optional[WireColumns]:
        """Pop up to `max_records` oldest records as one column batch
        (None when empty).  FIFO across submits; a submit may split
        across drains."""
        if self.depth == 0:
            return None
        n = self.depth if max_records is None else min(self.depth,
                                                       int(max_records))
        if n <= 0:
            # a zero/negative cap pops nothing — None, same as empty
            # (NOT _pop(n): a negative n would corrupt depth/counters)
            return None
        chunks = self._pop(n)
        if self.wait_hist is not None:
            # submit -> drain wait, chunk granularity: every record of
            # a chunk was admitted in one submit, so (now - chunk.ts)
            # weighted by the chunk's records IS the per-record wait
            now = self._clock()
            for c in chunks:
                self.wait_hist.record(now - c.ts, len(c))
        t_first = min(c.ts for c in chunks)
        if len(chunks) == 1:
            cols = chunks[0].cols
            dig = chunks[0].dig
        else:
            cols = tuple(np.concatenate([c.cols[k] for c in chunks])
                         for k in range(8))
            # cache attachment is per queue, so digests are all-or-none
            # across chunks
            dig = (np.concatenate([c.dig for c in chunks])
                   if chunks[0].dig is not None else None)
        return WireColumns(*cols, digest=dig, t_first=t_first)
