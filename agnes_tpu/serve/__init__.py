"""The streaming vote service plane (ISSUE 2 tentpole).

Everything before this package was an offline batch build: tests and
bench hand VoteBatcher a complete tick and drive the device by hand.
This package is the ONLINE path between a network frontend and the
device driver — the subsystem a "millions of users" deployment
actually runs:

  queue.py      bounded admission over packed 96-byte wire records;
                explicit backpressure (reject-newest default,
                drop-oldest optional) + per-instance fairness caps
  batcher.py    deadline-aware micro-batching (close on size OR
                deadline) over a precomputed ShapeLadder, so no
                request-dependent shape ever triggers a fresh jit
                compile
  pipeline.py   double-buffered densify/dispatch: host densifies
                batch k+1 (VoteBatcher.add_arrays — the offline
                densify stage, reused) while the device runs the
                async fused signed step on batch k with donated
                state/tally buffers
  service.py    the façade: submit / pump / poll_decisions / drain,
                wired into utils.metrics (windowed serve rates,
                queue-depth / batch-fill / latency gauges) and
                utils.tracing spans

Single-device (packed-lane fused path).  Mesh serving — sharding the
admission plane with the dense lane layout — is a ROADMAP item.
"""

from agnes_tpu.serve.batcher import MicroBatcher, ShapeLadder  # noqa: F401
from agnes_tpu.serve.pipeline import ServePipeline  # noqa: F401
from agnes_tpu.serve.queue import (  # noqa: F401
    AdmissionQueue,
    AdmitResult,
    DROP_OLDEST,
    REJECT_NEWEST,
    WireColumns,
)
from agnes_tpu.serve.service import Decision, VoteService  # noqa: F401
