"""The streaming vote service plane (ISSUE 2 tentpole).

Everything before this package was an offline batch build: tests and
bench hand VoteBatcher a complete tick and drive the device by hand.
This package is the ONLINE path between a network frontend and the
device driver — the subsystem a "millions of users" deployment
actually runs:

  queue.py      bounded admission over packed 96-byte wire records;
                explicit backpressure (reject-newest default,
                drop-oldest optional) + per-instance fairness caps
  batcher.py    deadline-aware micro-batching (close on size OR
                deadline) over a precomputed ShapeLadder, so no
                request-dependent shape ever triggers a fresh jit
                compile
  pipeline.py   double-buffered densify/dispatch: host densifies
                batch k+1 (VoteBatcher.add_arrays — the offline
                densify stage, reused) while the device runs the
                async fused signed step on batch k with donated
                state/tally buffers
  service.py    the façade: submit / pump / poll_decisions / drain,
                wired into utils.metrics (windowed serve rates,
                queue-depth / batch-fill / latency gauges) and
                utils.tracing spans

Dispatch is layout-polymorphic (ISSUE 3 tentpole): single-device
drivers run the packed-lane fused path; drivers built on a MESH
densify through `VoteBatcher.build_phases_device_dense` and dispatch
the shard_map-sharded dense fused signed step (donated buffers, zero
added collectives — parallel/sharded.py).  threaded.py adds the host
event loop above VoteService: a submit thread draining a socket-shaped
Inbox into admission while a dispatch thread pumps ticks, with submit
wait-free relative to in-flight XLA dispatch.

cache.py (ISSUE 5 tentpole) adds the verified-vote dedup layer:
gossip delivers each vote O(peers) times, and without it every
re-delivery pays a device Ed25519 lane.  A bounded thread-safe
`VerifiedCache` keyed by the wire record's SHA-256 is consulted at
admission; hits are admitted pre-verified and the pipeline's
SPLIT-RUNG dispatch routes them to the verify-free unsigned step
entries while fresh traffic keeps the signed fused path (at a smaller
rung).  Entries are inserted only after a dispatch's device verify
settles with zero rejected lanes, so forged duplicates can never
pre-populate the cache.

native_admission.py (ISSUE 14 tentpole) adds the C++ admission
front-end: the per-record hot path — wire parse, malformed/fairness/
capacity screens, dedup-cache SHA-256, densify-to-columns — moves
behind one GIL-releasing ctypes call per submit and per drain
(core/native/admission.cpp), byte-compatible with AdmissionQueue and
opt-in via `VoteService(native_admission=True)`; the threaded host
elides its admission lock around the internally-synchronized handle.

bls_lane.py (ISSUE 10 tentpole) adds the BLS aggregate-precommit
lane: same-class precommits fold into per-(height, round, value)
AggregateClass buckets at admission, aggregate on device
(crypto/bls_jax stake-weighted MSMs on one padded ladder rung), and
clear with ONE pairing-product per class — the whole class then rides
the verify-free unsigned entries like a dedup hit.  Rogue-key defense
is an admission-time proof-of-possession registry; a failed pairing
falls back to per-share verification so a forged share can never
poison or suppress honest votes (README "BLS aggregate lane").
"""

from agnes_tpu.serve.batcher import MicroBatcher, ShapeLadder  # noqa: F401
from agnes_tpu.serve.cache import VerifiedCache  # noqa: F401
# the C++ admission front-end's wrapper (ISSUE 14) is jax-free at
# import like the queue (building the shared library happens on first
# use), so it rides the eager admission-side imports
from agnes_tpu.serve.native_admission import (  # noqa: F401
    NativeAdmissionQueue,
)
from agnes_tpu.serve.queue import (  # noqa: F401
    AdmissionQueue,
    AdmitResult,
    DROP_OLDEST,
    Inbox,
    REJECT_NEWEST,
    WireColumns,
)

# The dispatch-side members (pipeline/service/threaded) import jax at
# module top; the admission side (queue/batcher/cache) is pure
# numpy/stdlib and is what the jax-free pre-test gate consumes
# (analysis/admission_mc.py, the harness/__init__ lazy-DeviceDriver
# pattern) — resolve them on first attribute access instead of at
# package import.
from agnes_tpu.utils.lazy import make_lazy_getattr  # noqa: E402

__getattr__ = make_lazy_getattr(__name__, {
    # bls_lane's MODULE is jax-free, but BlsKeyRegistry's constructor
    # packs device pubkey limbs through the jax kernels — keep the
    # whole lane behind the lazy seam with the other dispatch members
    "BlsClassTable": ("agnes_tpu.serve.bls_lane", "BlsClassTable"),
    "BlsKeyRegistry": ("agnes_tpu.serve.bls_lane", "BlsKeyRegistry"),
    "BlsLane": ("agnes_tpu.serve.bls_lane", "BlsLane"),
    "ServePipeline": ("agnes_tpu.serve.pipeline", "ServePipeline"),
    "Decision": ("agnes_tpu.serve.service", "Decision"),
    "VoteService": ("agnes_tpu.serve.service", "VoteService"),
    "ThreadedVoteService": ("agnes_tpu.serve.threaded",
                            "ThreadedVoteService"),
    "threaded_service": ("agnes_tpu.serve.threaded",
                         "threaded_service"),
}, globals())
