"""Pod topology: instance-range sharding math, the decision-gather
wire codec, and heartbeat-age liveness — the jax-free half of the
multi-host serve subsystem (ISSUE 15).

Three small pieces, each independently testable without a backend:

* **HostPlan** — the one source of truth for which instances a host
  owns.  The pod mesh puts hosts on the OUTER instance axis (the
  slice axis of parallel/mesh.py — DCN, zero collectives), so every
  host's instance range is a CONTIGUOUS block and local<->global id
  translation is an offset.  Per-host serve fronts screen on this
  range; the dense sharded step's data layout follows it by
  construction (parallel/sharded.py shards the instance dimension
  slice-major).
* **Decision-gather codec** — per-tick decision exchange rides the
  EXISTING 96-byte wire ABI (bridge/native_ingest.pack_wire_votes):
  one wire record per newly latched decision (instance = GLOBAL id,
  validator = reporting host, height/round = the decision's, value =
  the decided value id), framed into a FIXED-size buffer so an
  allgather can carry it (every host contributes the same shape; the
  frame header counts the real records, the tail is zero padding).
  Reusing the vote ABI means one parser, one byte layout, and a
  decision frame is replayable/loggable with the exact tooling the
  vote plane already has.
* **StragglerMonitor** — per-host last-evidence ages (fed by
  completed gathers, peer heartbeat files, or anything else that
  proves a host recently made progress) with two thresholds: a
  STRAGGLER warning age and a DEAD age.  `check()` raises
  DeadHostError past the dead threshold — the fail-closed hook
  HostShard.drain uses to stop waiting on pod collectives that can
  never complete (a dead host never joins another allgather).

Pure numpy + stdlib; no jax anywhere (conftest _CHEAP eligible).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from agnes_tpu.bridge.native_ingest import (
    REC_SIZE,
    pack_wire_votes,
    unpack_wire_votes,
)

#: decision frame header: record count (u32) + reporting host (u32)
FRAME_HEADER = 8


class PodConfigError(ValueError):
    """A pod shape the sharding math cannot satisfy."""


class DeadHostError(RuntimeError):
    """A host's liveness evidence is older than the dead threshold —
    pod collectives would hang on it; drain must fail closed."""


@dataclasses.dataclass(frozen=True)
class HostPlan:
    """Which contiguous instance block each of `n_hosts` hosts owns.

    `n_instances` must divide evenly: the sharded step requires the
    instance dimension to split exactly over the mesh's data axes,
    and a ragged host would need padding instances whose state the
    differential would then have to exclude — reject at plan time
    instead (the deployment picks I as a multiple of the pod)."""

    n_hosts: int
    n_instances: int

    def __post_init__(self):
        if self.n_hosts <= 0:
            raise PodConfigError(f"n_hosts must be >= 1: {self.n_hosts}")
        if self.n_instances <= 0:
            raise PodConfigError(
                f"n_instances must be >= 1: {self.n_instances}")
        if self.n_instances % self.n_hosts:
            raise PodConfigError(
                f"{self.n_instances} instances do not shard evenly "
                f"over {self.n_hosts} hosts (the sharded step's data "
                f"axes need an exact split — pad the deployment or "
                f"change the pod size)")

    @property
    def local_instances(self) -> int:
        return self.n_instances // self.n_hosts

    def instance_range(self, host: int) -> Tuple[int, int]:
        """[lo, hi) global instance ids host `host` owns."""
        self._check_host(host)
        lo = host * self.local_instances
        return lo, lo + self.local_instances

    def owner_of(self, instance: int) -> int:
        """The host owning global instance id `instance`."""
        if not 0 <= instance < self.n_instances:
            raise PodConfigError(
                f"instance {instance} outside [0, {self.n_instances})")
        return instance // self.local_instances

    def to_local(self, host: int, instance) -> np.ndarray:
        """Global instance ids -> host-local ids (vectorized; caller
        guarantees ownership — see `owned_mask`)."""
        lo, _ = self.instance_range(host)
        return np.asarray(instance, np.int64) - lo

    def to_global(self, host: int, instance) -> np.ndarray:
        """Host-local instance ids -> global ids (vectorized)."""
        lo, _ = self.instance_range(host)
        return np.asarray(instance, np.int64) + lo

    def owned_mask(self, host: int, instance) -> np.ndarray:
        """[N] bool: which global ids fall in `host`'s range."""
        lo, hi = self.instance_range(host)
        inst = np.asarray(instance, np.int64)
        return (inst >= lo) & (inst < hi)

    def _check_host(self, host: int) -> None:
        if not 0 <= host < self.n_hosts:
            raise PodConfigError(
                f"host {host} outside [0, {self.n_hosts})")


def wire_instance_ids(rec: np.ndarray) -> np.ndarray:
    """[N] int64 instance ids of a [N, REC_SIZE] record view —
    the one shared extraction the front door's screen and the rebase
    both use (instance is the first little-endian u32)."""
    n = len(rec)
    return rec[:, 0:4].copy().view(np.uint32).reshape(n) \
        .astype(np.int64)


def shift_instances_inplace(rec: np.ndarray, offset: int) -> None:
    """Shift every record's instance field by `offset` IN a writable
    [N, REC_SIZE] record array (one pass, no re-parse)."""
    n = len(rec)
    if n:
        inst = (wire_instance_ids(rec) + offset).astype(np.uint32)
        rec[:, 0:4] = inst[:, None].view(np.uint8).reshape(n, 4)


def rebase_wire_instances(wire_bytes, offset: int) -> bytes:
    """Shift every whole record's instance field by `offset` IN the
    raw 96-byte wire layout — the per-host front door rebases global
    gossip ids onto its local VoteService slice without an
    unpack/repack round trip.  A truncated tail is preserved
    untouched (the admission queue counts it malformed, exactly as it
    would have)."""
    buf = np.frombuffer(bytes(wire_bytes), np.uint8).copy()
    n = len(buf) // REC_SIZE
    if n:
        shift_instances_inplace(buf[:n * REC_SIZE].reshape(n,
                                                           REC_SIZE),
                                offset)
    return buf.tobytes()


# -- decision-gather codec ----------------------------------------------------

def frame_capacity_bytes(max_decisions: int) -> int:
    """Fixed per-host frame size for a gather carrying up to
    `max_decisions` records (a host can latch at most its local
    instance count of NEW first-decisions per tick)."""
    return FRAME_HEADER + int(max_decisions) * REC_SIZE


def pack_decision_frame(host: int, instances, values, rounds, heights,
                        max_decisions: int) -> np.ndarray:
    """[frame_capacity_bytes] uint8: header + one 96-byte wire record
    per decision + zero padding.  `instances` are GLOBAL ids; `values`
    the decided value ids (< 0 = nil — the wire codec's encoding);
    signatures ride as zeros (a decision report is not a vote — its
    authenticity comes from the pod transport, not a lane verify)."""
    inst = np.asarray(instances, np.int64)
    n = len(inst)
    if n > max_decisions:
        raise PodConfigError(
            f"{n} decisions exceed the frame capacity {max_decisions}")
    frame = np.zeros(frame_capacity_bytes(max_decisions), np.uint8)
    frame[0:4] = np.uint32(n).reshape(1).view(np.uint8)
    frame[4:8] = np.uint32(host).reshape(1).view(np.uint8)
    if n:
        wire = pack_wire_votes(
            inst, np.full(n, host, np.int64),
            np.asarray(heights, np.int64), np.asarray(rounds, np.int64),
            np.zeros(n, np.int64), np.asarray(values, np.int64))
        frame[FRAME_HEADER:FRAME_HEADER + n * REC_SIZE] = \
            np.frombuffer(wire, np.uint8)
    return frame


@dataclasses.dataclass(frozen=True)
class PodDecision:
    """One decision as gathered pod-wide (global instance id)."""

    instance: int
    host: int
    height: int
    round: int
    value_id: Optional[int]        # None = nil


def unpack_decision_frame(frame: np.ndarray) -> List[PodDecision]:
    """Inverse of pack_decision_frame (one host's frame)."""
    frame = np.asarray(frame, np.uint8)
    if len(frame) < FRAME_HEADER:
        raise PodConfigError(f"frame shorter than the header: "
                             f"{len(frame)} bytes")
    n = int(frame[0:4].view(np.uint32)[0])
    host = int(frame[4:8].view(np.uint32)[0])
    cap = (len(frame) - FRAME_HEADER) // REC_SIZE
    if n > cap:
        raise PodConfigError(
            f"frame claims {n} records but holds at most {cap}")
    if n == 0:
        return []
    raw = frame[FRAME_HEADER:FRAME_HEADER + n * REC_SIZE].tobytes()
    inst, val, hts, rnd, _typ, value, _sigs = unpack_wire_votes(raw)
    return [PodDecision(
        instance=int(inst[k]), host=int(val[k]), height=int(hts[k]),
        round=int(rnd[k]),
        value_id=(int(value[k]) if value[k] >= 0 else None))
        for k in range(n)]


def unpack_decision_frames(frames: np.ndarray) -> List[PodDecision]:
    """All hosts' gathered frames ([n_hosts, frame_bytes] — the
    allgather output) -> flat decision list, host-major order."""
    out: List[PodDecision] = []
    for row in np.asarray(frames, np.uint8):
        out.extend(unpack_decision_frame(row))
    return out


# -- liveness -----------------------------------------------------------------

class StragglerMonitor:
    """Per-host liveness from last-evidence ages (module docstring).

    Evidence is anything proving recent progress: `beat(host)` after a
    completed gather/barrier (an allgather completing IS an all-hosts
    liveness proof), or `observe_heartbeat_files` reading co-located
    heartbeat NDJSON trails (utils/flightrec.last_line_age_s).  The
    clock is injectable so the detection logic tests with stubbed
    time (the ISSUE 15 satellite).

    Recovery/readmission (ISSUE 17): a dead verdict is NOT permanent.
    Fresh evidence for a host whose age had crossed `dead_after_s`
    clears the verdict and counts a `readmissions` — the documented
    recovery path the elastic membership plane consumes: with a
    membership plane attached (`attach_membership`), a dead peer
    becomes a latched LEAVE intent (applied at the next epoch
    boundary) and `check()` degrades to the straggler report instead
    of raising, while resumed evidence latches the matching JOIN.
    WITHOUT a membership plane the historical fail-closed contract is
    untouched: `check()` still raises DeadHostError, because without
    a repartition protocol a dead peer really does hang the next
    collective."""

    def __init__(self, n_hosts: int, host: int,
                 dead_after_s: float = 30.0,
                 straggler_after_s: float = 5.0,
                 clock=time.monotonic):
        if dead_after_s <= straggler_after_s:
            raise PodConfigError(
                f"dead_after_s ({dead_after_s}) must exceed "
                f"straggler_after_s ({straggler_after_s})")
        self.n_hosts = int(n_hosts)
        self.host = int(host)
        self.dead_after_s = float(dead_after_s)
        self.straggler_after_s = float(straggler_after_s)
        self._clock = clock
        now = self._clock()
        self._last: Dict[int, float] = {h: now for h in
                                        range(self.n_hosts)}
        # ISSUE 17 recovery path (class docstring)
        self.membership = None         # optional MembershipEpoch
        self.readmissions = 0          # dead verdicts cleared by
        #                                fresh evidence
        self._reported_dead: set = set()

    def attach_membership(self, membership) -> None:
        """Attach the elastic membership plane: dead peers degrade to
        leave intents and resumed peers to join intents, instead of
        check() failing closed (class docstring)."""
        self.membership = membership

    def beat(self, host: Optional[int] = None,
             now: Optional[float] = None) -> None:
        """Record evidence for one host (None = ALL hosts — the
        completed-collective case: nobody missing, everybody live).
        Evidence for a host past the dead age is a RECOVERY: the
        verdict clears, `readmissions` counts it, and an attached
        membership plane latches the join intent."""
        now = self._clock() if now is None else now
        hosts = range(self.n_hosts) if host is None else (int(host),)
        for h in hosts:
            self._evidence(h, now, now)

    def _evidence(self, h: int, t: float, now: float) -> None:
        """Fold one liveness observation in (evidence instant `t`,
        judged at clock instant `now`) — the recovery detection lives
        here so every evidence source shares it."""
        if t <= self._last[h]:
            return
        if h != self.host and now - self._last[h] > self.dead_after_s:
            self.readmissions += 1
            self._reported_dead.discard(h)
            if self.membership is not None:
                self.membership.note_join(h)
        self._last[h] = t

    def observe_heartbeat_files(self, paths: Sequence[Optional[str]],
                                now: Optional[float] = None) -> None:
        """Fold peer heartbeat trails in: paths[h] is host h's NDJSON
        file (None/unreadable = no new evidence).  Ages come from the
        trail's last valid line — the same number a post-mortem reads
        (utils/flightrec.last_line_age_s, wall-clock based; mixed into
        the monotonic ledger as now - age)."""
        from agnes_tpu.utils.flightrec import last_line_age_s

        now = self._clock() if now is None else now
        for h, path in enumerate(paths):
            if h >= self.n_hosts or path is None:
                continue
            age = last_line_age_s(path)
            if age is not None:
                self._evidence(h, now - age, now)

    def ages(self, now: Optional[float] = None) -> Dict[int, float]:
        now = self._clock() if now is None else now
        return {h: now - t for h, t in self._last.items()}

    def stragglers(self, now: Optional[float] = None) -> List[int]:
        """Hosts past the straggler age but not yet dead (self
        excluded — a host is never its own straggler)."""
        return [h for h, age in self.ages(now).items()
                if h != self.host
                and self.straggler_after_s < age <= self.dead_after_s]

    def dead(self, now: Optional[float] = None) -> List[int]:
        return [h for h, age in self.ages(now).items()
                if h != self.host and age > self.dead_after_s]

    def check(self, now: Optional[float] = None) -> List[int]:
        """Raise DeadHostError when any peer is past the dead age;
        returns the (possibly empty) straggler list otherwise — the
        pre-collective gate: a dead peer means the next allgather
        would hang forever, so the caller drains fail-closed instead
        of joining it.

        With a membership plane attached the verdict DEGRADES instead
        (class docstring): each newly-dead peer latches a leave intent
        once and the straggler list is returned — the elastic pod
        keeps ticking, the boundary repartitions, and the peer's
        ranges degrade boundedly rather than the whole pod wedging."""
        gone = self.dead(now)
        if gone and self.membership is not None:
            for h in gone:
                if h not in self._reported_dead:
                    self._reported_dead.add(h)
                    self.membership.note_leave(h)
            return self.stragglers(now)
        if gone:
            ages = self.ages(now)
            raise DeadHostError(
                f"host(s) {gone} show no liveness evidence for "
                + ", ".join(f"{ages[h]:.1f}s" for h in gone)
                + f" (> dead_after_s={self.dead_after_s}); pod "
                f"collectives would hang — drain fail-closed")
        return self.stragglers(now)
