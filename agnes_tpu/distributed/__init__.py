"""Multi-host distributed serve: pod-scale drivers over
jax.distributed (ISSUE 15) + the elastic membership plane (ISSUE 17).

Layout:
  topology.py    jax-free sharding math, decision codec, liveness
  membership.py  jax-free repartition/re-lift/negotiation math +
                 the MembershipEpoch protocol
  pod.py         lockstep agree/barrier + byte-frame allgather
  driver.py      DistributedDriver (global-SPMD dispatch, local views)
  shard.py       HostShard (per-host serve front-end)
  elastic.py     ElasticShard (per-tick negotiation, join/leave)
  smoke.py       spawnable worker + pod spawner (CI / bench / tests)

Imports are LAZY for every jax-bearing member (the serve/__init__
pattern): the topology/membership layers, the admission path and the
CLIs stay importable with no backend.  (elastic.py itself imports
jax-free, but it pulls shard.py -> serve, so it stays lazy here.)
"""

from agnes_tpu.distributed.membership import (  # noqa: F401 (jax-free)
    MembershipEpoch,
    MembershipError,
    MembershipView,
    Repartition,
    TickSlot,
    merge_tick_plans,
    partition_ranges,
    relift_ranges,
    relift_tree,
    validate_partition,
)
from agnes_tpu.distributed.topology import (  # noqa: F401 (jax-free)
    DeadHostError,
    HostPlan,
    PodConfigError,
    PodDecision,
    StragglerMonitor,
    frame_capacity_bytes,
    pack_decision_frame,
    rebase_wire_instances,
    unpack_decision_frame,
    unpack_decision_frames,
)

_LAZY = {
    "PodCoordinator": ("agnes_tpu.distributed.pod", "PodCoordinator"),
    "PodDivergenceError": ("agnes_tpu.distributed.pod",
                           "PodDivergenceError"),
    "DistributedDriver": ("agnes_tpu.distributed.driver",
                          "DistributedDriver"),
    "initialize_pod": ("agnes_tpu.distributed.pod",
                       "initialize_pod"),
    "make_pod_mesh": ("agnes_tpu.distributed.driver", "make_pod_mesh"),
    "fetch_local_block": ("agnes_tpu.distributed.driver",
                          "fetch_local_block"),
    "HostShard": ("agnes_tpu.distributed.shard", "HostShard"),
    "ElasticShard": ("agnes_tpu.distributed.elastic", "ElasticShard"),
    "ElasticFrame": ("agnes_tpu.distributed.elastic", "ElasticFrame"),
    "pack_elastic_frame": ("agnes_tpu.distributed.elastic",
                           "pack_elastic_frame"),
    "unpack_elastic_frame": ("agnes_tpu.distributed.elastic",
                             "unpack_elastic_frame"),
    "spawn_pod": ("agnes_tpu.distributed.smoke", "spawn_pod"),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(entry[0]), entry[1])


__all__ = [
    "DeadHostError", "HostPlan", "PodConfigError", "PodDecision",
    "StragglerMonitor", "frame_capacity_bytes", "pack_decision_frame",
    "rebase_wire_instances", "unpack_decision_frame",
    "unpack_decision_frames",
    "MembershipEpoch", "MembershipError", "MembershipView",
    "Repartition", "TickSlot", "merge_tick_plans", "partition_ranges",
    "relift_ranges", "relift_tree", "validate_partition", *_LAZY,
]
