"""Elastic pod: per-tick shape negotiation + epoch-boundary host
join/leave over the static pod plane (ISSUE 17).

PR 15's pod serves only homogeneous, immortal hosts: a deadline-closed
P=2 batch on one host against a full P=3 batch on another is a
`PodDivergenceError`, and a dead peer fails every later drain closed.
This module layers the membership-and-negotiation control plane from
`membership.py` onto `HostShard` so BOTH become survivable:

* **Per-tick plan negotiation** (`ElasticShard.tick`): each host
  closes its micro-batch, stages builds WITHOUT dispatching, and
  exchanges its staged shape plan — (kind, P, rung, BLS class rung)
  tick slots — in the SAME fixed-size allgather frame that carries
  its newly latched decisions, membership intents and re-routed
  gossip.  The merged plan is the per-slot MAX
  (membership.merge_tick_plans); every host pads up to it
  (pipeline.pad_staged_to / stage_padding — empty phases and all-zero
  dense rows are state-machine no-ops) and only then dispatches, so
  `PodCoordinator.agree` sees IDENTICAL plans under honest
  heterogeneity and keeps its full strictness for statics.  Padding
  lands exclusively on shapes `ServePipeline.warmup` compiled —
  `warmup_covers` is checked BEFORE dispatch and the retrace sentinel
  would catch anything that slipped past it — so negotiation costs
  zero new compiles.
* **Epoch-boundary join/leave**: leave/join intents (explicit
  `announce_leave`/`announce_join`, or verdicts from the attached
  StragglerMonitor) latch mid-epoch and apply at boundaries
  (`tick(boundary=True)` — callers invoke it at height boundaries, a
  lockstep point by construction).  A departed host is sleepy churn
  at pod granularity: its PROCESS stays in the jax.distributed
  fabric dispatching pure padding (the global-SPMD mesh cannot
  shrink), while its instance ranges repartition onto the survivors.
  Held gossip routes by STATIC HOME — the host whose device block
  serves an instance — exactly the model checker's `_home_serving`
  predicate (analysis/membership_mc.py), so the implementation walks
  the proven path: the current epoch OWNER of a range holds records
  whose static home is departed (bounded by `reroute_capacity`;
  overflow is counted, dropped, and event-logged — bounded
  degradation, never a wedge); records whose home is alive are never
  held — the home's own front door serves them, and holding them
  here would only manufacture duplicates while burning reroute
  capacity.  Once the home is live again (its rejoin rides the
  prospective view of the readmission boundary's own frame) the
  holder re-routes the held bytes — global-id 96-byte wire records,
  instance fields intact — and the home, the ONE peer whose static
  screen absorbs them, replays them in height order and catches up.
  While the home stays away the holder simply keeps the records —
  even across its own departure, since a sleeping process still
  ticks — which is the lossless holder bookkeeping the checker's
  `_relift_held` models.  What still fails closed: a host dead to
  the FABRIC (not just the membership plane) still hangs jax
  collectives — the monitor without a membership plane attached
  keeps raising DeadHostError for exactly that reason.

The frame codec and negotiator below are jax-free (numpy + the
topology codec) so tests/test_elastic.py exercises them in-process;
only ElasticShard's serve plumbing touches jax, via HostShard.
"""

from __future__ import annotations

import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from agnes_tpu.bridge.native_ingest import REC_SIZE
from agnes_tpu.distributed.membership import (
    KIND_DENSE_SIGNED,
    KIND_NAMES,
    KIND_SIGNED,
    KIND_UNSIGNED,
    MembershipEpoch,
    MembershipError,
    Repartition,
    TickSlot,
    merge_tick_plans,
)
from agnes_tpu.distributed.shard import HostShard
from agnes_tpu.distributed.topology import (
    PodDecision,
    frame_capacity_bytes,
    pack_decision_frame,
    unpack_decision_frame,
    wire_instance_ids,
)

# -- the combined elastic frame ----------------------------------------------
#
# One fixed-size allgather row per host per tick (all-zero padding —
# every host packs the identical capacity, so the collective shape is
# static):
#
#   [0:28)  header, 7 LE u32: magic 'ELA1' | host | epoch |
#           alive_mask | leave_mask | join_mask | reserved
#   [28:..) slot section: u32 n_slots + max_slots x 16-byte slots
#           (u32 kind | n_phases | rung | bls_class_rung)
#   [..:..) decision section: the UNCHANGED ISSUE-15 decision frame
#           (topology.pack_decision_frame: u32 count + u32 host +
#           max_decisions x 96-byte wire records)
#   [..:..) reroute section: u32 nbytes + reroute_cap raw bytes of
#           held 96-byte wire records, GLOBAL instance ids intact
#
# Masks are u32 bitmaps (bit h = host h), capping the elastic pod at
# 32 processes — well past any pod this repo drives today, and the
# reserved word is where a wider encoding would negotiate itself in.

ELASTIC_MAGIC = 0x454C4131          # 'ELA1'
EHDR = 28
SLOT_BYTES = 16
MAX_POD_HOSTS = 32


class ElasticFrame(NamedTuple):
    """One host's unpacked negotiation frame."""

    host: int
    epoch: int
    alive_mask: int
    leave_mask: int
    join_mask: int
    slots: Tuple[TickSlot, ...]
    decisions: List[PodDecision]
    reroute: bytes


def elastic_frame_capacity(max_slots: int, max_decisions: int,
                           reroute_cap: int) -> int:
    """Total frame bytes for the given section capacities."""
    return (EHDR + 4 + int(max_slots) * SLOT_BYTES
            + frame_capacity_bytes(max_decisions)
            + 4 + int(reroute_cap))


def pack_elastic_frame(host: int, epoch: int, alive_mask: int,
                       leave_mask: int, join_mask: int,
                       slots: Sequence[TickSlot],
                       decision_frame: np.ndarray,
                       reroute: bytes, *,
                       max_slots: int,
                       reroute_cap: int) -> np.ndarray:
    """[frame_bytes] uint8 — layout above.  `decision_frame` is the
    topology.pack_decision_frame output (embedded verbatim, so the
    decision codec stays ONE implementation)."""
    if len(slots) > max_slots:
        raise MembershipError(
            f"{len(slots)} tick slots exceed the negotiated frame "
            f"capacity {max_slots}")
    if len(reroute) > reroute_cap:
        raise MembershipError(
            f"{len(reroute)} reroute bytes exceed capacity "
            f"{reroute_cap}")
    if len(reroute) % REC_SIZE:
        raise MembershipError(
            f"reroute payload {len(reroute)}B is not whole "
            f"{REC_SIZE}-byte records")
    dec = np.asarray(decision_frame, np.uint8)
    frame = np.zeros(
        elastic_frame_capacity(max_slots, 0, reroute_cap)
        + len(dec) - frame_capacity_bytes(0), np.uint8)
    hdr = np.asarray([ELASTIC_MAGIC, host, epoch, alive_mask,
                      leave_mask, join_mask, 0], np.uint32)
    frame[:EHDR] = hdr.view(np.uint8)
    o = EHDR
    frame[o:o + 4] = np.asarray([len(slots)],
                                np.uint32).view(np.uint8)
    o += 4
    for s in slots:
        frame[o:o + SLOT_BYTES] = np.asarray(
            [s.kind, s.n_phases, s.rung, s.bls_class_rung],
            np.uint32).view(np.uint8)
        o += SLOT_BYTES
    o = EHDR + 4 + max_slots * SLOT_BYTES
    frame[o:o + len(dec)] = dec
    o += len(dec)
    frame[o:o + 4] = np.asarray([len(reroute)],
                                np.uint32).view(np.uint8)
    o += 4
    if reroute:
        frame[o:o + len(reroute)] = np.frombuffer(reroute, np.uint8)
    return frame


def unpack_elastic_frame(row, max_slots: int, max_decisions: int,
                         reroute_cap: int) -> ElasticFrame:
    """Inverse of pack_elastic_frame for one gathered row."""
    row = np.asarray(row, np.uint8)
    want = elastic_frame_capacity(max_slots, max_decisions,
                                  reroute_cap)
    if len(row) != want:
        raise MembershipError(
            f"elastic frame is {len(row)}B, capacities say {want}B")
    hdr = row[:EHDR].view(np.uint32)
    if int(hdr[0]) != ELASTIC_MAGIC:
        raise MembershipError(
            f"bad elastic frame magic {int(hdr[0]):#x}")
    o = EHDR
    n_slots = int(row[o:o + 4].view(np.uint32)[0])
    if n_slots > max_slots:
        raise MembershipError(
            f"frame claims {n_slots} slots > capacity {max_slots}")
    o += 4
    slots = []
    for k in range(n_slots):
        kind, n_phases, rung, bcr = (
            int(x) for x in
            row[o + k * SLOT_BYTES:
                o + (k + 1) * SLOT_BYTES].view(np.uint32))
        slots.append(TickSlot(kind, n_phases, rung, bcr))
    o = EHDR + 4 + max_slots * SLOT_BYTES
    dlen = frame_capacity_bytes(max_decisions)
    decisions = unpack_decision_frame(row[o:o + dlen])
    o += dlen
    nre = int(row[o:o + 4].view(np.uint32)[0])
    if nre > reroute_cap:
        raise MembershipError(
            f"frame claims {nre} reroute bytes > capacity "
            f"{reroute_cap}")
    o += 4
    reroute = row[o:o + nre].tobytes()
    return ElasticFrame(
        host=int(hdr[1]), epoch=int(hdr[2]),
        alive_mask=int(hdr[3]), leave_mask=int(hdr[4]),
        join_mask=int(hdr[5]), slots=tuple(slots),
        decisions=decisions, reroute=reroute)


# -- the elastic shard --------------------------------------------------------

class ElasticShard(HostShard):
    """HostShard + the membership/negotiation plane (module
    docstring).  Drop-in everywhere HostShard goes; the ONE new
    lockstep obligation is `tick()` — every live-or-sleeping host
    calls it at the same protocol points (the smoke drives a fixed
    tick schedule per height), because the tick's allgather is a pod
    collective."""

    def __init__(self, driver, batcher, pubkeys=None, *,
                 membership: Optional[MembershipEpoch] = None,
                 rejoin_holddown_ticks: int = 0,
                 max_slots: int = 8,
                 reroute_capacity: Optional[int] = None,
                 clock=time.monotonic,
                 **service_kwargs):
        super().__init__(driver, batcher, pubkeys, clock=clock,
                         **service_kwargs)
        if self.n_hosts > MAX_POD_HOSTS:
            raise MembershipError(
                f"elastic frame masks cap the pod at "
                f"{MAX_POD_HOSTS} hosts ({self.n_hosts} configured)")
        self.membership = membership if membership is not None else \
            MembershipEpoch(self.n_hosts, driver.global_I,
                            rejoin_holddown_ticks=rejoin_holddown_ticks)
        if (self.membership.view.n_hosts != self.n_hosts
                or self.membership.view.n_instances
                != driver.global_I):
            raise MembershipError(
                f"membership plane ({self.membership.view.n_hosts} "
                f"hosts x {self.membership.view.n_instances} "
                f"instances) does not match the pod "
                f"({self.n_hosts} x {driver.global_I})")
        # dead-peer verdicts degrade to leave intents from here on;
        # resumed evidence latches the join (topology.StragglerMonitor
        # recovery path — the ISSUE 17 satellite this plane consumes)
        self.monitor.attach_membership(self.membership)
        self.max_slots = int(max_slots)
        self.reroute_capacity = (
            int(reroute_capacity) if reroute_capacity is not None
            else 4 * self.plan.local_instances * driver.V * REC_SIZE)
        self._frame_bytes = elastic_frame_capacity(
            self.max_slots, self._frame_cap, self.reroute_capacity)
        # held gossip for ADOPTED ranges: [REC_SIZE] uint8 record rows
        # in GLOBAL instance ids, replayable byte-for-byte
        self._held: List[np.ndarray] = []
        self._clock = clock
        self.negotiation_ticks = 0
        self.padded_slots = 0          # slots this host padded up/into
        self.adopted_held = 0          # records held for away homes
        self.held_dropped = 0          # capacity overflow (degrades)
        self.reroute_sent = 0
        self.reroute_received = 0
        self.reroute_reheld = 0        # stray reroutes re-held (bug net)
        self.boundaries = 0            # applied repartitions
        self._mirror_membership()

    # -- intents -------------------------------------------------------------

    def announce_leave(self, host: Optional[int] = None) -> bool:
        """Latch a leave intent (default: THIS host — planned
        drain/maintenance).  Broadcast on the next tick, applied at
        the next boundary."""
        return self.membership.note_leave(
            self.host if host is None else host)

    def announce_join(self, host: Optional[int] = None) -> bool:
        """Latch a rejoin intent (default: THIS host)."""
        return self.membership.note_join(
            self.host if host is None else host)

    @property
    def serving(self) -> bool:
        """Does the CURRENT epoch assign this host any instances?"""
        return self.membership.view.owned_range(self.host) is not None

    # -- ingress: membership-aware front door --------------------------------

    def _alive_lut(self, view) -> np.ndarray:
        """[n_hosts] bool: is host h alive under `view`?"""
        lut = np.zeros(self.n_hosts, bool)
        lut[list(view.alive)] = True
        return lut

    def _home_of(self, inst: np.ndarray) -> np.ndarray:
        """STATIC home host of each global instance id — the host
        whose device block serves it (HostPlan.host_of, vectorized).
        Clipped so an out-of-range id indexes safely (such a record
        never passes the owned-range screen anyway)."""
        return np.minimum(inst // self.plan.local_instances,
                          self.n_hosts - 1)

    def submit(self, wire_bytes):
        """The HostShard screen, elastically: records in this host's
        static block feed the local service; records this host
        epoch-OWNS whose STATIC home host is departed are HELD for
        re-routing instead of foreign-rejected (the model checker's
        `_home_serving` predicate — module docstring); the rest are
        foreign as before.  In particular a record in this host's
        owned range whose home is another LIVE host is foreign, not
        adopted: the home's own front door serves it, and holding it
        here would replay it as a duplicate while consuming reroute
        capacity.  Holding is capacity-bounded: overflow drops are
        counted and event-logged, never a wedge (module docstring)."""
        buf = np.frombuffer(bytes(wire_bytes), np.uint8)
        n = len(buf) // REC_SIZE
        tail = buf[n * REC_SIZE:]
        if not n:
            return self.service.submit(tail.tobytes())
        rec = buf[:n * REC_SIZE].reshape(n, REC_SIZE)
        inst = wire_instance_ids(rec)
        mine = (inst >= self.lo) & (inst < self.hi)
        owned = self.membership.view.owned_range(self.host)
        adopt = np.zeros(n, bool)
        if owned is not None:
            vlo, vhi = owned
            home_away = ~self._alive_lut(
                self.membership.view)[self._home_of(inst)]
            adopt = (inst >= vlo) & (inst < vhi) & ~mine & home_away
        if adopt.any():
            self._hold(rec[adopt])
        foreign = int(n - mine.sum() - adopt.sum())
        self.foreign_rejects += foreign
        if foreign:
            from agnes_tpu.utils.metrics import POD_FOREIGN_REJECTS

            self.service.metrics.count(POD_FOREIGN_REJECTS, foreign)
        kept = rec[mine]
        from agnes_tpu.distributed.topology import \
            shift_instances_inplace

        shift_instances_inplace(kept, -self.lo)
        return self.service.submit(kept.tobytes() + tail.tobytes())

    def _hold(self, rows: np.ndarray) -> None:
        free = (self.reroute_capacity // REC_SIZE
                - len(self._held)) if self.reroute_capacity else 0
        take = max(0, min(len(rows), free))
        for r in rows[:take]:
            self._held.append(r.copy())
        self.adopted_held += take
        dropped = len(rows) - take
        if dropped:
            self.held_dropped += dropped
            if self.service.flightrec is not None:
                self.service.flightrec.event(
                    "membership_hold_overflow", host=self.host,
                    dropped=dropped,
                    epoch=self.membership.view.epoch)

    # -- the negotiation tick ------------------------------------------------

    def _slot_of(self, st) -> TickSlot:
        """Negotiated shape of one staged build."""
        n_phases = len(st.phases) + (1 if st.entry else 0)
        if st.lanes is None:
            return TickSlot(KIND_UNSIGNED, n_phases)
        if self.pipeline.dense:
            return TickSlot(KIND_DENSE_SIGNED, n_phases)
        return TickSlot(KIND_SIGNED, n_phases,
                        rung=int(st.lanes.pub.shape[0]))

    def _local_decision_frame(self) -> np.ndarray:
        """Newly latched LOCAL decisions as the ISSUE-15 frame (the
        same stamping as HostShard.poll_pod_decisions — the codec and
        the height bookkeeping stay one implementation's semantics)."""
        local = self.service.poll_decisions()
        inst = self.plan.to_global(
            self.host, np.asarray([d.instance for d in local],  # lint: allow (host list -> array)
                                  np.int64))
        fah = self.service.pipeline.first_advance_height
        hts = np.asarray(  # lint: allow (host list -> array)
            [fah.get(d.instance,
                     int(self.service.batcher.heights[d.instance]))
             for d in local], np.int64)
        return pack_decision_frame(
            self.host, inst,
            np.asarray([(d.value_id if d.value_id is not None else -1)  # lint: allow (host list -> array)
                        for d in local], np.int64),
            np.asarray([d.round for d in local], np.int64),  # lint: allow (host list -> array)
            hts, self._frame_cap)

    def _take_reroute(self, view) -> bytes:
        """Pop held records whose STATIC home host is alive under
        `view` — the bytes the next frame re-routes, so the home's
        own front door (the ONE peer whose `_ingest_reroute` absorbs
        them) replays them.  Capacity-bounded; leftovers go on later
        ticks.  Records whose home is still departed stay held HERE,
        across any intervening repartition and even across this
        holder's own departure (a sleeping process keeps ticking):
        targeting the EPOCH owner instead would hand records to a
        host whose static screen discards them — silent decision
        loss the checker's lossless holder bookkeeping never
        modeled."""
        if not self._held:
            return b""
        send: List[np.ndarray] = []
        keep: List[np.ndarray] = []
        cap = self.reroute_capacity // REC_SIZE
        alive = self._alive_lut(view)
        per = self.plan.local_instances
        for row in self._held:
            i = int(wire_instance_ids(row[None, :])[0])
            home = min(i // per, self.n_hosts - 1)
            if alive[home] and len(send) < cap:
                send.append(row)
            else:
                keep.append(row)
        self._held = keep
        self.reroute_sent += len(send)
        return b"".join(r.tobytes() for r in send)

    def _ingest_reroute(self, raw: bytes) -> None:
        """Absorb re-routed records addressed to THIS host's static
        block (the readmitted owner's catch-up path): global-id wire
        bytes, screened and rebased like any gossip — but via the
        LOCAL service directly, so they are never foreign-counted
        (the sender already routed them).  The reroute section rides
        the allgathered frame, so every host sees every sender's
        bytes: records for OTHER hosts' static blocks are theirs to
        absorb and are ignored here — EXCEPT a record whose home is
        still departed (a sender bug: honest reroutes only ever
        target live homes), which the current epoch owner RE-HOLDS,
        counted and event-logged, instead of letting it silently
        fall out of the protocol."""
        n = len(raw) // REC_SIZE
        if not n:
            return
        rec = np.frombuffer(raw, np.uint8)[:n * REC_SIZE].reshape(
            n, REC_SIZE).copy()
        inst = wire_instance_ids(rec)
        mine = (inst >= self.lo) & (inst < self.hi)
        if mine.any():
            kept = rec[mine]
            from agnes_tpu.distributed.topology import \
                shift_instances_inplace

            shift_instances_inplace(kept, -self.lo)
            self.reroute_received += int(mine.sum())
            self.service.submit(kept.tobytes())
        if mine.all():
            return
        view = self.membership.view
        owned = view.owned_range(self.host)
        if owned is None:
            return
        stray = (~mine & (inst >= owned[0]) & (inst < owned[1])
                 & ~self._alive_lut(view)[self._home_of(inst)])
        if stray.any():
            self.reroute_reheld += int(stray.sum())
            self._hold(rec[stray])
            if self.service.flightrec is not None:
                self.service.flightrec.event(
                    "membership_reroute_rehold", host=self.host,
                    records=int(stray.sum()), epoch=view.epoch)

    def tick(self, now: Optional[float] = None,
             boundary: bool = False) -> dict:
        """One lockstep elastic tick (module docstring): close +
        stage, negotiate shapes + decisions + intents + reroutes in
        ONE allgather, pad to the merged plan, dispatch.  With
        `boundary=True` (callers pass it at height boundaries) the
        latched membership intents apply after the exchange.  EVERY
        pod process calls tick at the same protocol points, serving
        or sleeping — a sleeper stages nothing and dispatches pure
        padding, which is exactly what keeps the global-SPMD
        collectives lockstep while its ranges are away."""
        t0 = self._clock()
        # advance the lockstep logical clock FIRST: intents latched
        # anywhere in this tick (monitor verdicts, merged peer masks)
        # stamp against the same pod-identical counter
        self.membership.note_tick()
        self.monitor.check()   # degrades to leave intents (attached)
        # 1. close the micro-batch and stage builds — NO dispatch yet
        batch = self.service.micro.flush()
        if batch is not None or self.service.batcher.pending_votes:
            self.pipeline.stage(batch)
        staged = self.pipeline._staged
        slots = tuple(self._slot_of(st) for st in staged)
        # 2. decisions + intents + (boundary) prospective reroute
        dec_frame = self._local_decision_frame()
        prospective = (self.membership.prospective() if boundary
                       else None)
        reroute = self._take_reroute(
            prospective if prospective is not None
            else self.membership.view)
        leave_mask, join_mask = self.membership.pending()
        view = self.membership.view
        frame = pack_elastic_frame(
            self.host, view.epoch, view.alive_mask(),
            leave_mask, join_mask, slots, dec_frame, reroute,
            max_slots=self.max_slots,
            reroute_cap=self.reroute_capacity)
        # 3. ONE allgather: shapes + decisions + intents + reroutes
        rows = self.coordinator.negotiate(frame)
        frames = [unpack_elastic_frame(
            rows[h], self.max_slots, self._frame_cap,
            self.reroute_capacity) for h in range(self.n_hosts)]
        # 4. statics stay loud: every host must be IN the same epoch
        #    looking at the same membership — anything else is a bug
        #    in the lockstep protocol, not honest heterogeneity
        for f in frames:
            if (f.epoch, f.alive_mask) != (view.epoch,
                                           view.alive_mask()):
                raise MembershipError(
                    f"membership diverged: host {f.host} is at epoch "
                    f"{f.epoch}/alive={f.alive_mask:#x}, host "
                    f"{self.host} at {view.epoch}/"
                    f"{view.alive_mask():#x}")
        # 5. merge + pad + PROVE warmed + dispatch
        merged = merge_tick_plans([f.slots for f in frames])
        for slot in merged:
            if not self.pipeline.warmup_covers(
                    KIND_NAMES.get(slot.kind, "?"), slot.n_phases,
                    slot.rung):
                raise MembershipError(
                    f"negotiated slot {slot} is outside the warmed "
                    f"shape set {sorted(self.pipeline.warmed_keys)} "
                    f"— padding must never buy a live compile")
        padded = 0
        for k, slot in enumerate(merged):
            if k < len(staged):
                padded += 1 if self.pipeline.pad_staged_to(
                    staged[k], slot.n_phases) else 0
            else:
                self.pipeline.stage_padding(
                    slot.n_phases,
                    signed=slot.kind != KIND_UNSIGNED)
                padded += 1
        self.padded_slots += padded
        dispatched = self.pipeline.dispatch_staged()
        # 6. absorb the pod-wide decision view
        for f in frames:
            self.pod_decisions.extend(f.decisions)
        # 7. fold peer intents in; apply the boundary; then ingest
        #    reroutes (order matters: a readmitted owner's ranges are
        #    live again BEFORE its catch-up bytes arrive at the
        #    service)
        for f in frames:
            if f.host != self.host:
                self.membership.merge_intents(f.leave_mask,
                                              f.join_mask)
        rep: Optional[Repartition] = None
        if boundary:
            rep = self.membership.boundary()
            if rep is not None:
                self.boundaries += 1
                self._on_boundary(rep)
        for f in frames:
            if f.host != self.host and f.reroute:
                self._ingest_reroute(f.reroute)
        self.negotiation_ticks += 1
        wall = self._clock() - t0
        from agnes_tpu.utils.metrics import POD_NEGOTIATION_WALL_S

        self.service.metrics.observe(POD_NEGOTIATION_WALL_S, wall)
        return {"dispatched": dispatched, "slots": len(merged),
                "padded": padded, "epoch": self.membership.view.epoch,
                "boundary": rep is not None,
                "negotiation_wall_s": wall}

    def _on_boundary(self, rep: Repartition) -> None:
        """Applied repartition bookkeeping: flight-recorder events,
        epoch gauge, readmission counter mirror — the observability
        satellite's live wiring."""
        fr = self.service.flightrec
        if fr is not None:
            fr.event("membership_boundary",
                     epoch=rep.new.epoch,
                     alive=list(rep.new.alive),
                     joined=list(rep.joined), left=list(rep.left))
            for src, dst, lo, hi in rep.transfers:
                fr.event("membership_relift", src=src, dst=dst,
                         lo=lo, hi=hi, epoch=rep.new.epoch)
        self._mirror_membership()

    def _mirror_membership(self) -> None:
        from agnes_tpu.utils.metrics import (
            POD_HOST_READMISSIONS,
            POD_MEMBERSHIP_EPOCH,
        )

        m = self.service.metrics
        m.gauge(POD_MEMBERSHIP_EPOCH, self.membership.view.epoch)
        have = m.counters.get(POD_HOST_READMISSIONS, 0)
        want = self.membership.readmissions
        if want > have:
            m.count(POD_HOST_READMISSIONS, want - have)

    # -- ladder replan (the budget satellite's live consumer) ----------------

    def replan_ladder(self, **plan_kwargs):
        """Re-plan the shape ladder against the CURRENT membership: a
        shrunken pod's surviving owner serves a bigger slice, so both
        the per-device budget check and the top rung re-derive from
        the live count (ShapeLadder.plan_dense(n_live=...) /
        mesh_local_shape(n_live=...)).  Returns the new ladder and
        installs it on the pipeline; rungs only pace micro-batches in
        dense mode (the compile key is (P, I, V)), so swapping the
        ladder never touches the warmed shape set."""
        from agnes_tpu.serve.batcher import ShapeLadder

        live = len(self.membership.view.alive)
        d = self.driver
        lad = ShapeLadder.plan_dense(
            d.global_I, d.V,
            local_shape=d._local_shape(n_live=live),
            n_hosts=self.n_hosts, n_live=live, **plan_kwargs)
        old = self.pipeline.ladder
        if old.bls_rungs or old.bls_class_rungs:
            lad = ShapeLadder(rungs=lad.rungs,
                              bls_rungs=old.bls_rungs,
                              bls_class_rungs=old.bls_class_rungs)
        self.pipeline.ladder = lad
        return lad

    # -- drain ---------------------------------------------------------------

    def drain(self, gather: bool = True) -> dict:
        """HostShard.drain + the elastic section of the pod report."""
        rep = super().drain(gather=gather)
        rep["pod"]["elastic"] = {
            "epoch": self.membership.view.epoch,
            "alive": list(self.membership.view.alive),
            "negotiation_ticks": self.negotiation_ticks,
            "padded_slots": self.padded_slots,
            "pad_builds": self.pipeline.pad_builds,
            "padded_phases": self.pipeline.padded_phases,
            "boundaries": self.boundaries,
            "readmissions": self.membership.readmissions,
            "monitor_readmissions": self.monitor.readmissions,
            "departures": self.membership.departures,
            "adopted_held": self.adopted_held,
            "held_dropped": self.held_dropped,
            "held_pending": len(self._held),
            "reroute_sent": self.reroute_sent,
            "reroute_received": self.reroute_received,
            "reroute_reheld": self.reroute_reheld,
        }
        return rep
