"""Pod membership + negotiation math: the jax-free core of the
elastic pod (ISSUE 17).

Three independently testable pieces, mirroring topology.py's role for
the static pod:

* **Range repartition** — `partition_ranges` assigns contiguous global
  instance blocks to the *live* host set (sorted, even split enforced
  exactly like HostPlan — a ragged membership is rejected at plan
  time, not papered over); `validate_partition` is the
  disjoint-and-covering invariant the model checker's monitors and the
  live boundary path both call, so the proof and the pod police the
  SAME predicate.  `relift_ranges(old, new)` is the transfer plan: the
  minimal list of (src host, dst host, lo, hi) global ranges that
  change owner — what the live pod uses to re-route held gossip and
  the checker uses to move held batches.
* **Spec-tree re-lift** — `instance_axis_of` + `relift_tree` re-lift a
  per-host tree of numpy state/tally blocks onto a new partition,
  driven by the SAME PartitionSpec trees the sharded dispatch uses
  (parallel/sharded.seq_in_specs / dense_lane_specs — the caller maps
  each spec leaf to its instance axis with `instance_axis_of`, so the
  re-lift can never disagree with the dispatch lift about which axis
  is the instance dimension).
* **Per-tick plan negotiation** — `TickSlot`/`merge_tick_plans`: each
  host's closed batch shapes for one lockstep tick, merged to the
  per-slot MAX (P, rung, BLS class rung) so heterogeneous honest
  traffic pads up onto an already-warmed shape instead of diverging
  the pod.  Slot KINDS must agree (a signed slot against an unsigned
  slot is a statics divergence, not honest heterogeneity) — that
  still fails loudly, exactly like PodCoordinator.agree.

`MembershipEpoch` is the protocol object: leave/join intents latch
mid-epoch (a departed host is TOB-SVD sleepy churn at pod granularity
— it stops serving, the pod does not stop ticking) and apply ONLY at
epoch boundaries, where the partition recomputes, held gossip
re-routes along `relift_ranges`, and a returned host is readmitted —
after a LOGICAL-TICK holddown, so a flapping peer cannot churn the
partition every tick.  The holddown clock is `note_tick` (every host
advances it at the same lockstep protocol point) and departures are
stamped at the boundary that applied them, so every holddown verdict
is a pure function of pod-shared state — per-process wall clocks are
deliberately NOT consulted: hosts near a wall-clock threshold would
disagree on deferring a merged join, diverge their pending sets, and
wedge the pod on the next epoch/alive statics check.

Pure numpy + stdlib; no jax anywhere (conftest _CHEAP eligible).
"""

from __future__ import annotations

import dataclasses
from typing import (
    Dict,
    Iterable,
    List,
    Mapping,
    NamedTuple,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from agnes_tpu.distributed.topology import PodConfigError


class MembershipError(PodConfigError):
    """A membership/negotiation invariant the elastic pod cannot
    satisfy (uneven repartition, kind-diverged tick slots, ...)."""


# -- range repartition --------------------------------------------------------

def partition_ranges(n_instances: int,
                     hosts: Iterable[int]) -> Dict[int, Tuple[int, int]]:
    """Contiguous [lo, hi) global instance ranges over the SORTED live
    host set.  Even split enforced (HostPlan's rule: the sharded data
    axes need an exact split; a deployment picks I as a multiple of
    every pod size it intends to survive)."""
    live = sorted(set(int(h) for h in hosts))
    if not live:
        raise MembershipError("cannot partition over an empty host set")
    if n_instances <= 0:
        raise MembershipError(
            f"n_instances must be >= 1: {n_instances}")
    if n_instances % len(live):
        raise MembershipError(
            f"{n_instances} instances do not repartition evenly over "
            f"{len(live)} live host(s) {live} — uneven splits are "
            f"rejected (pad the deployment or change the pod size)")
    per = n_instances // len(live)
    return {h: (k * per, (k + 1) * per) for k, h in enumerate(live)}


def validate_partition(ranges: Mapping[int, Tuple[int, int]],
                       n_instances: int) -> None:
    """THE disjoint-and-covering invariant (module docstring): every
    global instance id in [0, n_instances) owned by exactly one host.
    Raises MembershipError naming the first violation."""
    owned = np.zeros(n_instances, np.int64)
    for h, (lo, hi) in ranges.items():
        if not (0 <= lo <= hi <= n_instances):
            raise MembershipError(
                f"host {h} range [{lo}, {hi}) outside "
                f"[0, {n_instances})")
        owned[lo:hi] += 1
    over = np.nonzero(owned > 1)[0]
    if len(over):
        raise MembershipError(
            f"partition overlaps at instance {int(over[0])}: "
            f"{dict(ranges)}")
    gap = np.nonzero(owned == 0)[0]
    if len(gap):
        raise MembershipError(
            f"partition leaves instance {int(gap[0])} unowned: "
            f"{dict(ranges)}")


def relift_ranges(old: Mapping[int, Tuple[int, int]],
                  new: Mapping[int, Tuple[int, int]],
                  ) -> List[Tuple[int, int, int, int]]:
    """Transfer plan between two partitions of the same instance
    space: [(src_host, dst_host, lo, hi)] for every maximal global
    range whose owner changed, sorted by lo.  Ranges owned by the same
    host in both partitions do not appear (nothing moves)."""
    def owner_at(ranges, i):
        for h, (lo, hi) in ranges.items():
            if lo <= i < hi:
                return h
        raise MembershipError(f"instance {i} unowned in {dict(ranges)}")

    n = max((hi for _, hi in old.values()), default=0)
    out: List[Tuple[int, int, int, int]] = []
    i = 0
    while i < n:
        src, dst = owner_at(old, i), owner_at(new, i)
        j = i + 1
        while j < n and owner_at(old, j) == src \
                and owner_at(new, j) == dst:
            j += 1
        if src != dst:
            out.append((src, dst, i, j))
        i = j
    return out


# -- spec-tree re-lift --------------------------------------------------------

def instance_axis_of(spec, instance_axes: Sequence[str]) -> Optional[int]:
    """The axis index of `spec` (a PartitionSpec-like tuple of
    mesh-axis names / tuples / Nones) sharded over any of
    `instance_axes` — i.e. the INSTANCE dimension of the leaf this
    spec shards.  None when the leaf carries no instance dimension
    (replicated operands: powers, pubkey tables).  Shares the
    normalization rule of DistributedDriver._spec_dim_axes so the
    re-lift and the dispatch lift can never disagree."""
    want = set(instance_axes)
    for a, axes in enumerate(tuple(spec)):
        axes_t = (axes,) if isinstance(axes, str) else tuple(axes or ())
        if want & set(axes_t):
            return a
    return None


def relift_tree(blocks_by_host: Mapping[int, Sequence[np.ndarray]],
                old: Mapping[int, Tuple[int, int]],
                new: Mapping[int, Tuple[int, int]],
                axes: Sequence[Optional[int]],
                ) -> Dict[int, List[np.ndarray]]:
    """Re-lift per-host state/tally leaf blocks onto a NEW partition:
    `blocks_by_host[h]` is host h's flat leaf list (numpy, fetched
    addressable blocks), `axes[k]` the instance axis of leaf k
    (`instance_axis_of` over the matching spec tree; None = replicated
    leaf, copied from any host).  Returns the same structure keyed by
    the new partition's hosts.  Pure data movement — assembling the
    global leaf and re-slicing it — so old and new assemblies are
    bit-identical by construction; `validate_partition` both sides
    first, so a hole or overlap fails HERE, not as silent state
    loss."""
    if not blocks_by_host:
        return {}
    n = max(hi for _, hi in old.values())
    validate_partition(old, n)
    validate_partition(new, n)
    n_leaves = len(next(iter(blocks_by_host.values())))
    out: Dict[int, List[np.ndarray]] = {h: [] for h in new}
    for k in range(n_leaves):
        ax = axes[k]
        if ax is None:
            any_host = next(iter(blocks_by_host))
            for h in new:
                out[h].append(np.asarray(
                    blocks_by_host[any_host][k]).copy())
            continue
        # assemble the global leaf from the old blocks ...
        sample = np.asarray(next(iter(blocks_by_host.values()))[k])
        gshape = list(sample.shape)
        per_old = gshape[ax]
        gshape[ax] = n
        g = np.empty(gshape, sample.dtype)
        for h, (lo, hi) in old.items():
            blk = np.asarray(blocks_by_host[h][k])
            if blk.shape[ax] != hi - lo or hi - lo != per_old:
                raise MembershipError(
                    f"leaf {k}: host {h} block extent "
                    f"{blk.shape[ax]} != owned range {hi - lo}")
            sel = [slice(None)] * g.ndim
            sel[ax] = slice(lo, hi)
            g[tuple(sel)] = blk
        # ... and re-slice it along the new partition
        for h, (lo, hi) in new.items():
            sel = [slice(None)] * g.ndim
            sel[ax] = slice(lo, hi)
            out[h].append(g[tuple(sel)].copy())
    return out


# -- per-tick plan negotiation ------------------------------------------------

#: tick-slot kinds (wire-stable small ints)
KIND_DENSE_SIGNED = 1          # dense fused signed step (pod serve)
KIND_SIGNED = 2                # packed-lane signed (rung-carrying)
KIND_UNSIGNED = 3              # pre-verified / unsigned sequence
KIND_NAMES = {KIND_DENSE_SIGNED: "dense_signed", KIND_SIGNED: "signed",
              KIND_UNSIGNED: "unsigned"}


class TickSlot(NamedTuple):
    """One closed build's shape, as negotiated: total step-sequence
    length P (entry included), the padded lane rung (0 on dense /
    unsigned builds — their compile key carries no rung) and the BLS
    class rung (0 = no BLS lane)."""

    kind: int
    n_phases: int
    rung: int = 0
    bls_class_rung: int = 0


def merge_tick_plans(plans: Sequence[Sequence[TickSlot]],
                     ) -> Tuple[TickSlot, ...]:
    """The pod plan for one tick: per slot position, the MAX of every
    contributing host's (P, rung, BLS class rung) — hosts with fewer
    slots (or smaller shapes) pad up.  Kind mismatch at a slot is a
    STATICS divergence (module docstring) and raises."""
    n_slots = max((len(p) for p in plans), default=0)
    merged: List[TickSlot] = []
    for k in range(n_slots):
        slots = [TickSlot(*p[k]) for p in plans if len(p) > k]
        kinds = {s.kind for s in slots}
        if len(kinds) != 1:
            raise MembershipError(
                f"tick slot {k} kind diverged across the pod: "
                + ", ".join(sorted(KIND_NAMES.get(kd, str(kd))
                                   for kd in kinds))
                + " — mixed slot kinds are a statics divergence, not "
                  "honest heterogeneity; failing loudly")
        merged.append(TickSlot(
            kind=kinds.pop(),
            n_phases=max(s.n_phases for s in slots),
            rung=max(s.rung for s in slots),
            bls_class_rung=max(s.bls_class_rung for s in slots)))
    return tuple(merged)


# -- the membership protocol --------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MembershipView:
    """One epoch's membership: the live host set and its partition.
    Immutable — boundaries produce a NEW view, so every consumer can
    hold a view across a tick without seeing it mutate."""

    epoch: int
    n_hosts: int                   # the pod's FULL process count
    n_instances: int
    alive: Tuple[int, ...]
    ranges: Mapping[int, Tuple[int, int]]

    def owner_of(self, instance: int) -> int:
        for h, (lo, hi) in self.ranges.items():
            if lo <= instance < hi:
                return h
        raise MembershipError(
            f"instance {instance} unowned in epoch {self.epoch}")

    def owned_range(self, host: int) -> Optional[Tuple[int, int]]:
        """[lo, hi) host owns this epoch, None while departed."""
        return self.ranges.get(int(host))

    def alive_mask(self) -> int:
        return sum(1 << h for h in self.alive)


@dataclasses.dataclass(frozen=True)
class Repartition:
    """One applied epoch boundary: the view before/after and the
    transfer plan (`relift_ranges`) between their partitions."""

    old: MembershipView
    new: MembershipView
    transfers: Tuple[Tuple[int, int, int, int], ...]
    joined: Tuple[int, ...]
    left: Tuple[int, ...]


class MembershipEpoch:
    """Leave/join intents latch mid-epoch, apply at boundaries
    (module docstring).  The rejoin holddown counts LOGICAL ticks
    (`note_tick` — injectable progression for tests, lockstep in
    production); counters are plain ints the owning shard mirrors
    into its metrics registry."""

    def __init__(self, n_hosts: int, n_instances: int, *,
                 rejoin_holddown_ticks: int = 0):
        self.rejoin_holddown_ticks = int(rejoin_holddown_ticks)
        self.tick = 0                  # the lockstep logical clock
        view = MembershipView(
            epoch=0, n_hosts=int(n_hosts),
            n_instances=int(n_instances),
            alive=tuple(range(int(n_hosts))),
            ranges=partition_ranges(n_instances, range(int(n_hosts))))
        validate_partition(view.ranges, n_instances)
        self.view = view
        self._pending_leave: set = set()
        self._pending_join: set = set()
        #: tick of the BOUNDARY that applied each departure — a
        #: lockstep point where every host holds the identical merged
        #: intents and tick counter, so the stamp (and every holddown
        #: verdict derived from it) is identical pod-wide
        self._left_at: Dict[int, int] = {}
        self.readmissions = 0          # applied rejoins (boundaries)
        self.departures = 0
        self.deferred_joins = 0        # holddown pushed a join back

    # -- intents (latch mid-epoch, apply at boundary) ------------------------

    def note_tick(self) -> int:
        """Advance the pod-lockstep logical clock one elastic tick.
        Every host calls this at the same protocol point
        (ElasticShard.tick, before intents merge), so the counter is
        identical pod-wide — which is what makes the rejoin-holddown
        verdict deterministic: an originator that latches a join at
        tick T broadcasts it on the NEXT frame, so every peer
        evaluates the (monotone) holddown predicate at tick >= T and
        latches too.  Wall clocks cannot give that guarantee (module
        docstring)."""
        self.tick += 1
        return self.tick

    def note_leave(self, host: int) -> bool:
        """Latch a leave intent (idempotent).  Returns True when newly
        latched.  The host stops being served IMMEDIATELY in the sense
        that callers should hold its gossip; the partition itself only
        changes at the next boundary."""
        host = int(host)
        if host not in self.view.alive or host in self._pending_leave:
            return False
        self._pending_leave.add(host)
        self._pending_join.discard(host)
        return True

    def note_join(self, host: int) -> bool:
        """Latch a join intent for a departed (or departing) host.
        A join within `rejoin_holddown_ticks` of the boundary that
        APPLIED the departure is DEFERRED (counted, returns False): a
        flapping peer must stay quiet before the pod repartitions for
        it.  A leave still latched but not yet applied carries no
        holddown — cancelling it intra-epoch is free (no partition
        ever moved).  The verdict is deterministic pod-wide: both
        operands are lockstep state (`_left_at` stamps at boundaries,
        `tick` advances via note_tick)."""
        host = int(host)
        already = (host in self.view.alive
                   and host not in self._pending_leave)
        if already or host in self._pending_join:
            return False
        left = self._left_at.get(host)
        if left is not None and self.rejoin_holddown_ticks > 0 \
                and self.tick - left < self.rejoin_holddown_ticks:
            self.deferred_joins += 1
            return False
        self._pending_join.add(host)
        self._pending_leave.discard(host)
        return True

    def merge_intents(self, leave_mask: int, join_mask: int) -> None:
        """Fold intents gathered from peers' frames in — the union is
        what keeps every host's pending sets (and therefore the next
        boundary's partition) identical without a second protocol."""
        for h in range(self.view.n_hosts):
            if leave_mask >> h & 1:
                self.note_leave(h)
            if join_mask >> h & 1:
                self.note_join(h)

    def pending(self) -> Tuple[int, int]:
        """(leave_mask, join_mask) of latched intents — what this
        host's next negotiation frame broadcasts."""
        return (sum(1 << h for h in self._pending_leave),
                sum(1 << h for h in self._pending_join))

    def prospective(self) -> Optional[MembershipView]:
        """The view the NEXT boundary would produce (None = no pending
        change) — what a survivor consults to pack re-routed gossip
        for ranges it is about to relinquish, BEFORE the boundary
        applies.  Pure function of latched intents: every host
        computes the identical answer from the gathered masks."""
        alive = set(self.view.alive) - self._pending_leave \
            | self._pending_join
        if tuple(sorted(alive)) == self.view.alive:
            return None
        if not alive:
            return None                  # never partition to nobody
        return MembershipView(
            epoch=self.view.epoch + 1, n_hosts=self.view.n_hosts,
            n_instances=self.view.n_instances,
            alive=tuple(sorted(alive)),
            ranges=partition_ranges(self.view.n_instances,
                                    sorted(alive)))

    # -- model-checker hooks (analysis/membership_mc.py) ---------------------

    def mc_clone(self) -> "MembershipEpoch":
        """Branchable copy for the exhaustive explorer (the
        AdmissionQueue/VerifiedCache precedent: the protocol object
        under check is THIS class, so the hook lives here).  Views are
        frozen and shared; intent sets are copied."""
        c = type(self).__new__(type(self))
        c.rejoin_holddown_ticks = self.rejoin_holddown_ticks
        c.tick = self.tick
        c.view = self.view
        c._pending_leave = set(self._pending_leave)
        c._pending_join = set(self._pending_join)
        c._left_at = dict(self._left_at)
        c.readmissions = self.readmissions
        c.departures = self.departures
        c.deferred_joins = self.deferred_joins
        return c

    def mc_canonical(self) -> tuple:
        """Dedup key: the live set, its partition, and the latched
        intents.  The epoch COUNTER is deliberately excluded — two
        states differing only in how many boundaries produced the same
        partition are behaviorally identical, and excluding it keeps
        the explored space finite.  `tick`/`_left_at` are excluded for
        the same reason: with the checker's holddown of 0 (the
        membership_mc configs) they are behaviorally inert, and an
        exploration of a nonzero holddown would have to add them to
        the key alongside a tick bound."""
        return (self.view.alive,
                tuple(sorted((h, r) for h, r in self.view.ranges.items())),
                self.pending())

    # -- the boundary --------------------------------------------------------

    def boundary(self) -> Optional[Repartition]:
        """Apply latched intents at an epoch boundary: repartition,
        compute the transfer plan, readmit joiners (counted), age out
        leavers.  Returns None when nothing changed (no epoch is
        burned on a no-op boundary).  All hosts call this at the SAME
        lockstep point with the SAME merged intents, so every host
        steps to the identical new view."""
        new = self.prospective()
        self._pending_leave.clear()
        joined = tuple(sorted(self._pending_join))
        self._pending_join.clear()
        if new is None:
            return None
        validate_partition(new.ranges, new.n_instances)
        old = self.view
        left = tuple(sorted(set(old.alive) - set(new.alive)))
        joined = tuple(h for h in joined if h in new.alive
                       and h not in old.alive)
        rep = Repartition(
            old=old, new=new,
            transfers=tuple(relift_ranges(old.ranges, new.ranges)),
            joined=joined, left=left)
        self.view = new
        self.readmissions += len(joined)
        self.departures += len(left)
        for h in left:
            # the holddown clock starts HERE, not at note_leave: the
            # boundary is a lockstep point (same merged intents, same
            # tick on every host), so the stamp is pod-identical —
            # and a leave cancelled before any boundary never aged a
            # partition, so it owes no holddown
            self._left_at[h] = self.tick
        for h in joined:
            self._left_at.pop(h, None)
        return rep
