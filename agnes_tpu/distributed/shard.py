"""HostShard: the per-host serve front-end of the pod (ISSUE 15).

One HostShard per process, wrapping a REAL VoteService built over the
DistributedDriver at host-local shape — its own admission queue
(native front-end eligible: ``native_admission=True`` flows straight
through), its own inbox-fed threaded host if the caller wraps it, its
own dedup cache / BLS class table, flight recorder and metrics — and
adding exactly the pod-facing parts a single-host service doesn't
have:

* **Instance-range screening** (the front door): gossip traffic
  carries GLOBAL instance ids; ``submit`` drops records outside this
  host's block (counted ``pod_foreign``), rebases the survivors'
  instance field IN the 96-byte wire layout
  (topology.shift_instances_inplace on the one survivor copy — no
  unpack/repack round trip) and
  feeds the local VoteService, whose queue then screens/fairness-caps
  the local range exactly as a single-host deployment would.
* **Barrier-synchronized warmup**: every host warms the identical
  (entry, rung) set — the warmup PLAN is digest-compared at a pod
  barrier before and after, and each host's retrace sentinel arms its
  own no-recompile invariant, so an off-ladder dispatch on ANY host
  fails loudly (that host's RetraceError) and a mismatched PLAN fails
  every host (PodDivergenceError).
* **Per-tick decision gather**: newly latched local decisions ride
  the existing 96-byte wire ABI in fixed-size frames through one
  allgather (topology codec + pod transport), so every host holds the
  pod-wide decision view.
* **Fail-closed liveness**: a StragglerMonitor fed by completed
  collectives (and, when co-located, peer heartbeat files) gates
  every pod collective; a peer past the dead age raises DeadHostError
  BEFORE this host walks into an allgather that can never complete,
  and ``drain`` degrades to a local-only drain with the failure
  recorded in the report.
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from agnes_tpu.distributed.pod import PodCoordinator
from agnes_tpu.distributed.topology import (
    DeadHostError,
    PodDecision,
    StragglerMonitor,
    pack_decision_frame,
    unpack_decision_frames,
)
from agnes_tpu.serve.queue import AdmitResult
from agnes_tpu.utils.metrics import POD_FOREIGN_REJECTS  # noqa: F401
#     ^ the front-door screen counter (well-known name, ISSUE 15)


class HostShard:
    """Per-host serve front-end (module docstring).  `driver` must be
    a DistributedDriver; `service_kwargs` forward to VoteService
    (dedup_cache, bls_lane, native_admission, native_shards, metrics,
    flightrec,
    window_predictor, target_votes ... — the full single-host
    surface)."""

    def __init__(self, driver, batcher, pubkeys=None, *,
                 coordinator: Optional[PodCoordinator] = None,
                 monitor: Optional[StragglerMonitor] = None,
                 dead_after_s: float = 60.0,
                 straggler_after_s: float = 10.0,
                 clock=time.monotonic,
                 **service_kwargs):
        from agnes_tpu.serve import VoteService

        self.driver = driver
        self.plan = driver.plan
        self.host = driver.process_index
        self.n_hosts = driver.n_hosts
        self.monitor = monitor if monitor is not None else \
            StragglerMonitor(self.n_hosts, self.host,
                             dead_after_s=dead_after_s,
                             straggler_after_s=straggler_after_s,
                             clock=clock)
        self.coordinator = coordinator if coordinator is not None else \
            PodCoordinator(self.n_hosts, self.host,
                           monitor=self.monitor,
                           flightrec=service_kwargs.get("flightrec"))
        if self.coordinator.monitor is None:
            self.coordinator.monitor = self.monitor
        # the driver's per-dispatch lockstep agree() rides the same
        # coordinator (one collective ordering domain for the pod)
        driver.coordinator = self.coordinator
        self.service = VoteService(driver, batcher, pubkeys,
                                   clock=clock, **service_kwargs)
        self.lo, self.hi = self.plan.instance_range(self.host)
        self._frame_cap = self.plan.local_instances
        self.foreign_rejects = 0
        self.pod_decisions: List[PodDecision] = []
        self._gather_failed: Optional[str] = None

    # -- ingress: the pod front door -----------------------------------------

    def submit(self, wire_bytes) -> AdmitResult:
        """Admit pod-wide gossip: screen to this host's instance
        block, rebase ids onto the local service, count the foreign
        remainder (module docstring).  One parse, one survivor copy:
        the fancy-indexed `rec[mine]` IS the kept copy, rebased in
        place before the single serialization."""
        buf = np.frombuffer(bytes(wire_bytes), np.uint8)
        from agnes_tpu.bridge.native_ingest import REC_SIZE
        from agnes_tpu.distributed.topology import (
            shift_instances_inplace,
            wire_instance_ids,
        )

        n = len(buf) // REC_SIZE
        tail = buf[n * REC_SIZE:]
        if n:
            rec = buf[:n * REC_SIZE].reshape(n, REC_SIZE)
            inst = wire_instance_ids(rec)
            mine = (inst >= self.lo) & (inst < self.hi)
            foreign = int(n - mine.sum())
            kept = rec[mine]                 # fancy index = new copy
            shift_instances_inplace(kept, -self.lo)
            local_wire = kept.tobytes() + tail.tobytes()
        else:
            foreign = 0
            local_wire = tail.tobytes()
        self.foreign_rejects += foreign
        if foreign:
            self.service.metrics.count(POD_FOREIGN_REJECTS, foreign)
        return self.service.submit(local_wire)

    def submit_local(self, wire_bytes) -> AdmitResult:
        """Admit traffic already in LOCAL instance ids (a router that
        pre-shards by host skips the screen)."""
        return self.service.submit(wire_bytes)

    # -- lifecycle (delegates + pod semantics) -------------------------------

    def warmup(self, n_phases=(2, 3), arm: bool = True) -> int:
        """Barrier-synchronized pod warmup (module docstring)."""
        lad = self.service.pipeline.ladder
        plan = ("warmup", tuple(n_phases), self.driver.I,
                self.driver.V, self.driver.global_I, lad.rungs,
                lad.bls_rungs, lad.bls_class_rungs,
                self.service.pipeline.dense, bool(arm))
        self.coordinator.barrier("warmup_enter", plan)
        warmed = self.service.pipeline.warmup(n_phases, arm=arm)
        self.coordinator.barrier("warmup_exit", ("warmed", warmed))
        return warmed

    def pump(self, now: Optional[float] = None) -> dict:
        return self.service.pump(now)

    def poll_decisions(self):
        """LOCAL decisions only (no collective — safe at any cadence
        on any host)."""
        return self.service.poll_decisions()

    def poll_pod_decisions(self) -> List[PodDecision]:
        """Local poll + pod-wide decision gather (ONE allgather; all
        hosts must call in lockstep).  Returns the NEW pod-wide
        decisions this gather surfaced; `pod_decisions` accumulates
        them.  Fails closed on a dead peer (module docstring)."""
        self.monitor.check()
        local = self.service.poll_decisions()
        inst = self.plan.to_global(
            self.host, np.asarray([d.instance for d in local],
                                  np.int64))
        # height stamp: the instance's first-advance height (exactly
        # its latched first decision's height — pipeline bookkeeping);
        # an instance polled before its window ever advanced is still
        # ON its decided height, so the live height is the fallback
        fah = self.service.pipeline.first_advance_height
        hts = np.asarray(
            [fah.get(d.instance,
                     int(self.service.batcher.heights[d.instance]))
             for d in local], np.int64)
        frame = pack_decision_frame(
            self.host, inst,
            np.asarray([(d.value_id if d.value_id is not None else -1)
                        for d in local], np.int64),
            np.asarray([d.round for d in local], np.int64),
            hts, self._frame_cap)
        frames = self.coordinator.allgather_bytes(frame)
        new = unpack_decision_frames(frames)
        self.pod_decisions.extend(new)
        return new

    def drain(self, gather: bool = True) -> dict:
        """Drain the local slice and (lockstep) run one final
        decision gather; a dead peer degrades to local-only drain
        with the failure in the report — never a hang."""
        if gather:
            try:
                self.monitor.check()
            except DeadHostError as e:
                self._gather_failed = str(e)
                gather = False
        rep = self.service.drain()
        final: List[PodDecision] = []
        if gather:                 # pod-of-1 gathers are local no-ops
            try:
                final = self.poll_pod_decisions()
            except DeadHostError as e:
                self._gather_failed = str(e)
        rep["pod"] = {
            "host_id": self.host,
            "n_hosts": self.n_hosts,
            "instance_range": [self.lo, self.hi],
            "foreign_rejects": self.foreign_rejects,
            "final_gathered": len(final),
            "pod_decisions": len(self.pod_decisions),
            "stragglers": self.monitor.stragglers(),
            "dead_hosts": self.monitor.dead(),
            "gather_failed": self._gather_failed,
            "agrees": self.coordinator.agrees,
            "barriers": self.coordinator.barriers,
        }
        return rep

    # -- passthroughs --------------------------------------------------------

    @property
    def metrics(self):
        return self.service.metrics

    @property
    def pipeline(self):
        return self.service.pipeline

    @property
    def queue(self):
        return self.service.queue
