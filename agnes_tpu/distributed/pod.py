"""Pod coordination: lockstep agreement, barriers, and the byte-frame
allgather transport (ISSUE 15 tentpole, coordination layer).

Global-SPMD serving has ONE hard protocol rule: every host must launch
the SAME pod computations in the SAME order (a dispatch is a pod-wide
program — on real hardware a host sitting one out wedges the ICI
collective, and a host launching a DIFFERENT shape wedges it with a
mismatched executable).  The per-host serve fronts make that a traffic
property, so this layer turns a violation into a loud, dated failure
instead of a silent pod-wide hang:

* **agree(tag)** — before every pod dispatch each host contributes a
  digest of its dispatch plan (entry name, statics, local arg
  signature) to a tiny fixed-size allgather; any mismatch raises
  PodDivergenceError ON EVERY HOST naming who diverged.  Because all
  hosts run the same code path, the check itself stays in lockstep:
  when plans diverge, both sides are sitting in the SAME agree call
  when it fails — the check can never deadlock the pod worse than
  the divergence it just caught.
* **barrier(name, payload)** — agree() with rendezvous semantics: the
  multi-process warmup brackets itself in barriers whose payload is a
  digest of the warmup PLAN (entries × rungs × shapes), so "every
  host warms the identical set" is checked, not hoped.
* **allgather_bytes(frame)** — the decision-gather transport: each
  host contributes one fixed-size uint8 frame, gets [n_hosts, len]
  back (process-index-major).  Rides
  jax.experimental.multihost_utils.process_allgather, i.e. the same
  device fabric as the steps — no second network stack.

A pod of ONE degenerates to no-ops (agree/barrier trivially pass,
allgather returns the caller's frame), so every consumer is testable
single-process with zero collectives.
"""

from __future__ import annotations

import hashlib
from typing import Optional

import numpy as np

from agnes_tpu.distributed.topology import StragglerMonitor

#: digest frame bytes (blake2b-16 — collision strength is irrelevant,
#: the check is against honest config/traffic drift, not an adversary)
DIGEST_BYTES = 16


def initialize_pod(coordinator_address: str, num_processes: int,
                   process_id: int):
    """Bring up jax.distributed for this process and return
    (process_index, process_count).  MUST run before ANY backend use
    — the first jit/devices()/default_backend() call pins the client
    and jax.distributed then refuses to initialize; heavyweight agnes
    imports count too (device/step and the crypto modules build
    device constants at import), which is why this lives HERE in the
    light coordination module and not beside DistributedDriver: a
    worker imports pod.py, initializes, and only then imports the
    serve stack (distributed/smoke.py is the reference ordering).
    On CPU the collectives implementation is forced to gloo — without
    it every cross-process computation dies with "Multiprocess
    computations aren't implemented on the CPU backend", the failure
    mode the 2-process CI smoke exists to keep caught."""
    import os

    import jax

    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:  # noqa: BLE001 — older jaxlib: surface the
            pass           # real capability error at first dispatch
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id)
    return jax.process_index(), jax.process_count()


class PodDivergenceError(RuntimeError):
    """Hosts disagreed on a pod-wide dispatch plan or barrier."""


def plan_digest(tag) -> bytes:
    """Stable digest of a (nested, repr-able) dispatch-plan tag."""
    return hashlib.blake2b(repr(tag).encode(),
                           digest_size=DIGEST_BYTES).digest()


class PodCoordinator:
    """Lockstep/gather primitives over process_allgather (module
    docstring).  Constructed AFTER jax.distributed is initialized;
    `monitor` (topology.StragglerMonitor) is beaten on every completed
    collective — an allgather that returned IS a pod-wide liveness
    proof; `flightrec` gets one event per divergence so the heartbeat
    trail dates a wedge's cause."""

    def __init__(self, n_hosts: Optional[int] = None,
                 host: Optional[int] = None,
                 monitor: Optional[StragglerMonitor] = None,
                 flightrec=None):
        if n_hosts is None or host is None:
            import jax

            n_hosts = jax.process_count() if n_hosts is None else n_hosts
            host = jax.process_index() if host is None else host
        self.n_hosts = int(n_hosts)
        self.host = int(host)
        self.monitor = monitor
        self.flightrec = flightrec
        self.agrees = 0
        self.barriers = 0
        self.gathered_frames = 0
        self.negotiations = 0

    # -- transport -----------------------------------------------------------

    def allgather_bytes(self, frame: np.ndarray) -> np.ndarray:
        """One fixed-size uint8 frame per host -> [n_hosts, len]
        (process-index order).  Every host MUST call with the same
        frame length — that is the lockstep contract this class
        exists to police, and process_allgather enforces it at the
        device level."""
        frame = np.ascontiguousarray(frame, np.uint8)
        if self.n_hosts == 1:
            out = frame[None]
        else:
            from jax.experimental import multihost_utils

            out = np.asarray(
                multihost_utils.process_allgather(frame), np.uint8)
        self.gathered_frames += 1
        if self.monitor is not None:
            self.monitor.beat(None)     # completed == everybody live
        return out

    def negotiate(self, frame: np.ndarray) -> np.ndarray:
        """The elastic pod's per-tick exchange (ISSUE 17): the same
        fixed-size allgather as `allgather_bytes`, counted separately
        — `negotiations` tells a postmortem how many ticks this pod
        NEGOTIATED (shape plans + decisions + membership intents ride
        one frame, distributed/elastic.py) versus plain decision
        gathers.  Padding to the merged plan happens in the caller;
        this transport's only new obligation is the count."""
        out = self.allgather_bytes(frame)
        self.negotiations += 1
        return out

    # -- lockstep ------------------------------------------------------------

    def agree(self, tag, kind: str = "dispatch") -> bytes:
        """All-hosts digest compare of `tag`; raises
        PodDivergenceError on mismatch (module docstring).  Returns
        the agreed digest."""
        mine = plan_digest(tag)
        if self.n_hosts > 1:
            frames = self.allgather_bytes(
                np.frombuffer(mine, np.uint8))
            digests = [bytes(row.tobytes()) for row in frames]
            bad = [h for h, d in enumerate(digests) if d != mine]
            if bad:
                if self.flightrec is not None:
                    self.flightrec.event("pod_divergence", kind=kind,
                                         host=self.host, differing=bad)
                raise PodDivergenceError(
                    f"{kind} plan diverged across the pod: host "
                    f"{self.host} disagrees with host(s) {bad} "
                    f"(local tag: {tag!r}) — a global-SPMD dispatch "
                    f"with mismatched plans would wedge the pod; "
                    f"failing loudly instead")
        self.agrees += 1
        return mine

    def barrier(self, name: str, payload=()) -> None:
        """Rendezvous + payload-digest compare (module docstring)."""
        self.agree((name, payload), kind=f"barrier:{name}")
        self.barriers += 1
