"""DistributedDriver: the fused signed step as a pod-wide global-SPMD
dispatch over ``jax.distributed`` (ISSUE 15 tentpole).

One process per host.  ``initialize_pod`` brings up the coordination
service (and, on CPU, the gloo collectives backend — XLA:CPU's default
client refuses multi-process computations, which is why the 2-process
CI smoke ever works at all); ``make_pod_mesh`` builds ONE global mesh
over (hosts x local devices) with hosts on the OUTER slice axis of
parallel/mesh.py — DCN-crossing, and by the sharded layout's design
carrying ZERO collectives: the tally's quorum psums stay on the
intra-host val axis, so a pod step communicates exactly as much
across hosts as a single-host step does (nothing).

The driver subclasses DeviceDriver with ``I = the host's instance
slice``: the per-host serve plane (admission, batching, densify)
builds everything at LOCAL shape exactly as a single-host deployment
would, and this class lifts the host-local arrays into global jax
Arrays at the dispatch boundary (``jax.make_array_from_process_local_
data`` against the SAME PartitionSpec trees the shard_map wrappers
use — parallel/sharded.seq_in_specs/dense_lane_specs, one source of
truth).  Outputs come back as global arrays; the driver reads ONLY
its addressable block (``fetch_local_block``), so stats, decisions
and reject settlement stay host-local and fetch-free across hosts.

Lockstep: a pod dispatch is a pod-wide program — every host must
launch the same entries in the same order.  With a PodCoordinator
attached, every dispatch first ``agree()``s on a digest of its plan
(entry, statics, local signature); divergence fails loudly on every
host instead of wedging the fabric (distributed/pod.py docstring).

step()/step_seq()/the canned offline scenarios are deliberately
NotImplemented here: the pod driver exists for the serve plane's
``step_async`` path (the offline differential runs single-process —
that's the acceptance bar it is compared against).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from agnes_tpu.distributed.topology import HostPlan
from agnes_tpu.harness.device_driver import DeviceDriver


from agnes_tpu.distributed.pod import initialize_pod  # noqa: F401
#                      ^ re-export: lives in pod.py (the light module
#                        a worker can import BEFORE the backend pins)


def make_pod_mesh(n_val: int = 1, devices=None):
    """The pod's ONE global mesh: (slice=n_hosts, data=local/n_val,
    val=n_val) with hosts on the slice axis.  Requires jax's global
    device enumeration to be host-major (it is: devices sort by
    process index first) — asserted, because an interleaved grid
    would silently scatter each host's instance block across the pod
    and every "local" fetch would be wrong."""
    import jax

    from agnes_tpu.parallel.mesh import make_hierarchical_mesh

    devs = list(jax.devices()) if devices is None else list(devices)
    n_hosts = jax.process_count()
    if len(devs) % n_hosts:
        raise ValueError(f"{len(devs)} devices do not split over "
                         f"{n_hosts} hosts")
    per_host = len(devs) // n_hosts
    for k, d in enumerate(devs):
        if d.process_index != k // per_host:
            raise ValueError(
                "device enumeration is not host-major: device "
                f"{k} belongs to process {d.process_index}, expected "
                f"{k // per_host} — build the mesh from an explicitly "
                f"grouped device list")
    if per_host % n_val:
        raise ValueError(f"{per_host} local devices do not split into "
                         f"val={n_val}")
    return make_hierarchical_mesh(n_hosts, per_host // n_val, n_val,
                                  devs)


def _shifted_slices(index, offsets, global_shape):
    """A global shard `index` (tuple of slices, Nones = whole dim)
    rebased into a local block that starts at `offsets` — THE one
    place the contiguous-host-block layout arithmetic lives, shared
    by the output fetch and the dispatch lift so the two can never
    disagree about where a host's block sits in the global array."""
    return tuple(
        slice((ix.start or 0) - off,
              (ix.stop if ix.stop is not None else dim) - off)
        for ix, off, dim in zip(index, offsets, global_shape))


def fetch_local_block(x) -> np.ndarray:
    """This process's addressable block of a (possibly global) array,
    as numpy.  Fully-addressable arrays (single-host pods, host
    numpy) fetch whole; multi-host arrays assemble the host's
    contiguous region from its addressable shards (replicated shards
    overlap-write identical bytes — harmless)."""
    if not hasattr(x, "addressable_shards") or \
            getattr(x, "is_fully_addressable", True):
        return np.asarray(x)  # lint: allow (host/local fetch by contract)
    shards = list(x.addressable_shards)
    ndim = x.ndim
    lo = [min((s.index[a].start or 0) for s in shards)
          for a in range(ndim)]
    hi = [max((s.index[a].stop if s.index[a].stop is not None
               else x.shape[a]) for s in shards) for a in range(ndim)]
    out = np.empty([h - l for l, h in zip(lo, hi)], x.dtype)
    for s in shards:
        sel = _shifted_slices(s.index, lo, x.shape)
        out[sel] = np.asarray(s.data)  # lint: allow (addressable shard)
    return out


class _LocalRejects:
    """Lazy view of a pod dispatch's [global_I] rejected-lane count
    that materializes only THIS host's block — the serve pipeline's
    dedup-cache gate does ``np.asarray(rejects).sum()`` at settle,
    and a host's cache holds only digests of its own admitted lanes,
    so the local block is exactly the verdict that gates them."""

    def __init__(self, global_counts):
        self._x = global_counts

    def __array__(self, dtype=None, copy=None):
        block = fetch_local_block(self._x)
        return block.astype(dtype) if dtype is not None else block


class DistributedDriver(DeviceDriver):
    """DeviceDriver lifted to a (hosts x local devices) pod (module
    docstring).  `n_instances` is the GLOBAL deployment figure; the
    instance block this host owns (`HostPlan`) becomes `self.I`, so
    the whole serve plane composes unchanged at host-local shape."""

    def __init__(self, n_instances: int, n_validators: int,
                 n_rounds: int = 4, n_slots: int = 4,
                 proposer_is_self: bool = True,
                 advance_height: bool = False,
                 defer_collect: bool = False,
                 verify_chunk=None, hbm_budget_bytes: int = None,
                 audit: bool = False,
                 n_val: int = 1, mesh=None,
                 coordinator=None, lockstep_check: bool = True):
        import jax

        from agnes_tpu.parallel import sharded as _sh

        self.n_hosts = jax.process_count()
        self.process_index = jax.process_index()
        self.plan = HostPlan(self.n_hosts, n_instances)
        self.global_I = int(n_instances)
        self.coordinator = coordinator
        self.lockstep_check = bool(lockstep_check)
        pod_mesh = mesh if mesh is not None else make_pod_mesh(n_val)
        if n_validators % n_val:
            raise ValueError(f"V={n_validators} does not shard over "
                             f"val={n_val}")
        # build everything host-LOCAL through the parent (mesh=None so
        # its single-device placement path never device_puts onto a
        # non-addressable sharding), then lift state onto the pod
        super().__init__(self.plan.local_instances, n_validators,
                         n_rounds=n_rounds, n_slots=n_slots,
                         proposer_is_self=proposer_is_self,
                         advance_height=advance_height,
                         defer_collect=defer_collect,
                         verify_chunk=verify_chunk,
                         hbm_budget_bytes=hbm_budget_bytes,
                         audit=audit, mesh=None)
        self.mesh = pod_mesh
        self._sh = _sh
        self._seq_specs = _sh.seq_in_specs(pod_mesh)
        self._dense_specs = _sh.dense_lane_specs(pod_mesh)
        self._sharded_signed_cache = {}
        self._seq_fn_cache = {}
        self._copy_fn = None
        # replicated-over-hosts operands stay HOST numpy: jit shards an
        # uncommitted array per the in_specs, and numpy is the one form
        # that is never committed to a wrong (single-device) sharding
        self.powers = np.ones((self.V,), np.int32)
        self.total = np.asarray(self.V, np.int32)
        # instance-dim operands lift: each host contributes its block
        self.proposer_flag = self._lift(
            np.full((self.I, n_rounds), proposer_is_self, bool),
            self._seq_specs[6])
        self.propose_value = self._lift(np.full((self.I,), 1, np.int32),
                                        self._seq_specs[7])
        self.state = self._lift_tree(
            jax.tree.map(np.asarray, self.state), self._seq_specs[0])
        self.tally = self._lift_tree(
            jax.tree.map(np.asarray, self.tally), self._seq_specs[1])

    # -- global-array plumbing -----------------------------------------------

    def _global_shape(self, local_shape, spec) -> Tuple[int, ...]:
        """Local block shape -> global shape: only the slice axis
        crosses processes, so a dim sharded on it scales by
        n_hosts."""
        from agnes_tpu.parallel.mesh import SLICE_AXIS

        return tuple(
            dim * (self.n_hosts
                   if SLICE_AXIS in self._spec_dim_axes(spec, a)
                   else 1)
            for a, dim in enumerate(local_shape))

    def _lift(self, local, spec):
        """Host-local block -> global jax Array on the pod mesh.

        Two paths, chosen by where the block lives: HOST (numpy)
        blocks assemble via make_array_from_process_local_data;
        DEVICE-RESIDENT blocks (the serve plane's freshly built
        phases/lanes are jnp arrays) scatter per-device pieces with
        local device_puts + make_array_from_single_device_arrays —
        never a device->host fetch, because this runs per dispatch on
        the pod hot path and on real hardware np.asarray here would
        be a blocking HBM round trip of the very tensors the host
        just uploaded."""
        import jax
        from jax.sharding import NamedSharding

        sharding = NamedSharding(self.mesh, spec)
        if self.n_hosts == 1:
            return jax.device_put(local, sharding)
        if not isinstance(local, jax.Array):
            local = np.asarray(local)  # lint: allow (host-built block by contract)
            return jax.make_array_from_process_local_data(
                sharding, local, self._global_shape(local.shape,
                                                    spec))
        gshape = self._global_shape(local.shape, spec)
        offs = self._host_offsets(local.shape, spec)
        pieces = []
        for dev, idx in sharding.addressable_devices_indices_map(
                gshape).items():
            sel = _shifted_slices(idx, offs, gshape)
            pieces.append(jax.device_put(local[sel], dev))
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, pieces)

    @staticmethod
    def _spec_dim_axes(spec, a):
        """The mesh-axis set sharding dim `a` of `spec`, normalized
        to a tuple (shared by _global_shape/_host_offsets so the
        slice-axis test can never diverge between them)."""
        spec_t = tuple(spec)
        axes = spec_t[a] if a < len(spec_t) else None
        return (axes,) if isinstance(axes, str) else (axes or ())

    def _host_offsets(self, local_shape, spec):
        """Per-dim global offset of this host's block (nonzero only
        on slice-sharded dims — the instance axes)."""
        from agnes_tpu.parallel.mesh import SLICE_AXIS

        return [dim * self.process_index
                if SLICE_AXIS in self._spec_dim_axes(spec, a) else 0
                for a, dim in enumerate(local_shape)]

    def _lift_tree(self, tree, spec_tree):
        import jax
        from jax.sharding import PartitionSpec

        return jax.tree.map(self._lift, tree, spec_tree,
                            is_leaf=lambda x: isinstance(
                                x, PartitionSpec))

    def _agree(self, entry: str, statics, sig) -> None:
        """Pre-dispatch lockstep check (module docstring): digest the
        plan every host is about to launch; mismatch fails loudly on
        every host (PodDivergenceError)."""
        if (self.coordinator is not None and self.lockstep_check
                and self.n_hosts > 1):
            self.coordinator.agree((entry, tuple(statics), sig))

    def _plan_sig(self, args) -> tuple:
        """Cheap shape/dtype tag of the LOCAL args (identical across
        hosts iff the hosts' builds agree — local slices are
        same-shaped by the HostPlan's even split)."""
        import jax

        return tuple((tuple(getattr(x, "shape", ())),
                      str(getattr(x, "dtype", type(x).__name__)))
                     for x in jax.tree_util.tree_leaves(args))

    # -- dispatch (the step_async surface) -----------------------------------

    def _dense_dispatch_fn(self, n_dense_phases: int, donate: bool):
        from agnes_tpu.device import registry as _registry

        chunk = self._resolve_dense_chunk(n_dense_phases)
        key = (chunk, bool(donate))
        if key not in self._sharded_signed_cache:
            self._sharded_signed_cache[key] = \
                self._sh.make_sharded_step_seq_signed(
                    self.mesh, advance_height=self.advance_height,
                    verify_chunk=chunk, donate=donate)
        fn = self._sharded_signed_cache[key]

        def dispatch(st, ta, ex, ph, dn):
            largs = (st, ta, ex, ph, dn, self.powers, self.total,
                     self.proposer_flag, self.propose_value)
            self._observe("sharded_step_seq_signed", largs,
                          (self.advance_height, chunk, donate))
            self._agree("sharded_step_seq_signed",
                        (self.advance_height, chunk, donate),
                        self._plan_sig((ex, ph, dn)))
            ex_g = self._lift_tree(ex, self._seq_specs[2])
            ph_g = self._lift_tree(ph, self._seq_specs[3])
            dn_g = self._lift_tree(dn, self._dense_specs)
            return _registry.timed_call(
                "sharded_step_seq_signed", fn, st, ta, ex_g, ph_g,
                dn_g, self.powers, self.total, self.proposer_flag,
                self.propose_value)

        return dispatch

    def _make_sharded_seq(self, mesh, advance_height: bool = False,
                          donate: bool = False):
        """The unsigned sharded sequence entry (pre-verified/unsigned
        builds), lifted the same way.  Bound-method override of the
        attribute the parent's mesh branch installs."""
        key = (bool(advance_height), bool(donate))
        if key not in self._seq_fn_cache:
            self._seq_fn_cache[key] = self._sh.make_sharded_step_seq(
                mesh, advance_height=advance_height, donate=donate)
        fn = self._seq_fn_cache[key]

        def call(st, ta, ex, ph, powers, total, prop, pv):
            self._agree("sharded_step_seq",
                        (advance_height, donate),
                        self._plan_sig((ex, ph)))
            ex_g = self._lift_tree(ex, self._seq_specs[2])
            ph_g = self._lift_tree(ph, self._seq_specs[3])
            return fn(st, ta, ex_g, ph_g, powers, total, prop, pv)

        return call

    # -- local views of global outputs ---------------------------------------

    def step_async(self, phases, lanes=None, exts=None,
                   donate: bool = True, tick: Optional[int] = None):
        msgs = super().step_async(phases, lanes, exts, donate=donate,
                                  tick=tick)
        if self.last_step_rejects is not None:
            # the serve pipeline's settle gate reads this with
            # np.asarray — hand it a lazily-local view (class doc)
            self.last_step_rejects = _LocalRejects(
                self.last_step_rejects)
        return msgs

    def _collect(self, msgs) -> None:
        import jax

        super()._collect(jax.tree.map(fetch_local_block, msgs))

    def _settle_rejects(self) -> None:
        rejects, self._pending_rejects = self._pending_rejects, []
        for r in rejects:
            n = int(np.asarray(r).sum() if isinstance(r, _LocalRejects)
                    else fetch_local_block(r).sum())
            self.rejected_signature_device += n
            self.stats.votes_ingested -= n

    def _local_shape(self, n_live=None):
        from agnes_tpu.utils.budget import mesh_local_shape

        # self.I is the STATIC per-host slice (the host plan divided
        # the deployment before this driver saw it — ISSUE 15).  With
        # a shrunken LIVE membership (ISSUE 17) a surviving owner
        # serves the bigger slice I * n_hosts / live, spread over the
        # mesh's data extent / live columns — so scale I up HERE and
        # let mesh_local_shape's live divisor cancel it: the
        # per-device figure stays invariant under membership changes
        # (the global SPMD mesh never shrinks).  Passing the static
        # slice with a live divisor would under-claim per-device
        # instances by live/n_hosts — the HBM bound would pass on a
        # shape the full deployment OOMs at.
        live = self.n_hosts if n_live is None else int(n_live)
        if live < 1 or (self.I * self.n_hosts) % live:
            raise ValueError(
                f"{self.I * self.n_hosts} instances do not "
                f"repartition evenly over {live} live host(s)")
        return mesh_local_shape(self.mesh, self.I * self.n_hosts // live,
                                self.V, n_hosts=self.n_hosts,
                                n_live=live)

    def state_copies(self):
        """Warmup's throwaway state/tally copies, as a jitted pod
        computation: an EAGER per-leaf .copy() on a multi-host array
        is an unsupported eager op, and warmup runs at the same point
        on every host, so a jitted copy is both legal and lockstep."""
        if self.n_hosts == 1:
            return super().state_copies()
        import jax

        if self._copy_fn is None:
            self._copy_fn = jax.jit(
                lambda s, t: jax.tree.map(lambda x: x.copy(), (s, t)))
        return self._copy_fn(self.state, self.tally)

    def set_validators(self, powers) -> None:
        pw = np.asarray(powers)
        if pw.shape != (self.V,):
            raise ValueError(f"powers must be [{self.V}], got "
                             f"{pw.shape}")
        self.powers = pw.astype(np.int32)
        self.total = np.asarray(int(pw.sum()), np.int32)

    def set_proposer_table(self, flags, rotation_period: int) -> None:
        raise NotImplementedError(
            "proposer tables on a pod driver: lift flags per host "
            "(not yet wired — the serve plane uses the constant "
            "default)")

    # -- offline surfaces: single-process only -------------------------------

    def _pod_only(self, what: str):
        raise NotImplementedError(
            f"{what} is a single-process surface; the pod driver "
            f"serves through step_async (module docstring)")

    def step(self, ext=None, phase=None):
        self._pod_only("step()")

    def step_seq(self, phases, exts=None):
        self._pod_only("step_seq()")

    def step_seq_signed(self, phases, lanes, exts=None):
        self._pod_only("step_seq_signed()")

    def step_seq_signed_dense(self, phases, dense, exts=None):
        self._pod_only("step_seq_signed_dense()")

    def run_heights_fused(self, n_heights: int, slot: int = 1,
                          frac: float = 1.0):
        self._pod_only("run_heights_fused()")
