"""Spawnable multi-host serve worker + pod spawner (ISSUE 15).

THE one executable the 2-process CPU CI path runs for real — shared
by the bench probe (`_pipeline_serve_multihost`), the ci.sh gate and
the slow differential test, so all three exercise the identical
worker:

  python -m agnes_tpu.distributed.smoke --mode pod --pid 0 \
      --n-processes 2 --coordinator localhost:PORT ...

Three modes, each dumping a result JSON (and optionally the final
state/tally as .npz) so a jax-free parent can compare planes
leaf-for-leaf:

* ``pod``     one pod process: jax.distributed + gloo CPU
              collectives over faked local devices, DistributedDriver
              + HostShard height-paced serve, per-host heartbeat,
              warmup barrier, per-height decision gathers, drain.
              Dumps this host's LOCAL state/tally block.
* ``elastic`` the same deployment through ElasticShard's negotiated
              ticks (ISSUE 17): heterogeneous per-host traffic padded
              to the per-tick max, plus one host leave + rejoin cycle
              across membership epoch boundaries.
* ``single``  the SAME deployment served by ONE process over the
              same-shaped (hierarchical) mesh — the single-host mesh
              serve plane the differential compares against.  Dumps
              the full global state/tally.
* ``offline`` the offline fused reference (VoteBatcher dense build ->
              step_seq_signed_dense on one device) — the third plane
              of the acceptance differential.

Environment discipline: main() pins XLA_FLAGS (forced host device
count + the single-threaded-codegen workaround), JAX_PLATFORMS=cpu
and the in-process config BEFORE any backend init — the same
two-step tests/conftest.py uses, because this environment's
sitecustomize forces an axon TPU platform.

``spawn_pod`` is the parent-side helper: picks a coordinator port,
launches N workers, enforces a wall-clock deadline (SIGKILL on
breach — a wedged pod must never outlive its budget), and returns
each worker's parsed result record.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time
from typing import List, Optional

# NOTE: numpy/agnes imports stay inside the run functions — main()
# must fix the environment before anything can touch a jax backend.

PV, PC = 0, 1                   # VoteType.{PREVOTE,PRECOMMIT} values


def _setup_env(devices: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags
                 + f" --xla_force_host_platform_device_count={devices}"
                 ).strip()
    if "xla_cpu_parallel_codegen_split_count" not in flags:
        # the XLA:CPU codegen/serialization race workaround
        # (utils/compile_cache.py has the post-mortem)
        flags = (flags
                 + " --xla_cpu_parallel_codegen_split_count=1").strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"


def _setup_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from agnes_tpu.utils.compile_cache import disable_persistent_cache

    disable_persistent_cache()
    return jax


def _wire_height(I: int, V: int, seeds, h: int) -> bytes:
    """Both vote classes of one honest pod-wide height (GLOBAL ids)."""
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.harness.fixtures import full_mesh_cols

    return b"".join(
        pack_wire_votes(*full_mesh_cols(I, V, seeds, h, typ, 7))
        for typ in (PV, PC))


def _dump_state(npz_path: str, driver, local: bool) -> None:
    """state/tally (+ decision stats) -> npz.  `local=True` dumps
    this host's block (distributed/driver.fetch_local_block); the
    parent concatenates blocks host-major, which IS global instance
    order because the pod mesh puts hosts on the outer data axis."""
    import numpy as np

    from agnes_tpu.distributed.driver import fetch_local_block

    fetch = fetch_local_block if local else \
        (lambda x: np.asarray(x))
    out = {}
    for name, leaf in zip(type(driver.state)._fields, driver.state):
        out[f"state_{name}"] = fetch(leaf)
    for name, leaf in zip(type(driver.tally)._fields, driver.tally):
        out[f"tally_{name}"] = fetch(leaf)
    out["decided"] = driver.stats.decided
    out["decision_value"] = driver.stats.decision_value
    out["decision_round"] = driver.stats.decision_round
    np.savez(npz_path, **out)


def _result(path: str, rec: dict) -> None:
    with open(path, "w") as f:
        json.dump(rec, f, sort_keys=True)
        f.write("\n")


def run_pod_worker(args) -> dict:
    """One pod process's serve loop (module docstring).  Import
    order is load-bearing: jax.distributed must initialize before
    ANY backend use, and the heavyweight agnes imports (device/step,
    crypto) build device constants at import — so initialize_pod runs
    first, against the minimal distributed.driver import (which
    defers its own serve-stack imports)."""
    import numpy as np

    _setup_jax()
    from agnes_tpu.distributed.pod import initialize_pod

    pid, I, V = args.pid, args.instances, args.validators
    initialize_pod(args.coordinator, args.n_processes, pid)
    from agnes_tpu.distributed.driver import DistributedDriver
    from agnes_tpu.distributed.shard import HostShard
    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        validator_pubkeys,
    )
    from agnes_tpu.serve import ShapeLadder
    from agnes_tpu.utils.flightrec import FlightRecorder, Heartbeat
    flightrec = FlightRecorder()
    hb = None
    if args.heartbeat:
        hb = Heartbeat(args.heartbeat, interval_s=args.hb_interval,
                       recorder=flightrec, host_id=pid).start()
    d = DistributedDriver(I, V, advance_height=True,
                          defer_collect=True, audit=True,
                          n_val=args.n_val)
    n_local = d.I * V
    box = {"h": 0}
    shard = HostShard(
        d, VoteBatcher(d.I, V, n_slots=4),
        validator_pubkeys(deterministic_seeds(V)),
        capacity=4 * 2 * n_local, target_votes=2 * n_local,
        max_delay_s=1e9,                 # size-closed batches
        ladder=ShapeLadder.plan_dense(
            I, V, local_shape=d._local_shape(), n_hosts=d.n_hosts,
            min_rung=1 << (2 * n_local - 1).bit_length()),
        window_predictor=lambda: (np.zeros(d.I, np.int64),
                                  np.full(d.I, box["h"], np.int64)),
        flightrec=flightrec,
        native_admission=args.native_admission)
    if hb is not None:
        hb.sources.append(lambda: shard.metrics.snapshot(
            window=True, window_key="heartbeat"))
    # barrier-synchronized warmup: P=3 (entry + both classes) is the
    # only shape honest height-paced traffic dispatches; each host's
    # sentinel then ARMS the no-recompile invariant
    warmed = shard.warmup(n_phases=(3,), arm=True)

    seeds = deterministic_seeds(V)

    def feed(h: int, wire: bytes, budget_s: float = 3600.0) -> None:
        box["h"] = h
        res = shard.submit(wire)
        if res.accepted != 2 * n_local:
            raise RuntimeError(
                f"host {pid} admitted {res.accepted} of the expected "
                f"{2 * n_local} local records at height {h}: {res}")
        want = 2 * n_local * (h + 1)
        t_end = time.monotonic() + budget_s
        while shard.pipeline.dispatched_votes < want:
            shard.pump()
            if time.monotonic() > t_end:
                raise RuntimeError(
                    f"host {pid} stalled at height {h}: "
                    f"{shard.pipeline.dispatched_votes}/{want}")

    # height 0: the (warmed) steady shape's first real traffic
    feed(0, _wire_height(I, V, seeds, 0))
    pod0 = shard.poll_pod_decisions()
    if len(pod0) != I:
        raise RuntimeError(f"host {pid}: height-0 gather surfaced "
                           f"{len(pod0)} decisions, expected {I}")

    all_wire = [_wire_height(I, V, seeds, h)
                for h in range(1, args.heights + 1)]
    t0 = time.perf_counter()
    for h in range(1, args.heights + 1):
        feed(h, all_wire[h - 1])
    shard.poll_pod_decisions()       # settle + lockstep gather
    dt = time.perf_counter() - t0
    rep = shard.drain()
    if hb is not None:
        hb.stop()
    retrace = d.sentinel.metrics.counters.get("retrace_unexpected", 0)
    if args.state_npz:
        _dump_state(args.state_npz, d, local=True)
    from agnes_tpu.device import registry as _registry

    rate = 2 * I * V * args.heights / dt     # pod-wide votes/sec
    return {
        "mode": "pod", "host": pid, "n_hosts": d.n_hosts,
        "devices_per_host": args.devices_per_host,
        "instances": I, "validators": V, "heights": args.heights,
        "local_instances": d.I,
        "votes_per_sec": round(rate, 1),
        "decisions_total": d.stats.decisions_total,
        "pod_decisions": len(shard.pod_decisions),
        "pod_decision_rows": sorted(
            [pd.instance, pd.host, pd.round,
             -1 if pd.value_id is None else pd.value_id]
            for pd in shard.pod_decisions),
        "foreign_rejects": shard.foreign_rejects,
        "rejected_signature_device": d.rejected_signature_device,
        "retrace_unexpected": int(retrace),
        "warmed_shapes": warmed,
        "offladder_builds": rep["offladder_builds"],
        "host_fallback_builds": rep["host_fallback_builds"],
        "agrees": rep["pod"]["agrees"],
        "barriers": rep["pod"]["barriers"],
        "native_admission": bool(args.native_admission),
        "compile_entries": sorted(_registry.compile_ms()),
        "heartbeat_path": args.heartbeat or None,
    }


def _wire_range(I: int, V: int, seeds, h: int, lo: int, hi: int,
                typs) -> bytes:
    """One height's honest wire for instances [lo, hi) only, per
    class — the per-host traffic split the elastic smoke routes."""
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.harness.fixtures import full_mesh_cols

    parts = []
    for typ in typs:
        cols = full_mesh_cols(I, V, seeds, h, typ, 7)
        keep = (cols[0] >= lo) & (cols[0] < hi)
        parts.append(pack_wire_votes(*(c[keep] for c in cols)))
    return b"".join(parts)


def run_elastic_worker(args) -> dict:
    """One ELASTIC pod process (ISSUE 17): the same deployment as
    ``pod`` mode but served through ElasticShard's negotiated ticks —
    deliberately HETEROGENEOUS per-host traffic (host 0 splits each
    height's two vote classes across two ticks while host 1 submits
    both at once, so the staged plans disagree every tick and the
    per-tick max-merge + padding is what keeps the pod lockstep —
    every height but the last, which both hosts serve split-class so
    the final state snapshot comes from a quiesced pod) plus one
    host leave + rejoin cycle across epoch boundaries:

      height `leave_height - 1`, last tick: host 1 latches its leave
      height `leave_height` boundary: repartition, host 1 sleeps —
          its process keeps ticking (pure padding), host 0 adopts its
          ranges and HOLDS its gossip
      height `rejoin_height - 1`, last tick: host 1 latches rejoin
      height `rejoin_height` boundary: readmission; host 0's held
          bytes re-route through the SAME tick's frame; catch-up
          ticks replay them in height order before live traffic
          resumes

    The tick schedule is a pure function of the shared args — every
    process executes the identical collective sequence, which is the
    lockstep contract.  Requires n_processes == 2 when the cycle is
    enabled (the held-gossip routing sends the sleeper's traffic to
    THE surviving host)."""
    import numpy as np

    _setup_jax()
    from agnes_tpu.distributed.pod import initialize_pod

    pid, I, V = args.pid, args.instances, args.validators
    initialize_pod(args.coordinator, args.n_processes, pid)
    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.distributed.driver import DistributedDriver
    from agnes_tpu.distributed.elastic import ElasticShard
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        validator_pubkeys,
    )
    from agnes_tpu.serve import ShapeLadder
    from agnes_tpu.utils.flightrec import FlightRecorder, Heartbeat

    leave_h, rejoin_h = args.leave_height, args.rejoin_height
    churn = 0 <= leave_h < rejoin_h <= args.heights
    if churn and args.n_processes != 2:
        raise RuntimeError("the elastic leave/rejoin smoke choreographs "
                           "a 2-process pod")
    flightrec = FlightRecorder()
    hb = None
    if args.heartbeat:
        hb = Heartbeat(args.heartbeat, interval_s=args.hb_interval,
                       recorder=flightrec, host_id=pid).start()
    d = DistributedDriver(I, V, advance_height=True,
                          defer_collect=True, audit=True,
                          n_val=args.n_val)
    n_local = d.I * V
    box = {"h": 0}
    shard = ElasticShard(
        d, VoteBatcher(d.I, V, n_slots=4),
        validator_pubkeys(deterministic_seeds(V)),
        capacity=4 * 2 * n_local, target_votes=2 * n_local,
        max_delay_s=1e9,                 # ticks close every batch
        ladder=ShapeLadder.plan_dense(
            I, V, local_shape=d._local_shape(), n_hosts=d.n_hosts,
            min_rung=1 << (2 * n_local - 1).bit_length()),
        window_predictor=lambda: (np.zeros(d.I, np.int64),
                                  np.full(d.I, box["h"], np.int64)),
        flightrec=flightrec,
        native_admission=args.native_admission)
    if hb is not None:
        hb.sources.append(lambda: shard.metrics.snapshot(
            window=True, window_key="heartbeat"))
    # honest heterogeneous traffic dispatches P=2 (entry + one class)
    # AND P=3 (entry + both classes); warm BOTH, then arm — padding up
    # to the negotiated max must never buy a live compile
    warmed = shard.warmup(n_phases=(2, 3), arm=True)

    seeds = deterministic_seeds(V)
    sleeper = args.n_processes - 1
    lo_s, hi_s = shard.plan.instance_range(sleeper)
    PV_PC = (PV, PC)
    ticks: List[dict] = []

    def tick(boundary: bool = False) -> dict:
        res = shard.tick(boundary=boundary)
        ticks.append(res)
        return res

    t0 = time.perf_counter()
    for h in range(args.heights + 1):
        # A: the height edge IS the epoch boundary (lockstep point)
        tick(boundary=True)
        if churn and h == rejoin_h:
            # catch-up: the boundary tick above re-routed the held
            # wire to the readmitted owner; replay it height by
            # height (the sleeper paces its window through the gap,
            # the survivor ticks along staging nothing)
            for hh in range(leave_h, rejoin_h):
                if pid == sleeper:
                    box["h"] = hh
                tick()
        asleep = churn and pid == sleeper and leave_h <= h < rejoin_h
        if not asleep:
            box["h"] = h
        # the FINAL height is served homogeneously (both hosts split
        # classes): the state snapshot must come from a quiesced pod —
        # a padding dispatch after a host's final decide would leave
        # its intra-height phase cursors (state_step / tally_q_*)
        # ahead of the static planes' while changing no decision
        hetero = h != args.heights
        if asleep:
            tick()                       # B: pure padding
        elif pid == sleeper and hetero:
            shard.submit(_wire_range(I, V, seeds, h, shard.lo,
                                     shard.hi, PV_PC))
            tick()                       # B: P=3 (both classes)
        else:
            shard.submit(_wire_range(I, V, seeds, h, shard.lo,
                                     shard.hi, (PV,)))
            if churn and pid == 0 and leave_h <= h < rejoin_h:
                # route the sleeper's traffic at its OWN host: the
                # adopted ranges hold it for the readmission re-route
                shard.submit(_wire_range(I, V, seeds, h, lo_s, hi_s,
                                         PV_PC))
            tick()                       # B: P=2 (prevotes)
        # intents latch on the LAST tick of the height before the
        # boundary that applies them — the join one tick early, so
        # the re-route can ride the boundary tick's frame (the
        # survivor's prospective view must already include the
        # rejoiner when it packs)
        if churn and pid == sleeper:
            if h == leave_h - 1:
                shard.announce_leave()
            if h == rejoin_h - 1:
                shard.announce_join()
        if asleep or (pid == sleeper and hetero):
            tick()                       # C: padding (nothing staged)
        else:
            shard.submit(_wire_range(I, V, seeds, h, shard.lo,
                                     shard.hi, (PC,)))
            tick()                       # C: P=2 (precommits)
    for _ in range(3):                   # settle + latch + gather
        tick()
    dt = time.perf_counter() - t0
    rep = shard.drain()
    if hb is not None:
        hb.stop()
    retrace = d.sentinel.metrics.counters.get("retrace_unexpected", 0)
    if args.state_npz:
        _dump_state(args.state_npz, d, local=True)
    from agnes_tpu.device import registry as _registry

    ela = rep["pod"]["elastic"]
    rate = 2 * I * V * (args.heights + 1) / dt   # pod-wide votes/sec
    return {
        "mode": "elastic", "host": pid, "n_hosts": d.n_hosts,
        "devices_per_host": args.devices_per_host,
        "instances": I, "validators": V, "heights": args.heights,
        "local_instances": d.I,
        "leave_height": leave_h if churn else -1,
        "rejoin_height": rejoin_h if churn else -1,
        "votes_per_sec": round(rate, 1),
        "decisions_total": d.stats.decisions_total,
        "pod_decisions": len(shard.pod_decisions),
        "pod_decision_rows": sorted(
            [pd.instance, pd.height, pd.round,
             -1 if pd.value_id is None else pd.value_id]
            for pd in shard.pod_decisions),
        "foreign_rejects": shard.foreign_rejects,
        "rejected_signature_device": d.rejected_signature_device,
        "retrace_unexpected": int(retrace),
        "warmed_shapes": warmed,
        "offladder_builds": rep["offladder_builds"],
        "host_fallback_builds": rep["host_fallback_builds"],
        "agrees": rep["pod"]["agrees"],
        "barriers": rep["pod"]["barriers"],
        "native_admission": bool(args.native_admission),
        "compile_entries": sorted(_registry.compile_ms()),
        "heartbeat_path": args.heartbeat or None,
        # the elastic section (negotiation + membership evidence the
        # gate/test assert on)
        "negotiation_ticks": ela["negotiation_ticks"],
        "ticks_dispatched": sum(t["dispatched"] for t in ticks),
        "ticks_padded": sum(t["padded"] for t in ticks),
        "padded_slots": ela["padded_slots"],
        "pad_builds": ela["pad_builds"],
        "padded_phases": ela["padded_phases"],
        "boundaries": ela["boundaries"],
        "membership_epoch": ela["epoch"],
        "alive": ela["alive"],
        "readmissions": ela["readmissions"],
        "departures": ela["departures"],
        "adopted_held": ela["adopted_held"],
        "held_dropped": ela["held_dropped"],
        "held_pending": ela["held_pending"],
        "reroute_sent": ela["reroute_sent"],
        "reroute_received": ela["reroute_received"],
    }


def run_single_worker(args) -> dict:
    """The single-process mesh serve plane over the SAME global mesh
    shape (differential plane 2)."""
    import numpy as np

    _setup_jax()
    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        validator_pubkeys,
    )
    from agnes_tpu.parallel import make_hierarchical_mesh
    from agnes_tpu.serve import ShapeLadder, VoteService

    I, V = args.instances, args.validators
    dph = args.devices_per_host
    mesh = make_hierarchical_mesh(args.n_processes,
                                  dph // args.n_val, args.n_val)
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True,
                     mesh=mesh, audit=True)
    n = I * V
    box = {"h": 0}
    svc = VoteService(
        d, VoteBatcher(I, V, n_slots=4),
        validator_pubkeys(deterministic_seeds(V)),
        capacity=4 * 2 * n, target_votes=2 * n, max_delay_s=1e9,
        ladder=ShapeLadder.plan_dense(
            I, V, local_shape=d._local_shape(),
            min_rung=1 << (2 * n - 1).bit_length()),
        window_predictor=lambda: (np.zeros(I, np.int64),
                                  np.full(I, box["h"], np.int64)))
    svc.pipeline.warmup(n_phases=(3,), arm=True)
    seeds = deterministic_seeds(V)
    for h in range(args.heights + 1):
        box["h"] = h
        res = svc.submit(_wire_height(I, V, seeds, h))
        if res.accepted != 2 * n:
            raise RuntimeError(f"single plane admitted {res.accepted}")
        t_end = time.monotonic() + 3600
        while svc.pipeline.dispatched_votes < 2 * n * (h + 1):
            svc.pump()
            if time.monotonic() > t_end:
                raise RuntimeError(f"single plane stalled at {h}")
    rep = svc.drain()
    if args.state_npz:
        _dump_state(args.state_npz, d, local=False)
    return {
        "mode": "single", "decisions_total": d.stats.decisions_total,
        "rejected_signature_device": d.rejected_signature_device,
        "offladder_builds": rep["offladder_builds"],
    }


def run_offline_worker(args) -> dict:
    """The offline fused dense reference (differential plane 3)."""
    import numpy as np

    _setup_jax()
    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        full_mesh_cols,
        validator_pubkeys,
    )

    I, V = args.instances, args.validators
    seeds = deterministic_seeds(V)
    pubkeys = validator_pubkeys(seeds)
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bat = VoteBatcher(I, V, n_slots=4)
    for h in range(args.heights + 1):
        bat.sync_device(np.zeros(I, np.int64), np.full(I, h, np.int64))
        for typ in (PV, PC):
            bat.add_arrays(*full_mesh_cols(I, V, seeds, h, typ, 7))
        phases, dense = bat.build_phases_device_dense(pubkeys)
        if dense is None:
            raise RuntimeError("offline dense build fell back to host")
        d.step_seq_signed_dense([d.empty_phase()]
                                + [p for p, _ in phases], dense)
    d.block_until_ready()
    if args.state_npz:
        _dump_state(args.state_npz, d, local=False)
    return {
        "mode": "offline", "decisions_total": d.stats.decisions_total,
        "rejected_signature_device": d.rejected_signature_device,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m agnes_tpu.distributed.smoke")
    ap.add_argument("--mode",
                    choices=("pod", "elastic", "single", "offline"),
                    required=True)
    ap.add_argument("--pid", type=int, default=0)
    ap.add_argument("--n-processes", type=int, default=2)
    ap.add_argument("--coordinator", default="localhost:0")
    ap.add_argument("--instances", type=int, default=8)
    ap.add_argument("--validators", type=int, default=8)
    ap.add_argument("--heights", type=int, default=2)
    ap.add_argument("--devices-per-host", type=int, default=2)
    ap.add_argument("--n-val", type=int, default=2)
    ap.add_argument("--out", required=True,
                    help="result JSON path")
    ap.add_argument("--state-npz", default=None)
    ap.add_argument("--heartbeat", default=None)
    ap.add_argument("--hb-interval", type=float, default=1.0)
    ap.add_argument("--native-admission", action="store_true")
    ap.add_argument("--leave-height", type=int, default=-1,
                    help="elastic mode: the sleeper host departs at "
                         "this height's boundary (-1 = no churn)")
    ap.add_argument("--rejoin-height", type=int, default=-1,
                    help="elastic mode: readmission boundary height")
    args = ap.parse_args(argv)

    if args.mode == "pod":
        _setup_env(args.devices_per_host)
        run = run_pod_worker
    elif args.mode == "elastic":
        _setup_env(args.devices_per_host)
        run = run_elastic_worker
    elif args.mode == "single":
        _setup_env(args.n_processes * args.devices_per_host)
        run = run_single_worker
    else:
        _setup_env(1)
        run = run_offline_worker
    try:
        rec = run(args)
    except BaseException as e:  # noqa: BLE001 — the parent must see a
        import traceback        # record even when a worker dies

        traceback.print_exc(file=sys.stderr)
        _result(args.out, {"mode": args.mode, "host": args.pid,
                           "error": f"{type(e).__name__}: {e}"})
        return 1
    _result(args.out, rec)
    return 0


# -- parent-side spawner ------------------------------------------------------

def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _repo_root() -> str:
    import agnes_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(agnes_tpu.__file__)))


def _die_with_parent():
    """Child preexec: SIGKILL on parent death (PR_SET_PDEATHSIG — the
    bench probe-reaper discipline): a crash-safe parent that emits
    its sentinel and os._exit()s must never leave a 2-process pod
    spinning behind it."""
    try:
        import ctypes
        import signal as _sig

        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(1, _sig.SIGKILL, 0, 0, 0)   # 1 = PR_SET_PDEATHSIG
        if os.getppid() == 1:                  # parent already gone
            os._exit(1)
    except Exception:  # noqa: BLE001 — non-Linux: spawner deadline
        pass           # remains the only bound


def spawn_pod(n_processes: int = 2, *, instances: int = 8,
              validators: int = 8, heights: int = 2,
              devices_per_host: int = 2, n_val: int = 2,
              out_dir: str, timeout_s: float = 1200.0,
              heartbeat: bool = False, hb_interval: float = 1.0,
              dump_state: bool = False,
              native_admission: bool = False,
              elastic: bool = False, leave_height: int = -1,
              rejoin_height: int = -1,
              extra_modes: Optional[List[str]] = None) -> dict:
    """Launch the pod workers (+ optional `single`/`offline`
    comparison workers, each its own process — composing with the
    XLA:CPU child-interpreter discipline) under one wall-clock
    deadline; SIGKILL everything on breach.  `elastic=True` runs the
    pod workers through ElasticShard's negotiated ticks (mode
    ``elastic``) with an optional leave/rejoin cycle at the given
    boundary heights.  Returns {"pod": [rec per host],
    "single": rec?, "offline": rec?, "paths": {...}} with every
    record parsed from its worker's result JSON."""
    os.makedirs(out_dir, exist_ok=True)
    port = free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _repo_root() + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.pop("XLA_FLAGS", None)       # workers pin their own
    env.pop("JAX_PLATFORMS", None)

    def launch(mode: str, pid: int, tag: str):
        out = os.path.join(out_dir, f"{tag}.json")
        cmd = [sys.executable, "-m", "agnes_tpu.distributed.smoke",
               "--mode", mode, "--pid", str(pid),
               "--n-processes", str(n_processes),
               "--coordinator", f"localhost:{port}",
               "--instances", str(instances),
               "--validators", str(validators),
               "--heights", str(heights),
               "--devices-per-host", str(devices_per_host),
               "--n-val", str(n_val), "--out", out]
        if dump_state:
            cmd += ["--state-npz", os.path.join(out_dir, f"{tag}.npz")]
        if heartbeat and mode in ("pod", "elastic"):
            cmd += ["--heartbeat",
                    os.path.join(out_dir, f"heartbeat.{tag}.ndjson"),
                    "--hb-interval", str(hb_interval)]
        if native_admission and mode in ("pod", "elastic"):
            cmd.append("--native-admission")
        if mode == "elastic":
            cmd += ["--leave-height", str(leave_height),
                    "--rejoin-height", str(rejoin_height)]
        log = open(os.path.join(out_dir, f"{tag}.log"), "w")
        proc = subprocess.Popen(cmd, stdout=log, stderr=log, env=env,
                                cwd=_repo_root(),
                                preexec_fn=_die_with_parent)
        return tag, mode, out, proc, log

    pod_mode = "elastic" if elastic else "pod"
    jobs = [launch(pod_mode, k, f"pod{k}") for k in range(n_processes)]
    for mode in (extra_modes or ()):
        jobs.append(launch(mode, 0, mode))

    deadline = time.monotonic() + timeout_s
    killed = False
    for tag, mode, out, proc, log in jobs:
        rem = deadline - time.monotonic()
        try:
            proc.wait(timeout=max(0.1, rem))
        except subprocess.TimeoutExpired:
            killed = True
            break
    if killed:
        for _, _, _, proc, _ in jobs:
            if proc.poll() is None:
                proc.kill()
        for _, _, _, proc, _ in jobs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
    results: dict = {"pod": [], "paths": {}, "killed": killed}
    for tag, mode, out, proc, log in jobs:
        log.close()
        results["paths"][tag] = {
            "json": out, "log": os.path.join(out_dir, f"{tag}.log"),
            "npz": (os.path.join(out_dir, f"{tag}.npz")
                    if dump_state else None),
            "heartbeat": (os.path.join(out_dir,
                                       f"heartbeat.{tag}.ndjson")
                          if heartbeat and mode in ("pod", "elastic")
                          else None),
            "rc": proc.returncode,
        }
        try:
            with open(out) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            rec = {"mode": mode, "error":
                   f"no result record (rc={proc.returncode}"
                   + (", killed on deadline" if killed else "") + ")"}
        if mode in ("pod", "elastic"):
            results["pod"].append(rec)
        else:
            results[mode] = rec
    return results


if __name__ == "__main__":
    sys.exit(main())
