"""agnes-metrics: render flight-recorder heartbeat NDJSON trails into
a human postmortem summary (ISSUE 8 tentpole, layer 3; multi-host
merge, ISSUE 15).

The workflow after the NEXT wedged hardware round: the crash-safe
bench verdict record carries `heartbeat_path`; point this CLI at it
and read where the run was when it died —

  agnes-metrics BENCH_heartbeat.ndjson           # postmortem summary
  agnes-metrics --check heartbeat.ndjson         # schema gate (ci.sh)
  agnes-metrics --json heartbeat.ndjson          # machine summary
  agnes-metrics hb.host0.ndjson hb.host1.ndjson  # POD merge: per-host
                                                 # wedge timeline

`--check` exits nonzero when any file is missing, holds zero valid
lines, or any line fails the schema (utils/flightrec.REQUIRED_KEYS +
the v2 OPTIONAL_KEYS host stamp) — the ci.sh serve-smoke gates run it
over each smoke's heartbeat(s) so a format regression fails CI, not
the next post-mortem.  With SEVERAL paths, every file must pass
independently (a pod run must leave one parseable trail PER process).

Multiple paths without --check render the merged pod postmortem
(utils/flightrec.render_pod_postmortem): hosts ranked by last-beat
age — the first host to go quiet is where the wedge began.

JAX-FREE: imports only stdlib + utils.flightrec (itself stdlib-only),
so the CLI works on a box whose accelerator stack is the thing being
post-mortemed.  Console entry point `agnes-metrics` (pyproject) with
the historical `scripts/agnes_metrics.py` shim, like agnes-lint.
"""

from __future__ import annotations

import argparse
import json
import sys

from agnes_tpu.utils.flightrec import (
    read_heartbeat,
    render_pod_postmortem,
    render_postmortem,
)


def _check_one(path: str) -> int:
    """Schema-gate one trail (the historical --check semantics)."""
    try:
        lines, bad = read_heartbeat(path)
    except OSError as e:
        print(f"agnes-metrics: cannot read {path}: {e}",
              file=sys.stderr)
        return 2
    # ONE bad line that is the FILE'S LAST is the expected artifact
    # of abrupt death mid-write (SIGKILL / os._exit while the
    # heartbeat thread writes) — the exact scenario the recorder
    # exists to survive.  Tolerate precisely that; any interior bad
    # line, or a trail with no valid line, fails.
    with open(path) as f:
        n_raw = sum(1 for raw in f if raw.strip())
    trailing = (len(bad) == 1 and bool(lines) and bad[0][0] == n_raw)
    for i, why in bad:
        print(f"BAD line {i}: {why}"
              + (" (trailing — tolerated as a death-cut line)"
                 if trailing else ""), file=sys.stderr)
    if (bad and not trailing) or not lines:
        print(f"heartbeat check FAILED: {len(lines)} valid, "
              f"{len(bad)} bad line(s) in {path}", file=sys.stderr)
        return 1
    print(f"heartbeat check OK: {path}: {len(lines)} valid line(s), "
          f"schema v{lines[-1]['v']}, last seq {lines[-1]['seq']}"
          + (", 1 trailing death-cut line tolerated" if trailing
             else "")
          + (f", host_id {lines[-1]['host_id']}"
             if "host_id" in lines[-1] else ""))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="agnes-metrics",
        description="render / schema-check flight-recorder heartbeat "
                    "NDJSON trails (several paths = pod merge)")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="heartbeat NDJSON file(s) — one per pod "
                         "process")
    ap.add_argument("--check", action="store_true",
                    help="schema gate: exit nonzero unless every "
                         "file's every line parses and validates and "
                         "each file holds at least one valid line")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable summary instead of prose")
    args = ap.parse_args(argv)

    if args.check:
        rcs = [_check_one(p) for p in args.paths]
        return max(rcs)

    if args.as_json:
        files = []
        ok = True
        for path in args.paths:
            try:
                lines, bad = read_heartbeat(path)
            except OSError:
                files.append({"path": path, "valid_lines": 0,
                              "bad_lines": 0, "unreadable": True,
                              "first": None, "last": None})
                ok = False
                continue
            files.append({
                "path": path,
                "valid_lines": len(lines),
                "bad_lines": len(bad),
                "first": lines[0] if lines else None,
                "last": lines[-1] if lines else None,
            })
            ok = ok and bool(lines)
        summary = files[0] if len(files) == 1 else {"files": files}
        print(json.dumps(summary, sort_keys=True))
        return 0 if ok else 1

    if len(args.paths) == 1:
        path = args.paths[0]
        try:
            lines, bad = read_heartbeat(path)
        except OSError as e:
            print(f"agnes-metrics: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
        print(render_postmortem(path))
        return 0 if lines and not bad else 1

    print(render_pod_postmortem(args.paths))
    # rc mirrors the single-path render PER TRAIL: any unreadable
    # file -> 2, any file with bad lines or zero valid lines -> 1 — a
    # gating script keying on the render's rc must see a pod with one
    # corrupt/missing trail as unhealthy, exactly like the merge's
    # prose does
    worst = 0
    for path in args.paths:
        try:
            lines, bad = read_heartbeat(path)
        except OSError:
            worst = max(worst, 2)
            continue
        if bad or not lines:
            worst = max(worst, 1)
    return worst


if __name__ == "__main__":
    sys.exit(main())
