"""agnes-metrics: render a flight-recorder heartbeat NDJSON into a
human postmortem summary (ISSUE 8 tentpole, layer 3).

The workflow after the NEXT wedged hardware round: the crash-safe
bench verdict record carries `heartbeat_path`; point this CLI at it
and read where the run was when it died —

  agnes-metrics BENCH_heartbeat.ndjson           # postmortem summary
  agnes-metrics --check heartbeat.ndjson         # schema gate (ci.sh)
  agnes-metrics --json heartbeat.ndjson          # machine summary

`--check` exits nonzero when the file is missing, holds zero valid
lines, or any line fails the schema (utils/flightrec.REQUIRED_KEYS) —
the ci.sh serve-smoke gate runs it over the smoke's heartbeat so a
format regression fails CI, not the next post-mortem.

JAX-FREE: imports only stdlib + utils.flightrec (itself stdlib-only),
so the CLI works on a box whose accelerator stack is the thing being
post-mortemed.  Console entry point `agnes-metrics` (pyproject) with
the historical `scripts/agnes_metrics.py` shim, like agnes-lint.
"""

from __future__ import annotations

import argparse
import json
import sys

from agnes_tpu.utils.flightrec import (
    read_heartbeat,
    render_postmortem,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="agnes-metrics",
        description="render / schema-check a flight-recorder "
                    "heartbeat NDJSON")
    ap.add_argument("path", help="heartbeat NDJSON file")
    ap.add_argument("--check", action="store_true",
                    help="schema gate: exit nonzero unless every line "
                         "parses and validates and at least one valid "
                         "line exists")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable summary instead of prose")
    args = ap.parse_args(argv)

    try:
        lines, bad = read_heartbeat(args.path)
    except OSError as e:
        print(f"agnes-metrics: cannot read {args.path}: {e}",
              file=sys.stderr)
        return 2

    if args.check:
        # ONE bad line that is the FILE'S LAST is the expected
        # artifact of abrupt death mid-write (SIGKILL / os._exit
        # while the heartbeat thread writes) — the exact scenario the
        # recorder exists to survive.  Tolerate precisely that; any
        # interior bad line, or a trail with no valid line, fails.
        with open(args.path) as f:
            n_raw = sum(1 for raw in f if raw.strip())
        trailing = (len(bad) == 1 and bool(lines)
                    and bad[0][0] == n_raw)
        for i, why in bad:
            print(f"BAD line {i}: {why}"
                  + (" (trailing — tolerated as a death-cut line)"
                     if trailing else ""), file=sys.stderr)
        if (bad and not trailing) or not lines:
            print(f"heartbeat check FAILED: {len(lines)} valid, "
                  f"{len(bad)} bad line(s) in {args.path}",
                  file=sys.stderr)
            return 1
        print(f"heartbeat check OK: {len(lines)} valid line(s), "
              f"schema v{lines[-1]['v']}, last seq {lines[-1]['seq']}"
              + (", 1 trailing death-cut line tolerated" if trailing
                 else ""))
        return 0

    if args.as_json:
        summary = {
            "path": args.path,
            "valid_lines": len(lines),
            "bad_lines": len(bad),
            "first": lines[0] if lines else None,
            "last": lines[-1] if lines else None,
        }
        print(json.dumps(summary, sort_keys=True))
        return 0 if lines else 1

    print(render_postmortem(args.path))
    return 0 if lines and not bad else 1


if __name__ == "__main__":
    sys.exit(main())
