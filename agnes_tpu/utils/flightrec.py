"""Flight recorder: bounded in-memory event ring + a crash-surviving
heartbeat file (ISSUE 8 tentpole, layer 2).

Three straight hardware rounds wedged and died leaving nothing but a
sentinel record (ROADMAP "Scoreboard reality") — every metric in the
system was an end-of-run snapshot, so a SIGKILLed or wedged run
yielded zero evidence about *where* it wedged.  This module is the
always-on fix:

* **FlightRecorder** — a bounded ring of structured events (tick
  open/close, rung chosen, rejects by cause, retrace-unexpected,
  compile observed, thread failure, ...).  Thread-safe, fixed memory,
  per-kind monotone counters that survive ring eviction.
* **Heartbeat** — a daemon thread appending ONE NDJSON line per
  interval to an on-disk file: interval-windowed rates + histogram
  quantiles + gauges (via caller-supplied `sources` callables, e.g.
  ``Metrics.snapshot(window=True)``), the recorder's per-kind event
  counts, and the in-flight stage.  Every line is flushed to the
  kernel before the thread sleeps, so an outright SIGKILL still
  leaves a parseable trail whose LAST LINE DATES THE WEDGE.  The file
  is atomically rotated (``os.replace`` to ``<path>.1``) when it
  outgrows ``max_bytes``.
* **Schema helpers** — `validate_heartbeat_line` / `read_heartbeat` /
  `last_line_age_s` / `render_postmortem`: the parsing half, shared by
  the `agnes-metrics` CLI (utils/metrics_cli.py) and the ci.sh gate's
  schema check.

STDLIB-ONLY BY CONTRACT (like utils/budget.py): bench.py loads this
module by FILE PATH before the probe guard runs, i.e. before jax — or
even numpy-bearing agnes modules — may be imported.  Keep it that way.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

#: heartbeat line schema version.  v2 (ISSUE 15): multi-host pods —
#: every line may carry `host_id`/`process_index` so a pod-wide wedge
#: is datable PER PROCESS (one trail per host, merged by the
#: `agnes-metrics` multi-file postmortem).  v1 lines stay valid (the
#: host keys are optional; a single-process run omits them).
SCHEMA_VERSION = 2

#: required heartbeat keys -> accepted types (the ci.sh gate and
#: `agnes-metrics --check` validate every line against this)
REQUIRED_KEYS = {
    "v": int,
    "kind": str,
    "seq": int,
    "t": (int, float),          # wall-clock epoch seconds
    "pid": int,
    "uptime_s": (int, float),
}

#: optional keys type-checked WHEN present (schema v2: the multi-host
#: identity stamp — `agnes-metrics --check` rejects a pod trail whose
#: host stamp is the wrong type, the same way it rejects a bad seq)
OPTIONAL_KEYS = {
    "host_id": int,
    "process_index": int,
}


class FlightRecorder:
    """Bounded ring of structured events (module docstring).

    `event(kind, **fields)` is the one producer call: a dict append
    under a leaf mutex — cheap enough for the serve plane's
    never-wait-on-device sections.  The ring holds the newest
    `capacity` events (older ones evicted and counted in `dropped`);
    `counts()` are per-kind MONOTONE totals independent of eviction,
    which is what the heartbeat line reports."""

    def __init__(self, capacity: int = 4096):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive: {capacity}")
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque()
        self._counts: Dict[str, int] = {}
        self._last: Dict[str, dict] = {}
        self.dropped = 0
        self._mu = threading.Lock()

    def event(self, kind: str, **fields) -> None:
        ev = {"kind": kind, "t": round(time.time(), 3)}
        ev.update(fields)
        with self._mu:
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self.dropped += 1
            self._ring.append(ev)
            self._counts[kind] = self._counts.get(kind, 0) + 1
            self._last[kind] = ev

    def __len__(self) -> int:
        return len(self._ring)

    def counts(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._counts)

    def last(self, kind: str) -> Optional[dict]:
        with self._mu:
            ev = self._last.get(kind)
            return dict(ev) if ev is not None else None

    def tail(self, n: Optional[int] = None,
             kind: Optional[str] = None) -> List[dict]:
        """Newest-last snapshot of the ring (optionally one kind)."""
        with self._mu:
            evs = [dict(e) for e in self._ring
                   if kind is None or e["kind"] == kind]
        return evs if n is None else evs[-n:]


def _json_safe(obj):
    """Best-effort JSON-safe copy: a heartbeat line must NEVER fail to
    serialize (a crashing telemetry thread is worse than a lossy
    field)."""
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    try:                       # numpy scalars et al. without importing
        return float(obj)      # numpy here (stdlib-only contract)
    except Exception:  # noqa: BLE001
        return repr(obj)


class Heartbeat:
    """Appends one NDJSON heartbeat line per interval (module
    docstring).  `sources` is a MUTABLE sequence of zero-arg callables
    returning dicts, re-read every beat — callers append sources as
    subsystems come up (bench registers the serve probe's metrics
    snapshot when the probe builds its service).  A source that raises
    is counted in `source_errors`, never fatal."""

    def __init__(self, path: str, interval_s: float = 1.0,
                 recorder: Optional[FlightRecorder] = None,
                 sources=None, max_bytes: int = 8_000_000,
                 host_id: Optional[int] = None):
        """`host_id` (schema v2, ISSUE 15): the pod process index —
        when set, every line carries `host_id` + `process_index` so a
        merged multi-host postmortem can attribute each trail (None =
        single-process, keys omitted, v1-shaped lines)."""
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive: {interval_s}")
        self.path = str(path)
        self.interval_s = float(interval_s)
        self.recorder = recorder
        self.sources = sources if sources is not None else []
        self.max_bytes = int(max_bytes)
        self.host_id = None if host_id is None else int(host_id)
        self.seq = 0
        self.source_errors = 0
        self._t0 = time.monotonic()
        self._last_beat: Optional[float] = None      # monotonic
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()

    # -- line production -----------------------------------------------------

    def _line(self) -> dict:
        line = {
            "v": SCHEMA_VERSION,
            "kind": "hb",
            "seq": self.seq,
            "t": round(time.time(), 3),
            "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "interval_s": self.interval_s,
        }
        if self.host_id is not None:
            line["host_id"] = self.host_id
            line["process_index"] = self.host_id
        if self.recorder is not None:
            line["events"] = self.recorder.counts()
            line["events_dropped"] = self.recorder.dropped
        for src in list(self.sources):
            try:
                d = src()
            except Exception:  # noqa: BLE001 — telemetry never kills
                self.source_errors += 1
                continue
            if isinstance(d, dict):
                line.update(_json_safe(d))
        if self.source_errors:
            line["source_errors"] = self.source_errors
        return line

    def _rotate_locked(self) -> None:
        try:
            if os.path.getsize(self.path) > self.max_bytes:
                os.replace(self.path, self.path + ".1")   # atomic
        except OSError:
            pass

    def beat(self) -> dict:
        """Append one line NOW (the thread's tick; also callable
        directly — tests and shutdown paths use it)."""
        with self._mu:
            self._rotate_locked()
            line = self._line()
            self.seq += 1
            payload = json.dumps(line, sort_keys=True, default=repr)
            with open(self.path, "a") as f:
                f.write(payload + "\n")
                f.flush()       # into the kernel: survives SIGKILL
            self._last_beat = time.monotonic()
        return line

    def last_line_age(self) -> Optional[float]:
        """Seconds since the last appended line (None = never beat)."""
        with self._mu:
            last = self._last_beat
        return None if last is None else time.monotonic() - last

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Heartbeat":
        if self._thread is None:
            self.beat()         # even a run killed in second 0 leaves
            self._thread = threading.Thread(         # a dated line
                target=self._loop, daemon=True,
                name="agnes-heartbeat")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except Exception:  # noqa: BLE001 — a telemetry thread
                pass           # must never take the host down

    def stop(self, final_beat: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 5)
        if final_beat:
            try:
                self.beat()
            except Exception:  # noqa: BLE001
                pass


# -- parsing / schema (the agnes-metrics CLI + ci.sh gate half) --------------

def validate_heartbeat_line(obj) -> List[str]:
    """Schema problems of one parsed line (empty list = valid)."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return [f"not an object: {type(obj).__name__}"]
    for key, types in REQUIRED_KEYS.items():
        if key not in obj:
            problems.append(f"missing required key {key!r}")
        elif not isinstance(obj[key], types) or isinstance(obj[key],
                                                           bool):
            problems.append(
                f"key {key!r} has type {type(obj[key]).__name__}")
    for key, types in OPTIONAL_KEYS.items():
        if key in obj and (not isinstance(obj[key], types)
                           or isinstance(obj[key], bool)):
            problems.append(
                f"optional key {key!r} has type "
                f"{type(obj[key]).__name__}")
    if not problems and obj["v"] > SCHEMA_VERSION:
        problems.append(f"schema version {obj['v']} from the future")
    return problems


def read_heartbeat(path: str) -> Tuple[List[dict],
                                       List[Tuple[int, str]]]:
    """Parse an NDJSON heartbeat file -> (lines, bad) where `bad` is
    [(1-based line number, problem)].  A final TRUNCATED line (the
    process died mid-write) is reported in `bad`, not raised."""
    lines: List[dict] = []
    bad: List[Tuple[int, str]] = []
    with open(path) as f:
        for i, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                obj = json.loads(raw)
            except ValueError:
                bad.append((i, "unparseable JSON"))
                continue
            problems = validate_heartbeat_line(obj)
            if problems:
                bad.append((i, "; ".join(problems)))
            else:
                lines.append(obj)
    return lines, bad


def last_line_age_s(path: str,
                    now: Optional[float] = None) -> Optional[float]:
    """Age (seconds) of the newest VALID line's wall timestamp — the
    number that dates a wedge post-mortem.  None when the file is
    missing or holds no valid line."""
    try:
        lines, _ = read_heartbeat(path)
    except OSError:
        return None
    if not lines:
        return None
    now = time.time() if now is None else now
    return now - lines[-1]["t"]


def _fmt_t(t: float) -> str:
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(t))


def render_postmortem(path: str,
                      now: Optional[float] = None) -> str:
    """Human post-mortem summary of a heartbeat file — what the next
    wedged-round investigation reads FIRST (the `agnes-metrics` CLI's
    default output)."""
    now = time.time() if now is None else now
    lines, bad = read_heartbeat(path)
    out = [f"heartbeat {path}: {len(lines)} valid line(s), "
           f"{len(bad)} bad"]
    for i, why in bad[:5]:
        out.append(f"  BAD line {i}: {why}")
    if not lines:
        out.append("  no valid lines — nothing to reconstruct")
        return "\n".join(out)
    first, last = lines[0], lines[-1]
    age = now - last["t"]
    interval = float(last.get("interval_s", 0)) or None
    out.append(f"  run: pid {last['pid']}, first beat "
               f"{_fmt_t(first['t'])}, last beat {_fmt_t(last['t'])} "
               f"(uptime {last['uptime_s']:.1f}s, {len(lines)} beats)")
    stale = interval is not None and age > 2 * interval
    out.append(f"  last line age: {age:.1f}s"
               + (f" — STALE (> 2x the {interval:.1f}s interval): "
                  f"the process died or wedged around "
                  f"{_fmt_t(last['t'])}" if stale else
                  " (fresh: within two heartbeat intervals)"))
    if "stage" in last:
        out.append(f"  stage at last beat: {last['stage']}")
    ev = last.get("events")
    if isinstance(ev, dict) and ev:
        top = sorted(ev.items(), key=lambda kv: -kv[1])
        out.append("  events: " + ", ".join(
            f"{k}={v}" for k, v in top[:10])
            + (f" (+{last.get('events_dropped', 0)} evicted from the "
               f"ring)" if last.get("events_dropped") else ""))
    rates = {k: v for k, v in last.items()
             if k.endswith("_per_sec") and isinstance(v, (int, float))
             and v > 0}
    if rates:
        top = sorted(rates.items(), key=lambda kv: -kv[1])
        out.append("  rates over the last window: " + ", ".join(
            f"{k}={v:g}" for k, v in top[:8]))
    quants = {k: v for k, v in last.items()
              if (k.endswith("_p50") or k.endswith("_p99"))
              and isinstance(v, (int, float)) and v > 0}
    if quants:
        out.append("  latency quantiles at last beat: " + ", ".join(
            f"{k}={v:.6g}s" for k, v in sorted(quants.items())))
    comp = {k: v for k, v in last.items()
            if k.startswith("compile_ms_")
            and isinstance(v, (int, float))}
    if comp:
        top = sorted(comp.items(), key=lambda kv: -kv[1])
        out.append("  first-dispatch compile walls: " + ", ".join(
            f"{k[len('compile_ms_'):]}={v:.0f}ms" for k, v in top[:8]))
    # ISSUE 13: the BLS device-pairing steady state + the census
    # gate's drift count, called out by name (a wedge inside the
    # pairing dispatch or a silently-regrown graph should be the
    # FIRST thing the post-mortem reader sees, not a dig through the
    # events dict).  The names are spelled literally because this
    # module is stdlib-only BY CONTRACT (loaded by file path before
    # any package import) — they mirror utils/metrics.py's
    # BLS_DEVICE_PAIRING_DISPATCHES / CENSUS_DRIFT_ENTRIES constants
    # (one name serves as counter, gauge-source key AND event kind)
    bls_disp = last.get("bls_device_pairing_dispatches")
    if isinstance(ev, dict):
        bls_disp = bls_disp or ev.get("bls_device_pairing_dispatches")
    if bls_disp:
        out.append(f"  bls device pairing: {bls_disp} dispatch(es)")
    drift = last.get("census_drift_entries")
    if isinstance(drift, (int, float)) and drift >= 0:
        out.append(f"  jaxpr census drift: {int(drift)} entr"
                   + ("y" if drift == 1 else "ies")
                   + (" (clean)" if drift == 0 else " — GRAPH GREW"))
    # ISSUE 17: the elastic membership trail — current epoch,
    # readmission count and the boundary / re-lift / hold-overflow
    # event counts, by name (same stdlib-only contract as the BLS
    # block: the names mirror utils/metrics.py's POD_MEMBERSHIP_EPOCH
    # / POD_HOST_READMISSIONS and ElasticShard's event kinds).  A pod
    # that churned hosts should say so in its post-mortem header, and
    # a hold-overflow — dropped held gossip — is a red flag the
    # reader must not have to dig for.
    epoch = last.get("pod_membership_epoch")
    readm = last.get("pod_host_readmissions")
    memb = {}
    if isinstance(ev, dict):
        readm = readm or ev.get("pod_host_readmissions")
        memb = {k: ev[k] for k in ("membership_boundary",
                                   "membership_relift",
                                   "membership_hold_overflow")
                if ev.get(k)}
    if isinstance(epoch, (int, float)) or readm or memb:
        bits = []
        if isinstance(epoch, (int, float)):
            bits.append(f"epoch {int(epoch)}")
        if readm:
            bits.append(f"{int(readm)} readmission(s)")
        bits.extend(f"{k}={v}" for k, v in sorted(memb.items()))
        out.append("  elastic membership: " + ", ".join(bits)
                   + (" — HELD GOSSIP DROPPED (reroute capacity "
                      "overflow)" if memb.get(
                          "membership_hold_overflow") else ""))
    # ISSUE 20: the native admission front-end — zero-copy phase
    # builds actually taken, per-shard queue depths and per-cause
    # shard rejects, by name (same stdlib-only contract: the names
    # mirror utils/metrics.py's SERVE_NATIVE_PHASE_BUILDS /
    # SERVE_NATIVE_SHARD_DEPTH_PREFIX / _REJECTS_PREFIX).  A host
    # whose densify fell back to the Python path — phase builds zero
    # while native submits flowed — should say so here, and a shard
    # sitting deep while its siblings drain is a routing red flag.
    nat_builds = last.get("serve_native_phase_builds")
    depths = {k: v for k, v in last.items()
              if k.startswith("serve_native_shard_depth_")
              and isinstance(v, (int, float))}
    rejects = {k: v for k, v in last.items()
               if k.startswith("serve_native_shard_rejects_")
               and isinstance(v, (int, float)) and v > 0}
    if nat_builds or depths or rejects:
        bits = []
        if isinstance(nat_builds, (int, float)):
            bits.append(f"{int(nat_builds)} zero-copy phase build(s)")
        if depths:
            bits.append("shard depths " + "/".join(
                f"{int(v)}" for _k, v in sorted(depths.items())))
        bits.extend(
            f"{k[len('serve_native_shard_rejects_'):]}={int(v)}"
            for k, v in sorted(rejects.items()))
        out.append("  native admission: " + ", ".join(bits))
    return "\n".join(out)


def render_pod_postmortem(paths: Sequence[str],
                          now: Optional[float] = None) -> str:
    """Merged per-host wedge timeline over SEVERAL heartbeat trails
    (ISSUE 15: one file per pod process).  The header ranks hosts by
    last-beat age — on a wedged pod the host that stopped beating
    FIRST is where the post-mortem starts — then each host's full
    single-file summary follows.  A missing/empty trail is itself a
    ranked finding (a host that never beat died before its recorder
    armed)."""
    now = time.time() if now is None else now
    rows = []                  # (sort key, label line)
    for k, path in enumerate(paths):
        label = f"host file {k} ({path})"
        try:
            lines, bad = read_heartbeat(path)
        except OSError as e:
            rows.append((float("-inf"), f"  {label}: UNREADABLE "
                                       f"({e.__class__.__name__}) — "
                                       f"died before first beat?"))
            continue
        if not lines:
            rows.append((float("-inf"),
                         f"  {label}: no valid lines ({len(bad)} bad)"))
            continue
        last = lines[-1]
        age = now - last["t"]
        host = last.get("host_id")
        who = (f"host {host}" if host is not None
               else f"pid {last['pid']}")
        interval = float(last.get("interval_s", 0)) or None
        stale = interval is not None and age > 2 * interval
        ep = last.get("pod_membership_epoch")
        rows.append((
            -age,
            f"  {who}: last beat {_fmt_t(last['t'])} "
            f"(age {age:.1f}s, {len(lines)} beats, seq "
            f"{last['seq']}"
            # per-host epoch in the ranked header: hosts wedged on
            # DIFFERENT membership epochs is the elastic-pod failure
            # signature (ISSUE 17)
            + (f", epoch {int(ep)}" if isinstance(ep, (int, float))
               else "") + ")"
            + (" — STALE: wedged/died around this time" if stale
               else " — fresh")))
    out = [f"pod heartbeat merge: {len(paths)} trail(s), oldest "
           f"last-beat first (the first host to go quiet is where "
           f"the wedge began)"]
    out.extend(line for _, line in sorted(rows, key=lambda r: r[0]))
    for path in paths:
        out.append("")
        try:
            out.append(render_postmortem(path, now=now))
        except OSError as e:
            out.append(f"heartbeat {path}: unreadable ({e})")
    return "\n".join(out)
