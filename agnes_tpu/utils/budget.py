"""Deadline & HBM-budget subsystem — graceful degradation primitives.

Two hot failure surfaces motivated this module (VERDICT r5):

* **HBM**: the fused signed consensus step reshapes every
  (phase, instance, validator) signature lane into ONE batched Ed25519
  verify.  At the north-star shape (Ps=2 vote classes x 10k instances
  x 1000 validators = 20M lanes) the operands alone are ~10 GB and the
  20-limb field temporaries add ~80 B per live field element per lane
  — far past a 16 GB v5e.  `plan_dense_verify` / `plan_lane_verify`
  size verify microbatches so the chunked step variants
  (device/step.py `verify_chunk`) stream tiles through the same kernel
  with a bounded peak, bit-identically (per-lane integer math is
  independent of the batch it rides in).

* **Wall clock**: bench.py's probe-retry budget historically exceeded
  the driver's enclosing ``timeout 1800`` and was SIGKILLed before
  emitting its JSON verdict (three rounds of missing scoreboard data).
  `Deadline` discovers the enclosing budget (env override, else a
  /proc walk that finds an ancestor ``timeout N`` invocation and
  subtracts its elapsed time) so retry/backoff caps derive from the
  time that actually remains, and `install_deadline_signals` arms
  SIGTERM/SIGALRM so a verdict is emitted even on a kill.

IMPORT CONTRACT: this module must be importable BEFORE jax — bench.py
loads it by file path in its pre-import probe guard (importing
``agnes_tpu.utils`` proper would pull jax via the package __init__ and
initialize a backend).  jax is imported lazily inside functions only;
module level is stdlib-only.
"""

from __future__ import annotations

import dataclasses
import math
import os
import signal
import time
from typing import Callable, List, Optional, Tuple

# --- static operand-size math (int32 lane encoding, 20-limb field) ---------

GIB = 1 << 30
#: default per-chip HBM when the backend exposes no memory_stats
#: (TPU v5e: 16 GB; override with AGNES_HBM_BUDGET_BYTES)
DEFAULT_HBM_BYTES = 16 * GIB

#: bytes per verify lane for each operand (the bridge packs bytes as
#: int32 lanes — crypto/ed25519_jax.pack_verify_inputs_host layout)
SIG_LANE_BYTES = 64 * 4            # [.., 64] int32
PUB_LANE_BYTES = 32 * 4            # [.., 32] int32
BLOCK_LANE_BYTES = 32 * 4          # per SHA-512 block: [.., 32] uint32

#: one field element = 20 int32 limbs (crypto/field_jax.NLIMBS)
FIELD_ELEM_BYTES = 20 * 4

#: live field elements per lane while the verify dataflow runs — the
#: Straus scan carry point (4 elems) + the {B, -A, B-A} table (12) +
#: unified-addition temporaries, both decompressions, the SHA-512
#: message schedule and Barrett reduction, with slack for XLA fusion
#: keeping several stages live at once.  Deliberately conservative: a
#: 2x overestimate halves the tile, it never breaks correctness, while
#: an underestimate OOMs at full shape.
VERIFY_WORKSPACE_ELEMS = 128
VERIFY_WORKSPACE_LANE_BYTES = VERIFY_WORKSPACE_ELEMS * FIELD_ELEM_BYTES


class BudgetError(RuntimeError):
    """No verify tiling fits the given HBM budget."""


@dataclasses.dataclass(frozen=True)
class VerifyPlan:
    """A chunked-execution plan for the fused signed verify.

    ``tile`` is the microbatch size along the planned axis — INSTANCE
    ROWS for `plan_dense_verify` (each row is n_phases * n_validators
    lanes), RAW LANES for `plan_lane_verify`.  The last chunk may be
    ragged; the chunked kernels pad it (padding lanes verify garbage
    that is sliced off, so results stay bit-identical)."""

    n_phases: int
    n_instances: int
    n_validators: int
    n_blocks: int
    tile: int                  # rows (dense) or lanes (lane plan) per chunk
    n_chunks: int
    lanes_per_chunk: int
    resident_bytes: int        # persistent operands (live for the whole step)
    chunk_bytes: int           # transient workspace of ONE microbatch
    hbm_bytes: int
    safety: float

    @property
    def peak_bytes(self) -> int:
        return self.resident_bytes + self.chunk_bytes

    def fits(self, hbm_bytes: Optional[int] = None) -> bool:
        budget = self.hbm_bytes if hbm_bytes is None else hbm_bytes
        return self.peak_bytes <= budget * self.safety

    @property
    def chunked(self) -> bool:
        return self.n_chunks > 1

    def describe(self) -> str:
        return (f"verify plan: {self.n_chunks} chunk(s) x {self.tile} "
                f"(lanes/chunk={self.lanes_per_chunk}); resident "
                f"{self.resident_bytes / GIB:.2f} GiB + chunk "
                f"{self.chunk_bytes / GIB:.2f} GiB = peak "
                f"{self.peak_bytes / GIB:.2f} GiB of "
                f"{self.hbm_bytes / GIB:.2f} GiB "
                f"(safety {self.safety:.2f})")


def _floor_pow2(n: int) -> int:
    return 1 << (n.bit_length() - 1)


def dense_resident_bytes(n_phases: int, n_instances: int,
                         n_validators: int, n_blocks: int = 1,
                         n_seq_phases: Optional[int] = None) -> int:
    """Persistent HBM for the dense fused signed step: the full
    sig/blocks tensors (inputs stay resident while chunks stream), the
    pubkey table, the dense phase tensors and verdict mask, and the
    tally's per-validator arrays (voted/equiv dominate; W=2 classes x
    4-round window, device/tally.py)."""
    P = n_seq_phases if n_seq_phases is not None else n_phases + 1
    lanes = n_phases * n_instances * n_validators
    cells = n_instances * n_validators
    sig = lanes * SIG_LANE_BYTES
    blocks = lanes * n_blocks * BLOCK_LANE_BYTES
    pub_table = n_validators * PUB_LANE_BYTES
    # phases: slots int32 + mask bool per (seq phase, cell); vmask bool
    phases = P * cells * (4 + 1) + P * cells
    # tally: voted [I, W=4, 2, V] int32 + equiv [I, V] bool
    tally = cells * 4 * 2 * 4 + cells
    return sig + blocks + pub_table + phases + tally


def plan_dense_verify(n_phases: int, n_instances: int, n_validators: int,
                      n_blocks: int = 1,
                      hbm_bytes: Optional[int] = None,
                      safety: float = 0.9,
                      workspace_lane_bytes: int = VERIFY_WORKSPACE_LANE_BYTES,
                      ) -> VerifyPlan:
    """Size the instance-row tile for the DENSE fused signed path
    (consensus_step_seq_signed_dense): largest power-of-two row count
    whose microbatch workspace fits the HBM left over after the
    resident operands.  Pure static math — nothing is allocated or
    traced; usable for shapes (10k x 1000) no test machine can hold.

    Raises BudgetError when even a one-row tile exceeds the budget
    (the shape cannot run on this chip at all)."""
    if min(n_phases, n_instances, n_validators) <= 0:
        raise ValueError("n_phases/n_instances/n_validators must be >= 1")
    hbm = device_hbm_bytes() if hbm_bytes is None else int(hbm_bytes)
    resident = dense_resident_bytes(n_phases, n_instances, n_validators,
                                    n_blocks)
    avail = hbm * safety - resident
    # per-lane transient cost: the verify workspace plus the pubkey
    # broadcast each chunk materializes ([Ps, tile, V, 32] int32)
    lane_cost = workspace_lane_bytes + PUB_LANE_BYTES
    row_lanes = n_phases * n_validators
    max_rows = int(avail // (row_lanes * lane_cost))
    if max_rows < 1:
        raise BudgetError(
            f"dense fused verify cannot fit {n_phases}x{n_instances}x"
            f"{n_validators} (nb={n_blocks}) in {hbm / GIB:.2f} GiB: "
            f"resident {resident / GIB:.2f} GiB leaves "
            f"{max(avail, 0) / GIB:.2f} GiB, one instance row needs "
            f"{row_lanes * lane_cost / GIB:.3f} GiB")
    tile = min(n_instances, _floor_pow2(max_rows))
    n_chunks = -(-n_instances // tile)
    return VerifyPlan(
        n_phases=n_phases, n_instances=n_instances,
        n_validators=n_validators, n_blocks=n_blocks,
        tile=tile, n_chunks=n_chunks,
        lanes_per_chunk=tile * row_lanes,
        resident_bytes=resident,
        chunk_bytes=tile * row_lanes * lane_cost,
        hbm_bytes=hbm, safety=safety)


def plan_lane_verify(n_lanes: int, n_blocks: int = 1,
                     hbm_bytes: Optional[int] = None,
                     safety: float = 0.9,
                     workspace_lane_bytes: int = VERIFY_WORKSPACE_LANE_BYTES,
                     ) -> VerifyPlan:
    """Size the lane chunk for the PACKED-lane fused signed path
    (consensus_step_seq_signed): same math with one lane per 'row'."""
    if n_lanes <= 0:
        raise ValueError("n_lanes must be >= 1")
    hbm = device_hbm_bytes() if hbm_bytes is None else int(hbm_bytes)
    resident = n_lanes * (SIG_LANE_BYTES + PUB_LANE_BYTES
                          + n_blocks * BLOCK_LANE_BYTES)
    avail = hbm * safety - resident
    max_lanes = int(avail // workspace_lane_bytes)
    if max_lanes < 1:
        raise BudgetError(
            f"lane fused verify cannot fit {n_lanes} lanes "
            f"(nb={n_blocks}) in {hbm / GIB:.2f} GiB")
    tile = min(n_lanes, _floor_pow2(max_lanes))
    return VerifyPlan(
        n_phases=1, n_instances=n_lanes, n_validators=1,
        n_blocks=n_blocks, tile=tile, n_chunks=-(-n_lanes // tile),
        lanes_per_chunk=tile, resident_bytes=resident,
        chunk_bytes=tile * workspace_lane_bytes,
        hbm_bytes=hbm, safety=safety)


def mesh_local_shape(mesh, n_instances: int, n_validators: int,
                     n_hosts: int = 1,
                     n_live: Optional[int] = None) -> Tuple[int, int]:
    """(instances, validators) as ONE device of `mesh` sees them — the
    shape every per-device budget plan must bound (under shard_map the
    verify and tally run on local cells).  `mesh=None` is the
    single-device identity.  One source of truth shared by
    DeviceDriver's chunk planning and the serve ShapeLadder's dense
    planning, so the two can never disagree about what "per-device
    slice of the budget" means.

    `n_hosts` (ISSUE 15): on a POD mesh spanning several processes,
    `mesh.shape` counts the GLOBAL device grid but a multi-host
    driver's `n_instances` is already the PER-HOST slice (the host
    plan divided the deployment before the driver ever saw it) —
    dividing a host's slice by the pod-wide data extent would plan
    verify tiles against an instance count n_hosts times too small
    (a silent HBM under-claim that OOMs at full shape).  Pass the
    host count the instance figure was already divided by; the data
    extent one host actually owns is global_data / n_hosts.

    `n_live` (ISSUE 17): an ELASTIC pod's live membership can be
    smaller than the process count — ownership concentrates on the
    survivors while every device (the sleepers' included) stays in
    the fixed jax mesh serving padding.  A live owner's instance
    slice is n_instances_global / n_live spread over
    global_data / n_live device columns, so the per-device figure
    must divide by the LIVE count, not the static one.  CALLER
    CONTRACT: with `n_live` set, `n_instances` must be the slice the
    live owner actually SERVES (static per-host slice scaled by
    n_hosts / n_live — DistributedDriver._local_shape does this), so
    the live divisors cancel and the per-device figure is INVARIANT
    under membership changes, as the fixed SPMD mesh dictates.
    Passing the static per-host slice instead shrinks the figure by
    live/n_hosts — an HBM under-claim that OOMs at full shape.
    Defaults to `n_hosts` (the static pod)."""
    if mesh is None:
        return int(n_instances), int(n_validators)
    from agnes_tpu.parallel.mesh import DATA_AXIS, SLICE_AXIS, VAL_AXIS

    live = int(n_live) if n_live is not None else int(n_hosts)
    if not 1 <= live <= max(1, int(n_hosts)):
        raise ValueError(
            f"live membership {live} outside [1, {n_hosts}]")
    shape = dict(mesh.shape)
    n_data = shape.get(DATA_AXIS, 1) * shape.get(SLICE_AXIS, 1)
    if live > 1:
        if n_data % live:
            raise ValueError(
                f"mesh data extent {n_data} does not split over "
                f"{live} live host(s)")
        n_data //= live
    return (int(n_instances) // n_data,
            int(n_validators) // shape.get(VAL_AXIS, 1))


def device_hbm_bytes(device=None) -> int:
    """Best-effort per-device memory budget, in preference order:
    AGNES_HBM_BUDGET_BYTES env override; the backend's
    `Device.memory_stats()` limit (absent on CPU and on some tunneled
    TPU platforms); DEFAULT_HBM_BYTES (v5e)."""
    env = os.environ.get("AGNES_HBM_BUDGET_BYTES")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    try:
        import jax

        d = device if device is not None else jax.devices()[0]
        stats = d.memory_stats()
        if stats:
            limit = (stats.get("bytes_limit")
                     or stats.get("bytes_reservable_limit"))
            if limit:
                return int(limit)
    except Exception:  # noqa: BLE001 — any backend failure -> default
        pass
    return DEFAULT_HBM_BYTES


def compiled_peak_bytes(compiled) -> Optional[int]:
    """Measured peak from an AOT-compiled function
    (`jit(f).lower(*args).compile().memory_analysis()`), or None when
    the backend doesn't expose it (XLA:CPU returns None; the tunneled
    TPU client sometimes raises).  When available this VERIFIES a
    static plan: planner estimates are upper bounds, the compiler's
    number is ground truth."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        return None
    if ma is None:
        return None
    total = 0
    for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                 "temp_size_in_bytes", "generated_code_size_in_bytes"):
        total += int(getattr(ma, attr, 0) or 0)
    # arguments that alias outputs (donated state) are counted twice
    # above; treat the sum as the conservative upper bound it is
    return total if total > 0 else None


# --- wall-clock deadline discovery ------------------------------------------

#: how far up the process tree to look for an enclosing `timeout`
_MAX_ANCESTOR_HOPS = 20

#: suffix multipliers accepted by coreutils timeout durations
_SUFFIX = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}

#: timeout(1) options that consume a following argument
_TIMEOUT_OPTS_WITH_ARG = ("-k", "--kill-after", "-s", "--signal")


def parse_timeout_duration(tok: str) -> Optional[float]:
    """'870' -> 870.0, '30m' -> 1800.0; None if not a duration."""
    mult = 1.0
    if tok and tok[-1] in _SUFFIX:
        mult, tok = _SUFFIX[tok[-1]], tok[:-1]
    try:
        v = float(tok)
    except ValueError:
        return None
    return v * mult if v >= 0 else None


def parse_timeout_argv(argv: List[str]) -> Optional[float]:
    """The duration of a coreutils `timeout` invocation's argv, or None
    when argv is not one (or is unparseable).  Handles `-k 10 870`,
    `--kill-after=10`, `-s TERM`, and s/m/h/d suffixes."""
    if not argv or os.path.basename(argv[0]) != "timeout":
        return None
    i = 1
    while i < len(argv):
        a = argv[i]
        if a.startswith("-") and a != "-":
            if a in _TIMEOUT_OPTS_WITH_ARG:
                i += 2
            else:
                i += 1  # flag (or --opt=value) without separate arg
            continue
        return parse_timeout_duration(a)
    return None


def _proc_stat_fields(pid: int) -> Optional[List[str]]:
    """Fields of /proc/<pid>/stat AFTER the (comm) — comm may contain
    spaces/parens, so split at the last ')'."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            raw = f.read().decode("ascii", "replace")
    except OSError:
        return None
    rp = raw.rfind(")")
    if rp < 0:
        return None
    return raw[rp + 1:].split()


def _proc_ppid(pid: int) -> Optional[int]:
    f = _proc_stat_fields(pid)
    try:
        return int(f[1]) if f else None      # field 4 overall
    except (ValueError, IndexError):
        return None


def _proc_elapsed_s(pid: int) -> Optional[float]:
    """Seconds since process start (start_time field vs /proc/uptime)."""
    f = _proc_stat_fields(pid)
    if not f or len(f) < 20:
        return None
    try:
        start_ticks = float(f[19])           # field 22 overall
        with open("/proc/uptime") as up:
            uptime = float(up.read().split()[0])
        tck = os.sysconf("SC_CLK_TCK")
    except (ValueError, OSError):
        return None
    return max(0.0, uptime - start_ticks / tck)


def _proc_cmdline(pid: int) -> Optional[List[str]]:
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            raw = f.read()
    except OSError:
        return None
    return [a.decode("utf-8", "replace")
            for a in raw.split(b"\0") if a] or None


def enclosing_timeout_remaining() -> Optional[float]:
    """Walk the ancestor chain; for every `timeout N ...` wrapper found,
    compute N minus its elapsed runtime; return the tightest remaining
    seconds, or None when no ancestor is a timeout (or /proc is
    unavailable — non-Linux)."""
    best: Optional[float] = None
    pid, hops = os.getppid(), 0
    while pid and pid > 1 and hops < _MAX_ANCESTOR_HOPS:
        argv = _proc_cmdline(pid)
        if argv:
            dur = parse_timeout_argv(argv)
            if dur is not None:
                elapsed = _proc_elapsed_s(pid)
                if elapsed is not None:
                    rem = dur - elapsed
                    best = rem if best is None else min(best, rem)
        pid = _proc_ppid(pid)
        hops += 1
    return best


@dataclasses.dataclass(frozen=True)
class Deadline:
    """An absolute wall-clock budget: `at` is a time.monotonic()
    instant, or None for unbounded.  `source` records where it came
    from so -1 bench records can state it."""

    at: Optional[float]
    source: str = "none"

    @classmethod
    def none(cls) -> "Deadline":
        return cls(at=None, source="none")

    @classmethod
    def after(cls, seconds: float, source: str = "explicit") -> "Deadline":
        return cls(at=time.monotonic() + seconds, source=source)

    @classmethod
    def discover(cls, env_var: str = "AGNES_BENCH_DEADLINE_S",
                 default_s: Optional[float] = None) -> "Deadline":
        """The enclosing wall-clock budget, in preference order: the
        env override; an ancestor `timeout N` found via /proc (minus
        its elapsed time); `default_s`; unbounded."""
        env = os.environ.get(env_var)
        if env:
            try:
                return cls.after(float(env), source=f"env:{env_var}")
            except ValueError:
                pass
        rem = enclosing_timeout_remaining()
        if rem is not None:
            return cls.after(max(0.0, rem), source="proc:timeout")
        if default_s is not None:
            return cls.after(default_s, source="default")
        return cls.none()

    def remaining(self) -> float:
        return math.inf if self.at is None else self.at - time.monotonic()

    def expired(self) -> bool:
        return self.at is not None and self.remaining() <= 0

    def cap(self, want: float, margin: float = 0.0) -> float:
        """`want` seconds, clamped so it ends `margin` before the
        deadline (never below 0); `want` unchanged when unbounded."""
        if self.at is None:
            return want
        return max(0.0, min(want, self.remaining() - margin))


def deadline_margin_s(rem: float) -> float:
    """Alarm margin for a finite remaining budget of `rem` seconds —
    the gap between "all derived work caps must have ended" and the
    last-resort SIGALRM.  SHARED by `install_deadline_signals` and
    bench's `_probe_caps` clamps: the probe loop only provably beats
    the alarm because both sides subtract THIS number."""
    return min(30.0, max(5.0, rem * 0.2))


def install_deadline_signals(callback: Callable[[int], None],
                             deadline: Deadline,
                             margin_s: Optional[float] = None) -> float:
    """Arm SIGTERM and SIGALRM with `callback(signum)` and, for a
    finite deadline, schedule an alarm `margin_s` before it — the
    last-resort guarantee that a verdict is emitted even when the
    process is about to be killed from outside (coreutils timeout
    sends SIGTERM first; the alarm fires even if that TERM never
    reaches us through an intermediate shell).  Returns the scheduled
    alarm delay (0.0 = no alarm).  Call from the main thread."""
    signal.signal(signal.SIGTERM, lambda sn, fr: callback(sn))
    signal.signal(signal.SIGALRM, lambda sn, fr: callback(sn))
    rem = deadline.remaining()
    if not math.isfinite(rem):
        return 0.0
    if margin_s is None:
        margin_s = deadline_margin_s(rem)
    delay = max(1, int(rem - margin_s))
    signal.alarm(delay)
    return float(delay)
