"""The typed run configuration.

The reference's only "config system" is constructor arguments
(State::new(height), RoundVotes::new(height, round, total) — SURVEY.md
§5); timeout durations don't exist there at all (the consumer owns
them).  This dataclass is the single place a deployment describes
itself: scale (validators, instances), the tally window, mesh shape,
timeouts, and dtype policy.  `from_args` gives every benchmark/driver
CLI the same flags.
"""

from __future__ import annotations

import argparse
from dataclasses import asdict, dataclass, field
from typing import Optional, Tuple

from agnes_tpu.core.executor import TimeoutConfig


@dataclass(frozen=True)
class RunConfig:
    # scale
    n_validators: int = 1000
    n_instances: int = 10_000
    # tally / proposer window (rounds tracked on device)
    n_rounds: int = 4
    n_slots: int = 4
    # mesh: (data/instances axis, validator axis); None = single device
    mesh: Optional[Tuple[int, int]] = None
    # timeouts (virtual units)
    timeouts: TimeoutConfig = field(default_factory=TimeoutConfig)
    # dtype policy: tally weights stay int32; this switches any future
    # floating-point surfaces (bf16 on TPU by default)
    float_dtype: str = "bfloat16"
    # signature verification strategy for the ingestion bridge:
    # "lanes" = per-lane kernel; "msm" = batch random-linear-
    # combination fast path with per-lane fallback (both cofactored,
    # identical verdicts — a throughput choice; crypto/msm_jax.py)
    verify_mode: str = "lanes"
    # bound on the bridge's pre-verification future-round hold-back
    # queue (None = 2 full [instances, validators] ticks, floor 64k)
    held_cap: Optional[int] = None
    # checkpointing
    checkpoint_dir: Optional[str] = None
    checkpoint_every_steps: int = 0     # 0 = disabled

    def validate(self) -> "RunConfig":
        assert self.n_validators >= 1 and self.n_instances >= 1
        assert self.n_rounds >= 1 and self.n_slots >= 1
        if self.mesh is not None:
            d, v = self.mesh
            assert self.n_instances % d == 0, "instances % mesh data axis"
            assert self.n_validators % v == 0, "validators % mesh val axis"
        assert self.float_dtype in ("bfloat16", "float32")
        assert self.verify_mode in ("lanes", "msm")
        assert self.held_cap is None or self.held_cap > 0
        return self

    def as_dict(self) -> dict:
        return asdict(self)

    # -- bridge factories: THE way a deployment's ingestion bridge is
    # built, so verify_mode/held_cap actually govern the run ----------------

    def make_batcher(self, **kw):
        """VoteBatcher sized and policied by this config (kw overrides
        forward to the constructor)."""
        from agnes_tpu.bridge import VoteBatcher
        kw.setdefault("n_slots", self.n_slots)
        kw.setdefault("n_rounds", self.n_rounds)
        kw.setdefault("held_cap", self.held_cap)
        kw.setdefault("verify_mode", self.verify_mode)
        return VoteBatcher(self.n_instances, self.n_validators, **kw)

    def make_native_loop(self, pubkeys=None, powers=None, **kw):
        """NativeIngestLoop (C++ event loop) for this config.  The
        native loop's verify stage is per-lane only; a config
        declaring verify_mode='msm' must use make_batcher (failing
        loudly here beats silently misreporting the run)."""
        if self.verify_mode != "lanes":
            raise ValueError(
                f"verify_mode={self.verify_mode!r} is not supported by "
                "the native ingest loop; use make_batcher()")
        from agnes_tpu.bridge import NativeIngestLoop
        kw.setdefault("n_slots", self.n_slots)
        kw.setdefault("n_rounds", self.n_rounds)
        kw.setdefault("held_cap", self.held_cap)
        return NativeIngestLoop(self.n_instances, self.n_validators,
                                pubkeys=pubkeys, powers=powers, **kw)

    @classmethod
    def from_args(cls, argv=None) -> "RunConfig":
        p = argparse.ArgumentParser(description=__doc__)
        p.add_argument("--validators", type=int, default=cls.n_validators)
        p.add_argument("--instances", type=int, default=cls.n_instances)
        p.add_argument("--rounds", type=int, default=cls.n_rounds)
        p.add_argument("--slots", type=int, default=cls.n_slots)
        p.add_argument("--mesh", type=str, default=None,
                       help="DxV, e.g. 4x2")
        p.add_argument("--float-dtype", default=cls.float_dtype)
        p.add_argument("--verify-mode", default=cls.verify_mode,
                       choices=("lanes", "msm"))
        p.add_argument("--held-cap", type=int, default=None)
        p.add_argument("--checkpoint-dir", default=None)
        p.add_argument("--checkpoint-every", type=int, default=0)
        a = p.parse_args(argv)
        mesh = None
        if a.mesh:
            d, v = a.mesh.lower().split("x")
            mesh = (int(d), int(v))
        return cls(n_validators=a.validators, n_instances=a.instances,
                   n_rounds=a.rounds, n_slots=a.slots, mesh=mesh,
                   float_dtype=a.float_dtype,
                   verify_mode=a.verify_mode, held_cap=a.held_cap,
                   checkpoint_dir=a.checkpoint_dir,
                   checkpoint_every_steps=a.checkpoint_every).validate()
