"""Lazy package re-exports (PEP 562) — one implementation.

Several packages split their public surface into a numpy/stdlib half
(eager, importable jax-free — the serve admission path and the
pre-test model-checker gate depend on that) and a jax-bearing half
(resolved on first attribute access): agnes_tpu.serve,
agnes_tpu.bridge, agnes_tpu.utils.  Each builds its module-level
``__getattr__`` with :func:`make_lazy_getattr` instead of hand-rolling
the same resolver three times.

Pure stdlib — this module must never import jax.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple


def make_lazy_getattr(module_name: str,
                      mapping: Dict[str, Tuple[str, str]],
                      module_globals: dict) -> Callable[[str], object]:
    """A module ``__getattr__`` resolving `mapping` entries
    (attr -> (module, name)) on first access and caching the result in
    `module_globals` (one resolution per process)."""

    def __getattr__(name: str):
        entry = mapping.get(name)
        if entry is None:
            raise AttributeError(
                f"module {module_name!r} has no attribute {name!r}")
        import importlib

        value = getattr(importlib.import_module(entry[0]), entry[1])
        module_globals[name] = value
        return value

    return __getattr__
