"""Checkpoint / resume for the device consensus plane.

The reference has no serialization at all — `State` is 5 small fields
and a height restart is `State::new(h+1)` (README.md:43-44, SURVEY.md
§5).  Here the unit of state is much bigger: 10k instances' int32
arrays (DeviceState) plus the tally window (TallyState) and the
driver's decided log.  A snapshot is a flat .npz of named leaves —
`jax.device_get` pulls everything in one transfer, resume re-uploads
with `jnp.asarray`.  Every leaf is a plain int/bool array, so the
format is dtype-exact and framework-agnostic (orbax would add async/
sharded saves; this keeps the dependency surface zero until needed).

Host executors snapshot separately (`save_executor`): their state is a
handful of Python scalars plus the decided log.
"""

from __future__ import annotations

import json
import os
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from agnes_tpu.device.encoding import DeviceState
from agnes_tpu.device.tally import TallyConfig, TallyState

_STATE_PREFIX = "state."
_TALLY_PREFIX = "tally."
_STATS_PREFIX = "stats."



def _atomic_savez(path: str, leaves: dict) -> None:
    """Write-then-rename so a crash mid-save never clobbers the
    previous snapshot (shared by every .npz saver here)."""
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **leaves)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def save_driver(driver, path: str) -> None:
    """Snapshot a harness.DeviceDriver (device arrays + stats) to
    `path` (.npz).  One device_get for the whole tree."""
    leaves = {}
    state_host = jax.device_get(driver.state)
    tally_host = jax.device_get(driver.tally)
    for name, arr in zip(DeviceState._fields, state_host):
        leaves[_STATE_PREFIX + name] = np.asarray(arr)
    for name, arr in zip(TallyState._fields, tally_host):
        leaves[_TALLY_PREFIX + name] = np.asarray(arr)
    leaves[_STATS_PREFIX + "decided"] = driver.stats.decided
    leaves[_STATS_PREFIX + "decision_value"] = driver.stats.decision_value
    leaves[_STATS_PREFIX + "decision_round"] = driver.stats.decision_round
    # full driver configuration: a resumed driver must behave
    # identically (proposer schedule, powers, propose values)
    leaves["cfg.proposer_flag"] = np.asarray(
        jax.device_get(driver.proposer_flag))
    leaves["cfg.powers"] = np.asarray(jax.device_get(driver.powers))
    leaves["cfg.total"] = np.asarray(jax.device_get(driver.total))
    leaves["cfg.propose_value"] = np.asarray(
        jax.device_get(driver.propose_value))
    leaves["meta"] = np.asarray([driver.I, driver.V, driver.cfg.n_rounds,
                                 driver.cfg.n_slots,
                                 driver.stats.votes_ingested,
                                 driver.stats.steps,
                                 int(driver.advance_height),
                                 driver.stats.decisions_total], np.int64)
    _atomic_savez(path, leaves)


def load_driver(path: str):
    """Rebuild a DeviceDriver from a snapshot (arrays re-uploaded)."""
    from agnes_tpu.harness.device_driver import DeviceDriver

    with np.load(path) as z:
        meta = z["meta"]
        d = DeviceDriver(int(meta[0]), int(meta[1]),
                         n_rounds=int(meta[2]), n_slots=int(meta[3]),
                         advance_height=bool(meta[6]) if len(meta) > 6
                         else False)

        def leaf(prefix, n, default):
            """Pre-rotation snapshots lack the newer leaves (height,
            base_round); they resume with the fresh-constructed zeros."""
            key = prefix + n
            return jnp.asarray(z[key]) if key in z.files else default

        d.state = DeviceState(*[leaf(_STATE_PREFIX, n, getattr(d.state, n))
                                for n in DeviceState._fields])
        d.tally = TallyState(*[leaf(_TALLY_PREFIX, n, getattr(d.tally, n))
                               for n in TallyState._fields])
        d.proposer_flag = jnp.asarray(z["cfg.proposer_flag"])
        d.powers = jnp.asarray(z["cfg.powers"])
        d.total = jnp.asarray(z["cfg.total"])
        d.propose_value = jnp.asarray(z["cfg.propose_value"])
        d.stats.decided = z[_STATS_PREFIX + "decided"].copy()
        d.stats.decision_value = z[_STATS_PREFIX + "decision_value"].copy()
        d.stats.decision_round = z[_STATS_PREFIX + "decision_round"].copy()
        d.stats.votes_ingested = int(meta[4])
        d.stats.steps = int(meta[5])
        d.stats.decisions_total = int(meta[7]) if len(meta) > 7 else 0
    return d


# --- host executor snapshots ------------------------------------------------


def save_executor(ex, path: str) -> None:
    """Persist a ConsensusExecutor's progress: height, state fields and
    the decided log (votes in flight are not persisted — on resume the
    node rejoins at its height and catches up from peers, the same
    crash-recovery story as any BFT node)."""
    from agnes_tpu.device.encoding import encode_state

    s = encode_state(ex.state)
    doc = {
        "height": ex.height,
        "state": {f: int(getattr(s, f)) for f in s._fields},
        "decided": {h: [d.height, d.round, d.value]
                    for h, d in ex.decided.items()},
        "now": ex.wheel.now,
        # slashing evidence survives restarts: archived records plus the
        # live height's (the live VoteExecutor is not persisted, so its
        # equivocations would otherwise vanish with it)
        "evidence": [[e.height, e.round, int(e.typ), e.validator,
                      e.first_value, e.second_value]
                     for e in ex.all_equivocations()],
    }
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def load_executor_into(ex, path: str) -> Tuple[int, dict]:
    """Restore height/state/decisions into a freshly built executor
    (same validator set + seed).  Returns (height, decided)."""
    from agnes_tpu.core.executor import Decision
    from agnes_tpu.core.round_votes import Equivocation
    from agnes_tpu.core.vote_executor import VoteExecutor
    from agnes_tpu.device.encoding import DeviceState, decode_state
    from agnes_tpu.types import VoteType

    with open(path) as f:
        doc = json.load(f)
    ex.height = doc["height"]
    ex.evidence = [Equivocation(h, r, VoteType(t), v, fv, sv)
                   for h, r, t, v, fv, sv in doc.get("evidence", [])]
    leaves = dict(doc["state"])
    # pre-height-field snapshots carry height only at the doc level
    leaves.setdefault("height", doc["height"])
    ds = DeviceState(*[np.int32(leaves[f]) for f in DeviceState._fields])
    ex.state = decode_state(ds, height=ex.height)
    ex.decided = {int(h): Decision(*v) for h, v in doc["decided"].items()}
    ex.decisions = sorted(ex.decided.values(), key=lambda d: d.height)
    ex.votes = VoteExecutor(height=ex.height,
                            total_weight=ex.vset.total_power,
                            edge_triggered=True)
    ex.wheel.now = doc["now"]
    return ex.height, ex.decided


# --- ingestion bridge snapshots ---------------------------------------------


def save_batcher(bat, path: str) -> None:
    """Persist a bridge.VoteBatcher's durable state: the slot<->value
    maps (without which device decision slots cannot be decoded after
    a crash), the synced window (heights/base_round), counters, and
    the retained verified-vote log — the SLASHING EVIDENCE, which must
    survive restarts just like the executor's equivocation records.
    In-flight votes (pending/held) and host-fallback tallies are NOT
    persisted: a restarted node re-receives them from peers, the same
    crash-recovery story as `save_executor`."""
    from agnes_tpu.bridge.ingest import _concat

    leaves = {
        "meta": np.asarray(
            [bat.I, bat.V, bat.W, bat.slots.n_slots, bat.held_cap,
             bat.msm_leaf, bat.rejected_signature, bat.rejected_malformed,
             bat.overflow_votes, bat.dropped_stale_height,
             bat.dropped_held_overflow, bat.slots.overflowed], np.int64),
        "verify_mode": np.asarray(bat.verify_mode),
        "heights": bat.heights,
        "base_round": bat.base_round,
        "powers": bat.powers,
    }
    # slot maps as a dense [I, S] value-id array in slot order
    smap = np.full((bat.I, bat.slots.n_slots), -1, np.int64)
    for i, m in enumerate(bat.slots._maps):
        for vid, s in m.items():
            smap[i, s] = vid
    leaves["slot_values"] = smap
    if bat._log:
        log = _concat(bat._log)    # zero-fills sig-less batches (>1);
        for f in ("instance", "validator", "height", "round", "typ",
                  "value"):
            leaves["log." + f] = getattr(log, f)
        if log.signature is not None:
            leaves["log.signature"] = log.signature
            # per-row mask keeps zero-filled rows None after restore
            # (all-zero bytes must never surface as 'signed' evidence)
            leaves["log.has_sig"] = np.concatenate(
                [np.full(len(b), b.signature is not None)
                 for b in bat._log])
        # device-verify evidence epochs (_log_pk): per-row index into a
        # stacked table set, -1 = logged post-screen (trusted) — so a
        # restore re-verifies pre-verdict rows against the SAME pubkey
        # epoch the live batcher would have used
        log_pk = list(bat._log_pk) + [None] * (len(bat._log)
                                               - len(bat._log_pk))
        tables: list = []
        row_ep = []
        for b, pk in zip(bat._log, log_pk):
            if pk is None:
                row_ep.append(np.full(len(b), -1, np.int64))
                continue
            pk = np.asarray(pk)
            for j, t in enumerate(tables):
                if np.array_equal(t, pk):
                    idx = j
                    break
            else:
                tables.append(pk)
                idx = len(tables) - 1
            row_ep.append(np.full(len(b), idx, np.int64))
        if tables:
            leaves["log.pk_epoch"] = np.concatenate(row_ep)
            leaves["log.pk_tables"] = np.stack(tables)
    _atomic_savez(path, leaves)


def load_batcher(path: str):
    """Rebuild a VoteBatcher from a snapshot (decoding and evidence
    extraction work immediately; in-flight votes re-arrive from
    peers)."""
    from agnes_tpu.bridge.ingest import VoteBatcher, _Batch

    with np.load(path) as z:
        m = z["meta"]
        bat = VoteBatcher(int(m[0]), int(m[1]), n_slots=int(m[3]),
                          n_rounds=int(m[2]), powers=z["powers"],
                          held_cap=int(m[4]),
                          verify_mode=str(z["verify_mode"]),
                          msm_leaf=int(m[5]))
        bat.heights = z["heights"].astype(np.int64)
        bat.base_round = z["base_round"].astype(np.int64)
        (bat.rejected_signature, bat.rejected_malformed,
         bat.overflow_votes, bat.dropped_stale_height,
         bat.dropped_held_overflow) = (int(x) for x in m[6:11])
        bat.slots.overflowed = int(m[11])
        smap = z["slot_values"]
        for i in range(smap.shape[0]):
            for s in range(smap.shape[1]):
                if smap[i, s] >= 0:
                    bat.slots._maps[i][int(smap[i, s])] = s
        if "log.instance" in z.files:
            cols = tuple(z["log." + f] for f in
                         ("instance", "validator", "height", "round",
                          "typ", "value"))
            n_rows = len(cols[0])
            ep = (z["log.pk_epoch"] if "log.pk_epoch" in z.files
                  else np.full(n_rows, -1, np.int64))
            tables = (z["log.pk_tables"] if "log.pk_tables" in z.files
                      else None)
            if "log.signature" not in z.files:
                bat._log = [_Batch(*cols, None)]
                bat._log_pk = [None]
            else:
                # Rebuild preserving the ARRIVAL interleaving: split the
                # concatenated rows into maximal runs of constant
                # (signedness, evidence-epoch) — the original batch
                # boundaries are gone, but run order == arrival order —
                # so signed_evidence() scans rows in the same order and
                # re-verifies pre-verdict rows against the same pubkey
                # epoch before and after a restore.
                has = z["log.has_sig"]
                sig = z["log.signature"]
                key = has.astype(np.int64) * (int(ep.max()) + 2) + ep
                cuts = np.flatnonzero(np.diff(key))
                bounds = np.concatenate(([0], cuts + 1, [n_rows]))
                bat._log, bat._log_pk = [], []
                for lo, hi in zip(bounds[:-1], bounds[1:]):
                    if hi <= lo:
                        continue
                    bat._log.append(_Batch(
                        *(c[lo:hi] for c in cols),
                        sig[lo:hi] if has[lo] else None))
                    bat._log_pk.append(
                        tables[ep[lo]] if ep[lo] >= 0 else None)
    return bat


def save_native_loop(loop, path: str) -> None:
    """Persist a bridge.NativeIngestLoop's durable state (same policy
    as `save_batcher`: slot decode, evidence log, counters, window;
    in-flight votes re-arrive from peers)."""
    st = loop.export_state()
    leaves = {"meta": np.asarray(
        [loop.I, loop.V, loop._n_rounds, loop._n_slots,
         int(loop.signed), loop.held_cap], np.int64)}
    if loop._powers is not None:
        leaves["powers"] = loop._powers
    leaves.update(st)
    _atomic_savez(path, leaves)


def load_native_loop(path: str, pubkeys=None, powers=None):
    """Rebuild a NativeIngestLoop from a snapshot.  A loop saved with
    signature verification enabled must be given the pubkey table
    again (it is the validator set, not snapshot-private state);
    voting powers and the held cap restore from the snapshot unless
    overridden."""
    from agnes_tpu.bridge import NativeIngestLoop

    with np.load(path) as z:
        m = z["meta"]
        if bool(m[4]) and pubkeys is None:
            raise ValueError(
                "snapshot was taken with signature verification on; "
                "pass the validator pubkey table")
        if powers is None and "powers" in z.files:
            powers = z["powers"]
        loop = NativeIngestLoop(int(m[0]), int(m[1]), n_slots=int(m[3]),
                                n_rounds=int(m[2]), pubkeys=pubkeys,
                                powers=powers, held_cap=int(m[5]))
        loop.import_state({k: z[k] for k in
                           ("slots", "log", "counters", "heights",
                            "base_round")})
    return loop
