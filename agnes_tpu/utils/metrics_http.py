"""`/metrics` endpoint: Prometheus text exposition over stdlib
http.server (ISSUE 8 tentpole, layer 3).

Renders a `utils.metrics.Metrics` registry — counters, gauges and the
log-bucket latency `Histogram`s — in the Prometheus text format
(version 0.0.4: `# TYPE` lines, `_bucket{le=...}` cumulative
histogram series, `_sum`/`_count`).  `MetricsServer` is the
attachable scraper target: a ThreadingHTTPServer on a daemon thread,
bound to localhost by default, serving GET /metrics; VoteService
grows a `start_metrics_server()` convenience that wires its registry
(plus the per-entry `compile_ms_<entry>` gauges) through here.

JAX-FREE AND STDLIB-ONLY BY CONTRACT: a scrape must work — and this
module must import — even when the accelerator stack is wedged,
which is exactly when an operator needs it.  The registry is read
through `Metrics.export_view()` (duck-typed), never through jax or
numpy.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Iterable, Optional

#: exposition content type (Prometheus text format 0.0.4)
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _sanitize(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    return out if out and not out[0].isdigit() else "_" + out


def _fmt(v) -> str:
    if v != v:                                   # NaN
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def render_prometheus(metrics,
                      extra_sources: Iterable[Callable[[], dict]] = ()
                      ) -> str:
    """One scrape body: every counter, gauge and histogram in
    `metrics` (via `export_view()`), plus gauge dicts from
    `extra_sources` callables (e.g. the registry's compile_ms view).
    A source that raises is skipped — a scrape must always answer."""
    counters, gauges, hists = metrics.export_view()
    lines = []
    for name in sorted(counters):
        pn = _sanitize(name)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_fmt(counters[name])}")
    extra: Dict[str, float] = {}
    for src in extra_sources:
        try:
            d = src()
        except Exception:  # noqa: BLE001 — scrape must answer
            continue
        if isinstance(d, dict):
            extra.update(d)
    for name in sorted({**gauges, **extra}):
        pn = _sanitize(name)
        val = extra.get(name, gauges.get(name))
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(val)}")
    for name in sorted(hists):
        pn = _sanitize(name)
        buckets, total, count = hists[name].prom_buckets()
        lines.append(f"# TYPE {pn} histogram")
        for le, cum in buckets:
            lines.append(f'{pn}_bucket{{le="{_fmt(le)}"}} {cum}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{pn}_sum {_fmt(total)}")
        lines.append(f"{pn}_count {count}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> Dict[str, float]:
    """Inverse of `render_prometheus` for tests and self-scrapes:
    {series -> value}, labeled series keyed as rendered (e.g.
    'h_bucket{le="0.001"}').  Comment/blank lines skipped."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        series, _, value = line.rpartition(" ")
        out[series] = float(value)
    return out


class MetricsServer:
    """GET /metrics scraper target over one Metrics registry (module
    docstring).  `start()` binds (port 0 = ephemeral) and returns the
    actual port; `stop()` shuts the listener down.  Handler threads
    are daemonic — an abandoned server never blocks interpreter
    exit."""

    def __init__(self, metrics, host: str = "127.0.0.1", port: int = 0,
                 extra_sources: Iterable[Callable[[], dict]] = ()):
        self.metrics = metrics
        self.host = host
        self.port = int(port)
        self.extra_sources = tuple(extra_sources)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?")[0] != "/metrics":
                    self.send_error(404)
                    return
                try:
                    body = render_prometheus(
                        outer.metrics, outer.extra_sources
                    ).encode()
                except Exception:  # noqa: BLE001 — never hang a scrape
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):          # quiet: a scrape per
                pass                            # interval is not news

        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="agnes-metrics-http")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None
