"""Auxiliary subsystems (SURVEY.md §5 — every one absent in the
reference, present here):

  checkpoint.py   snapshot/resume of device consensus state + the
                  decided-height log (the reference restarts by
                  constructing State::new(h+1); here 10k instances'
                  arrays snapshot and re-upload).
  metrics.py      counters/gauges off the hot loops (votes verified,
                  thresholds crossed, decisions/sec) with one-line
                  JSON export — the north-star metrics are built in —
                  plus the log-bucket latency Histogram (ISSUE 8).
  tracing.py      host spans (chrome-trace JSON for perfetto, bounded
                  ring, stable thread ids, tick flow events) +
                  jax.named_scope helpers for device kernels.
  flightrec.py    flight recorder: bounded event ring + the crash-
                  surviving heartbeat NDJSON (stdlib-only; bench.py
                  loads it by file path before the probe guard).
  metrics_http.py jax-free /metrics Prometheus endpoint over a
                  Metrics registry (VoteService.start_metrics_server).
  metrics_cli.py  the `agnes-metrics` heartbeat postmortem /
                  schema-check CLI (scripts/agnes_metrics.py shim).
  config.py       the typed run configuration (validators, instances,
                  mesh shape, timeouts, dtypes) + CLI parsing.
"""

from agnes_tpu.utils.config import RunConfig  # noqa: F401
from agnes_tpu.utils.metrics import Metrics  # noqa: F401
from agnes_tpu.utils.tracing import Tracer, span  # noqa: F401

# checkpoint.py imports jax at module top (device snapshot/resume);
# budget/metrics/tracing/config are stdlib+numpy.  Resolving the
# checkpoint members lazily keeps `utils.budget` importable jax-free —
# the model-checker gate's deadline discovery and the serve admission
# path both ride on that (serve/__init__.py has the same split).
from agnes_tpu.utils.lazy import make_lazy_getattr  # noqa: E402

__getattr__ = make_lazy_getattr(
    __name__,
    {name: ("agnes_tpu.utils.checkpoint", name)
     for name in ("load_batcher", "load_driver", "load_executor_into",
                  "load_native_loop", "save_batcher", "save_driver",
                  "save_executor", "save_native_loop")},
    globals())
