"""Tracing: host spans (chrome-trace JSON) + device scope helpers.

Host side: `Tracer` records begin/end spans and writes the standard
chrome://tracing / perfetto JSON array format.  Device side: `span`
wraps `jax.named_scope`, so kernel regions show up named in XLA/JAX
profiler dumps (`jax.profiler.trace` being the heavyweight option).
The reference has no instrumentation anywhere (SURVEY.md §5).

ISSUE 8 hardening for always-on service use:

* **Bounded.**  `spans` is a ring of `max_events` entries (oldest
  evicted, `dropped_events` counted) — the unbounded list grew without
  limit on a long-lived service.
* **Stable thread ids.**  `threading.get_ident() & 0xFFFF` collided
  across recycled idents; threads now get small SEQUENTIAL ids in
  first-seen order, and `write()` emits chrome-trace `thread_name`
  metadata events so the submit/dispatch threads are labeled rows in
  the viewer (`name_thread()` overrides the auto-captured name).
* **Flow events.**  `flow(name, fid, phase)` records chrome-trace
  flow events (`ph` s/t/f) keyed by a tick id, so one vote tick's
  submit -> dispatch -> settle lifecycle renders as ONE connected
  arrow chain across threads instead of disjoint spans
  (serve/pipeline.py threads the tick id through).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, Optional

#: flow-event phases: start / step / end (chrome-trace ph values)
FLOW_START, FLOW_STEP, FLOW_END = "s", "t", "f"


@dataclass
class _Span:
    name: str
    ts_us: float
    dur_us: float
    tid: int
    ph: str = "X"                  # "X" span | "s"/"t"/"f" flow event
    fid: Optional[int] = None      # flow (tick) id for flow events


@dataclass
class Tracer:
    """Collects host spans; `write(path)` emits chrome-trace JSON."""

    max_events: int = 65536
    spans: Deque[_Span] = None
    dropped_events: int = 0
    _t0: float = field(default_factory=time.perf_counter)
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _tids: Dict[int, int] = field(default_factory=dict)
    _thread_names: Dict[int, str] = field(default_factory=dict)

    def __post_init__(self):
        if self.spans is None:
            self.spans = collections.deque()

    def _tid_locked(self) -> int:
        """Small stable id of the calling thread (first-seen order);
        captures the thread's name on first sight.  Caller holds the
        lock."""
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = self._tids[ident] = len(self._tids) + 1
            self._thread_names.setdefault(
                tid, threading.current_thread().name)
        return tid

    def name_thread(self, name: str) -> None:
        """Label the CALLING thread's row in the trace viewer (e.g.
        the serve host names its submit/dispatch loops)."""
        with self._lock:
            self._thread_names[self._tid_locked()] = name

    def _append_locked(self, span: _Span) -> None:
        if len(self.spans) >= self.max_events:
            self.spans.popleft()
            self.dropped_events += 1
        self.spans.append(span)

    @contextlib.contextmanager
    def span(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            with self._lock:
                self._append_locked(_Span(
                    name=name,
                    ts_us=(start - self._t0) * 1e6,
                    dur_us=(end - start) * 1e6,
                    tid=self._tid_locked()))

    def flow(self, name: str, fid: int, phase: str) -> None:
        """Record one flow event (`phase` in "s"/"t"/"f") on the
        calling thread — the cross-thread correlation arrow for flow
        id `fid` (the serve plane's tick id)."""
        assert phase in (FLOW_START, FLOW_STEP, FLOW_END), phase
        now = time.perf_counter()
        with self._lock:
            self._append_locked(_Span(
                name=name, ts_us=(now - self._t0) * 1e6, dur_us=0.0,
                tid=self._tid_locked(), ph=phase, fid=int(fid)))

    def write(self, path: str) -> None:
        pid = os.getpid()
        with self._lock:
            spans = list(self.spans)
            names = dict(self._thread_names)
        events = [{"ph": "M", "name": "thread_name", "pid": pid,
                   "tid": tid, "args": {"name": name}}
                  for tid, name in sorted(names.items())]
        for s in spans:
            if s.ph == "X":
                events.append({"name": s.name, "ph": "X", "ts": s.ts_us,
                               "dur": s.dur_us, "pid": pid,
                               "tid": s.tid})
            else:
                ev = {"name": s.name, "ph": s.ph, "ts": s.ts_us,
                      "pid": pid, "tid": s.tid, "cat": "tick",
                      "id": s.fid}
                if s.ph == FLOW_END:
                    ev["bp"] = "e"     # bind to enclosing slice's end
                events.append(ev)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": events}, f)
        os.replace(tmp, path)

    def total_us(self, name: str) -> float:
        with self._lock:
            return sum(s.dur_us for s in self.spans
                       if s.name == name and s.ph == "X")

    def flow_phases(self, fid: int) -> set:
        """The flow phases recorded for `fid` (test/debug helper):
        a fully correlated tick shows {"s", "t", "f"}."""
        with self._lock:
            return {s.ph for s in self.spans if s.fid == fid
                    and s.ph != "X"}


@contextlib.contextmanager
def span(name: str, tracer: Optional[Tracer] = None):
    """Device+host combined scope: names the region for the XLA
    profiler AND records a host span when a tracer is given."""
    import jax

    with jax.named_scope(name):
        if tracer is None:
            yield
        else:
            with tracer.span(name):
                yield
