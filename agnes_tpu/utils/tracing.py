"""Tracing: host spans (chrome-trace JSON) + device scope helpers.

Host side: `Tracer` records begin/end spans and writes the standard
chrome://tracing / perfetto JSON array format.  Device side: `span`
wraps `jax.named_scope`, so kernel regions show up named in XLA/JAX
profiler dumps (`jax.profiler.trace` being the heavyweight option).
The reference has no instrumentation anywhere (SURVEY.md §5).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class _Span:
    name: str
    ts_us: float
    dur_us: float
    tid: int


@dataclass
class Tracer:
    """Collects host spans; `write(path)` emits chrome-trace JSON."""

    spans: List[_Span] = field(default_factory=list)
    _t0: float = field(default_factory=time.perf_counter)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    @contextlib.contextmanager
    def span(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            with self._lock:
                self.spans.append(_Span(
                    name=name,
                    ts_us=(start - self._t0) * 1e6,
                    dur_us=(end - start) * 1e6,
                    tid=threading.get_ident() & 0xFFFF))

    def write(self, path: str) -> None:
        events = [{"name": s.name, "ph": "X", "ts": s.ts_us,
                   "dur": s.dur_us, "pid": os.getpid(), "tid": s.tid}
                  for s in self.spans]
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": events}, f)
        os.replace(tmp, path)

    def total_us(self, name: str) -> float:
        return sum(s.dur_us for s in self.spans if s.name == name)


@contextlib.contextmanager
def span(name: str, tracer: Optional[Tracer] = None):
    """Device+host combined scope: names the region for the XLA
    profiler AND records a host span when a tracer is given."""
    import jax

    with jax.named_scope(name):
        if tracer is None:
            yield
        else:
            with tracer.span(name):
                yield
