"""Counters and gauges for the consensus planes.

The reference has zero observability (SURVEY.md §5).  Here the tally
kernels yield the interesting numbers for free — votes ingested,
thresholds crossed, decisions — and the host wraps them in a tiny
registry with monotonic counters, gauges, and rate derivation.  Export
is one JSON line (the bench.py / driver contract) or a plain dict.

Two rate families, because they answer different questions:

* `rate(name)` — lifetime average (counter / process elapsed).  Right
  for a bench that starts, measures, exits.  WRONG for a long-running
  service: the divisor grows forever, so a steady 1M votes/s reads as
  0 after enough idle hours (the ISSUE-2 serve-gauge bug).
* `interval_rate(name)` / `interval_rates()` — windowed: the delta
  since the PREVIOUS call over the time since that call, then the
  window resets.  This is what a scrape loop wants, and what the
  serve plane's gauges report.
"""

from __future__ import annotations

import json
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class Histogram:
    """Thread-safe, mergeable log-bucket latency histogram (ISSUE 8).

    Fixed bucket table: ``SUB`` sub-buckets per octave (relative bucket
    width 2**(1/SUB) ~ 19%) spanning [2**LO_EXP, 2**HI_EXP) — with the
    defaults ~60 ns to ~18 h, which covers everything from a lock hold
    to a wedged-tunnel stall.  Values outside clamp into the edge
    buckets (counted, never lost).  The hot path is one ``log2``, one
    integer index and one increment under a leaf mutex: no allocation,
    no device access, safe inside the serve plane's never-wait-on-
    device sections.

    Mergeable by construction — every histogram shares the one static
    bucket table, so ``merge`` is element-wise addition: per-thread
    histograms can be folded into one scrape with zero loss (the
    N-thread conservation tests/test_observability.py asserts).

    Quantiles come from the bucket geometric midpoint, so a reported
    p99 is within one bucket width (~19%) of the exact order
    statistic — the right trade for a fixed-size always-on recorder.
    """

    SUB = 4                    # sub-buckets per octave
    LO_EXP = -24               # 2**-24 s ~ 60 ns
    HI_EXP = 16                # 2**16 s ~ 18 h
    NB = (HI_EXP - LO_EXP) * SUB

    __slots__ = ("name", "counts", "n", "total", "vmax", "_mu")

    def __init__(self, name: str = ""):
        self.name = name
        self.counts = [0] * self.NB
        self.n = 0
        self.total = 0.0
        self.vmax = 0.0
        self._mu = threading.Lock()

    @classmethod
    def _index(cls, value: float) -> int:
        if value <= 0.0:
            return 0
        i = int(math.floor(math.log2(value) * cls.SUB)) \
            - cls.LO_EXP * cls.SUB
        return 0 if i < 0 else (cls.NB - 1 if i >= cls.NB else i)

    @classmethod
    def bucket_upper(cls, i: int) -> float:
        """Upper edge of bucket `i` (seconds)."""
        return 2.0 ** (cls.LO_EXP + (i + 1) / cls.SUB)

    @classmethod
    def _bucket_mid(cls, i: int) -> float:
        return 2.0 ** (cls.LO_EXP + (i + 0.5) / cls.SUB)

    def record(self, value: float, n: int = 1) -> None:
        """Record `value` (seconds) `n` times — `n` lets a per-batch
        measurement stand for its votes without a per-vote loop."""
        i = self._index(value)
        with self._mu:
            self.counts[i] += n
            self.n += n
            self.total += value * n
            if value > self.vmax:
                self.vmax = value

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold `other` into self (element-wise; both share the static
        bucket table).  Returns self."""
        with other._mu:
            counts = list(other.counts)
            n, total, vmax = other.n, other.total, other.vmax
        with self._mu:
            for i, c in enumerate(counts):
                if c:
                    self.counts[i] += c
            self.n += n
            self.total += total
            if vmax > self.vmax:
                self.vmax = vmax
        return self

    def quantile(self, q: float) -> float:
        """The q-quantile (0 < q <= 1) as the geometric midpoint of
        the bucket holding the target order statistic; 0.0 when
        empty.  q=1 reports the exact tracked max."""
        with self._mu:
            if self.n == 0:
                return 0.0
            if q >= 1.0:
                return self.vmax
            target = max(1, math.ceil(q * self.n))
            acc = 0
            for i, c in enumerate(self.counts):
                acc += c
                if acc >= target:
                    return min(self._bucket_mid(i), self.vmax)
            return self.vmax

    def snapshot(self) -> dict:
        """p50/p90/p99/max/count/mean — the scrape/report view."""
        with self._mu:
            n, total, vmax = self.n, self.total, self.vmax
        return {
            "count": n,
            "mean": (total / n) if n else 0.0,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "max": vmax,
        }

    def prom_buckets(self) -> Tuple[List[Tuple[float, int]], float, int]:
        """Prometheus histogram view: ([(upper_edge_s, CUMULATIVE
        count)], sum, count) over the occupied bucket range (plus
        +Inf, which the renderer adds).  Consistent under the mutex."""
        with self._mu:
            counts = list(self.counts)
            total, n = self.total, self.n
        lo = next((i for i, c in enumerate(counts) if c), None)
        if lo is None:
            return [], total, n
        hi = max(i for i, c in enumerate(counts) if c)
        out: List[Tuple[float, int]] = []
        acc = sum(counts[:lo])
        for i in range(lo, hi + 1):
            acc += counts[i]
            out.append((self.bucket_upper(i), acc))
        return out, total, n


@dataclass
class Metrics:
    """Process-local metric registry.  Counters are monotonic;
    `rate(name)` derives lifetime per-second rates against the
    registry clock, `interval_rate(name)` windowed ones (see module
    docstring).

    THREAD-SAFE: the serve plane's threaded host (serve/threaded.py)
    has a submit thread and a dispatch thread feeding one registry,
    and a scraper may read from a third.  Every read-modify-write
    (`counters[name] = get + delta` is two bytecodes; first-touch
    registration races the dict resize) runs under one registry lock —
    an RLock so a locked snapshot may call the locked rate helpers.
    Contention is nil in practice: the critical sections are a dict
    op, nothing device-side ever holds the lock."""

    counters: Dict[str, int] = field(default_factory=dict)
    gauges: Dict[str, float] = field(default_factory=dict)
    hists: Dict[str, Histogram] = field(default_factory=dict)
    _t0: float = field(default_factory=time.perf_counter)
    # per-name interval windows: name -> (count at last call, t of
    # last call); all-counter windows for interval_rates()/
    # snapshot(window=True) live in _win_all KEYED BY CONSUMER
    # ("shared" default) — two independent scrape loops (e.g. the
    # drain report and the flight-recorder heartbeat) must not close
    # each other's windows
    _win: Dict[str, Tuple[int, float]] = field(default_factory=dict)
    _win_all: Dict[str, Tuple[Dict[str, int], float]] = \
        field(default_factory=dict)
    _lock: threading.RLock = field(default_factory=threading.RLock,
                                   repr=False, compare=False)

    def count(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def histogram(self, name: str) -> Histogram:
        """Get-or-create the named latency histogram.  The Histogram
        itself is thread-safe (leaf mutex), so hot paths hold a
        REFERENCE and record without touching the registry lock."""
        with self._lock:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = Histogram(name)
        return h

    def observe(self, name: str, value: float, n: int = 1) -> None:
        """Record `value` into the named histogram (creating it)."""
        self.histogram(name).record(value, n)

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def rate(self, name: str) -> float:
        """Lifetime average rate — see the module docstring for when
        this is the wrong tool."""
        dt = self.elapsed()
        with self._lock:
            c = self.counters.get(name, 0)
        return c / dt if dt > 0 else 0.0

    def interval_rate(self, name: str) -> float:
        """Per-second rate of `name` over the window since the LAST
        interval_rate(name) call (since construction on the first);
        reading it closes the window and opens the next one.  Each
        name keeps its own window, so independent scrapers of
        different counters don't shorten each other's intervals."""
        with self._lock:
            now = time.perf_counter()
            last_c, last_t = self._win.get(name, (0, self._t0))
            c = self.counters.get(name, 0)
            self._win[name] = (c, now)
        dt = now - last_t
        return (c - last_c) / dt if dt > 0 else 0.0

    def interval_rates(self) -> Dict[str, float]:
        """One windowed snapshot of EVERY counter: `{name}_per_sec`
        deltas since the previous interval_rates()/snapshot(window=
        True) call on the SAME window key ("shared" here — a
        consistent scrape line).  Does not disturb the per-name
        interval_rate windows."""
        with self._lock:
            return self._windowed_rates_locked("shared")

    def _windowed_rates_locked(self, key: str) -> Dict[str, float]:
        """Close the `key` window and return its per_sec deltas
        (caller holds the registry lock)."""
        now = time.perf_counter()
        base, last_t = self._win_all.get(key) or ({}, self._t0)
        dt = now - last_t
        out = {}
        for name, c in self.counters.items():
            d = c - base.get(name, 0)
            out[f"{name}_per_sec"] = (round(d / dt, 2) if dt > 0
                                      else 0.0)
        self._win_all[key] = (dict(self.counters), now)
        return out

    def snapshot(self, window: bool = False,
                 window_key: str = "shared") -> dict:
        """Counters + gauges + histogram quantiles in one dict.

        `window=False` (default) derives every `{name}_per_sec` from
        the LIFETIME `rate()` — right for a bench that starts,
        measures, exits, and exactly the trap the module docstring
        warns about for anything long-lived.  `window=True` derives
        them from an interval window instead: the serve drain report
        and the flight-recorder heartbeat use this so a long-lived
        service's rates describe the last window, not a decayed
        lifetime average.  `window_key` names the window — each
        INDEPENDENT periodic consumer must use its own key (the
        heartbeat passes "heartbeat") or it would close the "shared"
        window under the drain report / interval_rates() and corrupt
        their rates.  Counter/gauge values themselves are lifetime
        totals either way."""
        with self._lock:
            out = dict(self.counters)
            out.update(self.gauges)
            out["elapsed_s"] = round(self.elapsed(), 4)
            if window:
                out.update(self._windowed_rates_locked(window_key))
            else:
                for name in self.counters:
                    out[f"{name}_per_sec"] = round(self.rate(name), 2)
            hists = list(self.hists.items())
        for name, h in hists:            # hist mutexes: outside _lock
            snap = h.snapshot()
            out[f"{name}_count"] = snap["count"]
            for q in ("p50", "p90", "p99", "max"):
                out[f"{name}_{q}"] = round(snap[q], 6)
        return out

    def export_view(self) -> Tuple[Dict[str, int], Dict[str, float],
                                   Dict[str, Histogram]]:
        """Consistent (counters, gauges, hists) copies for an exporter
        (utils/metrics_http.py) — the one sanctioned way to read the
        registry from outside without reaching for `_lock`."""
        with self._lock:
            return dict(self.counters), dict(self.gauges), \
                dict(self.hists)

    def json_line(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


# the well-known counter names used across the harness/driver
#: static-analysis plane (agnes_tpu/analysis): entries the jaxpr
#: auditor abstractly traced, and dispatches the retrace sentinel saw
#: outside its expected trace set (hardware rounds record both so a
#: clean audit is part of the round artifact)
ANALYSIS_ENTRIES_AUDITED = "analysis_entries_audited"
RETRACE_UNEXPECTED = "retrace_unexpected"
#: bounded model checker (analysis/modelcheck.py, ISSUE 6): distinct
#: canonical states the exhaustive explorer visited, and property
#: violations found.  Bench verdict records carry both (ci.sh exports
#: the [1d] gate's numbers) so a round artifact states that the
#: semantic gate ran and ran clean — the PR 4 pattern.
MODELCHECK_STATES_EXPLORED = "modelcheck_states_explored"
MODELCHECK_VIOLATIONS = "modelcheck_violations"
#: ISSUE 7 additions: the measured orbit reduction of the
#: symmetry-reduced smoke sweep against PR 6's unreduced visit counts
#: on the shared configs (modelcheck.SYM_BASELINE_STATES; -1 = not
#: measured, e.g. --no-sym or a deadline-sentinel partial), and the
#: serve-plane admission model's distinct-state total
#: (analysis/admission_mc.py)
MODELCHECK_SYM_ORBIT_REDUCTION = "modelcheck_sym_orbit_reduction"
MODELCHECK_ADMISSION_STATES = "modelcheck_admission_states"
#: ISSUE 9 additions (epoch-aware, sleepy-churn checking): canonical
#: states visited by the smoke shards carrying validator-set epochs /
#: a sleepy-churn budget, and the measured orbit reduction of the
#: PER-EPOCH symmetry groups against their unreduced baselines
#: (modelcheck.SYM_BASELINE_STATES epoch rows; -1 = not measured).
#: ci.sh gate [1d] exports all three as AGNES_MODELCHECK_* env vars
#: so bench verdict records can state that the epoch/churn envelope
#: ran and ran clean — the same pattern as the four names above.
MODELCHECK_EPOCH_STATES = "modelcheck_epoch_states"
MODELCHECK_CHURN_STATES = "modelcheck_churn_states"
MODELCHECK_EPOCH_ORBIT_REDUCTION = "modelcheck_epoch_orbit_reduction"
#: ISSUE 8 observability plane — serve latency HISTOGRAMS (seconds;
#: log-bucket `Histogram`s living in `Metrics.hists`, quantiles
#: surfaced as `{name}_{p50,p90,p99,max,count}` snapshot keys and as
#: Prometheus histogram series on the /metrics endpoint):
#:   serve_admit_wait_s           submit -> drain wait per admitted
#:                                record (chunk granularity)
#:   serve_batch_close_age_s      oldest-record age when a micro-batch
#:                                closes (size- or deadline-closed)
#:   serve_dispatch_wall_s        host wall of queueing one staged
#:                                build's fused dispatch (step_async)
#:   serve_settle_wall_s          wall of the settle-side collect()
#:                                (the one host<->device sync point)
#:   serve_submit_to_decision_s   end-to-end: oldest admitted record
#:                                of a settled batch -> its decisions
#:                                visible, weighted by the batch's
#:                                votes
SERVE_ADMIT_WAIT_S = "serve_admit_wait_s"
SERVE_BATCH_CLOSE_AGE_S = "serve_batch_close_age_s"
SERVE_DISPATCH_WALL_S = "serve_dispatch_wall_s"
SERVE_SETTLE_WALL_S = "serve_settle_wall_s"
SERVE_E2E_DECISION_S = "serve_submit_to_decision_s"
#: threaded-host names (serve/threaded.py): per-thread depth and
#: utilization gauges plus the inbox-refusal / loop-failure counters.
#: They live HERE (not in serve/service.py, which re-exports them)
#: because the threaded host is jax-free at import by contract — the
#: schedule checker (analysis/schedcheck.py, ISSUE 19) runs the real
#: ThreadedVoteService loops in the same zero-XLA interpreter as the
#: other checkers, so the host's metric names must not pull the
#: pipeline (and with it jax) into the process.
SERVE_INBOX_DEPTH = "serve_inbox_depth"
SERVE_INBOX_DROPPED = "serve_inbox_dropped"          # counter
SERVE_THREAD_FAILURES = "serve_thread_failures"      # counter
SERVE_SUBMIT_BUSY_FRAC = "serve_submit_busy_frac"
SERVE_DISPATCH_BUSY_FRAC = "serve_dispatch_busy_frac"
#: ISSUE 10 (BLS aggregate lane, serve/bls_lane.py): host wall of one
#: class's pairing-product check — the O(1)-per-class cost the lane
#: trades N Ed25519 verifies for (memo hits record ~0; the histogram
#: lives in `Metrics.hists`, so the drain report, the /metrics scrape
#: and every heartbeat source reading a registry snapshot carry its
#: quantiles like the serve histograms above).  The lane's companion
#: COUNTERS — `serve_bls_agg_classes` / `serve_bls_fallback_votes` /
#: `bls_pop_missing` — are named in serve/service.py next to the rest
#: of the serve counter taxonomy.
BLS_PAIRING_WALL_S = "bls_pairing_wall_s"
#: ISSUE 13 (all-device pairing): batched `bls_pairing_product`
#: dispatches the lane issued (counter — > 0 proves the steady state
#: was device-paired; the flight recorder carries the same name as an
#: event kind), and the jaxpr census gate's drift count (gauge on the
#: serve smokes' registries; -1 = gate not run in this process tree).
#: utils/flightrec.py's postmortem renderer spells both literally —
#: it is stdlib-only BY CONTRACT (loaded by file path before any
#: package import) and must not import this module.
BLS_DEVICE_PAIRING_DISPATCHES = "bls_device_pairing_dispatches"
CENSUS_DRIFT_ENTRIES = "census_drift_entries"
#: ISSUE 14 (native admission front-end, serve/native_admission.py):
#:   serve_native_inbox_depth     gauge — records resident in the C++
#:                                admission queue (the native inbox the
#:                                submit thread memcpys into)
#:   serve_native_drain_wall_s    histogram — wall of one GIL-releasing
#:                                drain-and-densify native call
#:   serve_native_rejects_<cause> counters (<cause> in overflow /
#:                                fairness / malformed) — the native
#:                                screens' reject taxonomy, mirrored
#:                                beside the shared serve_rejected_*
#:                                counters so a native-vs-Python
#:                                comparison reads off one scrape.
#: All three live in the shared registry, so the drain report, the
#: heartbeat NDJSON, the /metrics scrape and the agnes-metrics
#: postmortem carry them like every other serve metric.
SERVE_NATIVE_INBOX_DEPTH = "serve_native_inbox_depth"
SERVE_NATIVE_DRAIN_WALL_S = "serve_native_drain_wall_s"
SERVE_NATIVE_REJECTS_PREFIX = "serve_native_rejects_"
#: the three cause counters spelled out (hot submit path: no
#: per-submit string concatenation)
SERVE_NATIVE_REJECTS_OVERFLOW = SERVE_NATIVE_REJECTS_PREFIX + "overflow"
SERVE_NATIVE_REJECTS_FAIRNESS = SERVE_NATIVE_REJECTS_PREFIX + "fairness"
SERVE_NATIVE_REJECTS_MALFORMED = (SERVE_NATIVE_REJECTS_PREFIX
                                  + "malformed")
#: ISSUE 20 (zero-copy densify + sharded ingest):
#:   serve_native_densify_wall_s — wall of drains whose phase/lane
#:                                 device-build arrays were filled
#:                                 NATIVELY (a subset of the plain
#:                                 drain histogram's population; the
#:                                 A/B between the two is the densify
#:                                 speedup read off one scrape)
#:   serve_native_phase_builds   — builds the pipeline adopted from a
#:                                 native phase drain (counter; zero
#:                                 per-record Python work end-to-end)
#:   serve_native_shard_depth_<s> — per-shard resident depth gauges
#:                                 (sharded ingest only; the aggregate
#:                                 stays serve_native_inbox_depth)
#:   serve_native_shard_rejects_<cause> — reject counters summed
#:                                 across shards, mirrored at settle
#:                                 (delta-reconciled from the native
#:                                 counters, so per-shard screens and
#:                                 the fan-in's routing are one
#:                                 number, not n_shards scrapes)
SERVE_NATIVE_DENSIFY_WALL_S = "serve_native_densify_wall_s"
SERVE_NATIVE_PHASE_BUILDS = "serve_native_phase_builds"
SERVE_NATIVE_SHARD_DEPTH_PREFIX = "serve_native_shard_depth_"
SERVE_NATIVE_SHARD_REJECTS_PREFIX = "serve_native_shard_rejects_"
#: ISSUE 15 (multi-host serve, agnes_tpu/distributed/): records the
#: pod front door screened off because their GLOBAL instance id
#: belongs to another host's block (counter, distributed/shard.py —
#: the same name is the drain report's `pod.foreign_rejects`), and
#: the verdict-record keys the multihost bench probe/gate carry:
#: `multihost_hosts` / `multihost_devices_per_host` (pod topology of
#: the measured run) beside `pipeline_serve_multihost_votes_per_sec`.
POD_FOREIGN_REJECTS = "pod_foreign_rejects"
MULTIHOST_HOSTS = "multihost_hosts"
MULTIHOST_DEVICES_PER_HOST = "multihost_devices_per_host"
#: ISSUE 17 elastic-pod membership plane (distributed/elastic.py):
#: the CURRENT membership epoch (gauge — steps at each applied
#: boundary, so a wedge timeline shows which partition was live), the
#: per-tick negotiation wall (histogram: pack + allgather + merge +
#: pad, the price of elasticity on the tick path), dead-peer verdicts
#: cleared by resumed evidence (counter, StragglerMonitor.beat — the
#: recovery path the membership plane consumes), and the membership
#: model's distinct-state total (analysis/membership_mc.py, exported
#: by ci gate [1d] like the admission/epoch totals above).  The
#: elastic bench probe's verdict records carry
#: `pipeline_serve_elastic_votes_per_sec` beside the multihost keys.
POD_MEMBERSHIP_EPOCH = "pod_membership_epoch"
POD_NEGOTIATION_WALL_S = "pod_negotiation_wall_s"
POD_HOST_READMISSIONS = "pod_host_readmissions"
MODELCHECK_MEMBERSHIP_STATES = "modelcheck_membership_states"
#: ISSUE 19 (deterministic interleaving explorer,
#: analysis/schedcheck.py): distinct complete thread schedules the
#: cooperative scheduler executed over the REAL threaded serve host,
#: and monitor violations found (conservation / deadlock / lock-order
#: / atomicity / gauge-sanity).  ci.sh gate [1e] exports both as
#: AGNES_SCHEDCHECK_* env vars so bench verdict records can state that
#: the schedule envelope ran and ran clean — the modelcheck pattern.
SCHEDCHECK_SCHEDULES_EXPLORED = "schedcheck_schedules_explored"
SCHEDCHECK_VIOLATIONS = "schedcheck_violations"
#: per-entry first-dispatch wall gauges, `compile_ms_<entry>` (ISSUE 8
#: satellite): the registry times the FIRST dispatch of every entry in
#: the process (trace + compile dominates that call), so the next
#: silent-double-compile class of bug is a number in the drain report
#: and the bench verdict record, not a 217s mystery stall
#: (device/registry.py `compile_ms()`; -1 never appears — an entry
#: that was not dispatched has no key)
COMPILE_MS_PREFIX = "compile_ms_"
VOTES_INGESTED = "votes_ingested"
VOTES_VERIFIED = "votes_verified"
THRESHOLDS_CROSSED = "thresholds_crossed"
DECISIONS = "decisions"
ROUNDS_SKIPPED = "rounds_skipped"
EQUIVOCATIONS = "equivocations"


def attach_to_driver(driver, metrics: Optional[Metrics] = None) -> Metrics:
    """Wrap a DeviceDriver's step() so the registry tracks the
    north-star counters without touching the jitted path.

    IDEMPOTENT: re-attaching used to stack a second wrapper on
    `driver.step`, double-counting every counter from then on (the
    ISSUE-2 satellite).  Now the wrapper is installed at most once and
    reads its registry through `driver._agnes_metrics` at call time —
    a re-attach with a new registry just rebinds that attribute (and
    returns it); a bare re-attach returns the registry already in
    place."""
    import numpy as np

    if getattr(driver.step, "_agnes_metrics_wrapper", False):
        if metrics is not None:
            driver._agnes_metrics = metrics
        return driver._agnes_metrics

    driver._agnes_metrics = metrics or Metrics()
    inner = driver.step

    def step(ext=None, phase=None):
        m = driver._agnes_metrics
        decided_before = int(driver.stats.decided.sum())
        votes_before = driver.stats.votes_ingested
        # tally.emitted holds the highest threshold code reached per
        # (instance, round, class); its sum rises exactly when a tally
        # threshold is newly crossed — the real counter, as opposed to
        # counting the state machine's output messages
        emitted_before = int(np.asarray(driver.tally.emitted).sum())
        msgs = inner(ext=ext, phase=phase)
        m.count(VOTES_INGESTED, driver.stats.votes_ingested - votes_before)
        m.count(DECISIONS, int(driver.stats.decided.sum()) - decided_before)
        emitted_now = int(np.asarray(driver.tally.emitted).sum())
        m.count(THRESHOLDS_CROSSED, emitted_now - emitted_before)
        m.gauge(EQUIVOCATIONS, int(driver.equivocators_detected().sum()))
        return msgs

    step._agnes_metrics_wrapper = True
    driver.step = step
    return driver._agnes_metrics
