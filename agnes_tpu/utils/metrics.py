"""Counters and gauges for the consensus planes.

The reference has zero observability (SURVEY.md §5).  Here the tally
kernels yield the interesting numbers for free — votes ingested,
thresholds crossed, decisions — and the host wraps them in a tiny
registry with monotonic counters, gauges, and rate derivation.  Export
is one JSON line (the bench.py / driver contract) or a plain dict.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class Metrics:
    """Process-local metric registry.  Counters are monotonic;
    `rate(name)` derives per-second rates against the registry clock."""

    counters: Dict[str, int] = field(default_factory=dict)
    gauges: Dict[str, float] = field(default_factory=dict)
    _t0: float = field(default_factory=time.perf_counter)

    def count(self, name: str, delta: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + delta

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def rate(self, name: str) -> float:
        dt = self.elapsed()
        return self.counters.get(name, 0) / dt if dt > 0 else 0.0

    def snapshot(self) -> dict:
        out = dict(self.counters)
        out.update(self.gauges)
        out["elapsed_s"] = round(self.elapsed(), 4)
        for name in self.counters:
            out[f"{name}_per_sec"] = round(self.rate(name), 2)
        return out

    def json_line(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


# the well-known counter names used across the harness/driver
VOTES_INGESTED = "votes_ingested"
VOTES_VERIFIED = "votes_verified"
THRESHOLDS_CROSSED = "thresholds_crossed"
DECISIONS = "decisions"
ROUNDS_SKIPPED = "rounds_skipped"
EQUIVOCATIONS = "equivocations"


def attach_to_driver(driver, metrics: Optional[Metrics] = None) -> Metrics:
    """Wrap a DeviceDriver's step() so the registry tracks the
    north-star counters without touching the jitted path."""
    import numpy as np

    m = metrics or Metrics()
    inner = driver.step

    def step(ext=None, phase=None):
        decided_before = int(driver.stats.decided.sum())
        votes_before = driver.stats.votes_ingested
        # tally.emitted holds the highest threshold code reached per
        # (instance, round, class); its sum rises exactly when a tally
        # threshold is newly crossed — the real counter, as opposed to
        # counting the state machine's output messages
        emitted_before = int(np.asarray(driver.tally.emitted).sum())
        msgs = inner(ext=ext, phase=phase)
        m.count(VOTES_INGESTED, driver.stats.votes_ingested - votes_before)
        m.count(DECISIONS, int(driver.stats.decided.sum()) - decided_before)
        emitted_now = int(np.asarray(driver.tally.emitted).sum())
        m.count(THRESHOLDS_CROSSED, emitted_now - emitted_before)
        m.gauge(EQUIVOCATIONS, int(driver.equivocators_detected().sum()))
        return msgs

    driver.step = step
    return m
