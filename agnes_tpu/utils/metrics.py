"""Counters and gauges for the consensus planes.

The reference has zero observability (SURVEY.md §5).  Here the tally
kernels yield the interesting numbers for free — votes ingested,
thresholds crossed, decisions — and the host wraps them in a tiny
registry with monotonic counters, gauges, and rate derivation.  Export
is one JSON line (the bench.py / driver contract) or a plain dict.

Two rate families, because they answer different questions:

* `rate(name)` — lifetime average (counter / process elapsed).  Right
  for a bench that starts, measures, exits.  WRONG for a long-running
  service: the divisor grows forever, so a steady 1M votes/s reads as
  0 after enough idle hours (the ISSUE-2 serve-gauge bug).
* `interval_rate(name)` / `interval_rates()` — windowed: the delta
  since the PREVIOUS call over the time since that call, then the
  window resets.  This is what a scrape loop wants, and what the
  serve plane's gauges report.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


@dataclass
class Metrics:
    """Process-local metric registry.  Counters are monotonic;
    `rate(name)` derives lifetime per-second rates against the
    registry clock, `interval_rate(name)` windowed ones (see module
    docstring).

    THREAD-SAFE: the serve plane's threaded host (serve/threaded.py)
    has a submit thread and a dispatch thread feeding one registry,
    and a scraper may read from a third.  Every read-modify-write
    (`counters[name] = get + delta` is two bytecodes; first-touch
    registration races the dict resize) runs under one registry lock —
    an RLock so a locked snapshot may call the locked rate helpers.
    Contention is nil in practice: the critical sections are a dict
    op, nothing device-side ever holds the lock."""

    counters: Dict[str, int] = field(default_factory=dict)
    gauges: Dict[str, float] = field(default_factory=dict)
    _t0: float = field(default_factory=time.perf_counter)
    # per-name interval windows: name -> (count at last call, t of
    # last call); a shared window for interval_rates() lives under a
    # key no counter can collide with
    _win: Dict[str, Tuple[int, float]] = field(default_factory=dict)
    _win_all: Optional[Tuple[Dict[str, int], float]] = None
    _lock: threading.RLock = field(default_factory=threading.RLock,
                                   repr=False, compare=False)

    def count(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def rate(self, name: str) -> float:
        """Lifetime average rate — see the module docstring for when
        this is the wrong tool."""
        dt = self.elapsed()
        with self._lock:
            c = self.counters.get(name, 0)
        return c / dt if dt > 0 else 0.0

    def interval_rate(self, name: str) -> float:
        """Per-second rate of `name` over the window since the LAST
        interval_rate(name) call (since construction on the first);
        reading it closes the window and opens the next one.  Each
        name keeps its own window, so independent scrapers of
        different counters don't shorten each other's intervals."""
        with self._lock:
            now = time.perf_counter()
            last_c, last_t = self._win.get(name, (0, self._t0))
            c = self.counters.get(name, 0)
            self._win[name] = (c, now)
        dt = now - last_t
        return (c - last_c) / dt if dt > 0 else 0.0

    def interval_rates(self) -> Dict[str, float]:
        """One windowed snapshot of EVERY counter: `{name}_per_sec`
        deltas since the previous interval_rates() call, sharing one
        window (a consistent scrape line).  Does not disturb the
        per-name interval_rate windows."""
        with self._lock:
            now = time.perf_counter()
            base, last_t = self._win_all or ({}, self._t0)
            dt = now - last_t
            out = {}
            for name, c in self.counters.items():
                d = c - base.get(name, 0)
                out[f"{name}_per_sec"] = (round(d / dt, 2) if dt > 0
                                          else 0.0)
            self._win_all = (dict(self.counters), now)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out.update(self.gauges)
            out["elapsed_s"] = round(self.elapsed(), 4)
            for name in self.counters:
                out[f"{name}_per_sec"] = round(self.rate(name), 2)
        return out

    def json_line(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


# the well-known counter names used across the harness/driver
#: static-analysis plane (agnes_tpu/analysis): entries the jaxpr
#: auditor abstractly traced, and dispatches the retrace sentinel saw
#: outside its expected trace set (hardware rounds record both so a
#: clean audit is part of the round artifact)
ANALYSIS_ENTRIES_AUDITED = "analysis_entries_audited"
RETRACE_UNEXPECTED = "retrace_unexpected"
#: bounded model checker (analysis/modelcheck.py, ISSUE 6): distinct
#: canonical states the exhaustive explorer visited, and property
#: violations found.  Bench verdict records carry both (ci.sh exports
#: the [1d] gate's numbers) so a round artifact states that the
#: semantic gate ran and ran clean — the PR 4 pattern.
MODELCHECK_STATES_EXPLORED = "modelcheck_states_explored"
MODELCHECK_VIOLATIONS = "modelcheck_violations"
#: ISSUE 7 additions: the measured orbit reduction of the
#: symmetry-reduced smoke sweep against PR 6's unreduced visit counts
#: on the shared configs (modelcheck.SYM_BASELINE_STATES; -1 = not
#: measured, e.g. --no-sym or a deadline-sentinel partial), and the
#: serve-plane admission model's distinct-state total
#: (analysis/admission_mc.py)
MODELCHECK_SYM_ORBIT_REDUCTION = "modelcheck_sym_orbit_reduction"
MODELCHECK_ADMISSION_STATES = "modelcheck_admission_states"
VOTES_INGESTED = "votes_ingested"
VOTES_VERIFIED = "votes_verified"
THRESHOLDS_CROSSED = "thresholds_crossed"
DECISIONS = "decisions"
ROUNDS_SKIPPED = "rounds_skipped"
EQUIVOCATIONS = "equivocations"


def attach_to_driver(driver, metrics: Optional[Metrics] = None) -> Metrics:
    """Wrap a DeviceDriver's step() so the registry tracks the
    north-star counters without touching the jitted path.

    IDEMPOTENT: re-attaching used to stack a second wrapper on
    `driver.step`, double-counting every counter from then on (the
    ISSUE-2 satellite).  Now the wrapper is installed at most once and
    reads its registry through `driver._agnes_metrics` at call time —
    a re-attach with a new registry just rebinds that attribute (and
    returns it); a bare re-attach returns the registry already in
    place."""
    import numpy as np

    if getattr(driver.step, "_agnes_metrics_wrapper", False):
        if metrics is not None:
            driver._agnes_metrics = metrics
        return driver._agnes_metrics

    driver._agnes_metrics = metrics or Metrics()
    inner = driver.step

    def step(ext=None, phase=None):
        m = driver._agnes_metrics
        decided_before = int(driver.stats.decided.sum())
        votes_before = driver.stats.votes_ingested
        # tally.emitted holds the highest threshold code reached per
        # (instance, round, class); its sum rises exactly when a tally
        # threshold is newly crossed — the real counter, as opposed to
        # counting the state machine's output messages
        emitted_before = int(np.asarray(driver.tally.emitted).sum())
        msgs = inner(ext=ext, phase=phase)
        m.count(VOTES_INGESTED, driver.stats.votes_ingested - votes_before)
        m.count(DECISIONS, int(driver.stats.decided.sum()) - decided_before)
        emitted_now = int(np.asarray(driver.tally.emitted).sum())
        m.count(THRESHOLDS_CROSSED, emitted_now - emitted_before)
        m.gauge(EQUIVOCATIONS, int(driver.equivocators_detected().sum()))
        return msgs

    step._agnes_metrics_wrapper = True
    driver.step = step
    return driver._agnes_metrics
