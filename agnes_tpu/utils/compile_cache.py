"""JAX compilation-cache policy: DISABLED, plus XLA:CPU de-racing.

Round-4 evidence forced this policy.  The persistent compile cache
(.jax_cache) produced four distinct segfault modes in this
environment before being abandoned:

  * loading entries written by a different-ISA machine (the repo is
    visited by several hosts across rounds) SIGILLs — XLA:CPU AOT
    executables are CPU-feature-specific;
  * a data race between XLA:CPU's parallel codegen threads and
    executable serialization (TSAN-confirmed in
    ThunkEmitter::ConsumeKernels) crashed cache WRITES intermittently;
  * the ~100k-op interpret-mode Pallas kernels crashed the serializer
    across every mitigation tried (stack ulimits, single-threaded
    codegen, fresh cache dirs);
  * and each mid-write crash can leave a torn entry that then crashes
    subsequent READS — cascading corruption (observed: a same-host
    entry segfaulting get_executable_and_time after earlier write
    crashes).

Per-host cache keying (a /proc/cpuinfo fingerprint sub-directory)
fixed only the first mode.  Correctness wins: no code path sets a
cache directory any more — every process pays its own compiles — and
entry points apply `serialize_cpu_codegen`'s de-race flag in the
environment before any agnes/jax import (package __init__ side
effects initialize the backend early).  Revisit if jaxlib updates.
"""

from __future__ import annotations

import os


def serialize_cpu_codegen() -> None:
    """Work around a data race in this jaxlib's XLA:CPU between its
    parallel codegen threads and executable serialization
    (TSAN-confirmed in ThunkEmitter::ConsumeKernels): single-threaded
    codegen removes the racing threads.  Must run before the first
    backend use — XLA_FLAGS is read at client creation, and importing
    most agnes modules initializes a backend, so entry points also set
    this in the environment before any agnes/jax import."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_parallel_codegen_split_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_parallel_codegen_split_count=1").strip()

