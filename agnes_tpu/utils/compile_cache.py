"""JAX compilation-cache policy: DISABLED, plus XLA:CPU de-racing.

Round-4 evidence forced this policy.  The persistent compile cache
(.jax_cache) produced four distinct segfault modes in this
environment before being abandoned:

  * loading entries written by a different-ISA machine (the repo is
    visited by several hosts across rounds) SIGILLs — XLA:CPU AOT
    executables are CPU-feature-specific;
  * a data race between XLA:CPU's parallel codegen threads and
    executable serialization (TSAN-confirmed in
    ThunkEmitter::ConsumeKernels) crashed cache WRITES intermittently;
  * the ~100k-op interpret-mode Pallas kernels crashed the serializer
    across every mitigation tried (stack ulimits, single-threaded
    codegen, fresh cache dirs);
  * and each mid-write crash can leave a torn entry that then crashes
    subsequent READS — cascading corruption (observed: a same-host
    entry segfaulting get_executable_and_time after earlier write
    crashes).

Per-host cache keying (a /proc/cpuinfo fingerprint sub-directory)
fixed only the first mode.  Correctness wins: no code path sets a
cache directory any more — every process pays its own compiles — and
every entry point inlines the de-race XLA_FLAGS snippet below in the
environment before any agnes/jax import (package __init__ side
effects initialize the backend early, so calling into this module
would already be too late — which is why the snippet is inlined
rather than imported).  `python -m agnes_tpu.harness.configs` cannot
even inline it (the package import precedes the module body under
-m); its wrapper scripts/run_hw_suite.sh exports the policy instead.
`disable_persistent_cache()` additionally pins the cache OFF
in-process so a leftover JAX_COMPILATION_CACHE_DIR in the environment
cannot re-enable the segfault modes above.  Revisit if jaxlib
updates.

The canonical de-race snippet (keep entry-point copies in sync):

    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_parallel_codegen_split_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_cpu_parallel_codegen_split_count=1").strip()
"""

from __future__ import annotations

import os


def disable_persistent_cache() -> None:
    """Pin the persistent compile cache OFF for this process even if
    the environment sets JAX_COMPILATION_CACHE_DIR (the pre-r4
    documented workflow): jax reads that env var at config init, so
    omission alone does not guarantee the disabled policy."""
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_enable_compilation_cache", False)
    except AttributeError:      # config name drift across jax versions
        pass

