"""Per-host persistent-compile-cache path (+ serializer stack room).

The repo's .jax_cache is visited by MULTIPLE machines across rounds
(this build VM, the bench driver's host, the axon remote-compile
relay), whose CPUs differ in ISA features (AMX/AVX512 sets,
prefer-no-scatter).  XLA:CPU AOT executables are feature-specific:
loading an entry compiled on a richer host SIGILLs/segfaults here —
observed as a segfault inside compilation_cache.get_executable_and_time
during the round-4 full-suite run.  Keying the cache directory by a
host fingerprint keeps every machine's entries separate while still
persisting across processes and rounds on the same machine.

Separately, SERIALIZING the very largest executables (the ~100k-op
interpret-mode fused verify kernels) segfaults XLA's cache writer
intermittently (put_executable_and_time) — r4 reproduced the crash
across stack limits (8 MiB and `ulimit -s 65536`), across
single-threaded codegen, and across fresh cache dirs.  Those graphs
are therefore NEVER persisted: crypto/pallas_verify.py disables the
compilation cache around interpret-mode compiles (tests-only path; a
deterministic recompile beats a nondeterministic CI segfault).
Normal-size executables — everything the production TPU/CPU paths
compile — serialize fine and stay cached."""

from __future__ import annotations

import hashlib
import os
import platform

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def cache_dir(root: str = os.path.join(_REPO_ROOT, ".jax_cache")) -> str:
    try:
        with open("/proc/cpuinfo") as f:
            # "flags" on x86, "Features" on aarch64 — both must fold
            # into the tag or same-arch hosts with different ISA
            # extensions would share AOT entries (the exact segfault
            # this module prevents)
            flags = next((ln for ln in f
                          if ln.startswith(("flags", "Features"))), "")
    except OSError:
        flags = ""
    tag = hashlib.sha256(
        (platform.machine() + flags).encode()).hexdigest()[:12]
    return os.path.join(root, tag)


def serialize_cpu_codegen() -> None:
    """Work around a data race in this jaxlib's XLA:CPU between its
    parallel codegen threads and executable serialization
    (TSAN-confirmed in ThunkEmitter::ConsumeKernels; intermittent
    segfaults inside compilation_cache get/put, r4): single-threaded
    codegen removes the racing threads.  Must run before the first
    backend use — XLA_FLAGS is read at client creation."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_parallel_codegen_split_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_parallel_codegen_split_count=1").strip()


def configure(jax_module) -> str:
    """Point jax's persistent cache at this host's sub-directory and
    de-race XLA:CPU codegen."""
    serialize_cpu_codegen()
    d = cache_dir()
    jax_module.config.update("jax_compilation_cache_dir", d)
    return d
