"""agnes-tpu: a TPU-native BFT consensus framework.

A brand-new implementation of the capabilities of the reference engine
(Liamsi/agnes, a pure Tendermint state-machine-replication core in Rust,
see /root/reference): the pure State/Event/Message consensus state machine
is kept semantically identical (reference src/state_machine.rs), while the
Event-*producer* side — signature verification, vote tally, polka/commit
threshold detection — is a JAX/TPU data plane: batched Ed25519 verification,
vmapped verify+tally kernels with psum over the validator mesh axis, a
device-resident validator pubkey table, and thousands of concurrent
(height, round) consensus instances.

Layout (mirrors SURVEY.md §7):
  core/      pure-Python oracle core + C++ native runtime (ctypes)
  device/    JAX data plane: int-encoded state machine, tally kernels
  crypto/    Ed25519: python oracle, JAX batched verify, Pallas kernels
  parallel/  mesh/sharding: instance-DP × validator-TP, XLA collectives
  bridge/    host<->device vote-batch ingestion ABI
  harness/   event-stream simulator, Byzantine schedules, benchmark configs
  utils/     tracing, checkpoint/resume, metrics
"""

__version__ = "0.5.0"

from agnes_tpu.types import (  # noqa: F401
    NIL,
    Proposal,
    Vote,
    VoteType,
)
